"""Hostile-internet actor behaviors — the population a scenario scripts.

Each :class:`Behavior` owns one ``ActorGroup`` from the spec and steps
it once per virtual tick against the world the engine built (real
``ShardedSwarmStore`` shards, a real ``DHTNode`` driven transportless,
the real indexer). Behaviors are deterministic: every identity,
info-hash, address, and payload derives from ``sha1`` of the actor's
coordinates or from the world's seeded rng, never from wall time.

The world object (``scenario/engine.py``) is the only surface a
behavior touches:

* ``world.announce(...)`` — tracker announce with presence bookkeeping
  and wall-latency capture; every completed announce is one
  availability EVENT.
* ``world.submit_piece(key, payload, digest)`` — the sentinel seam:
  digest-verified piece ingestion with strike-based conviction.
* ``world.datagram(data, addr)`` — a raw KRPC datagram into the DHT
  node; returns the decoded replies the node tried to send.
* ``world.record_shed()`` / ``world.record_failed()`` — availability
  ERRORS (shed connections, failed pieces).

Behaviors report two things at the end: ``facts()`` (plain data for
the verdict) and ``failures()`` (invariant violations, each a human
sentence — an empty list means the behavior's contract held).
"""

from __future__ import annotations

import hashlib

from torrent_tpu.codec.bencode import bencode
from torrent_tpu.net.types import AnnounceEvent

# Promoted to the live session in PR 17: the scenario plane attacks
# the SAME AcceptGate class the real accept path runs (virtual ticks
# here, monotonic seconds there) — re-exported so scenario code keeps
# its historical import site.
from torrent_tpu.session.torrent import AcceptGate

__all__ = ["Behavior", "AcceptGate", "build_behaviors", "BEHAVIOR_KINDS"]


def _h(*parts) -> bytes:
    """sha1 of the ':'-joined coordinates — the deterministic identity
    well every actor draws from."""
    return hashlib.sha1(":".join(str(p) for p in parts).encode()).digest()


def _ih(kind: str, gi: int, swarm: int) -> bytes:
    return _h("scn-ih", kind, gi, swarm)


def _pid(kind: str, gi: int, i: int, salt: int = 0) -> bytes:
    return b"-SC-" + _h("scn-pid", kind, gi, i, salt)[:16]


def _ip(kind: str, gi: int, i: int) -> str:
    d = _h("scn-ip", kind, gi, i)
    return f"10.{d[0]}.{d[1]}.{d[2]}"


class Behavior:
    """Base: one actor group's scripted conduct over the run."""

    kind = ""

    def __init__(self, group, gi: int):
        self.group = group
        self.gi = gi

    def setup(self, world) -> None:
        pass

    def step(self, world) -> None:
        raise NotImplementedError

    def facts(self, world) -> dict:
        return {}

    def failures(self, world) -> list[str]:
        return []


class HonestBehavior(Behavior):
    """Baseline announcers: the availability denominator. ``seed_pct``
    of the population are seeders; each peer announces (and submits one
    digest-valid piece) every ``interval_ticks``, spread over
    ``swarms`` info-hashes."""

    kind = "honest"

    def setup(self, world) -> None:
        g = self.group
        self.swarms = g.param("swarms")
        self.numwant = g.param("numwant")
        self.interval = g.param("interval_ticks")
        self.seeders = g.count * g.param("seed_pct") // 100
        self.announces = 0

    def step(self, world) -> None:
        for i in range(self.group.count):
            if (world.tick + i) % self.interval:
                continue
            ih = _ih(self.kind, self.gi, i % self.swarms)
            world.announce(
                ih, _pid(self.kind, self.gi, i), _ip(self.kind, self.gi, i),
                6881 + (i % 1000), 0 if i < self.seeders else 1,
                AnnounceEvent.EMPTY, self.numwant,
            )
            payload = _h("piece", self.gi, i, world.tick)
            world.submit_piece(
                f"honest:{self.gi}:{i}", payload,
                hashlib.sha1(payload).digest(),
            )
            self.announces += 1

    def facts(self, world) -> dict:
        return {"announces": self.announces}


class SybilBehavior(Behavior):
    """Announce stampede from forged identities: every tick, every
    sybil announces under a FRESH peer id with an oversized ``numwant``.
    The tracker's server-side clamp must bound every reply and its
    occupancy must stay a TTL-sweepable population, not a permanent
    allocation."""

    kind = "sybil"

    def setup(self, world) -> None:
        g = self.group
        self.swarms = g.param("swarms")
        self.numwant = g.param("numwant")
        self.announces = 0
        self.overflows = 0  # replies longer than the server-side cap

    def step(self, world) -> None:
        for i in range(self.group.count):
            out = world.announce(
                _ih(self.kind, self.gi, i % self.swarms),
                _pid(self.kind, self.gi, i, salt=world.tick),
                _ip(self.kind, self.gi, i), 1025 + (i % 60000), 1,
                AnnounceEvent.EMPTY, self.numwant,
            )
            self.announces += 1
            if len(out.peers) > world.clamp_cap:
                self.overflows += 1

    def facts(self, world) -> dict:
        snap = world.store.metrics_snapshot()
        return {
            "announces": self.announces,
            "overflows": self.overflows,
            "numwant_clamped": snap["numwant_clamped"],
        }

    def failures(self, world) -> list[str]:
        out = []
        if self.overflows:
            out.append(
                f"sybil reply clamp failed: {self.overflows} replies "
                f"exceeded the {world.clamp_cap}-peer cap"
            )
        if self.numwant > world.clamp_cap and self.announces:
            snap = world.store.metrics_snapshot()
            if not snap["numwant_clamped"]:
                out.append(
                    "sybil numwant above the cap but the tracker never "
                    "counted a clamp"
                )
        return out


class PoisonBehavior(Behavior):
    """Piece poisoners: every submission carries a payload whose digest
    does NOT verify. The sentinel must convict every scripted poisoner
    (strike threshold) and nobody else — zero false convictions is part
    of the verdict, not just zero escapes."""

    kind = "poison"

    def setup(self, world) -> None:
        g = self.group
        self.swarms = g.param("swarms")
        self.per_tick = g.param("per_tick")
        self.keys = [f"poison:{self.gi}:{i}" for i in range(g.count)]
        world.scripted_poisoners.update(self.keys)
        self.submitted = 0

    def step(self, world) -> None:
        for i in range(self.group.count):
            for k in range(self.per_tick):
                payload = _h("poisoned", self.gi, i, world.tick, k)
                # digest of DIFFERENT bytes: verification must fail
                world.submit_piece(
                    self.keys[i], payload,
                    hashlib.sha1(payload + b"!").digest(),
                )
                self.submitted += 1

    def facts(self, world) -> dict:
        convicted = sum(1 for k in self.keys if k in world.convicted)
        return {
            "scripted": len(self.keys),
            "submitted": self.submitted,
            "convicted": convicted,
            "false_convictions": world.false_convictions,
            "escapes": world.poison_escapes,
        }

    def failures(self, world) -> list[str]:
        out = []
        unconvicted = [k for k in self.keys if k not in world.convicted]
        if unconvicted:
            out.append(
                f"{len(unconvicted)}/{len(self.keys)} scripted poisoners "
                f"escaped conviction (first: {unconvicted[0]})"
            )
        if world.poison_escapes:
            out.append(
                f"{world.poison_escapes} poisoned pieces were accepted"
            )
        if world.false_convictions:
            out.append(
                f"{world.false_convictions} honest submitters were "
                f"falsely convicted"
            )
        return out


class ChurnBehavior(Behavior):
    """Churn storm: per tick each peer joins (announce), leaves
    politely (STOPPED), turns ghost (silent departure only the TTL
    sweep may reclaim), or refreshes — all by seeded-rng draw. The
    engine's end-of-run reconciliation must find tracker occupancy
    EXACTLY equal to the presence ledger."""

    kind = "churn"

    def setup(self, world) -> None:
        g = self.group
        self.swarms = g.param("swarms")
        self.join_pct = g.param("join_pct")
        self.stop_pct = g.param("stop_pct")
        self.ghost_pct = g.param("ghost_pct")
        self.state = ["out"] * g.count  # out | in | ghost
        self.joins = self.stops = self.ghosts = 0

    def step(self, world) -> None:
        for i in range(self.group.count):
            r = world.rng.randrange(100)
            state = self.state[i]
            ih = _ih(self.kind, self.gi, i % self.swarms)
            pid = _pid(self.kind, self.gi, i)
            ip = _ip(self.kind, self.gi, i)
            port = 2000 + (i % 60000)
            if state == "out":
                if r < self.join_pct:
                    world.announce(
                        ih, pid, ip, port, 1, AnnounceEvent.STARTED, 10
                    )
                    self.state[i] = "in"
                    self.joins += 1
            elif state == "in":
                if r < self.stop_pct:
                    world.announce(
                        ih, pid, ip, port, 1, AnnounceEvent.STOPPED, 0
                    )
                    self.state[i] = "out"
                    self.stops += 1
                elif r < self.stop_pct + self.ghost_pct:
                    self.state[i] = "ghost"  # silent: TTL must reclaim
                    self.ghosts += 1
                else:
                    world.announce(
                        ih, pid, ip, port, 1, AnnounceEvent.EMPTY, 10
                    )
            # ghosts never announce again

    def facts(self, world) -> dict:
        return {
            "joins": self.joins,
            "stops": self.stops,
            "ghosted": self.ghosts,
        }


class SlowlorisBehavior(Behavior):
    """Slot-holders against the accept gate: the whole population
    connects at the top of every ``hold_ticks`` wave and then never
    makes progress; the gate's ``idle_ticks`` eviction must reclaim
    them. ``honest_conns`` short-lived connections per tick are the
    availability probe — shed ones are SLO errors."""

    kind = "slowloris"

    def setup(self, world) -> None:
        g = self.group
        self.hold_ticks = g.param("hold_ticks")
        self.gate = AcceptGate(g.param("capacity"), g.param("idle_ticks"))
        self.honest_conns = g.param("honest_conns")
        self.honest_ok = 0
        self.honest_shed = 0

    def step(self, world) -> None:
        tick = world.tick
        if tick % self.hold_ticks == 0:
            for i in range(self.group.count):
                self.gate.connect(("loris", self.gi, i), tick)
        for j in range(self.honest_conns):
            key = ("conn", self.gi, tick, j)
            if self.gate.connect(key, tick):
                self.gate.release(key)
                self.honest_ok += 1
                world.record_ok()
            else:
                self.honest_shed += 1
                world.record_shed()
        self.gate.sweep(tick)

    def facts(self, world) -> dict:
        return {
            "honest_ok": self.honest_ok,
            "honest_shed": self.honest_shed,
            "idle_evicted": self.gate.evicted_idle,
            "slots_open": len(self.slots_left()),
        }

    def slots_left(self) -> dict:
        return self.gate.slots

    def failures(self, world) -> list[str]:
        out = []
        if self.honest_conns and not self.honest_ok:
            out.append(
                "slowloris held the accept gate shut for the whole run "
                "(no honest connection ever admitted)"
            )
        if not self.gate.evicted_idle and self.group.count:
            out.append("idle eviction never reclaimed a slowloris slot")
        return out


class LeecherBehavior(Behavior):
    """Leecher stampede against the seeder plane's two defenses: the
    accept gate's per-IP clamp and the DRR choke economics
    (``serve_plane/choke.py`` — the SAME class the live session's
    ``_choke_loop`` runs, driven here with virtual ticks).

    ``honest_pct`` of the population are honest leechers — unique IPs,
    real reciprocation weights; the rest are a stampede horde packed
    onto ``stampede_ips`` shared addresses that never reciprocates.
    Everyone dials in at tick 0; each subsequent tick is one unchoke
    round where every fed peer drinks a quantum (charged back, so the
    queue rotates). The contract: the per-IP clamp bounds the horde,
    no round unchokes more than ``slots`` + 1 peers, the optimistic
    slot rotates, and every admitted honest leecher is fed at least
    once before the run ends."""

    kind = "leecher"

    def setup(self, world) -> None:
        from torrent_tpu.serve_plane.choke import ChokeEconomics

        g = self.group
        self.slots = g.param("slots")
        self.per_ip = g.param("per_ip")
        self.stampede_ips = g.param("stampede_ips")
        self.honest_n = g.count * g.param("honest_pct") // 100
        self.stampede_n = g.count - self.honest_n
        self.quantum = g.param("quantum_kb") * 1024
        # idle_after far past the run: eviction is slowloris's exam,
        # not this one's — here the per-IP clamp is the front door
        self.gate = AcceptGate(
            g.param("capacity"), 1 << 30, per_ip=self.per_ip
        )
        # one virtual tick = one whole unchoke round, so the product's
        # cap (8 quanta, tuned for continuous charging between rounds)
        # would saturate in a handful of ticks and flatten the queue
        # order into a key tie-break; size the cap past the run instead
        self.econ = ChokeEconomics(
            self.slots,
            quantum=self.quantum,
            seed=int.from_bytes(_h("leecher-econ", self.gi)[:8], "big"),
            cap_rounds=128,
        )
        self.admitted: list[str] = []
        self.weights: dict[str, float] = {}
        self.honest_admitted = 0
        self.honest_shed = 0
        self.honest_fed: set[str] = set()
        self.max_unchoked = 0
        self.stampede_unchokes = 0

    def _connect_all(self, world, tick: int) -> None:
        # the horde races in first — the worst case for the honest crowd
        for i in range(self.stampede_n):
            key = f"s{self.gi}.{i}"
            ip = _ip("leecher-horde", self.gi, i % self.stampede_ips)
            if self.gate.connect(key, tick, ip=ip):
                self.admitted.append(key)
                self.weights[key] = 0.0  # never reciprocates
        for i in range(self.honest_n):
            key = f"h{self.gi}.{i}"
            if self.gate.connect(key, tick, ip=_ip(self.kind, self.gi, i)):
                self.admitted.append(key)
                d = _h("leecher-rate", self.gi, i)
                self.weights[key] = 0.25 + d[0] / 1024
                self.honest_admitted += 1
            else:
                self.honest_shed += 1
                world.record_shed()

    def step(self, world) -> None:
        if world.tick == 0:
            self._connect_all(world, world.tick)
        if not self.admitted:
            return
        verdict = self.econ.round(self.weights)
        fed = verdict.all_unchoked()
        self.max_unchoked = max(self.max_unchoked, len(fed))
        for key in fed:
            # every fed peer drinks its unchoke dry and is charged for
            # it — the same spend-on-egress the session does (one tick
            # here is a whole unchoke round; real egress dwarfs the
            # accrual quantum), so the queue rotates instead of
            # freezing on the first winners
            self.econ.charge(key, self.econ.deficit(key))
            if key.startswith("h"):
                self.honest_fed.add(key)
                world.record_ok()
            else:
                self.stampede_unchokes += 1

    def facts(self, world) -> dict:
        return {
            "admitted": len(self.admitted),
            "per_ip_rejected": self.gate.rejected_per_ip,
            "capacity_rejected": self.gate.rejected_capacity,
            "honest_admitted": self.honest_admitted,
            "honest_shed": self.honest_shed,
            "honest_fed": len(self.honest_fed),
            "max_unchoked": self.max_unchoked,
            "stampede_unchokes": self.stampede_unchokes,
            "rounds": self.econ.rounds,
            "optimistic_rotations": self.econ.rotations,
        }

    def failures(self, world) -> list[str]:
        out = []
        if self.max_unchoked > self.slots + 1:
            out.append(
                f"choke round unchoked {self.max_unchoked} peers "
                f"(bound is slots + optimistic = {self.slots + 1})"
            )
        if (
            self.stampede_n > self.per_ip * self.stampede_ips
            and not self.gate.rejected_per_ip
        ):
            out.append(
                f"per-IP clamp never fired against a {self.stampede_n}"
                f"-strong horde on {self.stampede_ips} addresses"
            )
        starved = self.honest_admitted - len(self.honest_fed)
        if starved > 0:
            out.append(
                f"{starved}/{self.honest_admitted} honest leechers were "
                "never unchoked (starved by the horde)"
            )
        if len(self.admitted) > self.slots and not self.econ.rotations:
            out.append("the optimistic unchoke slot never rotated")
        return out


class GhostBehavior(Behavior):
    """Ghost-swarm flood: ``per_tick`` bencoded ``get_peers`` queries
    per flooder per tick, each for a hash nobody has — straight into
    the DHT node's datagram path. The indexer census and its BEP 33
    bloom table must hold their FIFO bounds."""

    kind = "ghost"

    def setup(self, world) -> None:
        self.per_tick = self.group.param("per_tick")
        self.sent = 0

    def step(self, world) -> None:
        for i in range(self.group.count):
            src = (_ip(self.kind, self.gi, i), 7000 + (i % 1000))
            node_id = _h("ghost-node", self.gi, i)
            for k in range(self.per_tick):
                ih = _h("ghost-ih", self.gi, i, world.tick, k)
                world.datagram(
                    bencode({
                        b"t": b"gh", b"y": b"q", b"q": b"get_peers",
                        b"a": {b"id": node_id, b"info_hash": ih},
                    }),
                    src,
                )
                self.sent += 1

    def facts(self, world) -> dict:
        snap = world.indexer.snapshot()
        return {
            "flood_queries": self.sent,
            "indexer_hashes": snap["hashes"],
            "indexer_blooms": snap["blooms"],
            "indexer_unresolved": snap["unresolved"],
        }

    def failures(self, world) -> list[str]:
        snap = world.indexer.snapshot()
        out = []
        if snap["hashes"] > world.indexer.max_hashes:
            out.append(
                f"indexer hash census {snap['hashes']} exceeded its "
                f"bound {world.indexer.max_hashes}"
            )
        if snap["blooms"] > world.indexer.max_hashes:
            out.append(
                f"indexer bloom table {snap['blooms']} exceeded the "
                f"census bound {world.indexer.max_hashes}"
            )
        return out


class ForgeBehavior(Behavior):
    """Token forgers: ``announce_peer`` with an invented token must be
    rejected (KRPC 203) and never reach the tracker feed. Every
    ``valid_every`` ticks each forger also runs the legitimate dance —
    ``get_peers`` for a real token, then a valid announce — proving the
    gate rejects forgeries WITHOUT killing the protocol."""

    kind = "forge"

    def setup(self, world) -> None:
        self.valid_every = self.group.param("valid_every")
        self.forged = 0
        self.rejected = 0
        self.accepted_forgeries = 0
        self.valid_ok = 0

    def step(self, world) -> None:
        for i in range(self.group.count):
            src = (_ip(self.kind, self.gi, i), 8000 + (i % 1000))
            node_id = _h("forge-node", self.gi, i)
            ih = _h("forge-ih", self.gi, i)
            replies = world.datagram(
                bencode({
                    b"t": b"fg", b"y": b"q", b"q": b"announce_peer",
                    b"a": {
                        b"id": node_id, b"info_hash": ih,
                        b"token": b"FORGEDTK", b"port": src[1],
                    },
                }),
                src,
            )
            self.forged += 1
            for msg in replies:
                if msg.get(b"y") == b"e":
                    self.rejected += 1
                elif msg.get(b"y") == b"r":
                    self.accepted_forgeries += 1
                    world.record_forged_accepted()
            if world.tick % self.valid_every == 0:
                token = None
                for msg in world.datagram(
                    bencode({
                        b"t": b"fq", b"y": b"q", b"q": b"get_peers",
                        b"a": {b"id": node_id, b"info_hash": ih},
                    }),
                    src,
                ):
                    r = msg.get(b"r")
                    if isinstance(r, dict) and isinstance(
                        r.get(b"token"), bytes
                    ):
                        token = r[b"token"]
                if token is not None:
                    for msg in world.datagram(
                        bencode({
                            b"t": b"fa", b"y": b"q", b"q": b"announce_peer",
                            b"a": {
                                b"id": node_id, b"info_hash": ih,
                                b"token": token, b"port": src[1],
                                b"seed": 1,
                            },
                        }),
                        src,
                    ):
                        if msg.get(b"y") == b"r":
                            self.valid_ok += 1

    def facts(self, world) -> dict:
        return {
            "forged": self.forged,
            "rejected": self.rejected,
            "accepted_forgeries": self.accepted_forgeries,
            "valid_ok": self.valid_ok,
            "fed_peers": world.indexer.fed_peers,
        }

    def failures(self, world) -> list[str]:
        out = []
        if self.accepted_forgeries:
            out.append(
                f"{self.accepted_forgeries} forged-token announces were "
                f"accepted"
            )
        if self.forged and self.rejected != self.forged:
            out.append(
                f"only {self.rejected}/{self.forged} forged announces "
                f"drew a KRPC error"
            )
        if self.group.count and not self.valid_ok:
            out.append(
                "the valid-token control path never landed an announce"
            )
        if world.indexer.fed_peers != self.valid_ok:
            out.append(
                f"tracker feed saw {world.indexer.fed_peers} peers but "
                f"only {self.valid_ok} valid announces were made"
            )
        return out


class ByzantineBehavior(Behavior):
    """Byzantine receipt publishers against the fabric's Merkle
    receipt plane (``fabric/receipts.py`` — the SAME pure primitives
    the live verify fabric exchanges at ``byzantine_f > 0``). The
    population splits into ``honest_pct`` honest publishers and three
    liar archetypes by index:

    * **forged-root** — claims every piece ok under a root committed
      over invented digests; the auditor's ground-truth root
      recomputation must convict.
    * **equivocation** — commits two DIFFERENT roots for the same unit
      across its two ticks; first-root pinning must convict on the
      second.
    * **under-hash** — hashed only a prefix of the unit but claims all
      of it; the root matches its own lazy leaves, so only proof
      verification against the TRUE leaf catches it.

    Every honest receipt is proof-checked too: a single refuted honest
    receipt (false refutation) fails the run — zero false convictions
    is part of the verdict, exactly like the poison plane."""

    kind = "byzantine"

    def setup(self, world) -> None:
        from torrent_tpu.fabric.receipts import merkle_root

        g = self.group
        self.pieces = g.param("pieces")
        self.honest_pct = g.param("honest_pct")
        self.n_honest = g.count * self.honest_pct // 100
        self.first_root: dict[tuple[int, int], str] = {}
        self.convicted: set[int] = set()
        self.caught: dict[str, int] = {
            "forged-root": 0, "equivocation": 0, "under-hash": 0,
        }
        self.false_refutations = 0
        self.honest_verified = 0
        self.receipts = 0
        self._empty_root = merkle_root([])

    def _mode(self, i: int) -> str:
        if i < self.n_honest:
            return "honest"
        return ("forged-root", "equivocation", "under-hash")[i % 3]

    def _true_digests(self, i: int, unit: int) -> list[str]:
        return [
            _h("byz-digest", self.gi, i, unit, j).hex()
            for j in range(self.pieces)
        ]

    def step(self, world) -> None:
        from torrent_tpu.fabric.receipts import (
            leaf_hash,
            merkle_proof,
            merkle_root,
            verify_proof,
        )

        # each unit spans two ticks: consistent publishers re-commit
        # the same root on the second tick, equivocators switch roots —
        # the only lie that needs history to catch
        unit = world.tick // 2
        second_tick = world.tick % 2 == 1
        for i in range(self.group.count):
            if i in self.convicted:
                continue  # convicted publishers are dropped outright
            mode = self._mode(i)
            true_digests = self._true_digests(i, unit)
            true_leaves = [
                leaf_hash(unit, j, d, True)
                for j, d in enumerate(true_digests)
            ]
            if mode == "forged-root":
                # all-ok claim over invented digests
                lied = [
                    _h("byz-lie", self.gi, i, unit, j).hex()
                    for j in range(self.pieces)
                ]
                leaves = [
                    leaf_hash(unit, j, d, True) for j, d in enumerate(lied)
                ]
            elif mode == "equivocation" and second_tick:
                # same unit, different committed leaf set → new root
                leaves = [
                    leaf_hash(unit, j, _h("byz-equiv", self.gi, i, unit, j).hex(), True)
                    for j in range(self.pieces)
                ]
            elif mode == "under-hash":
                # hashed only the first piece, claims every piece ok:
                # the root is self-consistent over its lazy leaves
                leaves = [true_leaves[0]] + [
                    leaf_hash(unit, j, "", True)
                    for j in range(1, self.pieces)
                ]
            else:  # honest (and the equivocator's innocent first tick)
                leaves = true_leaves
            root = merkle_root(leaves)
            self.receipts += 1
            # ---- the auditor (ground truth in hand) ----
            key = (i, unit)
            pinned = self.first_root.setdefault(key, root)
            if pinned != root:
                self.caught["equivocation"] += 1
                self.convicted.add(i)
                continue
            sample = unit % self.pieces
            proof = merkle_proof(leaves, sample)
            proof_ok = verify_proof(
                true_leaves[sample], sample, len(leaves), proof, root
            )
            true_root = merkle_root(true_leaves)
            if mode == "honest" or (mode == "equivocation" and not second_tick):
                if root == true_root and proof_ok:
                    self.honest_verified += 1
                    world.record_ok()
                else:
                    self.false_refutations += 1
                    world.record_failed()
            elif root != true_root and sample > 0 and not proof_ok:
                # under-hash: root recomputation AND the sampled proof
                # disagree with ground truth (sample 0 is the one piece
                # it really hashed — wait for a later unit's sample)
                self.caught[mode] += 1
                self.convicted.add(i)
            elif mode == "forged-root" and root != true_root:
                self.caught[mode] += 1
                self.convicted.add(i)

    def facts(self, world) -> dict:
        return {
            "receipts": self.receipts,
            "convicted": len(self.convicted),
            "caught_forged_root": self.caught["forged-root"],
            "caught_equivocation": self.caught["equivocation"],
            "caught_under_hash": self.caught["under-hash"],
            "honest_verified": self.honest_verified,
            "false_refutations": self.false_refutations,
        }

    def failures(self, world) -> list[str]:
        out = []
        liars = [
            i for i in range(self.group.count) if self._mode(i) != "honest"
        ]
        free = [i for i in liars if i not in self.convicted]
        if free:
            out.append(
                f"{len(free)}/{len(liars)} byzantine publishers escaped "
                f"conviction (first: {self._mode(free[0])} #{free[0]})"
            )
        if self.false_refutations:
            out.append(
                f"{self.false_refutations} honest receipts were refuted"
            )
        if self.n_honest and not self.honest_verified:
            out.append("no honest receipt ever verified (auditor inert)")
        return out


BEHAVIOR_KINDS: dict[str, type[Behavior]] = {
    cls.kind: cls
    for cls in (
        HonestBehavior, SybilBehavior, PoisonBehavior, ChurnBehavior,
        SlowlorisBehavior, LeecherBehavior, GhostBehavior,
        ForgeBehavior, ByzantineBehavior,
    )
}


def build_behaviors(spec) -> list[Behavior]:
    """One Behavior per spec actor group, in spec order."""
    out = []
    for gi, group in enumerate(spec.actors):
        cls = BEHAVIOR_KINDS.get(group.kind)
        if cls is None:
            raise ValueError(f"no behavior for actor kind {group.kind!r}")
        out.append(cls(group, gi))
    return out
