"""ScenarioSpec — the declarative, replayable form of a hostile swarm.

A scenario is DATA: a seeded actor population plus the SLO objectives
its outcome is judged against, round-trippable through the compact
``key=value`` grammar (the ``FaultPlan.parse`` idiom of
``sched/faults.py``), JSON, and bencode — so a scenario can live in a
library module, a CI flag, or a ``.torrent``-adjacent artifact and
always replay bit-identically from (spec, seed).

Everything on the wire is an INT (bencode has no float type): durations
are milliseconds/seconds, ratios are percent. The only string payload
is the SLO objective spec, validated against ``obs.slo
.parse_objectives`` at construction so a typo'd objective fails at
parse time, never silently as an unarmed SLO.

This module is pure and total: no clocks, no randomness, no IO — it is
in the determinism pass SCOPE (``analysis/passes/determinism.py``) and
every iteration is sorted.
"""

# determinism-scope: module
# (specs must parse/serialize bit-identically across replays)

from __future__ import annotations

import json
from dataclasses import dataclass, replace

from torrent_tpu.obs.slo import parse_objectives

SPEC_VERSION = 1

# kind -> param -> (default, lo, hi); ``count`` is implicit on every
# kind. Behaviors live in scenario/actors.py — this table is the WIRE
# contract (what a spec may say), kept here so spec parsing stays pure.
ACTOR_PARAMS: dict[str, dict[str, tuple[int, int, int]]] = {
    # baseline announcers: the availability denominator. seed_pct of the
    # population announces as seeders; each peer announces every
    # ``interval_ticks`` virtual ticks across ``swarms`` info-hashes.
    "honest": {
        "swarms": (8, 1, 1_000_000),
        "numwant": (30, 0, 1_000_000),
        "seed_pct": (25, 0, 100),
        "interval_ticks": (1, 1, 100_000),
    },
    # Sybil stampede: forged identities, oversized numwant — the
    # tracker's server-side clamps and reservoir sampling must hold.
    "sybil": {
        "swarms": (2, 1, 1_000_000),
        "numwant": (10_000, 0, 10_000_000),
    },
    # piece poisoners: submit payloads that fail digest verification;
    # the sentinel/distrust plane must convict every one of them and
    # nobody else.
    "poison": {
        "swarms": (1, 1, 1_000_000),
        "per_tick": (1, 1, 10_000),
    },
    # churn storm: joins, explicit STOPPED leaves, and silent ghosts
    # that only the TTL sweep may reclaim — occupancy must reconcile
    # exactly at the end.
    "churn": {
        "swarms": (16, 1, 1_000_000),
        "join_pct": (30, 0, 100),
        "stop_pct": (20, 0, 100),
        "ghost_pct": (10, 0, 100),
    },
    # slowloris: hold accept slots open against the session accept
    # gate; honest connections shed at capacity burn availability until
    # idle eviction reclaims the slots.
    "slowloris": {
        "capacity": (32, 1, 1_000_000),
        "hold_ticks": (10, 1, 100_000),
        "idle_ticks": (5, 1, 100_000),
        "honest_conns": (16, 0, 1_000_000),
    },
    # ghost-swarm flood: bencoded get_peers datagrams for random hashes
    # straight into the DHT node; the indexer's census and BEP 33
    # blooms must stay FIFO-bounded.
    "ghost": {
        "per_tick": (64, 1, 1_000_000),
    },
    # token forgers: announce_peer with invented tokens must be
    # rejected (KRPC 203) and never reach the tracker feed; a valid
    # control path (token harvested from a real get_peers reply) must
    # still land.
    "forge": {
        "valid_every": (4, 1, 100_000),
    },
    # leecher stampede against the seeder plane: a shared-IP horde
    # (count - honest_pct% actors spread over ``stampede_ips``
    # addresses, never reciprocating) and an honest crowd (unique IPs,
    # real reciprocation weights) contend for the accept gate's per-IP
    # clamp and the DRR choke economics. The clamp must bound the
    # horde, unchoke slots must stay at ``slots`` + 1 (optimistic), and
    # every admitted honest leecher must be fed before the run ends.
    "leecher": {
        "capacity": (512, 1, 1_000_000),
        "per_ip": (8, 1, 1_000_000),
        "slots": (8, 1, 100_000),
        "honest_pct": (20, 0, 100),
        "stampede_ips": (4, 1, 1_000_000),
        "quantum_kb": (16, 1, 100_000),
    },
    # Byzantine receipt publishers against the verify fabric's Merkle
    # receipt plane (fabric/receipts.py): forged roots, equivocating
    # receipts, and under-hashing workers. The ground-truth auditor
    # must convict every liar (root recomputation, first-root pinning,
    # proof verification) and refute NO honest receipt. honest_pct of
    # the population publishes honest receipts as refutation bait.
    "byzantine": {
        "pieces": (8, 1, 4096),
        "honest_pct": (25, 0, 100),
    },
}

MAX_ACTOR_GROUPS = 64
MAX_TOTAL_POPULATION = 10_000_000
_NAME_CHARS = frozenset("abcdefghijklmnopqrstuvwxyz0123456789-_")


def _int_in(label: str, value, lo: int, hi: int) -> int:
    if isinstance(value, bool) or not isinstance(value, int):
        raise ValueError(f"{label} must be an int, got {value!r}")
    if not lo <= value <= hi:
        raise ValueError(f"{label} must be in [{lo}, {hi}], got {value}")
    return value


@dataclass(frozen=True)
class ActorGroup:
    """One behavior population: ``count`` actors of ``kind`` with the
    kind's int params (sorted tuple of pairs — hashable, order-stable)."""

    kind: str
    count: int
    params: tuple[tuple[str, int], ...] = ()

    def __post_init__(self):
        table = ACTOR_PARAMS.get(self.kind)
        if table is None:
            raise ValueError(
                f"unknown actor kind {self.kind!r} (one of "
                f"{', '.join(sorted(ACTOR_PARAMS))})"
            )
        _int_in(f"actor {self.kind} count", self.count, 1, MAX_TOTAL_POPULATION)
        if not isinstance(self.params, tuple):
            raise ValueError("actor params must be a tuple of (name, value)")
        seen = set()
        for pair in self.params:
            if not (isinstance(pair, tuple) and len(pair) == 2):
                raise ValueError("actor params must be (name, value) pairs")
            pname, pval = pair
            if pname not in table:
                raise ValueError(
                    f"unknown param {pname!r} for actor {self.kind!r} "
                    f"(one of {', '.join(sorted(table))})"
                )
            if pname in seen:
                raise ValueError(f"duplicate param {pname!r} for {self.kind!r}")
            seen.add(pname)
            _, lo, hi = table[pname]
            _int_in(f"actor {self.kind} param {pname}", pval, lo, hi)
        if tuple(sorted(self.params)) != self.params:
            raise ValueError("actor params must be sorted by name")

    def param(self, name: str) -> int:
        """Param value with the registry default filled in."""
        for pname, pval in self.params:
            if pname == name:
                return pval
        return ACTOR_PARAMS[self.kind][name][0]


@dataclass(frozen=True)
class ScenarioSpec:
    """The whole scenario, frozen. ``slo`` is a native
    ``parse_objectives`` spec string (``;``-separated); the compact
    grammar carries it with ``|`` separators so it nests inside one
    ``key=value`` field."""

    name: str
    seed: int
    ticks: int
    actors: tuple[ActorGroup, ...]
    slo: str
    tick_ms: int = 1000
    peer_ttl_s: int = 900
    shards: int = 8
    wall_p99_ms: int = 250
    short_samples: int = 8
    long_samples: int = 32

    def __post_init__(self):
        if not self.name or not set(self.name) <= _NAME_CHARS:
            raise ValueError(
                f"scenario name must be non-empty [a-z0-9_-], got {self.name!r}"
            )
        _int_in("seed", self.seed, 0, 2**32 - 1)
        _int_in("ticks", self.ticks, 1, 1_000_000)
        _int_in("tick_ms", self.tick_ms, 1, 3_600_000)
        _int_in("peer_ttl_s", self.peer_ttl_s, 1, 86_400)
        _int_in("shards", self.shards, 1, 1024)
        _int_in("wall_p99_ms", self.wall_p99_ms, 1, 60_000)
        _int_in("short_samples", self.short_samples, 1, 10_000)
        _int_in("long_samples", self.long_samples, 1, 1_000_000)
        if self.long_samples < self.short_samples:
            raise ValueError("long_samples must be >= short_samples")
        if not isinstance(self.actors, tuple) or not self.actors:
            raise ValueError("a scenario needs at least one actor group")
        if len(self.actors) > MAX_ACTOR_GROUPS:
            raise ValueError(f"at most {MAX_ACTOR_GROUPS} actor groups")
        for group in self.actors:
            if not isinstance(group, ActorGroup):
                raise ValueError("actors must be ActorGroup instances")
        total = sum(g.count for g in self.actors)
        if total > MAX_TOTAL_POPULATION:
            raise ValueError(
                f"total population {total} exceeds {MAX_TOTAL_POPULATION}"
            )
        if not isinstance(self.slo, str) or "|" in self.slo:
            raise ValueError("slo must be a ';'-separated objective spec")
        try:
            if not parse_objectives(self.slo):
                raise ValueError("empty objective spec")
        except ValueError as e:
            raise ValueError(f"bad slo spec {self.slo!r}: {e}") from None

    # ------------------------------------------------------------ derived

    def objectives(self):
        """The armed ``SloObjective`` tuple this scenario is judged by."""
        return parse_objectives(self.slo)

    def population(self) -> int:
        return sum(g.count for g in self.actors)

    def scaled(self, divisor: int, ticks: int | None = None) -> "ScenarioSpec":
        """A reduced-population copy (every count ``max(1, n //
        divisor)``) for tests and CI — same seed, same behaviors, same
        objectives, cheaper world."""
        if divisor < 1:
            raise ValueError("divisor must be >= 1")
        actors = tuple(
            replace(g, count=max(1, g.count // divisor)) for g in self.actors
        )
        return replace(
            self, actors=actors, ticks=ticks if ticks is not None else self.ticks
        )

    # ---------------------------------------------------- compact grammar

    @classmethod
    def parse(cls, text: str) -> "ScenarioSpec":
        """Parse the compact ``;``-separated grammar, e.g.::

            name=sybil-stampede;seed=7;ticks=40;slo=availability=0.999|integrity=on;actor=honest:count=64,numwant=30;actor=sybil:count=512,numwant=10000

        Unknown keys, malformed values, and invalid populations raise
        ``ValueError`` naming the offending part (FaultPlan idiom).
        """
        if not isinstance(text, str):
            raise ValueError("scenario spec must be a string")
        fields: dict[str, int | str] = {}
        actors: list[ActorGroup] = []
        int_keys = (
            "seed", "ticks", "tick_ms", "peer_ttl_s", "shards",
            "wall_p99_ms", "short_samples", "long_samples",
        )
        for part in text.split(";"):
            part = part.strip()
            if not part:
                continue
            key, sep, value = part.partition("=")
            key = key.strip()
            value = value.strip()
            if not sep:
                raise ValueError(f"bad scenario field {part!r}: missing '='")
            if key == "actor":
                actors.append(cls._parse_actor(value))
            elif key == "name":
                fields["name"] = value
            elif key == "slo":
                # '|' stands in for ';' so the objective spec nests
                # inside one field of the outer grammar
                fields["slo"] = value.replace("|", ";")
            elif key in int_keys:
                if key in fields:
                    raise ValueError(f"duplicate scenario field {key!r}")
                try:
                    fields[key] = int(value)
                except ValueError as e:
                    raise ValueError(
                        f"bad scenario {key} value {value!r}: {e}"
                    ) from None
            else:
                raise ValueError(f"unknown scenario field {key!r}")
        for required in ("name", "seed", "ticks", "slo"):
            if required not in fields:
                raise ValueError(f"scenario spec missing {required!r}")
        if not actors:
            raise ValueError("scenario spec declares no actor= groups")
        return cls(actors=tuple(actors), **fields)  # type: ignore[arg-type]

    @staticmethod
    def _parse_actor(value: str) -> ActorGroup:
        kind, sep, rest = value.partition(":")
        kind = kind.strip()
        if not sep:
            raise ValueError(
                f"bad actor {value!r}: want kind:count=N[,param=V...]"
            )
        count: int | None = None
        params: list[tuple[str, int]] = []
        for item in rest.split(","):
            item = item.strip()
            if not item:
                continue
            pname, psep, pval = item.partition("=")
            pname = pname.strip()
            if not psep:
                raise ValueError(f"bad actor param {item!r}: missing '='")
            try:
                ival = int(pval.strip())
            except ValueError as e:
                raise ValueError(
                    f"bad actor {kind} param {pname} value {pval!r}: {e}"
                ) from None
            if pname == "count":
                if count is not None:
                    raise ValueError(f"duplicate count for actor {kind!r}")
                count = ival
            else:
                params.append((pname, ival))
        if count is None:
            raise ValueError(f"actor {kind!r} missing count=")
        return ActorGroup(kind=kind, count=count, params=tuple(sorted(params)))

    def serialize(self) -> str:
        """The compact grammar form; ``parse(serialize()) == self``."""
        parts = [
            f"name={self.name}",
            f"seed={self.seed}",
            f"ticks={self.ticks}",
            f"tick_ms={self.tick_ms}",
            f"peer_ttl_s={self.peer_ttl_s}",
            f"shards={self.shards}",
            f"wall_p99_ms={self.wall_p99_ms}",
            f"short_samples={self.short_samples}",
            f"long_samples={self.long_samples}",
            f"slo={self.slo.replace(';', '|')}",
        ]
        for g in self.actors:
            items = [f"count={g.count}"] + [
                f"{pname}={pval}" for pname, pval in g.params
            ]
            parts.append(f"actor={g.kind}:{','.join(items)}")
        return ";".join(parts)

    # ------------------------------------------------------- dict / json

    def to_dict(self) -> dict:
        return {
            "v": SPEC_VERSION,
            "name": self.name,
            "seed": self.seed,
            "ticks": self.ticks,
            "tick_ms": self.tick_ms,
            "peer_ttl_s": self.peer_ttl_s,
            "shards": self.shards,
            "wall_p99_ms": self.wall_p99_ms,
            "short_samples": self.short_samples,
            "long_samples": self.long_samples,
            "slo": self.slo,
            "actors": [
                {
                    "kind": g.kind,
                    "count": g.count,
                    "params": {pname: pval for pname, pval in g.params},
                }
                for g in self.actors
            ],
        }

    @classmethod
    def from_dict(cls, d) -> "ScenarioSpec":
        if not isinstance(d, dict):
            raise ValueError("scenario dict must be a mapping")
        if d.get("v") != SPEC_VERSION:
            raise ValueError(f"unknown scenario spec version {d.get('v')!r}")
        known = {
            "v", "name", "seed", "ticks", "tick_ms", "peer_ttl_s", "shards",
            "wall_p99_ms", "short_samples", "long_samples", "slo", "actors",
        }
        extra = sorted(set(d) - known)
        if extra:
            raise ValueError(f"unknown scenario keys {extra}")
        raw_actors = d.get("actors")
        if not isinstance(raw_actors, list):
            raise ValueError("scenario actors must be a list")
        actors = []
        for entry in raw_actors:
            if not isinstance(entry, dict):
                raise ValueError("actor entry must be a mapping")
            if sorted(set(entry) - {"kind", "count", "params"}):
                raise ValueError(f"unknown actor keys in {sorted(entry)}")
            raw_params = entry.get("params", {})
            if not isinstance(raw_params, dict):
                raise ValueError("actor params must be a mapping")
            kind = entry.get("kind")
            if not isinstance(kind, str):
                raise ValueError(f"actor kind must be a string, got {kind!r}")
            for pname in raw_params:
                if not isinstance(pname, str):
                    raise ValueError(f"actor param name {pname!r} not a string")
            actors.append(
                ActorGroup(
                    kind=kind,
                    count=entry.get("count"),
                    params=tuple(sorted(raw_params.items())),
                )
            )
        name, slo = d.get("name"), d.get("slo")
        if not isinstance(name, str):
            raise ValueError(f"scenario name must be a string, got {name!r}")
        if not isinstance(slo, str):
            raise ValueError(f"scenario slo must be a string, got {slo!r}")
        kwargs = {}
        for key in (
            "seed", "ticks", "tick_ms", "peer_ttl_s", "shards",
            "wall_p99_ms", "short_samples", "long_samples",
        ):
            if key in d:
                kwargs[key] = d[key]
        return cls(name=name, slo=slo, actors=tuple(actors), **kwargs)

    def to_json(self) -> str:
        return json.dumps(self.to_dict(), sort_keys=True)

    @classmethod
    def from_json(cls, text: str) -> "ScenarioSpec":
        try:
            d = json.loads(text)
        except (TypeError, json.JSONDecodeError) as e:
            raise ValueError(f"bad scenario json: {e}") from None
        return cls.from_dict(d)

    # ----------------------------------------------------------- bencode

    def to_bencode(self) -> bytes:
        from torrent_tpu.codec.bencode import bencode

        return bencode(self.to_dict())

    @classmethod
    def from_bencode(cls, blob: bytes) -> "ScenarioSpec":
        from torrent_tpu.codec.bencode import BencodeError, bdecode

        try:
            decoded = bdecode(blob)
        except BencodeError as e:
            raise ValueError(f"bad scenario bencode: {e}") from None
        return cls.from_dict(_debytes(decoded))


def _debytes(value):
    """bdecode output → the JSON-shaped dict ``from_dict`` validates
    (bytes keys/strings become str; undecodable bytes stay bytes and
    fail the type checks downstream with a clear ValueError)."""
    if isinstance(value, bytes):
        try:
            return value.decode("utf-8")
        except UnicodeDecodeError:
            return value
    if isinstance(value, list):
        return [_debytes(v) for v in value]
    if isinstance(value, dict):
        return {_debytes(k): _debytes(v) for k, v in sorted(value.items())}
    return value
