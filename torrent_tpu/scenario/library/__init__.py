"""The bundled scenario library — named hostile-internet playbooks.

Each entry is ONE compact-grammar string (``ScenarioSpec.parse``), so
the library is greppable, diffable data; behaviors and populations
live in the spec, never in code. ``get(name)`` parses on demand;
``doctor --scenario`` and the CI stage pull from here, and tests run
the same specs at reduced population via ``ScenarioSpec.scaled``.
"""

from __future__ import annotations

from torrent_tpu.scenario.spec import ScenarioSpec

# name -> compact spec. Conventions: every scenario arms integrity
# (one distrust event anywhere is an instant fast burn) on top of its
# own availability target; windows sized to the run so the SLO deltas
# span real traffic.
SCENARIOS: dict[str, str] = {
    # 256 forged identities, fresh peer id every tick, numwant=10000:
    # the server-side clamp and reservoir sampling must bound every
    # reply while honest announces stay inside the latency budget.
    "sybil-stampede": (
        "name=sybil-stampede;seed=7;ticks=24;tick_ms=1000;peer_ttl_s=900;"
        "shards=8;wall_p99_ms=250;short_samples=8;long_samples=24;"
        "slo=availability=0.999|integrity=on;"
        "actor=honest:count=64,numwant=30,swarms=8;"
        "actor=sybil:count=256,numwant=10000,swarms=2"
    ),
    # four poisoners, every submission a digest mismatch: the sentinel
    # must convict all four within its strike budget and convict NOBODY
    # else — the honest population rides along as conviction bait.
    "piece-poison": (
        "name=piece-poison;seed=11;ticks=24;tick_ms=1000;peer_ttl_s=900;"
        "shards=8;wall_p99_ms=250;short_samples=8;long_samples=24;"
        "slo=availability=0.999|integrity=on;"
        "actor=honest:count=32,numwant=30,swarms=4;"
        "actor=poison:count=4,per_tick=1,swarms=1"
    ),
    # 512 peers joining/stopping/ghosting against a 10-second TTL: the
    # end-of-run occupancy reconciliation must balance to the peer —
    # silent ghosts reclaimed by the sweep, polite stops immediately.
    "churn-storm": (
        "name=churn-storm;seed=13;ticks=30;tick_ms=1000;peer_ttl_s=10;"
        "shards=8;wall_p99_ms=250;short_samples=8;long_samples=30;"
        "slo=availability=0.999|integrity=on;"
        "actor=churn:count=512,ghost_pct=5,join_pct=30,stop_pct=20,swarms=32"
    ),
    # 48 connection-holders against a 32-slot accept gate: idle
    # eviction must reclaim the slots each wave; the honest probe
    # connections shed in the window are the availability cost.
    "slowloris": (
        "name=slowloris;seed=17;ticks=36;tick_ms=1000;peer_ttl_s=900;"
        "shards=8;wall_p99_ms=250;short_samples=8;long_samples=32;"
        "slo=availability=0.9|integrity=on;"
        "actor=honest:count=64,numwant=30,swarms=8;"
        "actor=slowloris:count=48,capacity=32,hold_ticks=12,honest_conns=24,idle_ticks=3"
    ),
    # 5120 get_peers queries for hashes nobody has: the indexer census
    # and its BEP 33 bloom table must hold their FIFO bounds instead of
    # growing with the flood.
    "ghost-flood": (
        "name=ghost-flood;seed=19;ticks=20;tick_ms=1000;peer_ttl_s=900;"
        "shards=8;wall_p99_ms=250;short_samples=8;long_samples=20;"
        "slo=availability=0.999|integrity=on;"
        "actor=honest:count=16,numwant=30,swarms=4;"
        "actor=ghost:count=4,per_tick=64"
    ),
    # eight forgers hammering announce_peer with invented tokens: every
    # forgery must draw a KRPC 203 and never reach the tracker feed,
    # while the periodic valid-token control path keeps landing.
    "token-forge": (
        "name=token-forge;seed=23;ticks=24;tick_ms=1000;peer_ttl_s=900;"
        "shards=8;wall_p99_ms=250;short_samples=8;long_samples=24;"
        "slo=availability=0.999|integrity=on;"
        "actor=honest:count=16,numwant=30,swarms=4;"
        "actor=forge:count=8,valid_every=4"
    ),
    # 24 byzantine receipt publishers (a quarter honest bait, the rest
    # forged-root / equivocating / under-hashing liars by turns)
    # against the fabric's Merkle receipt primitives: every liar must
    # be convicted — root recomputation, first-root pinning, sampled
    # proof verification — and NO honest receipt refuted.
    "byzantine-fabric": (
        "name=byzantine-fabric;seed=29;ticks=24;tick_ms=1000;peer_ttl_s=900;"
        "shards=8;wall_p99_ms=250;short_samples=8;long_samples=24;"
        "slo=availability=0.999|integrity=on;"
        "actor=honest:count=32,numwant=30,swarms=4;"
        "actor=byzantine:count=24,pieces=8,honest_pct=25"
    ),
    # a two-thousand-strong leecher crowd against the seeder plane: a
    # 1600-peer horde packed onto 4 shared addresses must be clamped
    # by the per-IP accept limit while the DRR choke economics keeps
    # unchoke slots bounded at slots+1, rotates the optimistic slot,
    # and feeds every admitted honest leecher at least once.
    "leecher-stampede": (
        "name=leecher-stampede;seed=37;ticks=72;tick_ms=1000;peer_ttl_s=900;"
        "shards=8;wall_p99_ms=250;short_samples=8;long_samples=64;"
        "slo=availability=0.999|integrity=on;"
        "actor=honest:count=16,numwant=30,swarms=4;"
        "actor=leecher:count=2000,capacity=512,per_ip=8,slots=16,"
        "honest_pct=20,stampede_ips=4,quantum_kb=16"
    ),
    # the kitchen-sink adversary: sybil stampede + churn storm + piece
    # poisoners in ONE population — defenses must not regress when the
    # attacks overlap (clamps hold, occupancy reconciles, every
    # poisoner convicted, nobody else).
    "mixed-adversary": (
        "name=mixed-adversary;seed=31;ticks=30;tick_ms=1000;peer_ttl_s=10;"
        "shards=8;wall_p99_ms=250;short_samples=8;long_samples=30;"
        "slo=availability=0.999|integrity=on;"
        "actor=honest:count=64,numwant=30,swarms=8;"
        "actor=sybil:count=128,numwant=10000,swarms=2;"
        "actor=churn:count=256,ghost_pct=5,join_pct=30,stop_pct=20,swarms=16;"
        "actor=poison:count=4,per_tick=1,swarms=1"
    ),
}


def names() -> list[str]:
    return sorted(SCENARIOS)


def get(name: str) -> ScenarioSpec:
    """Parse a library scenario by name; ValueError for unknown names
    (listing what exists — the doctor flag surfaces this verbatim)."""
    text = SCENARIOS.get(name)
    if text is None:
        raise ValueError(
            f"unknown scenario {name!r} (one of {', '.join(names())})"
        )
    return ScenarioSpec.parse(text)
