"""The scenario driver: a spec, a world, a verdict.

``run_scenario(spec)`` builds a real serve stack in process — sharded
tracker store, DHT node (driven transportless through its datagram
path), DHT indexer feeding the store, BEP 33 blooms wired into scrape —
and steps the spec's actor population against it on a VIRTUAL timeline:
one tick advances the injected clock by ``tick_ms``, every timestamp
the stack takes routes through that clock, and every random draw
routes through one ``random.Random(spec.seed)``. Same spec + same seed
⇒ bit-identical canonical verdict and timeline, byte for byte.

Two planes, deliberately separated:

* **Deterministic plane** — the timeline ring (``obs.timeline
  .build_sample`` per tick), the SLO evaluation over it, the behavior
  facts and invariant failures, and the occupancy reconciliation. This
  is the replayable artifact; doctor diffs two same-seed runs of it.
* **Wall plane** — real ``perf_counter`` latency of every store
  announce, rendered as its own error-budget statement against the
  spec's ``wall_p99_ms``. Wall numbers vary run to run by nature, so
  they live under the verdict's ``"wall"`` key, which
  ``scenario.verdict.canonical_verdict`` strips before any bit-identity
  comparison.

The engine's own shared state (world counters, the conviction ledger)
sits behind ``analysis.sanitizer.named_lock`` + ``guard_attrs`` like
every other plane — the standing lint and tsan-lite gates cover it.
"""

from __future__ import annotations

import hashlib
import random
import time
from bisect import bisect_left

from torrent_tpu.analysis.sanitizer import guard_attrs, named_lock
from torrent_tpu.codec.bencode import BencodeError, bdecode
from torrent_tpu.net.dht import DHTNode
from torrent_tpu.net.indexer import DhtIndexer
from torrent_tpu.net.types import AnnounceEvent
from torrent_tpu.obs.hist import BUCKET_BOUNDS
from torrent_tpu.obs.slo import evaluate_slo, parse_objectives
from torrent_tpu.obs.timeline import Timeline, build_sample
from torrent_tpu.scenario.actors import build_behaviors
from torrent_tpu.scenario.spec import ScenarioSpec
from torrent_tpu.scenario.verdict import build_verdict
from torrent_tpu.server.shard import ShardedSwarmStore
from torrent_tpu.utils.log import get_logger

log = get_logger("scenario.engine")

CONVICT_STRIKES = 3  # digest failures before the sentinel convicts
WALL_SLO_CHUNKS = 8  # wall-latency samples fed to the wall-plane SLO


class VirtualClock:
    """The injected timeline: ``clock()`` is a plain callable (the
    ``time.monotonic`` drop-in the store/indexer seams take) that only
    moves when the engine says so."""

    def __init__(self, start: float = 0.0):
        self._now = float(start)

    def __call__(self) -> float:
        return self._now

    def advance(self, dt: float) -> None:
        self._now += dt


class World:
    """Everything the behaviors touch, with the engine's shared counters
    behind one leaf lock (the store and DHT node guard themselves)."""

    def __init__(self, spec: ScenarioSpec, store: ShardedSwarmStore,
                 clock: VirtualClock, rng: random.Random):
        self.spec = spec
        self.store = store
        self.clock = clock
        self.rng = rng
        self.tick = 0
        # the server-side reply bound every sybil reply is checked against
        self.clamp_cap = min(store.max_numwant, store.max_reply_bytes // 18)
        # world counters: one cell, one leaf lock — tsan-lite learns the
        # association and flags any unguarded touch
        self._lock = named_lock("scenario.engine._lock")
        self._cells = guard_attrs("scenario.world", "counters")
        self.ok = 0  # availability events (served announces/pieces/conns)
        self.shed = 0  # availability errors: refused connections
        self.failed = 0  # availability errors: failed pieces
        self.poison_rejected = 0
        self.poison_escapes = 0
        self.false_convictions = 0
        self.forged_accepted = 0
        self.strikes: dict[str, int] = {}
        self.convicted: set[str] = set()
        self.scripted_poisoners: set[str] = set()
        # presence ledger: (info_hash, peer_id) -> last announce virtual
        # time; STOPPED removes — the exact-occupancy oracle
        self.presence: dict[tuple[bytes, bytes], float] = {}
        self.wall: list[float] = []  # real seconds per announce (wall plane)
        # transportless DHT: replies are captured, never sent
        self.node = DHTNode(
            node_id=hashlib.sha1(f"scn-node:{spec.name}".encode()).digest(),
            read_only=False,
        )
        self._dht_out: list[tuple[bytes, tuple]] = []
        self.node._sendto = lambda data, addr: self._dht_out.append(
            (data, addr)
        )
        self.indexer = DhtIndexer(self.node, store, clock=clock)
        store.attach_bloom_source(self.indexer.blooms_for)
        # presence must also see DHT-fed peers: wrap the seed seam the
        # indexer drives so the occupancy oracle stays exact
        inner_seed = store.seed_peer

        def seed_peer(info_hash, ip, port, left=0, peer_id=None):
            inner_seed(info_hash, ip, port, left=left, peer_id=peer_id)
            pid = peer_id if peer_id is not None else (
                b"-IX-" + hashlib.sha1(f"{ip}:{port}".encode()).digest()[:16]
            )
            with self._lock:
                self._cells.write("counters")
                self.presence[(info_hash, pid)] = self.clock()

        store.seed_peer = seed_peer

    # ----------------------------------------------------------- announce

    def announce(self, info_hash, peer_id, ip, port, left, event, numwant):
        t0 = time.perf_counter()
        out = self.store.announce(
            info_hash, peer_id, ip, port, left, event, numwant
        )
        self.wall.append(time.perf_counter() - t0)
        with self._lock:
            self._cells.write("counters")
            self.ok += 1
            key = (info_hash, peer_id)
            if event == AnnounceEvent.STOPPED:
                self.presence.pop(key, None)
            else:
                self.presence[key] = self.clock()
        return out

    # ----------------------------------------------------------- sentinel

    def submit_piece(self, key: str, payload: bytes, digest: bytes) -> bool:
        """Digest-verified piece ingestion with strike-based conviction
        — the sentinel/distrust plane in the scenario world. Returns
        whether the piece was accepted."""
        valid = hashlib.sha1(payload).digest() == digest
        with self._lock:
            self._cells.write("counters")
            if key in self.convicted:
                return False  # convicted submitters are dropped outright
            if valid:
                if key in self.scripted_poisoners:
                    # defense-in-depth accounting: a poisoner's piece
                    # passing verification would be an escape
                    self.poison_escapes += 1
                self.ok += 1
                return True
            self.poison_rejected += 1
            self.strikes[key] = self.strikes.get(key, 0) + 1
            if self.strikes[key] >= CONVICT_STRIKES:
                self.convicted.add(key)
                if key not in self.scripted_poisoners:
                    self.false_convictions += 1
            return False

    # ----------------------------------------------------------- counters

    def record_ok(self, n: int = 1) -> None:
        with self._lock:
            self._cells.write("counters")
            self.ok += n

    def record_shed(self, n: int = 1) -> None:
        with self._lock:
            self._cells.write("counters")
            self.shed += n

    def record_failed(self, n: int = 1) -> None:
        with self._lock:
            self._cells.write("counters")
            self.failed += n

    def record_forged_accepted(self, n: int = 1) -> None:
        with self._lock:
            self._cells.write("counters")
            self.forged_accepted += n

    # ---------------------------------------------------------------- dht

    def datagram(self, data: bytes, addr: tuple) -> list[dict]:
        """One raw datagram into the DHT node; returns the decoded
        replies it produced (the captured ``_sendto`` traffic)."""
        del self._dht_out[:]
        self.node._on_datagram(data, addr)
        out = []
        for raw, _to in self._dht_out:
            try:
                msg = bdecode(raw)
            except BencodeError:
                continue
            if isinstance(msg, dict):
                out.append(msg)
        return out

    # ------------------------------------------------------------ samples

    def distrust_count(self) -> int:
        with self._lock:
            self._cells.read("counters")
            return (
                self.poison_escapes
                + self.false_convictions
                + self.forged_accepted
            )

    def sched_snap(self) -> dict:
        with self._lock:
            self._cells.read("counters")
            return {
                "shed_total": self.shed,
                "failed_pieces": self.failed,
                "tenants": {"scenario": {"served_pieces": self.ok}},
            }


def _percentile(values: list[float], q: float) -> float:
    if not values:
        return 0.0
    ordered = sorted(values)
    idx = min(len(ordered) - 1, max(0, int(q * (len(ordered) - 1) + 0.5)))
    return ordered[idx]


def _wall_report(spec: ScenarioSpec, wall: list[float]) -> dict:
    """The wall plane: measured announce latency vs the spec's budget,
    rendered through the SAME SLO machinery as the deterministic plane
    (synthetic cumulative-histogram samples, ``p99_ms=<budget>:request``
    objective) so the outcome is an error-budget statement too."""
    n = len(wall)
    total = sum(wall)
    p99 = _percentile(wall, 0.99)
    # cumulative log2-histogram progression, chunked so the SLO windows
    # have a delta to work with
    counts = [0] * (len(BUCKET_BOUNDS) + 1)
    running_count = 0
    running_sum = 0.0
    samples = [{"t": 0.0, "hist": {"request": {
        "count": 0, "sum": 0.0, "buckets": {}}}}]
    chunk = max(1, n // WALL_SLO_CHUNKS)
    for start in range(0, n, chunk):
        for v in wall[start:start + chunk]:
            counts[bisect_left(BUCKET_BOUNDS, v)] += 1
            running_count += 1
            running_sum += v
        samples.append({
            "t": float(len(samples)),
            "hist": {"request": {
                "count": running_count,
                "sum": running_sum,
                "buckets": {
                    str(i): c for i, c in enumerate(counts) if c
                },
            }},
        })
    objectives = parse_objectives(f"p99_ms={spec.wall_p99_ms}:request")
    slo = evaluate_slo(
        samples, objectives,
        short_samples=len(samples), long_samples=len(samples),
    )
    budget_s = spec.wall_p99_ms / 1e3
    return {
        "announces": n,
        "total_s": round(total, 6),
        "p50_us": round(_percentile(wall, 0.50) * 1e6, 1),
        "p99_us": round(p99 * 1e6, 1),
        "max_us": round(max(wall) * 1e6, 1) if wall else 0.0,
        "announces_per_s": round(n / total, 1) if total > 0 else 0.0,
        "budget_ms": spec.wall_p99_ms,
        "slo": slo,
        "ok": bool(p99 <= budget_s and not slo.get("breach_any")),
    }


def run_scenario(
    spec: ScenarioSpec,
    store: ShardedSwarmStore | None = None,
) -> dict:
    """Run one scenario to its verdict.

    Returns ``{"verdict", "timeline", "wall"}``: the SLO verdict (see
    ``scenario/verdict.py``), the full timeline ring snapshot (the
    ``torrent-tpu replay`` payload), and the wall-plane latency report.

    ``store`` may be a pre-filled :class:`ShardedSwarmStore` — the
    bench rung fills one with a million swarms first — but it MUST have
    been built with a :class:`VirtualClock` and a seeded rng; the
    engine adopts them so the virtual timeline stays coherent.
    """
    if store is None:
        clock = VirtualClock(float(spec.peer_ttl_s) + 1.0)
        rng = random.Random(spec.seed)
        store = ShardedSwarmStore(
            n_shards=spec.shards,
            peer_ttl=float(spec.peer_ttl_s),
            clock=clock,
            rng=rng,
        )
    else:
        clock = store._clock
        rng = store._rng
        if not isinstance(clock, VirtualClock) or not isinstance(
            rng, random.Random
        ):
            raise ValueError(
                "a pre-built scenario store needs clock=VirtualClock(...) "
                "and rng=random.Random(seed)"
            )
    world = World(spec, store, clock, rng)
    behaviors = build_behaviors(spec)
    for b in behaviors:
        b.setup(world)

    timeline = Timeline(depth=spec.ticks + 4)

    def push_sample() -> None:
        snap = store.metrics_snapshot()
        timeline.push(
            build_sample(
                clock(),
                {},
                sched_snap=world.sched_snap(),
                tracker={
                    "announces": snap["announces"],
                    "peers": snap["peers"],
                    "swarms": snap["swarms"],
                },
                distrust=world.distrust_count(),
            )
        )

    push_sample()  # the t0 baseline every window delta starts from
    tick_s = spec.tick_ms / 1e3
    for tick in range(spec.ticks):
        world.tick = tick
        for b in behaviors:
            b.step(world)
        store.sweep_one()
        clock.advance(tick_s)
        push_sample()

    # end of run: full expiry pass, then the exact-occupancy oracle —
    # the tracker's population must equal the presence ledger's fresh
    # entries, no more (ghost leaks) and no less (over-eviction)
    store.sweep()
    cutoff = clock() - store.peer_ttl
    expected = sum(1 for t in world.presence.values() if t >= cutoff)
    snap = store.metrics_snapshot()
    failures: list[str] = []
    if snap["peers"] != expected:
        failures.append(
            f"occupancy reconciliation failed: tracker holds "
            f"{snap['peers']} peers, presence ledger expects {expected}"
        )
    for b in behaviors:
        failures.extend(b.failures(world))

    timeline_snap = timeline.snapshot()
    slo_report = evaluate_slo(
        timeline_snap["samples"],
        spec.objectives(),
        short_samples=spec.short_samples,
        long_samples=spec.long_samples,
    )
    facts = {
        "population": spec.population(),
        "occupancy": {"expected": expected, "actual": snap["peers"]},
        "tracker": {
            "announces": snap["announces"],
            "swarms": snap["swarms"],
            "peers": snap["peers"],
            "evicted": snap["evicted"],
            "indexed": snap["indexed"],
            "numwant_clamped": snap["numwant_clamped"],
            "scrapes": snap["scrapes"],
        },
        "counters": {
            "ok": world.ok,
            "shed": world.shed,
            "failed": world.failed,
            "poison_rejected": world.poison_rejected,
            "poison_escapes": world.poison_escapes,
            "false_convictions": world.false_convictions,
            "forged_accepted": world.forged_accepted,
            "convicted": len(world.convicted),
        },
        "behaviors": {
            f"{b.kind}[{b.gi}]": b.facts(world) for b in behaviors
        },
    }
    verdict = build_verdict(spec, slo_report, facts, failures)
    verdict["wall"] = _wall_report(spec, world.wall)

    # stream into the shared obs plane: announce latency joins the real
    # tracker histogram family, and a failed scenario freezes a flight
    # dump exactly like a production SLO breach would
    if world.wall:
        from torrent_tpu.obs.hist import histograms

        histograms().get(
            "torrent_tpu_tracker_announce_seconds",
            help="Tracker announce handle latency (receive to reply)",
            transport="scenario",
        ).observe_batch(world.wall)
    if not verdict["pass"]:
        from torrent_tpu.obs.recorder import flight_recorder

        flight_recorder().trigger(
            "scenario_fail",
            detail={
                "scenario": spec.name,
                "seed": spec.seed,
                "reasons": verdict["reasons"][:8],
            },
        )
    return {"verdict": verdict, "timeline": timeline_snap, "wall": verdict["wall"]}
