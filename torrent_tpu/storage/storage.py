"""Pluggable storage with multi-file piece→file mapping (ref L5: storage.ts).

``StorageMethod`` is the pluggable byte-range backend (storage.ts:16-26);
``Storage`` maps torrent-global byte offsets onto one or more files by
walking the metainfo file table (storage.ts:89-137 ``findAndDo``) — a piece
may span several files in a multi-file torrent.

New vs the reference (BASELINE requirement): ``read_batch`` — contiguous
multi-piece reads into one preallocated numpy buffer, shaped for the TPU
verify plane ``[n_pieces, piece_length]``. Missing/short files zero-fill
(a zero-filled piece simply fails its SHA1 check, which is exactly the
resume-recheck semantics).

Also fixed vs the reference (SURVEY §8.15): duplicate-block suppression
keys by exact byte offset (not possibly-fractional ``offset/BLOCK_SIZE``)
and the written map can be rebuilt from a verified bitfield on resume.
"""

from __future__ import annotations

import hashlib
import os

from torrent_tpu.analysis.sanitizer import named_lock
from typing import Iterator, Protocol

import numpy as np

from torrent_tpu.codec.metainfo import InfoDict
from torrent_tpu.storage.piece import BLOCK_SIZE, piece_length


class StorageError(Exception):
    pass


class StorageMethod(Protocol):
    """Pluggable backend over ``(path, offset, length)`` (storage.ts:16-26)."""

    def get(self, path: tuple[str, ...], offset: int, length: int) -> bytes:
        """Read exactly ``length`` bytes; raise StorageError on missing/short."""
        ...

    def set(self, path: tuple[str, ...], offset: int, data: bytes) -> None:
        """Write ``data`` at ``offset``, creating the file/dirs as needed."""
        ...

    def exists(self, path: tuple[str, ...], length: int | None = None) -> bool:
        """Whether the file exists (and, if given, is at least ``length`` long)."""
        ...


class Storage:
    """Maps torrent-global offsets onto the metainfo file table."""

    def set_unwanted_files(self, file_indices) -> None:
        """Partfile routing for deselected files: their boundary-piece
        spill goes to a hidden mirror instead of visible stub files.
        No-op on backends without partfile support (MemoryStorage)."""
        setter = getattr(self.method, "set_unwanted", None)
        if setter is None:
            return
        unwanted_idx = set(file_indices)
        paths = set()
        all_paths = []
        for i, (path, _, _) in enumerate(self._files):
            if path is None:
                continue
            all_paths.append(path)
            if i in unwanted_idx:
                paths.add(path)
        setter(paths, all_paths)

    def __init__(self, method: StorageMethod, info: InfoDict):
        self.method = method
        self.info = info
        # (path, global_start, length) per file; single-file torrents store
        # at [name], multi-file at [name, *entry.path] (storage.ts:41-48).
        self._files: list[tuple[tuple[str, ...], int, int]] = []
        # BEP 47 pad files are VIRTUAL zero spans: they occupy piece space
        # (that's their whole purpose) but never touch disk — their table
        # entries carry path=None and get()/set() zero-fill/skip them.
        if info.files is None:
            self._files.append(((info.name,), 0, info.length))
        elif getattr(info, "piece_aligned", False):
            # BEP 52 piece space: every file starts on a piece boundary;
            # the tail gap after a short last piece is virtual (never on
            # disk, never requested — pieces don't span files in v2)
            plen = info.piece_length
            pos = 0
            for entry in info.files:
                self._files.append(((info.name, *entry.path), pos, entry.length))
                pos += -(-entry.length // plen) * plen
        else:
            pos = 0
            for entry in info.files:
                path = (
                    None
                    if getattr(entry, "pad", False)
                    else (info.name, *entry.path)
                )
                self._files.append((path, pos, entry.length))
                pos += entry.length
        # Exact byte offsets of blocks already written (duplicate-write
        # suppression, storage.ts:39,67-87 — fixed per SURVEY §8.15).
        self._written: set[int] = set()
        self._lock = named_lock("storage.written._lock")

    # ------------------------------------------------------------ mapping

    def segments(self, offset: int, length: int) -> Iterator[tuple[tuple[str, ...], int, int]]:
        """Yield ``(path, file_offset, chunk_len)`` covering the range.

        The file-boundary walk equivalent of storage.ts:89-137.
        """
        if offset < 0 or length < 0 or offset + length > self.info.length:
            raise StorageError(
                f"range [{offset}, {offset + length}) outside torrent of {self.info.length} bytes"
            )
        remaining = length
        for path, start, flen in self._files:
            if remaining == 0:
                break
            if flen == 0:
                continue
            end = start + flen
            if end <= offset or start >= offset + length:
                continue
            seg_start = max(offset, start)
            chunk = min(offset + length, end) - seg_start
            yield path, seg_start - start, chunk
            remaining -= chunk

    def contiguous_span(self, offset: int, length: int) -> tuple[tuple[str, ...], int] | None:
        """Resolve ``[offset, offset+length)`` to ``(path, file_offset)``
        when the whole range lives inside ONE real file.

        ``None`` for anything else — pad spans, file boundaries, bad
        ranges — which is the serve plane's signal to take the buffered
        copy path instead of zero-copy egress.
        """
        if length <= 0:
            return None
        try:
            segs = list(self.segments(offset, length))
        except StorageError:
            return None
        if len(segs) != 1:
            return None
        path, foff, chunk = segs[0]
        if path is None or chunk != length:
            return None
        return path, foff

    # ------------------------------------------------------------ get/set

    def get(self, offset: int, length: int) -> bytes:
        out = bytearray()
        for path, foff, chunk in self.segments(offset, length):
            if path is None:  # BEP 47 pad span: zeros by definition
                out += bytes(chunk)
            else:
                out += self.method.get(path, foff, chunk)
        return bytes(out)

    def set(self, offset: int, data: bytes) -> bool:
        """Write a block; returns False if this offset was already written."""
        with self._lock:
            if offset in self._written:
                return False
            self._written.add(offset)
        try:
            pos = 0
            for path, foff, chunk in self.segments(offset, len(data)):
                if path is not None:  # pad spans are never persisted
                    self.method.set(path, foff, data[pos : pos + chunk])
                pos += chunk
        except Exception:
            # A failed write must not poison duplicate suppression — the
            # peer will re-send the block and the retry must go to disk.
            with self._lock:
                self._written.discard(offset)
            raise
        return True

    def exists(self) -> bool:
        """All files present at full length (resume precondition probe)."""
        return all(
            self.method.exists(path, flen)
            for path, _, flen in self._files
            if path is not None  # pads have no on-disk presence to check
        )

    def mark_pieces_written(self, piece_indices) -> None:
        """Rebuild the written map from verified pieces (resume path)."""
        with self._lock:
            for idx in piece_indices:
                plen = piece_length(self.info, idx)
                base = idx * self.info.piece_length
                for boff in range(0, plen, BLOCK_SIZE):
                    self._written.add(base + boff)

    def unmark_piece_written(self, index: int) -> None:
        """Drop duplicate-write suppression for one piece.

        The piece-loss path (BEP 54 self-healing) re-downloads a piece
        whose blocks are already in the written map — without this the
        replacement bytes verify in memory, ``set`` returns False for
        every block, and the disk keeps the corrupt/missing data."""
        with self._lock:
            plen = piece_length(self.info, index)
            base = index * self.info.piece_length
            for boff in range(0, plen, BLOCK_SIZE):
                self._written.discard(base + boff)

    # ------------------------------------------------------------ batch IO

    def read_piece(self, index: int) -> bytes:
        return self.get(index * self.info.piece_length, piece_length(self.info, index))

    def read_batch(
        self,
        indices,
        out: np.ndarray | None = None,
        row_status: np.ndarray | None = None,
        zero_fill: bool = True,
    ) -> tuple[np.ndarray, np.ndarray]:
        """Read pieces ``indices`` into ``[n, piece_length]`` uint8 rows.

        Returns ``(buf, lengths)`` where ``lengths[i]`` is the true byte
        length of piece ``indices[i]`` (short for the final piece; the tail
        of its row is zero). Unreadable ranges zero-fill rather than raise —
        the verify plane turns those into hash mismatches.

        ``row_status``: optional caller-owned ``bool[n]``. When given,
        per-row read success lands there (False = any segment of the row
        was missing, short, or torn) — the zero-copy ingest path uses it
        to turn failed rows into ``nblocks=0`` sentinels instead of
        relying on zero-fill hash mismatches. ``zero_fill=False`` skips
        the upfront memset of a caller-provided ``out`` (rows may then
        hold stale/partial bytes wherever ``row_status`` is False; only
        pass it together with ``row_status``). BEP 47 pad spans are
        always written as zeros explicitly, so dirty reused buffers
        can't corrupt pad-covering pieces.
        """
        indices = list(indices)
        n = len(indices)
        plen_max = self.info.piece_length
        if out is None:
            out = np.zeros((n, plen_max), dtype=np.uint8)
        else:
            if out.shape != (n, plen_max) or out.dtype != np.uint8:
                raise StorageError("read_batch out buffer has wrong shape/dtype")
            if zero_fill:
                out[:] = 0
        if row_status is not None:
            if row_status.shape != (n,) or row_status.dtype != np.bool_:
                raise StorageError("read_batch row_status must be bool[n]")
            row_status[:] = True
        lengths = np.empty(n, dtype=np.int64)
        if self._native_read_batch(indices, out, lengths, row_status):
            return out, lengths
        # pure-Python fallback — the pipeline ledger's "read" boundary for
        # backends without the native pread pool (the native path accounts
        # inside io_engine.read_into; the two never both run for one row)
        from torrent_tpu.obs.ledger import pipeline_ledger

        with pipeline_ledger().track("read") as tracked:
            for row, idx in enumerate(indices):
                plen = piece_length(self.info, idx)
                lengths[row] = plen
                pos = 0
                base = idx * plen_max
                for path, foff, chunk in self.segments(base, plen):
                    if path is None:
                        # pad span: zeros by definition — written
                        # explicitly because a zero_fill=False caller
                        # (reused staging slab) may hand us dirty rows
                        out[row, pos : pos + chunk] = 0
                        pos += chunk
                        continue
                    try:
                        data = self.method.get(path, foff, chunk)
                        out[row, pos : pos + len(data)] = np.frombuffer(
                            data, dtype=np.uint8
                        )
                        tracked.add(len(data))
                    except (StorageError, OSError):
                        # leave zeros; SHA1 mismatch will flag the piece.
                        # OSError too: a file torn mid-recheck can surface a
                        # raw errno from backends that don't wrap, and the
                        # device paths must mark-and-continue like the CPU one
                        if row_status is not None:
                            row_status[row] = False
                    pos += chunk
        return out, lengths

    def _native_read_batch(
        self,
        indices,
        out: np.ndarray,
        lengths: np.ndarray,
        row_status: np.ndarray | None = None,
    ) -> bool:
        """Batch read via the C++ pread pool (native/io_engine.cpp).

        Only for filesystem-backed storage; any unreadable range is left
        zeroed (same semantics as the Python path — SHA1 flags the piece).
        Returns False to fall back when native IO is unavailable. With
        ``row_status`` given, a failed/short/torn segment marks its row
        False instead of raising or zero-rebuilding — the preads land
        directly in the caller's (possibly row-strided) buffer and the
        caller sentinels the failed rows.
        """
        if not isinstance(self.method, FsStorage):
            return False
        if out.strides[1] != 1 or out.strides[0] < out.shape[1]:
            return False  # need row-strided uint8 memory
        try:
            from torrent_tpu.native.io_engine import NativeIOError, get_engine
        except ImportError:
            return False
        engine = get_engine()
        if engine is None:
            return False
        row_stride = out.strides[0]
        paths: list[str] = []
        sizes: list[int] = []
        findex: dict[tuple[str, ...], int | None] = {}
        quads: list[tuple[int, int, int, int]] = []
        quad_rows: list[int] = []  # row owning each quad (status demux)
        for row, idx in enumerate(indices):
            plen = piece_length(self.info, idx)
            lengths[row] = plen
            pos = 0
            for path, foff, chunk in self.segments(idx * self.info.piece_length, plen):
                if path is None:
                    # pad span: zeros by definition — force them, since a
                    # zero_fill=False caller (reused staging slab) hands
                    # us rows that may hold a previous batch's bytes
                    out[row, pos : pos + chunk] = 0
                    pos += chunk
                    continue
                fi = findex.get(path, -1)
                if fi == -1:
                    try:
                        ap = self.method._abspath(path)
                        size = os.stat(ap).st_size
                        fi = len(paths)
                        paths.append(ap)
                        sizes.append(size)
                    except (StorageError, OSError):
                        fi = None  # missing file: whole range stays zero
                    findex[path] = fi
                if fi is not None and sizes[fi] - foff >= chunk:
                    quads.append((fi, foff, row * row_stride + pos, chunk))
                    quad_rows.append(row)
                elif row_status is not None:
                    # missing/short file: the row can never be complete
                    row_status[row] = False
                # else: leave the whole segment zeroed — same all-or-nothing
                # semantics as the Python path's short-read StorageError
                pos += chunk
        extent = (out.shape[0] - 1) * row_stride + out.shape[1] if out.shape[0] else 0
        try:
            if row_status is not None:
                import errno as _errno

                statuses = np.zeros(len(quads), dtype=np.int32)
                rc = engine.read_into(
                    paths, quads, out.ctypes.data, extent,
                    keepalive=out, statuses=statuses,
                )
                if rc != 0 and (statuses == _errno.ENOENT).any():
                    # a file vanished between our stat() and the
                    # engine's open(): tt_io_read_batch fast-fails
                    # WITHOUT submitting any segment, so the zero
                    # statuses of the other rows are meaningless —
                    # re-derive every row on the Python path
                    row_status[:] = True
                    return False
                for q in np.nonzero(statuses)[0]:
                    row_status[quad_rows[int(q)]] = False
            else:
                engine.read_into(paths, quads, out.ctypes.data, extent, keepalive=out)
        except (NativeIOError, ValueError):
            if row_status is None:
                out[:] = 0  # a failed segment can leave partial bytes; the
                return False  # Python fallback rebuilds from a clean buffer
            row_status[:] = True  # the fallback re-derives every row itself
            return False
        return True


# ---------------------------------------------------------------- backends


class FsStorage:
    """Filesystem backend (storage.ts:140-206 ``fsStorage``).

    Keeps an open-handle cache instead of the reference's open/seek/close
    per call — read_batch hits the same files tens of thousands of times.
    """

    PARTS_DIR = ".parts"

    def __init__(self, root: str | os.PathLike):
        self.root = os.fspath(root)
        self._handles: dict[tuple[str, ...], object] = {}
        self._lock = named_lock("storage.fs._lock")
        # deselected files: their boundary-piece spill is routed into a
        # hidden .parts mirror instead of creating visible stub files
        # (the partfile behavior of long-lived clients)
        self._unwanted: set[tuple[str, ...]] = set()
        # idempotent memo (same key always computes the same value, dict
        # setitem is atomic under the GIL): racing writers agree, and
        # taking _lock here would self-deadlock the locked callers
        self._parts_cache: dict[tuple[str, ...], str] = {}  # guarded-by: none

    def set_unwanted(self, paths, all_paths=()) -> None:
        """Route these files' IO into the parts mirror; every WANTED path
        (from ``all_paths``) that has a mirror file is promoted — mirror
        renamed into place — so spilled bytes survive both a selection
        widening and a process restart (the selection is re-applied
        before start, which re-triggers promotion)."""
        new = {tuple(p) for p in paths}
        with self._lock:
            self._unwanted = new
            # drop (don't close) cached handles: a worker thread may be
            # mid-pread on one — clearing lets in-flight readers finish
            # on their own reference while new opens re-route
            self._handles.clear()
        for path in {tuple(p) for p in all_paths} - new:
            self._promote(path)

    def _parts_abspath(self, path: tuple[str, ...]) -> str:
        cached = self._parts_cache.get(path)
        if cached is None:
            tail = path[-1][-40:]
            key = hashlib.sha1("/".join(path).encode("utf-8")).hexdigest()[:16]
            cached = os.path.join(self.root, self.PARTS_DIR, f"{key}_{tail}")
            self._parts_cache[path] = cached
        return cached

    def _promote(self, path: tuple[str, ...]) -> None:
        # under the lock: set() resolves-and-opens under the same lock,
        # so a threaded writer either opens the mirror BEFORE the rename
        # (its fd follows the inode — the write lands in the promoted
        # real file) or resolves the real path after; never a freshly
        # recreated mirror the rename already left behind
        with self._lock:
            parts = self._parts_abspath(path)
            if not os.path.exists(parts):
                return
            real = os.path.join(self.root, *path)
            if os.path.exists(real):
                # both exist (external interference or a pre-seeded
                # file): the real file wins for IO, but spilled bytes
                # are DATA — never delete them; the orphan is inert
                return
            os.makedirs(os.path.dirname(real), exist_ok=True)
            os.replace(parts, real)

    def _abspath(self, path: tuple[str, ...]) -> str:
        for part in path:
            if part in ("", ".", "..") or "/" in part or "\\" in part or "\x00" in part:
                raise StorageError(f"unsafe path component {part!r}")
        real = os.path.join(self.root, *path)
        if path in self._unwanted and not os.path.exists(real):
            # mirror only files with NO real presence: a deselected file
            # that already holds verified data keeps reading/writing in
            # place (no visible-artifact problem — it already exists)
            return self._parts_abspath(path)
        return real

    def _open_read(self, path: tuple[str, ...]):
        with self._lock:
            f = self._handles.get(path)
            if f is None or f.closed:  # type: ignore[union-attr]
                try:
                    f = open(self._abspath(path), "rb")
                except OSError as e:
                    raise StorageError(f"cannot open {path}: {e}") from e
                self._handles[path] = f
            return f

    def open_read_handle(self, path: tuple[str, ...]):
        """The cached read handle, for zero-copy egress (sendfile /
        preadv). The handle is shared with every other reader: callers
        must stick to positional IO (``os.sendfile``/``os.preadv``) and
        never seek or close it."""
        return self._open_read(path)

    def get(self, path: tuple[str, ...], offset: int, length: int) -> bytes:
        f = self._open_read(path)
        try:
            # pread is positional and atomic — no lock needed; the lock
            # only guards the handle cache in _open_read.
            data = os.pread(f.fileno(), length, offset)
        except (OSError, ValueError) as e:
            raise StorageError(f"read failed from {path}: {e}") from e
        if len(data) != length:
            raise StorageError(
                f"short read from {path}: wanted {length} at {offset}, got {len(data)}"
            )
        return data

    def set(self, path: tuple[str, ...], offset: int, data: bytes) -> None:
        try:
            # resolve+open under the lock (see _promote): routing and the
            # rename can't interleave with this open. The pwrite itself
            # runs unlocked — it follows the fd's inode wherever a
            # concurrent promote renamed it.
            with self._lock:
                abspath = self._abspath(path)
                os.makedirs(os.path.dirname(abspath), exist_ok=True)
                # in-place update without truncating (storage.ts:174-196)
                fd = os.open(abspath, os.O_WRONLY | os.O_CREAT, 0o644)
            try:
                os.pwrite(fd, data, offset)
            finally:
                os.close(fd)
        except OSError as e:
            raise StorageError(f"write failed to {path}: {e}") from e

    def exists(self, path: tuple[str, ...], length: int | None = None) -> bool:
        try:
            st = os.stat(self._abspath(path))
        except OSError:
            return False
        return length is None or st.st_size >= length

    def close(self) -> None:
        with self._lock:
            for f in self._handles.values():
                try:
                    f.close()  # type: ignore[union-attr]
                except Exception:
                    pass
            self._handles.clear()


class MemoryStorage:
    """In-memory backend for tests and the tracker-less verify benchmarks.

    The Python analogue of the reference tests' sinon mock StorageMethod
    (storage_test.ts:144-148), but fully functional.
    """

    def __init__(self):
        self.files: dict[tuple[str, ...], bytearray] = {}

    def get(self, path: tuple[str, ...], offset: int, length: int) -> bytes:
        buf = self.files.get(path)
        if buf is None:
            raise StorageError(f"no such file {path}")
        if offset + length > len(buf):
            raise StorageError(f"short read from {path}")
        return bytes(buf[offset : offset + length])

    def set(self, path: tuple[str, ...], offset: int, data: bytes) -> None:
        buf = self.files.setdefault(path, bytearray())
        if len(buf) < offset + len(data):
            buf.extend(b"\x00" * (offset + len(data) - len(buf)))
        buf[offset : offset + len(data)] = data

    def exists(self, path: tuple[str, ...], length: int | None = None) -> bool:
        buf = self.files.get(path)
        if buf is None:
            return False
        return length is None or len(buf) >= length
