"""Piece/block geometry (reference layer L5: piece.ts, 65 LoC).

``BLOCK_SIZE`` is the 16 KiB transfer unit (piece.ts:6). The last piece of
a torrent is short unless the total length divides evenly — the formula at
piece.ts:16-19 gets the ``length % piece_length == 0`` edge right only via
an ``||`` fallback; here it's explicit.
"""

from __future__ import annotations

from torrent_tpu.codec.metainfo import InfoDict

BLOCK_SIZE = 16 * 1024  # piece.ts:6


def piece_length(info: InfoDict, index: int) -> int:
    """Actual byte length of piece ``index`` (last piece may be short).

    v2 session infos (session/v2.py) carry explicit per-piece sizes —
    in BEP 52's file-aligned piece space the LAST PIECE OF EVERY FILE
    may be short, not just the torrent's final piece."""
    if index < 0 or index >= info.num_pieces:
        raise IndexError(f"piece index {index} out of range [0, {info.num_pieces})")
    sizes = getattr(info, "piece_sizes", None)
    if sizes is not None:
        return sizes[index]
    if index < info.num_pieces - 1:
        return info.piece_length
    rem = info.length - info.piece_length * (info.num_pieces - 1)
    return rem


def num_blocks(info: InfoDict, index: int) -> int:
    """Number of 16 KiB transfer blocks in piece ``index``."""
    plen = piece_length(info, index)
    return (plen + BLOCK_SIZE - 1) // BLOCK_SIZE


def block_length(info: InfoDict, index: int, offset: int) -> int:
    """Length of the block at ``offset`` within piece ``index``."""
    plen = piece_length(info, index)
    return min(BLOCK_SIZE, plen - offset)


def validate_requested_block(info: InfoDict, index: int, offset: int, length: int) -> bool:
    """Bounds-check an inbound ``request`` message (piece.ts:21-37).

    Rejects out-of-range piece indices, non-positive or over-sized lengths
    (spec caps requests at BLOCK_SIZE), and ranges past the piece end.
    """
    if index < 0 or index >= info.num_pieces:
        return False
    if length <= 0 or length > BLOCK_SIZE:
        return False
    if offset < 0:
        return False
    return offset + length <= piece_length(info, index)


def validate_received_block(info: InfoDict, index: int, offset: int, length: int) -> bool:
    """Geometry-check an inbound ``piece`` block (piece.ts:39-65).

    A valid block starts on a BLOCK_SIZE boundary and is exactly
    BLOCK_SIZE long, except the final block of a piece which is exactly
    the remainder.
    """
    if index < 0 or index >= info.num_pieces:
        return False
    if offset < 0 or offset % BLOCK_SIZE != 0:
        return False
    plen = piece_length(info, index)
    if offset >= plen:
        return False
    expected = min(BLOCK_SIZE, plen - offset)
    return length == expected
