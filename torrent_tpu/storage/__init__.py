from torrent_tpu.storage.piece import (
    BLOCK_SIZE,
    piece_length,
    validate_requested_block,
    validate_received_block,
)
from torrent_tpu.storage.storage import FsStorage, MemoryStorage, Storage, StorageMethod

__all__ = [
    "BLOCK_SIZE",
    "piece_length",
    "validate_requested_block",
    "validate_received_block",
    "Storage",
    "StorageMethod",
    "FsStorage",
    "MemoryStorage",
]
