"""Hand-tiled Pallas TPU SHA1 kernel — the fast path of the hash plane.

Same contract as ops/sha1_jax.py (``(data_u8[B, padded], nblocks[B]) →
u32[B, 5]``), but laid out for the VPU explicitly:

- Pieces are tiled **1024 per program** and shaped ``(8, 128)`` — every
  schedule word ``w[t]``, every state variable, and every round temp is
  exactly one int32 vector register (8 sublanes × 128 lanes).
- Input is pre-swizzled (one fused XLA pass: bitcast + byteswap +
  transpose) to ``[R, nblk, 16, 8, 128]`` so each grid step's DMA is one
  **contiguous 64 KiB slab** from HBM.
- Grid is ``(R, nblk)`` with the block axis innermost ("arbitrary"
  semantics): the 5-word running state lives in the revisited output
  block in VMEM across the whole chain — initialized at ``k == 0``,
  written back to HBM once per batch tile.
- Ragged batches: per-lane ``k < nblocks`` masks freeze a piece's state
  once its (shorter) chain ends — same semantics as the scan mask in
  sha1_jax.py, no dynamic shapes.

The 80 rounds are Python-unrolled with a 16-register rolling schedule
window: ~21 live vregs, well inside the register file; no VMEM traffic
inside the round loop at all.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from torrent_tpu.ops.sha1_jax import _IV, _K, _bswap32, _rotl
from torrent_tpu.utils.env import env_int

# Pieces per program instance: TILE_SUB sublane-rows × 128 lanes. At the
# default 8 each state/schedule variable is exactly one int32 vreg; larger
# TILE_SUB (16/32) makes every jnp op span multiple vregs, interleaving
# independent SHA1 chains to fill the VPU's ALUs past the single chain's
# serial dependency path (measured: the win on real v5e hardware).
TILE_SUB = env_int("TORRENT_TPU_SHA1_TILE_SUB", 8)
if TILE_SUB % 8 or TILE_SUB > 64:
    raise ValueError(
        f"TORRENT_TPU_SHA1_TILE_SUB={TILE_SUB}: must be a multiple of 8 (the "
        "int32 vreg sublane count) and <= 64 (VMEM block budget)"
    )
TILE_LANE = 128
TILE = TILE_SUB * TILE_LANE
# SHA1 blocks chained per grid step. Each block is only ~640 vector ops on
# a (8, 128) tile — far less than the fixed per-step cost (DMA issue,
# revisited-block bookkeeping), so one-block steps are overhead-bound.
# The kernel runs UNROLL blocks per step via an in-kernel fori_loop (NOT
# Python unrolling — 640 rounds in one basic block sends the backend
# compiler superlinear); 16 keeps the step's DMA at 1 MiB.
UNROLL = env_int("TORRENT_TPU_SHA1_UNROLL", 16)
if UNROLL > 128:
    raise ValueError(
        f"TORRENT_TPU_SHA1_UNROLL={UNROLL}: > 128 blows the per-step VMEM "
        "block (unroll*16 words per lane) with no amortization left to gain"
    )


def _one_block(state, w):
    """One 80-round SHA1 compression. state: 5-tuple of u32 vregs; w: 16 words.

    The 80-word schedule is a 16-entry rolling window so only 16 vectors
    are live at a time. Returns the chained (not yet masked) new state.
    """
    a, b, c, d, e = state
    for t in range(80):
        if t < 16:
            wt = w[t]
        else:
            wt = _rotl(w[(t - 3) % 16] ^ w[(t - 8) % 16] ^ w[(t - 14) % 16] ^ w[t % 16], 1)
            w[t % 16] = wt
        if t < 20:
            f = (b & c) | (jnp.bitwise_not(b) & d)
            kc = _K[0]
        elif t < 40:
            f = b ^ c ^ d
            kc = _K[1]
        elif t < 60:
            f = (b & c) | (b & d) | (c & d)
            kc = _K[2]
        else:
            f = b ^ c ^ d
            kc = _K[3]
        tmp = _rotl(a, 5) + f + e + np.uint32(kc) + wt
        e, d, c, b, a = d, c, _rotl(b, 30), a, tmp
    return (state[0] + a, state[1] + b, state[2] + c, state[3] + d, state[4] + e)


def _sha1_kernel(words_ref, nblocks_ref, state_ref, *, unroll: int):
    """``unroll`` chained SHA1 block steps for a 1024-piece tile.

    words_ref:   u32[1, unroll, 16, 8, 128] — this step's schedule words
    nblocks_ref: i32[1, 8, 128]             — per-piece chain lengths
    state_ref:   u32[1, 5, 8, 128]          — running digest state
                 (revisited across the k grid axis; read once, written once)
    """
    k = pl.program_id(1)

    @pl.when(k == 0)
    def _init():
        for i, v in enumerate(_IV):
            state_ref[0, i] = jnp.full((TILE_SUB, TILE_LANE), v, dtype=jnp.uint32)

    nblocks = nblocks_ref[0]

    def body(j, state):
        # Dynamic index on a leading (untiled) VMEM axis — one 64 KiB slab.
        w = [words_ref[0, j, t] for t in range(16)]
        new = _one_block(state, w)
        keep = k * unroll + j < nblocks
        return tuple(jnp.where(keep, n, o) for n, o in zip(new, state))

    state = tuple(state_ref[0, i] for i in range(5))
    if unroll == 1:
        state = body(0, state)
    else:
        state = jax.lax.fori_loop(0, unroll, body, state)
    for i in range(5):
        state_ref[0, i] = state[i]


def _swizzle(data_u8: jax.Array, r: int, nblk: int) -> jax.Array:
    """u8[R*1024, nblk*64] → u32[R, nblk, 16, 8, 128], big-endian words."""
    quads = data_u8.reshape(r, TILE_SUB, TILE_LANE, nblk, 16, 4)
    words = _bswap32(jax.lax.bitcast_convert_type(quads, jnp.uint32))
    return jnp.transpose(words, (0, 3, 4, 1, 2))


@functools.partial(jax.jit, static_argnames=("interpret",))
def _sha1_pallas_aligned(data_u8, nblocks, interpret):
    b, padded = data_u8.shape
    nblk = padded // 64
    r = b // TILE
    # Short chains (authoring tests, tiny pieces) keep unroll = chain
    # length so no work or trace time is wasted; long chains use the full
    # amortization factor. Static per input shape — no recompiles.
    unroll = min(UNROLL, nblk)
    # Round the chain up to an unroll multiple with zero blocks; they sit
    # beyond every row's nblocks so the masked updates skip them.
    nblk_pad = ((nblk + unroll - 1) // unroll) * unroll
    if nblk_pad != nblk:
        data_u8 = jnp.pad(data_u8, ((0, 0), (0, (nblk_pad - nblk) * 64)))
        nblk = nblk_pad
    words = _swizzle(data_u8, r, nblk)
    nb = nblocks.astype(jnp.int32).reshape(r, TILE_SUB, TILE_LANE)
    state = pl.pallas_call(
        functools.partial(_sha1_kernel, unroll=unroll),
        grid=(r, nblk // unroll),
        in_specs=[
            pl.BlockSpec(
                (1, unroll, 16, TILE_SUB, TILE_LANE),
                lambda i, k: (i, k, 0, 0, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec((1, TILE_SUB, TILE_LANE), lambda i, k: (i, 0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(
            (1, 5, TILE_SUB, TILE_LANE), lambda i, k: (i, 0, 0, 0), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((r, 5, TILE_SUB, TILE_LANE), jnp.uint32),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(words, nb)
    # [R, 5, 8, 128] → [B, 5]
    return jnp.transpose(state, (0, 2, 3, 1)).reshape(b, 5)


def _auto_interpret() -> bool:
    """Run the real Mosaic kernel on TPU-kind devices, interpret elsewhere."""
    d = jax.devices()[0]
    return "tpu" not in d.device_kind.lower() and d.platform not in ("tpu", "axon")


def sha1_pieces_pallas(
    data_u8: jax.Array, nblocks: jax.Array, interpret: bool | None = None
) -> jax.Array:
    """Batched SHA1 via the Pallas kernel; pads the batch to a TILE multiple.

    Rows added by padding get ``nblocks=0`` (their chain never runs) and
    are sliced off the result.
    """
    if interpret is None:
        interpret = _auto_interpret()
    b = data_u8.shape[0]
    bp = ((b + TILE - 1) // TILE) * TILE
    if bp != b:
        data_u8 = jnp.pad(data_u8, ((0, bp - b), (0, 0)))
        nblocks = jnp.pad(nblocks, (0, bp - b))
    out = _sha1_pallas_aligned(data_u8, nblocks, interpret)
    return out[:b]
