"""Hand-tiled Pallas TPU SHA1 kernel — the fast path of the hash plane.

Same contract as ops/sha1_jax.py (``(data[B, ...], nblocks[B]) →
u32[B, 5]``), but laid out for the VPU explicitly:

- Pieces are tiled ``tile_sub × 128`` per program — every schedule word
  ``w[t]``, every state variable, and every round temp is ``tile_sub/8``
  int32 vector registers (8 sublanes × 128 lanes each). Larger tile_sub
  interleaves more independent SHA1 chains per vector op, hiding the
  chain's serial dependency latency; the measured optimum on the real
  v5-lite chip is 32 (tools/tune_sha1.py, 256 KiB pieces, batch 4096:
  8x16 60.5k p/s · 16x16 65.1k · 32x8 67.0k · 32x16 67.1k; 32x32 and
  64-sublane tilings are rejected by the Mosaic compiler).
- Input is pre-swizzled (one fused XLA pass) to
  ``[nblk, 16, tile_sub, 128]`` per tile row so each grid step's DMA is
  one contiguous slab from HBM. The batch is processed **one tile row at
  a time** inside the jit: the swizzle's transpose materializes
  temporaries proportional to the slab, and per-tile slabs keep them
  bounded (a whole-batch swizzle at 4096 × 1 MiB pieces is 4.3 GiB of
  input and >8 GiB of temporaries — an instant HBM OOM).
- Accepts ``uint8[B, padded]`` or ``uint32[B, padded//4]`` (host order)
  input. The u32 form is the fast path: a u8→u32 bitcast lowers through
  a 4×-widened convert fusion on TPU, while u32 input needs only the
  in-place byteswap. Callers can reinterpret their staging buffer with
  ``ndarray.view(np.uint32)`` for free.
- Grid is ``(1, nblk)`` with the block axis innermost ("arbitrary"
  semantics): the 5-word running state lives in the revisited output
  block in VMEM across the whole chain — initialized at ``k == 0``,
  written back to HBM once per tile.
- Ragged batches: per-lane ``k < nblocks`` masks freeze a piece's state
  once its (shorter) chain ends — same semantics as the scan mask in
  sha1_jax.py, no dynamic shapes.

The 80 rounds are Python-unrolled with a 16-register rolling schedule
window: ~21 live vreg values, well inside the register file; no VMEM
traffic inside the round loop at all.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from torrent_tpu.ops.sha1_jax import _IV, _K, _bswap32, _rotl
from torrent_tpu.utils.env import env_bool, env_int

# jax renamed pltpu.TPUCompilerParams -> CompilerParams around 0.5;
# resolve whichever this jax ships so the kernels (and their interpret-
# mode tests) run on both sides of the rename.
_COMPILER_PARAMS_CLS = getattr(pltpu, "CompilerParams", None) or getattr(
    pltpu, "TPUCompilerParams"
)

TILE_LANE = 128
# Default pieces-per-program sublane rows; see the sweep table above.
TILE_SUB = env_int("TORRENT_TPU_SHA1_TILE_SUB", 32)
# SHA1 blocks chained per grid step. Each block is only ~640 vector ops
# per (8, 128) vreg — far less than the fixed per-step cost (DMA issue,
# revisited-block bookkeeping), so one-block steps are overhead-bound.
# The kernel runs UNROLL blocks per step via an in-kernel fori_loop (NOT
# Python unrolling — 640 rounds in one basic block sends the backend
# compiler superlinear).
UNROLL = env_int("TORRENT_TPU_SHA1_UNROLL", 16)
# 2-way round-chain interleave (BASELINE.md roofline's named knob):
# OFF by default — only an on-device A/B (tools/tune_sha1.py) should
# ever turn it on, exactly like the sha256 FULL_UNROLL variant.
INTERLEAVE2 = env_bool("TORRENT_TPU_SHA1_INTERLEAVE2")


def _check_tiling(tile_sub: int, unroll: int) -> None:
    if tile_sub % 8 or tile_sub > 64:
        raise ValueError(
            f"tile_sub={tile_sub}: must be a multiple of 8 (the int32 vreg "
            "sublane count) and <= 64 (VMEM block budget)"
        )
    if unroll > 128:
        raise ValueError(
            f"unroll={unroll}: > 128 blows the per-step VMEM block "
            "(unroll*16 words per lane) with no amortization left to gain"
        )


_check_tiling(TILE_SUB, UNROLL)
TILE = TILE_SUB * TILE_LANE  # default tile (rows per program instance)


def _round_t(t, a, b, c, d, e, w):
    """Round ``t`` of the SHA1 compression on one state tuple; ``w`` is
    the 16-entry rolling schedule window (mutated in place)."""
    if t < 16:
        wt = w[t]
    else:
        wt = _rotl(w[(t - 3) % 16] ^ w[(t - 8) % 16] ^ w[(t - 14) % 16] ^ w[t % 16], 1)
        w[t % 16] = wt
    if t < 20:
        # ch(b,c,d) = (b&c)|(~b&d), 4 ops naively; the mux form needs 3
        f = d ^ (b & (c ^ d))
        kc = _K[0]
    elif t < 40:
        f = b ^ c ^ d
        kc = _K[1]
    elif t < 60:
        # maj(b,c,d) = (b&c)|(b&d)|(c&d), 5 ops naively; 4 via the
        # b^c factoring (identical truth table)
        f = (b & c) | (d & (b ^ c))
        kc = _K[2]
    else:
        f = b ^ c ^ d
        kc = _K[3]
    tmp = _rotl(a, 5) + f + e + np.uint32(kc) + wt
    return tmp, a, _rotl(b, 30), c, d


def _one_block(state, w):
    """One 80-round SHA1 compression. state: 5-tuple of u32 vregs; w: 16 words.

    The 80-word schedule is a 16-entry rolling window so only 16 vectors
    are live at a time. Returns the chained (not yet masked) new state.
    """
    r = state
    for t in range(80):
        r = _round_t(t, *r, w)
    return tuple(s + x for s, x in zip(state, r))


def _one_block_x2(state_a, wa, state_b, wb):
    """One compression over TWO independent half-tiles with their round
    chains interleaved in program order (the roofline's named knob,
    BASELINE.md): each round's rotl→add critical path is ~5 dependent
    op-levels deep, so alternating rounds of two independent chains
    hands the backend a ready instruction from the other chain while one
    chain's adds are in flight. Whether Mosaic's scheduler benefits
    beyond what tile_sub-level vreg independence already gives is
    EMPIRICAL — this variant is opt-in and A/B'd on-chip by
    tools/tune_sha1.py, never a default."""
    ra, rb = state_a, state_b
    for t in range(80):
        ra = _round_t(t, *ra, wa)
        rb = _round_t(t, *rb, wb)
    return (
        tuple(s + x for s, x in zip(state_a, ra)),
        tuple(s + x for s, x in zip(state_b, rb)),
    )


def _sha1_kernel(
    words_ref,
    nblocks_ref,
    state_ref,
    *,
    unroll: int,
    tile_sub: int,
    interleave2: bool = False,
):
    """``unroll`` chained SHA1 block steps for one ``tile_sub*128``-piece tile.

    words_ref:   u32[1, unroll, 16, tile_sub, 128] — this step's schedule words
    nblocks_ref: i32[1, tile_sub, 128]             — per-piece chain lengths
    state_ref:   u32[1, 5, tile_sub, 128]          — running digest state
                 (revisited across the k grid axis; read once, written once)
    ``interleave2``: split the tile's sublanes in half and advance the
    two halves' round chains alternately (see _one_block_x2).
    """
    k = pl.program_id(1)

    @pl.when(k == 0)
    def _init():
        for i, v in enumerate(_IV):
            state_ref[0, i] = jnp.full((tile_sub, TILE_LANE), v, dtype=jnp.uint32)

    nblocks = nblocks_ref[0]
    half = tile_sub // 2

    def body(j, state):
        # Dynamic index on a leading (untiled) VMEM axis — one contiguous slab.
        w = [words_ref[0, j, t] for t in range(16)]
        if interleave2:
            sa = tuple(s[:half] for s in state)
            sb = tuple(s[half:] for s in state)
            na, nb = _one_block_x2(sa, [x[:half] for x in w], sb, [x[half:] for x in w])
            new = tuple(
                jnp.concatenate([x, y], axis=0) for x, y in zip(na, nb)
            )
        else:
            new = _one_block(state, w)
        keep = k * unroll + j < nblocks
        return tuple(jnp.where(keep, n, o) for n, o in zip(new, state))

    state = tuple(state_ref[0, i] for i in range(5))
    if unroll == 1:
        state = body(0, state)
    else:
        state = jax.lax.fori_loop(0, unroll, body, state)
    for i in range(5):
        state_ref[0, i] = state[i]


def _swizzle_tile(tile_words_u32: jax.Array, nblk: int, tile_sub: int) -> jax.Array:
    """Host-order u32[tile, nblk*16] → u32[1, nblk, 16, tile_sub, 128],
    big-endian schedule words, one contiguous slab per chain step."""
    words = _bswap32(tile_words_u32).reshape(1, tile_sub, TILE_LANE, nblk, 16)
    return jnp.transpose(words, (0, 3, 4, 1, 2))


@functools.partial(
    jax.jit, static_argnames=("interpret", "tile_sub", "unroll", "interleave2")
)
def _sha1_pallas_aligned(data, nblocks, interpret, tile_sub, unroll, interleave2=False):
    """Tile-aligned batch → digest words. ``data`` is u8[B, padded] or
    (fast path) u32[B, padded//4]; B must be a ``tile_sub*128`` multiple.

    The batch is processed one tile row per pallas_call so swizzle
    temporaries stay proportional to a single tile, not the batch.
    """
    tile = tile_sub * TILE_LANE
    b = data.shape[0]
    if data.dtype == jnp.uint32:
        data32 = data
    else:
        # compat path: u8 rows are bitcast in 4-byte quads (the widening
        # lowering makes this the slow/memory-hungry form on TPU)
        data32 = jax.lax.bitcast_convert_type(
            data.reshape(b, data.shape[1] // 4, 4), jnp.uint32
        )
    nblk = data32.shape[1] // 16
    # Short chains (authoring tests, tiny pieces) keep unroll = chain
    # length so no work or trace time is wasted; long chains use the full
    # amortization factor. Static per input shape — no recompiles.
    unroll = min(unroll, nblk)
    # Round the chain up to an unroll multiple with zero blocks; they sit
    # beyond every row's nblocks so the masked updates skip them.
    nblk_pad = ((nblk + unroll - 1) // unroll) * unroll
    if nblk_pad != nblk:
        data32 = jnp.pad(data32, ((0, 0), (0, (nblk_pad - nblk) * 16)))
        nblk = nblk_pad
    nb = nblocks.astype(jnp.int32).reshape(b // tile, tile_sub, TILE_LANE)

    call = pl.pallas_call(
        functools.partial(
            _sha1_kernel,
            unroll=unroll,
            tile_sub=tile_sub,
            interleave2=interleave2,
        ),
        grid=(1, nblk // unroll),
        in_specs=[
            pl.BlockSpec(
                (1, unroll, 16, tile_sub, TILE_LANE),
                lambda i, k: (i, k, 0, 0, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, tile_sub, TILE_LANE), lambda i, k: (i, 0, 0), memory_space=pltpu.VMEM
            ),
        ],
        out_specs=pl.BlockSpec(
            (1, 5, tile_sub, TILE_LANE), lambda i, k: (i, 0, 0, 0), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((1, 5, tile_sub, TILE_LANE), jnp.uint32),
        compiler_params=_COMPILER_PARAMS_CLS(
            dimension_semantics=("parallel", "arbitrary"),
        ),
        interpret=interpret,
    )

    states = []
    for r0 in range(0, b, tile):
        words = _swizzle_tile(data32[r0 : r0 + tile], nblk, tile_sub)
        states.append(call(words, nb[r0 // tile : r0 // tile + 1]))
    state = jnp.concatenate(states, axis=0) if len(states) > 1 else states[0]
    # [R, 5, tile_sub, 128] → [B, 5]
    return jnp.transpose(state, (0, 2, 3, 1)).reshape(b, 5)


def _auto_interpret() -> bool:
    """Run the real Mosaic kernel on TPU-kind devices, interpret elsewhere."""
    d = jax.devices()[0]
    return "tpu" not in d.device_kind.lower() and d.platform not in ("tpu", "axon")


def sha1_pieces_pallas(
    data: jax.Array,
    nblocks: jax.Array,
    interpret: bool | None = None,
    tile_sub: int | None = None,
    unroll: int | None = None,
    interleave2: bool | None = None,
) -> jax.Array:
    """Batched SHA1 via the Pallas kernel; pads the batch to a tile multiple.

    ``data`` is ``uint8[B, padded]`` or host-order ``uint32[B, padded//4]``
    (fast path — see module docstring). Rows added by padding get
    ``nblocks=0`` (their chain never runs) and are sliced off the result.
    ``tile_sub``/``unroll`` default to the env-tunable module constants;
    ``interleave2`` (env ``TORRENT_TPU_SHA1_INTERLEAVE2``, default off)
    selects the 2-way round-chain interleave variant — opt-in until an
    on-device A/B says it wins (tools/tune_sha1.py).
    """
    if interpret is None:
        interpret = _auto_interpret()
    ts = TILE_SUB if tile_sub is None else tile_sub
    un = UNROLL if unroll is None else unroll
    il2 = INTERLEAVE2 if interleave2 is None else interleave2
    _check_tiling(ts, un)
    if il2 and (ts < 16 or (ts // 2) % 8):
        raise ValueError(
            f"interleave2 needs tile_sub >= 16 with 8-sublane halves, got {ts}"
        )
    tile = ts * TILE_LANE
    b = data.shape[0]
    bp = ((b + tile - 1) // tile) * tile
    if bp != b:
        data = jnp.pad(data, ((0, bp - b), (0, 0)))
        nblocks = jnp.pad(nblocks, (0, bp - b))
    out = _sha1_pallas_aligned(data, nblocks, interpret, ts, un, il2)
    return out[:b]
