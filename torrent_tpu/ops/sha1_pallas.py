"""Hand-tiled Pallas TPU SHA1 kernel — the fast path of the hash plane.

Same contract as ops/sha1_jax.py (``(data_u8[B, padded], nblocks[B]) →
u32[B, 5]``), but laid out for the VPU explicitly:

- Pieces are tiled **1024 per program** and shaped ``(8, 128)`` — every
  schedule word ``w[t]``, every state variable, and every round temp is
  exactly one int32 vector register (8 sublanes × 128 lanes).
- Input is pre-swizzled (one fused XLA pass: bitcast + byteswap +
  transpose) to ``[R, nblk, 16, 8, 128]`` so each grid step's DMA is one
  **contiguous 64 KiB slab** from HBM.
- Grid is ``(R, nblk)`` with the block axis innermost ("arbitrary"
  semantics): the 5-word running state lives in the revisited output
  block in VMEM across the whole chain — initialized at ``k == 0``,
  written back to HBM once per batch tile.
- Ragged batches: per-lane ``k < nblocks`` masks freeze a piece's state
  once its (shorter) chain ends — same semantics as the scan mask in
  sha1_jax.py, no dynamic shapes.

The 80 rounds are Python-unrolled with a 16-register rolling schedule
window: ~21 live vregs, well inside the register file; no VMEM traffic
inside the round loop at all.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from torrent_tpu.ops.sha1_jax import _IV, _K, _bswap32, _rotl

# Pieces per program instance: one (8, 128) int32 vreg worth of lanes.
TILE_SUB = 8
TILE_LANE = 128
TILE = TILE_SUB * TILE_LANE  # 1024


def _sha1_kernel(words_ref, nblocks_ref, state_ref):
    """One SHA1 block step for a 1024-piece tile.

    words_ref:   u32[1, 1, 16, 8, 128] — this block's 16 schedule words
    nblocks_ref: i32[1, 8, 128]        — per-piece chain lengths
    state_ref:   u32[1, 5, 8, 128]     — running digest state (revisited
                                          across the k grid axis)
    """
    k = pl.program_id(1)

    @pl.when(k == 0)
    def _init():
        for i, v in enumerate(_IV):
            state_ref[0, i] = jnp.full((TILE_SUB, TILE_LANE), v, dtype=jnp.uint32)

    h0 = state_ref[0, 0]
    h1 = state_ref[0, 1]
    h2 = state_ref[0, 2]
    h3 = state_ref[0, 3]
    h4 = state_ref[0, 4]

    a, b, c, d, e = h0, h1, h2, h3, h4
    w = [words_ref[0, 0, t] for t in range(16)]
    for t in range(80):
        if t < 16:
            wt = w[t]
        else:
            wt = _rotl(w[(t - 3) % 16] ^ w[(t - 8) % 16] ^ w[(t - 14) % 16] ^ w[t % 16], 1)
            w[t % 16] = wt
        if t < 20:
            f = (b & c) | (jnp.bitwise_not(b) & d)
            kc = _K[0]
        elif t < 40:
            f = b ^ c ^ d
            kc = _K[1]
        elif t < 60:
            f = (b & c) | (b & d) | (c & d)
            kc = _K[2]
        else:
            f = b ^ c ^ d
            kc = _K[3]
        tmp = _rotl(a, 5) + f + e + np.uint32(kc) + wt
        e, d, c, b, a = d, c, _rotl(b, 30), a, tmp

    keep = k < nblocks_ref[0]
    state_ref[0, 0] = jnp.where(keep, h0 + a, h0)
    state_ref[0, 1] = jnp.where(keep, h1 + b, h1)
    state_ref[0, 2] = jnp.where(keep, h2 + c, h2)
    state_ref[0, 3] = jnp.where(keep, h3 + d, h3)
    state_ref[0, 4] = jnp.where(keep, h4 + e, h4)


def _swizzle(data_u8: jax.Array, r: int, nblk: int) -> jax.Array:
    """u8[R*1024, nblk*64] → u32[R, nblk, 16, 8, 128], big-endian words."""
    quads = data_u8.reshape(r, TILE_SUB, TILE_LANE, nblk, 16, 4)
    words = _bswap32(jax.lax.bitcast_convert_type(quads, jnp.uint32))
    return jnp.transpose(words, (0, 3, 4, 1, 2))


@functools.partial(jax.jit, static_argnames=("interpret",))
def _sha1_pallas_aligned(data_u8, nblocks, interpret):
    b, padded = data_u8.shape
    nblk = padded // 64
    r = b // TILE
    words = _swizzle(data_u8, r, nblk)
    nb = nblocks.astype(jnp.int32).reshape(r, TILE_SUB, TILE_LANE)
    state = pl.pallas_call(
        _sha1_kernel,
        grid=(r, nblk),
        in_specs=[
            pl.BlockSpec(
                (1, 1, 16, TILE_SUB, TILE_LANE),
                lambda i, k: (i, k, 0, 0, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec((1, TILE_SUB, TILE_LANE), lambda i, k: (i, 0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(
            (1, 5, TILE_SUB, TILE_LANE), lambda i, k: (i, 0, 0, 0), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((r, 5, TILE_SUB, TILE_LANE), jnp.uint32),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(words, nb)
    # [R, 5, 8, 128] → [B, 5]
    return jnp.transpose(state, (0, 2, 3, 1)).reshape(b, 5)


def _auto_interpret() -> bool:
    """Run the real Mosaic kernel on TPU-kind devices, interpret elsewhere."""
    d = jax.devices()[0]
    return "tpu" not in d.device_kind.lower() and d.platform not in ("tpu", "axon")


def sha1_pieces_pallas(
    data_u8: jax.Array, nblocks: jax.Array, interpret: bool | None = None
) -> jax.Array:
    """Batched SHA1 via the Pallas kernel; pads the batch to a TILE multiple.

    Rows added by padding get ``nblocks=0`` (their chain never runs) and
    are sliced off the result.
    """
    if interpret is None:
        interpret = _auto_interpret()
    b = data_u8.shape[0]
    bp = ((b + TILE - 1) // TILE) * TILE
    if bp != b:
        data_u8 = jnp.pad(data_u8, ((0, bp - b), (0, 0)))
        nblocks = jnp.pad(nblocks, (0, bp - b))
    out = _sha1_pallas_aligned(data_u8, nblocks, interpret)
    return out[:b]
