"""Host-side SHA1 message padding/packing for batched TPU hashing.

SHA1 (FIPS 180-4) processes 64-byte blocks; a message of ``n`` bytes is
padded with ``0x80``, zeros, then the 64-bit big-endian bit length, to a
multiple of 64. For a batch of pieces (equal-capacity rows, possibly
ragged true lengths — the last piece of a torrent is short) we pad every
row in place with vectorized numpy and hand the device one dense
``uint8[B, padded_len]`` plus an ``int32[B]`` block count; the kernels mask
the chain per-row beyond its own block count, keeping all shapes static
(XLA requirement — no data-dependent shapes on device).

This replaces the reference's per-piece ``crypto.subtle.digest`` calls
(tools/make_torrent.ts:28-32, metainfo.ts:141-143) with one batched launch.
"""

from __future__ import annotations

import numpy as np


def padded_len_for(piece_len: int) -> int:
    """Padded byte length for messages of up to ``piece_len`` bytes.

    The SHA minimum is ``((len + 8) // 64 + 1) * 64`` — at least one byte
    of 0x80 marker plus the 8-byte length field beyond the message. On
    top of that the row is rounded up to a 128-byte multiple: a device
    batch ``u8[B, padded_len]`` whose minor dim isn't lane-aligned (128)
    forces XLA into padded relayouts — at 512 KiB pieces the AOT compiler
    materializes a 32x-padded copy and dies with a 16 GiB allocation.
    Rows never exceed ``num_blocks_for`` blocks on device: the ghost tail
    block sits beyond every row's block count and is masked off by both
    the scan and Pallas kernels.
    """
    n = ((piece_len + 8) // 64 + 1) * 64
    return (n + 127) // 128 * 128


def num_blocks_for(length) -> np.ndarray:
    """Per-message SHA1 block count (works on scalars or arrays)."""
    return (np.asarray(length, dtype=np.int64) + 8) // 64 + 1


def alloc_padded(n: int, piece_len: int) -> tuple[np.ndarray, np.ndarray]:
    """Allocate a zeroed padded batch buffer and its data-region view.

    Returns ``(padded, data_view)`` where ``padded`` is
    ``uint8[n, padded_len]`` and ``data_view = padded[:, :piece_len]`` —
    ``Storage.read_batch`` can fill the view directly, avoiding a copy.
    """
    padded = np.zeros((n, padded_len_for(piece_len)), dtype=np.uint8)
    return padded, padded[:, :piece_len]


def pad_in_place(padded: np.ndarray, lengths: np.ndarray) -> np.ndarray:
    """Write SHA1 padding into ``padded`` rows; returns int32 block counts.

    ``padded[i, :lengths[i]]`` must hold the message and everything after
    it must be zero (alloc_padded guarantees this; for reused buffers the
    caller zeroes tails). Fully vectorized — O(B) fancy-indexed stores, no
    per-piece Python loop.
    """
    lengths = np.asarray(lengths, dtype=np.int64)
    b, padded_len = padded.shape
    if lengths.shape != (b,):
        raise ValueError("lengths must be [B]")
    if np.any(lengths < 0) or np.any((lengths + 8) // 64 * 64 + 64 > padded_len):
        raise ValueError("length too large for padded buffer")
    rows = np.arange(b)
    padded[rows, lengths] = 0x80
    nblocks = num_blocks_for(lengths)
    base = nblocks * 64 - 8  # offset of the 64-bit bit-length field
    bitlen = (lengths.astype(np.uint64)) * 8
    for k in range(8):
        padded[rows, base + k] = ((bitlen >> np.uint64(56 - 8 * k)) & np.uint64(0xFF)).astype(
            np.uint8
        )
    return nblocks.astype(np.int32)


def pad_pieces(pieces: list[bytes]) -> tuple[np.ndarray, np.ndarray]:
    """Pack a ragged list of byte strings into a padded batch.

    Convenience path for authoring/tests; the verify plane uses
    ``alloc_padded`` + ``Storage.read_batch`` + ``pad_in_place`` to avoid
    the extra copies.
    """
    if not pieces:
        return np.zeros((0, 64), dtype=np.uint8), np.zeros(0, dtype=np.int32)
    max_len = max(len(p) for p in pieces)
    padded, view = alloc_padded(len(pieces), max_len)
    lengths = np.array([len(p) for p in pieces], dtype=np.int64)
    for i, p in enumerate(pieces):
        view[i, : len(p)] = np.frombuffer(p, dtype=np.uint8)
    nblocks = pad_in_place(padded, lengths)
    return padded, nblocks


def digests_to_words(digests: list[bytes] | tuple[bytes, ...], words: int = 5) -> np.ndarray:
    """Fixed-width digests → ``uint32[B, words]`` big-endian words.

    ``words=5`` is SHA1 (20-byte digests), ``words=8`` SHA-256. The
    expected-hash side of on-device comparison: ``info.pieces`` uploaded
    once per torrent.
    """
    arr = np.frombuffer(b"".join(digests), dtype=">u4").reshape(len(digests), words)
    return arr.astype(np.uint32)


def words_to_digests(words: np.ndarray) -> list[bytes]:
    """``uint32[B, W]`` state words → digests (width follows the array)."""
    be = np.asarray(words, dtype=np.uint32).astype(">u4")
    return [be[i].tobytes() for i in range(be.shape[0])]
