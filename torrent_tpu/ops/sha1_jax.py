"""Batched SHA1 in pure JAX — the portable device path of the hash plane.

Replaces the reference's per-piece WebCrypto ``crypto.subtle.digest``
(tools/make_torrent.ts:29, metainfo.ts:142) with one XLA program hashing
thousands of pieces at once:

- **Batch axis = pieces** (the reference's only data parallelism, its
  ``Promise.all`` over digests, tools/make_torrent.ts:111 — here it's the
  vectorized lane dimension of the VPU).
- **Serial axis = the SHA1 block chain** within a piece, expressed as
  ``lax.scan`` over ``[nblk]`` — compiled once regardless of chain length.
- **Ragged batches** (short final piece) are handled with a per-row block
  count and masked state updates: all shapes static, no recompiles.

Data is uploaded as raw ``uint8[B, padded]`` and byte-swizzled to
big-endian u32 on device (bitcast + shifts — free relative to HBM reads),
then transposed to ``[nblk, 16, B]`` so each scan step streams one
contiguous slab and each schedule word ``w[t]`` is a contiguous ``[B]``
vector filling VPU lanes.

The TPU-optimized Pallas variant with identical semantics lives in
``ops/sha1_pallas.py``; both satisfy ``make_sha1_fn``'s contract.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

# FIPS 180-4 constants.
_IV = (0x67452301, 0xEFCDAB89, 0x98BADCFE, 0x10325476, 0xC3D2E1F0)
_K = (0x5A827999, 0x6ED9EBA1, 0x8F1BBCDC, 0xCA62C1D6)


def _rotl(x: jax.Array, n: int) -> jax.Array:
    return (x << np.uint32(n)) | (x >> np.uint32(32 - n))


def _bswap32(x: jax.Array) -> jax.Array:
    """Little-endian u32 (from bitcast of LE byte quads) → big-endian value."""
    return (
        ((x & np.uint32(0x000000FF)) << np.uint32(24))
        | ((x & np.uint32(0x0000FF00)) << np.uint32(8))
        | ((x >> np.uint32(8)) & np.uint32(0x0000FF00))
        | (x >> np.uint32(24))
    )


def _compress(state, w16):
    """One SHA1 compression: state 5×[B], w16 list of 16 [B] u32 vectors.

    80 rounds unrolled in Python (static trace); the 80-word schedule is a
    16-entry rolling window so only 16 [B] vectors are live at a time.
    """
    a, b, c, d, e = state
    w = list(w16)
    for t in range(80):
        if t < 16:
            wt = w[t]
        else:
            wt = _rotl(w[(t - 3) % 16] ^ w[(t - 8) % 16] ^ w[(t - 14) % 16] ^ w[t % 16], 1)
            w[t % 16] = wt
        if t < 20:
            f = d ^ (b & (c ^ d))  # ch, mux form: 3 ops vs 4
            k = _K[0]
        elif t < 40:
            f = b ^ c ^ d
            k = _K[1]
        elif t < 60:
            f = (b & c) | (d & (b ^ c))  # maj via b^c factoring: 4 ops vs 5
            k = _K[2]
        else:
            f = b ^ c ^ d
            k = _K[3]
        tmp = _rotl(a, 5) + f + e + np.uint32(k) + wt
        e, d, c, b, a = d, c, _rotl(b, 30), a, tmp
    return (
        state[0] + a,
        state[1] + b,
        state[2] + c,
        state[3] + d,
        state[4] + e,
    )


def bytes_to_schedule(data_u8: jax.Array) -> jax.Array:
    """``uint8[B, padded]`` → ``uint32[nblk, 16, B]`` big-endian schedule."""
    b, padded = data_u8.shape
    nblk = padded // 64
    quads = data_u8.reshape(b, nblk * 16, 4)
    words = jax.lax.bitcast_convert_type(quads, jnp.uint32)  # LE quads
    words = _bswap32(words)
    # [B, nblk, 16] → [nblk, 16, B]: one transpose so every scan step and
    # every schedule word is a contiguous [B] slab in HBM/VMEM.
    return jnp.transpose(words.reshape(b, nblk, 16), (1, 2, 0))


def sha1_chain(schedule: jax.Array, nblocks: jax.Array) -> jax.Array:
    """Run the masked block chain. schedule u32[nblk,16,B], nblocks i32[B].

    Returns digests as ``uint32[B, 5]`` big-endian state words.
    """
    nblk, _, b = schedule.shape
    init = tuple(jnp.full((b,), v, dtype=jnp.uint32) for v in _IV)

    def step(carry, xs):
        state, t = carry
        block = xs  # u32[16, B]
        w16 = [block[i] for i in range(16)]
        new = _compress(state, w16)
        keep = t < nblocks  # bool[B]
        state = tuple(jnp.where(keep, n, o) for n, o in zip(new, state))
        return (state, t + 1), None

    (final, _), _ = jax.lax.scan(step, (init, jnp.int32(0)), schedule)
    return jnp.stack(final, axis=1)  # [B, 5]


@functools.partial(jax.jit, static_argnames=())
def sha1_pieces_jax(data_u8: jax.Array, nblocks: jax.Array) -> jax.Array:
    """Batched SHA1: ``uint8[B, padded]``, ``int32[B]`` → ``uint32[B, 5]``."""
    return sha1_chain(bytes_to_schedule(data_u8), nblocks)


def make_sha1_fn(backend: str = "jax"):
    """Return a jittable ``(data_u8[B, padded], nblocks[B]) -> u32[B, 5]``.

    ``backend``: ``"jax"`` (this module, runs anywhere XLA does) or
    ``"pallas"`` (hand-tiled TPU kernel, ops/sha1_pallas.py).
    """
    if backend == "jax":
        return sha1_pieces_jax
    if backend == "pallas":
        try:
            from torrent_tpu.ops.sha1_pallas import sha1_pieces_pallas
        except ImportError as e:
            raise NotImplementedError(
                "pallas sha1 backend not available in this build"
            ) from e
        return sha1_pieces_pallas
    raise ValueError(f"unknown sha1 backend {backend!r}")
