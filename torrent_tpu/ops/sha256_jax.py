"""Batched SHA-256 in pure JAX — the v2 (BEP 52) side of the hash plane.

BitTorrent v2 replaces SHA1 piece hashes with SHA-256 merkle trees over
16 KiB leaf blocks (BEP 52; the reference predates v2 entirely — this is
beyond-parity surface). The shapes are even friendlier to the TPU than
v1's: leaves are uniform 16 KiB messages (8-block chains), and the merkle
reduction above them is batched SHA-256 over 64-byte pair messages — both
pure batch problems.

Same contract family as ``ops/sha1_jax.py``:
``(data_u8[B, padded], nblocks[B]) → u32[B, 8]``; padding/packing is the
identical FIPS 180-4 64-byte-block scheme, so ``ops/padding.py`` is
shared verbatim between the two hash planes.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from torrent_tpu.ops.sha1_jax import _bswap32

# FIPS 180-4 §5.3.3 / §4.2.2 constants.
_IV256 = (
    0x6A09E667, 0xBB67AE85, 0x3C6EF372, 0xA54FF53A,
    0x510E527F, 0x9B05688C, 0x1F83D9AB, 0x5BE0CD19,
)
_K256 = (
    0x428A2F98, 0x71374491, 0xB5C0FBCF, 0xE9B5DBA5, 0x3956C25B, 0x59F111F1,
    0x923F82A4, 0xAB1C5ED5, 0xD807AA98, 0x12835B01, 0x243185BE, 0x550C7DC3,
    0x72BE5D74, 0x80DEB1FE, 0x9BDC06A7, 0xC19BF174, 0xE49B69C1, 0xEFBE4786,
    0x0FC19DC6, 0x240CA1CC, 0x2DE92C6F, 0x4A7484AA, 0x5CB0A9DC, 0x76F988DA,
    0x983E5152, 0xA831C66D, 0xB00327C8, 0xBF597FC7, 0xC6E00BF3, 0xD5A79147,
    0x06CA6351, 0x14292967, 0x27B70A85, 0x2E1B2138, 0x4D2C6DFC, 0x53380D13,
    0x650A7354, 0x766A0ABB, 0x81C2C92E, 0x92722C85, 0xA2BFE8A1, 0xA81A664B,
    0xC24B8B70, 0xC76C51A3, 0xD192E819, 0xD6990624, 0xF40E3585, 0x106AA070,
    0x19A4C116, 0x1E376C08, 0x2748774C, 0x34B0BCB5, 0x391C0CB3, 0x4ED8AA4A,
    0x5B9CCA4F, 0x682E6FF3, 0x748F82EE, 0x78A5636F, 0x84C87814, 0x8CC70208,
    0x90BEFFFA, 0xA4506CEB, 0xBEF9A3F7, 0xC67178F2,
)


def _rotr(x: jax.Array, n: int) -> jax.Array:
    return (x >> np.uint32(n)) | (x << np.uint32(32 - n))


def _round(vars8, wt, kc):
    """One SHA-256 round on the 8 working variables."""
    a, b, c, d, e, f, g, h = vars8
    big_s1 = _rotr(e, 6) ^ _rotr(e, 11) ^ _rotr(e, 25)
    ch = g ^ (e & (f ^ g))  # mux form: 3 ops vs 4
    temp1 = h + big_s1 + ch + kc + wt
    big_s0 = _rotr(a, 2) ^ _rotr(a, 13) ^ _rotr(a, 22)
    maj = (a & b) | (c & (a ^ b))  # identical truth table, 4 ops vs 5
    return (temp1 + big_s0 + maj, a, b, c, d + temp1, e, f, g)


def _schedule_step(w, i):
    """Next schedule word for round ``16g + i`` (g ≥ 1): window indices are
    static functions of the in-group position ``i``."""
    w15 = w[(i + 1) % 16]
    w2 = w[(i + 14) % 16]
    s0 = _rotr(w15, 7) ^ _rotr(w15, 18) ^ (w15 >> np.uint32(3))
    s1 = _rotr(w2, 17) ^ _rotr(w2, 19) ^ (w2 >> np.uint32(10))
    return w[i] + s0 + w[(i + 9) % 16] + s1


def _compress256(state, w16):
    """One SHA-256 compression: state 8-tuple, w16 list of 16 u32 tensors.

    Structured as a 16-round prologue (schedule = message words) plus a
    ``lax.scan`` over the remaining three 16-round groups — within a
    group every rolling-window index is static. A fully unrolled 64-round
    graph both sends XLA's compile superlinear inside an outer block scan
    AND trips an algebraic-simplifier circular-rewrite loop on the CPU
    backend (observed: "stuck in a circular simplification loop"); the
    scan form compiles in seconds everywhere. The Pallas kernel
    (ops/sha256_pallas.py) keeps its full unroll — Mosaic has no such
    pathology and the VPU wants the straight-line rounds.
    """
    vars8 = state
    for t in range(16):
        vars8 = _round(vars8, w16[t], np.uint32(_K256[t]))

    k_groups = jnp.asarray(np.array(_K256[16:], dtype=np.uint32).reshape(3, 16))

    def group(carry, k16):
        vars8, w = carry
        w = list(w)
        for i in range(16):
            wt = _schedule_step(w, i)
            w[i] = wt
            vars8 = _round(vars8, wt, k16[i])
        return (vars8, tuple(w)), None

    (new, _), _ = jax.lax.scan(group, (vars8, tuple(w16)), k_groups)
    return tuple(s + n for s, n in zip(state, new))


def bytes_to_schedule256(data_u8: jax.Array) -> jax.Array:
    """``uint8[B, padded]`` → ``uint32[nblk, 16, B]`` big-endian schedule.

    Identical packing to SHA1 (both are big-endian 64-byte-block Merkle-
    Damgård); kept separate for call-site clarity.
    """
    b, padded = data_u8.shape
    nblk = padded // 64
    quads = data_u8.reshape(b, nblk * 16, 4)
    words = _bswap32(jax.lax.bitcast_convert_type(quads, jnp.uint32))
    return jnp.transpose(words.reshape(b, nblk, 16), (1, 2, 0))


def sha256_chain(schedule: jax.Array, nblocks: jax.Array) -> jax.Array:
    """Masked block chain → ``uint32[B, 8]`` digest words."""
    nblk, _, b = schedule.shape
    init = tuple(jnp.full((b,), v, dtype=jnp.uint32) for v in _IV256)

    def step(carry, block):
        state, t = carry
        new = _compress256(state, [block[i] for i in range(16)])
        keep = t < nblocks
        state = tuple(jnp.where(keep, n, o) for n, o in zip(new, state))
        return (state, t + 1), None

    (final, _), _ = jax.lax.scan(step, (init, jnp.int32(0)), schedule)
    return jnp.stack(final, axis=1)


@functools.partial(jax.jit, static_argnames=())
def sha256_pieces_jax(data_u8: jax.Array, nblocks: jax.Array) -> jax.Array:
    """Batched SHA-256: ``uint8[B, padded]``, ``int32[B]`` → ``uint32[B, 8]``."""
    return sha256_chain(bytes_to_schedule256(data_u8), nblocks)


def make_sha256_fn(backend: str = "jax"):
    """Jittable ``(data_u8[B, padded], nblocks[B]) -> u32[B, 8]`` factory."""
    if backend == "jax":
        return sha256_pieces_jax
    if backend == "pallas":
        try:
            from torrent_tpu.ops.sha256_pallas import sha256_pieces_pallas

            return sha256_pieces_pallas
        except ImportError as e:  # pragma: no cover - env without pallas
            raise RuntimeError(f"pallas backend unavailable: {e}") from e
    raise ValueError(f"unknown sha256 backend {backend!r}")
