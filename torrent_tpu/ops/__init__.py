from torrent_tpu.ops.padding import (
    padded_len_for,
    alloc_padded,
    pad_in_place,
    pad_pieces,
    digests_to_words,
    words_to_digests,
)
from torrent_tpu.ops.sha1_jax import sha1_pieces_jax, make_sha1_fn

__all__ = [
    "padded_len_for",
    "alloc_padded",
    "pad_in_place",
    "pad_pieces",
    "digests_to_words",
    "words_to_digests",
    "sha1_pieces_jax",
    "make_sha1_fn",
]
