"""Hand-tiled Pallas TPU SHA-256 kernel — the v2 fast path.

Identical structure to ``ops/sha1_pallas.py`` (see that module for the
layout rationale): pieces tiled ``tile_sub × 128`` per program, input
pre-swizzled to ``[1, nblk, 16, sub, 128]`` slabs, one pallas_call per
tile row (bounded swizzle temporaries), grid ``(1, nblk/unroll)`` with
the chain axis "arbitrary" and the running 8-word state living in the
revisited output block. Only the compression differs: 64 rounds of
FIPS 180-4 SHA-256 with a 16-entry rolling schedule window.

BEP 52 workloads hit this kernel with two shapes: 16 KiB leaf blocks
(nblk=9 with padding block) and 64-byte merkle pair messages (nblk=2) —
both short chains, so ``unroll`` folds to the chain length and every
piece is one grid step. Like the SHA1 kernel it accepts ``uint8`` or
host-order ``uint32`` input (u32 avoids the 4×-widened bitcast fusion).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from torrent_tpu.ops.sha1_pallas import (
    TILE_LANE,
    TILE_SUB as _SHA1_TILE_SUB,
    UNROLL as _SHA1_UNROLL,
    _COMPILER_PARAMS_CLS,
    _check_tiling,
    _swizzle_tile,
)
from torrent_tpu.ops.sha256_jax import _IV256, _K256, _round, _schedule_step
from torrent_tpu.utils.env import env_bool, env_int

# SHA-256's sweet spot need not match SHA-1's (different rounds/registers
# per block and the leaf plane's 16 KiB rows vs 256 KiB pieces) — own
# knobs, defaulting to the SHA-1 tuning until tools/tune_sha256 says
# otherwise on the real chip.
TILE_SUB = env_int("TORRENT_TPU_SHA256_TILE_SUB", _SHA1_TILE_SUB)
UNROLL = env_int("TORRENT_TPU_SHA256_UNROLL", _SHA1_UNROLL)
# Straight-line 64-round body (the SHA-1 kernel's shape) instead of the
# fori_loop-over-groups one. OFF by default: the unrolled graph hangs
# the XLA *CPU* compiler's algebraic simplifier (measured: >300 s, the
# documented circular-rewrite trap), so it cannot run — or be validated
# — in interpret mode; Mosaic compiles through a different pipeline
# where straight-line code is exactly what the SHA-1 kernel already
# ships. tools/tune_sha256 A/B-tests it on the real chip (golden-checked
# there); interpret mode always falls back to the loop body.
FULL_UNROLL = env_bool("TORRENT_TPU_SHA256_FULL_UNROLL")
# 2-way round-chain interleave — same roofline knob as the SHA-1
# kernel's (see ops/sha1_pallas.py _one_block_x2 / BASELINE.md): split
# the tile's sublanes in half, alternate the halves' rounds in program
# order. OFF by default; tools/tune_sha256 A/Bs it on-chip. Composes
# with FULL_UNROLL (straight-line alternation) and with the loop body
# (interpret-safe alternation inside the group fori_loop).
INTERLEAVE2 = env_bool("TORRENT_TPU_SHA256_INTERLEAVE2")
_check_tiling(TILE_SUB, UNROLL)  # bad env knobs fail at import, not mid-bench
if INTERLEAVE2 and (TILE_SUB < 16 or (TILE_SUB // 2) % 8):
    raise ValueError(
        "TORRENT_TPU_SHA256_INTERLEAVE2 needs TILE_SUB >= 16 with "
        f"8-sublane halves, got {TILE_SUB}"
    )

# Sub-tile launch granule: the smallest legal tile is 8 sublanes × 128
# lanes, so any launch stages a multiple of 1024 rows. Row-bucketed
# padding (below) rounds a live batch up to this granule instead of the
# configured TILE_SUB tile (default 32 → 4096 rows) — a 300-row partial
# flush pads to 1024 sentinel rows, not 4096.
SUB_TILE_ROWS = 8 * TILE_LANE


def pad_rows_for(n_rows: int) -> int:
    """Rows a pallas launch of ``n_rows`` live pieces actually stages:
    the nearest ``SUB_TILE_ROWS`` multiple at or above the batch (the
    sentinel rows carry ``nblocks=0`` and their chains never run)."""
    if n_rows <= 0:
        return SUB_TILE_ROWS
    return -(-n_rows // SUB_TILE_ROWS) * SUB_TILE_ROWS


def tile_sub_for_rows(padded_rows: int, cap: int | None = None) -> int:
    """Largest legal ``tile_sub`` that tiles ``padded_rows`` exactly.

    ``padded_rows`` must be a ``SUB_TILE_ROWS`` multiple (see
    :func:`pad_rows_for`). The cap defaults to the env-tuned TILE_SUB:
    full-target launches keep the sweep's fastest tiling, sub-tile
    launches drop to whatever multiple-of-8 sublane count divides the
    bucketed row count (8 for 1024 rows, 16 for 2048, 24 for 3072, …).
    """
    cap = TILE_SUB if cap is None else cap
    subs = padded_rows // TILE_LANE
    if padded_rows % SUB_TILE_ROWS:
        raise ValueError(f"padded_rows={padded_rows} is not a {SUB_TILE_ROWS} multiple")
    best = 8
    for cand in range(8, min(cap, 64) + 1, 8):
        if subs % cand == 0:
            best = cand
    return best


def _one_block256(state, w, kc_ref):
    """One 64-round SHA-256 compression on vreg-shaped u32 tensors.

    16-round prologue + ``fori_loop`` over the three schedule groups
    (static window indices within a group; the 48 tail K constants come
    from ``kc_ref`` in SMEM, row-indexed by the loop variable) — the same
    shape as the jax backend's ``_compress256``, and for the same reason:
    a fully unrolled 64-round graph trips XLA's algebraic-simplifier
    circular-rewrite loop in interpret mode.
    """
    vars8 = state
    for t in range(16):
        vars8 = _round(vars8, w[t], np.uint32(_K256[t]))

    def group(g, carry):
        vars8, w = carry
        w = list(w)
        for i in range(16):
            wt = _schedule_step(w, i)
            w[i] = wt
            vars8 = _round(vars8, wt, kc_ref[g, i])
        return (vars8, tuple(w))

    new, _ = jax.lax.fori_loop(0, 3, group, (vars8, tuple(w)))
    return tuple(s + n for s, n in zip(state, new))


def _one_block256_unrolled(state, w):
    """Straight-line 64-round compression with immediate K constants —
    no loop-carried 24-vreg tuple, no SMEM K loads, full cross-round
    scheduling freedom for Mosaic. NEVER reached under interpret (see
    FULL_UNROLL above)."""
    vars8 = state
    for t in range(64):
        if t < 16:
            wt = w[t]
        else:
            wt = _schedule_step(w, t % 16)
            w[t % 16] = wt
        vars8 = _round(vars8, wt, np.uint32(_K256[t]))
    return tuple(s + n for s, n in zip(state, vars8))


def _one_block256_x2(state_a, wa, state_b, wb, kc_ref):
    """Loop-body compression over TWO independent half-tiles, rounds
    alternated in program order (interpret-safe: same fori_loop-over-
    groups shape as _one_block256, carrying both halves)."""
    va, vb = state_a, state_b
    for t in range(16):
        va = _round(va, wa[t], np.uint32(_K256[t]))
        vb = _round(vb, wb[t], np.uint32(_K256[t]))

    def group(g, carry):
        va, wa, vb, wb = carry
        wa, wb = list(wa), list(wb)
        for i in range(16):
            wta = _schedule_step(wa, i)
            wa[i] = wta
            va = _round(va, wta, kc_ref[g, i])
            wtb = _schedule_step(wb, i)
            wb[i] = wtb
            vb = _round(vb, wtb, kc_ref[g, i])
        return (va, tuple(wa), vb, tuple(wb))

    va, _, vb, _ = jax.lax.fori_loop(
        0, 3, group, (va, tuple(wa), vb, tuple(wb))
    )
    return (
        tuple(s + n for s, n in zip(state_a, va)),
        tuple(s + n for s, n in zip(state_b, vb)),
    )


def _one_block256_x2_unrolled(state_a, wa, state_b, wb):
    """Straight-line alternation of two half-tiles' 64-round chains —
    FULL_UNROLL's scheduling freedom plus explicit cross-chain
    independence. NEVER reached under interpret (same XLA-CPU
    simplifier trap as _one_block256_unrolled)."""
    va, vb = state_a, state_b
    for t in range(64):
        if t < 16:
            wta, wtb = wa[t], wb[t]
        else:
            wta = _schedule_step(wa, t % 16)
            wa[t % 16] = wta
            wtb = _schedule_step(wb, t % 16)
            wb[t % 16] = wtb
        va = _round(va, wta, np.uint32(_K256[t]))
        vb = _round(vb, wtb, np.uint32(_K256[t]))
    return (
        tuple(s + n for s, n in zip(state_a, va)),
        tuple(s + n for s, n in zip(state_b, vb)),
    )


def _sha256_kernel(
    words_ref,
    nblocks_ref,
    kc_ref,
    state_ref,
    *,
    unroll: int,
    tile_sub: int,
    full: bool,
    interleave2: bool = False,
):
    k = pl.program_id(1)

    @pl.when(k == 0)
    def _init():
        for i, v in enumerate(_IV256):
            state_ref[0, i] = jnp.full((tile_sub, TILE_LANE), v, dtype=jnp.uint32)

    nblocks = nblocks_ref[0]
    half = tile_sub // 2

    def body(j, state):
        w = [words_ref[0, j, t] for t in range(16)]
        if interleave2:
            sa = tuple(s[:half] for s in state)
            sb = tuple(s[half:] for s in state)
            wa = [x[:half] for x in w]
            wb = [x[half:] for x in w]
            if full:
                na, nb = _one_block256_x2_unrolled(sa, wa, sb, wb)
            else:
                na, nb = _one_block256_x2(sa, wa, sb, wb, kc_ref)
            new = tuple(
                jnp.concatenate([x, y], axis=0) for x, y in zip(na, nb)
            )
        elif full:
            new = _one_block256_unrolled(state, w)
        else:
            new = _one_block256(state, w, kc_ref)
        keep = k * unroll + j < nblocks
        return tuple(jnp.where(keep, n, o) for n, o in zip(new, state))

    state = tuple(state_ref[0, i] for i in range(8))
    if unroll == 1:
        state = body(0, state)
    else:
        state = jax.lax.fori_loop(0, unroll, body, state)
    for i in range(8):
        state_ref[0, i] = state[i]


@functools.partial(
    jax.jit,
    static_argnames=("interpret", "tile_sub", "unroll", "full_unroll", "interleave2"),
)
def _sha256_pallas_aligned(
    data, nblocks, interpret, tile_sub, unroll, full_unroll, interleave2=False
):
    tile = tile_sub * TILE_LANE
    b = data.shape[0]
    if data.dtype == jnp.uint32:
        data32 = data
    else:
        data32 = jax.lax.bitcast_convert_type(
            data.reshape(b, data.shape[1] // 4, 4), jnp.uint32
        )
    nblk = data32.shape[1] // 16
    unroll = min(unroll, nblk)
    nblk_pad = ((nblk + unroll - 1) // unroll) * unroll
    if nblk_pad != nblk:
        data32 = jnp.pad(data32, ((0, 0), (0, (nblk_pad - nblk) * 16)))
        nblk = nblk_pad
    nb = nblocks.astype(jnp.int32).reshape(b // tile, tile_sub, TILE_LANE)
    kc = jnp.asarray(np.array(_K256[16:], dtype=np.uint32).reshape(3, 16))

    call = pl.pallas_call(
        functools.partial(
            _sha256_kernel,
            unroll=unroll,
            tile_sub=tile_sub,
            # interpret lowers through XLA CPU, whose simplifier hangs on
            # the straight-line body — the loop body is mandatory there
            full=bool(full_unroll) and not interpret,
            interleave2=interleave2,
        ),
        grid=(1, nblk // unroll),
        in_specs=[
            pl.BlockSpec(
                (1, unroll, 16, tile_sub, TILE_LANE),
                lambda i, k: (i, k, 0, 0, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, tile_sub, TILE_LANE), lambda i, k: (i, 0, 0), memory_space=pltpu.VMEM
            ),
            pl.BlockSpec((3, 16), lambda i, k: (0, 0), memory_space=pltpu.SMEM),
        ],
        out_specs=pl.BlockSpec(
            (1, 8, tile_sub, TILE_LANE), lambda i, k: (i, 0, 0, 0), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((1, 8, tile_sub, TILE_LANE), jnp.uint32),
        compiler_params=_COMPILER_PARAMS_CLS(
            dimension_semantics=("parallel", "arbitrary"),
        ),
        interpret=interpret,
    )

    states = []
    for r0 in range(0, b, tile):
        words = _swizzle_tile(data32[r0 : r0 + tile], nblk, tile_sub)
        states.append(call(words, nb[r0 // tile : r0 // tile + 1], kc))
    state = jnp.concatenate(states, axis=0) if len(states) > 1 else states[0]
    return jnp.transpose(state, (0, 2, 3, 1)).reshape(b, 8)


def sha256_pieces_pallas(
    data: jax.Array,
    nblocks: jax.Array,
    interpret: bool | None = None,
    tile_sub: int | None = None,
    unroll: int | None = None,
    full_unroll: bool | None = None,
    interleave2: bool | None = None,
) -> jax.Array:
    """Batched SHA-256 via Pallas; pads the batch to a tile multiple.

    ``interleave2`` (env ``TORRENT_TPU_SHA256_INTERLEAVE2``, default
    off) alternates two half-tiles' round chains — see the SHA-1
    kernel's variant; composes with ``full_unroll``."""
    from torrent_tpu.ops.sha1_pallas import _auto_interpret

    if interpret is None:
        interpret = _auto_interpret()
    ts = TILE_SUB if tile_sub is None else tile_sub
    un = UNROLL if unroll is None else unroll
    fu = FULL_UNROLL if full_unroll is None else full_unroll
    il2 = INTERLEAVE2 if interleave2 is None else interleave2
    _check_tiling(ts, un)
    if il2 and (ts < 16 or (ts // 2) % 8):
        raise ValueError(
            f"interleave2 needs tile_sub >= 16 with 8-sublane halves, got {ts}"
        )
    tile = ts * TILE_LANE
    b = data.shape[0]
    bp = ((b + tile - 1) // tile) * tile
    if bp != b:
        data = jnp.pad(data, ((0, bp - b), (0, 0)))
        nblocks = jnp.pad(nblocks, (0, bp - b))
    out = _sha256_pallas_aligned(data, nblocks, interpret, ts, un, fu, il2)
    return out[:b]
