"""Tracker server: HTTP + UDP listeners muxed into one request stream
(ref L3b: server/tracker.ts, 654 LoC).

``TrackerServer`` async-iterates parsed, validated announce/scrape request
objects from both listeners (the reference muxes with MuxAsyncIterator,
server/tracker.ts:599-613; here both listeners feed one asyncio.Queue).
Each request object carries its own ``respond``/``reject`` — policy lives
in the consumer (e.g. server/in_memory.py), transport here.

HTTP side (server/tracker.ts:439-485): raw %-escape parsing of binary
query params *before* any URL-decoding mangles them (parseParams,
server/tracker.ts:328-359), ``X-Forwarded-For`` honored, param
validation, optional info-hash allowlist, compact & full announce bodies.
A ``/stats`` route returns live counters (the reference routes it but
never implemented it, server/tracker.ts:477-479).

UDP side (server/tracker.ts:487-597): connect-magic check, random 8-byte
connection ids expired after 2 min, announce/scrape packet parsing.
Deliberate fix vs the reference (SURVEY §8.13): a request that fails
validation gets an error reply and is **dropped** — the reference sent
the error but then fell through and yielded the request anyway.
"""

from __future__ import annotations

import asyncio
import random
import time
from dataclasses import dataclass, field

from torrent_tpu.codec.bencode import bencode
from torrent_tpu.net.constants import (
    DEFAULT_ANNOUNCE_INTERVAL,
    DEFAULT_NUM_WANT,
    UDP_CONNECT_MAGIC,
)

MAX_NUM_WANT = 500  # bounds compact responses well under one UDP datagram
from torrent_tpu.net.types import (
    UDP_CODE_EVENT,
    AnnounceEvent,
    UdpTrackerAction,
)
from torrent_tpu.utils.bytesio import decode_binary_data, read_int, write_int

UDP_CONNECTION_TTL = 120  # seconds (server/tracker.ts:516)


# ============================================================== requests


@dataclass
class AnnounceRequest:
    """A validated announce, transport-agnostic (server/tracker.ts:33-60)."""

    info_hash: bytes
    peer_id: bytes
    ip: str
    port: int
    uploaded: int
    downloaded: int
    left: int
    event: AnnounceEvent
    num_want: int
    compact: bool = True
    key: bytes | None = None

    async def respond(self, interval: int, complete: int, incomplete: int, peers):
        raise NotImplementedError

    async def reject(self, reason: str):
        raise NotImplementedError


@dataclass
class ScrapeRequest:
    """A scrape for zero or more info hashes (server/tracker.ts:225-232)."""

    info_hashes: list[bytes]

    async def respond(self, files):
        """files: iterable of (info_hash, complete, downloaded, incomplete)."""
        raise NotImplementedError

    async def reject(self, reason: str):
        raise NotImplementedError


# ------------------------------------------------------------------ HTTP


def _pack_peers_compact(peers) -> bytes:
    """BEP 23 compact peers via the shared v4 packer: IPv6 peers ride
    peers6 instead, port-0 (firewalled) announces are never packed (every
    receiver's decoder drops them anyway), v4-mapped text normalizes."""
    from torrent_tpu.net.types import pack_compact_v4

    return pack_compact_v4((p.ip, p.port) for p in peers)


def _pack_peers_compact6(peers) -> bytes:
    """BEP 7 ``peers6`` via the shared compact-v6 codec (net/types.py)."""
    from torrent_tpu.net.types import pack_compact_v6

    return pack_compact_v6((p.ip, p.port) for p in peers)


@dataclass
class HttpAnnounceRequest(AnnounceRequest):
    _writer: asyncio.StreamWriter | None = None

    async def respond(self, interval: int, complete: int, incomplete: int, peers):
        """Compact or full bencoded body (server/tracker.ts:98-138)."""
        if self.compact:
            peers_val: object = _pack_peers_compact(peers)
        else:
            peers_val = [
                {
                    b"ip": p.ip.encode(),
                    b"port": p.port,
                    **({b"peer id": p.peer_id} if p.peer_id else {}),
                }
                for p in peers
            ]
        reply = {
            b"interval": interval,
            b"complete": complete,
            b"incomplete": incomplete,
            b"peers": peers_val,
        }
        if self.compact:
            peers6 = _pack_peers_compact6(peers)
            if peers6:
                reply[b"peers6"] = peers6  # BEP 7
        body = bencode(reply)
        await _http_reply(self._writer, 200, body)

    async def reject(self, reason: str):
        # bencoded `failure reason` with HTTP 200, per convention
        # (server/_helpers.ts:9-18).
        await _http_reply(self._writer, 200, bencode({b"failure reason": reason}))


@dataclass
class HttpScrapeRequest(ScrapeRequest):
    _writer: asyncio.StreamWriter | None = None

    async def respond(self, files):
        body = bencode(
            {
                b"files": {
                    h: {b"complete": c, b"downloaded": d, b"incomplete": i}
                    for h, c, d, i in files
                }
            }
        )
        await _http_reply(self._writer, 200, body)

    async def reject(self, reason: str):
        await _http_reply(self._writer, 200, bencode({b"failure reason": reason}))


async def _http_reply(
    writer: asyncio.StreamWriter,
    status: int,
    body: bytes,
    content_type: str = "text/plain",
):
    if writer is None or writer.is_closing():
        return
    head = (
        f"HTTP/1.1 {status} OK\r\nContent-Type: {content_type}\r\n"
        f"Content-Length: {len(body)}\r\nConnection: close\r\n\r\n"
    )
    try:
        writer.write(head.encode("latin-1") + body)
        await writer.drain()
    except (ConnectionError, OSError):
        pass
    finally:
        writer.close()


def _parse_query_raw(query: str) -> dict[str, list[bytes]]:
    """Binary-safe query parsing (server/tracker.ts:328-359).

    Splits on & and = *before* %-decoding so 20-byte info hashes survive;
    repeated keys accumulate (scrape takes many info_hash params).
    """
    params: dict[str, list[bytes]] = {}
    if not query:
        return params
    for part in query.split("&"):
        if not part:
            continue
        key, _, value = part.partition("=")
        try:
            params.setdefault(key, []).append(decode_binary_data(value))
        except ValueError:
            continue  # bad escape: drop the param, validation will catch it
    return params


def _validate_announce_params(params: dict[str, list[bytes]], peer_ip: str):
    """→ dict of fields or an error string (server/tracker.ts:361-397)."""

    def one(key: str) -> bytes | None:
        vals = params.get(key)
        return vals[0] if vals else None

    info_hash = one("info_hash")
    if info_hash is None or len(info_hash) != 20:
        return "invalid info_hash"
    peer_id = one("peer_id")
    if peer_id is None or len(peer_id) != 20:
        return "invalid peer_id"
    fields: dict = {"info_hash": info_hash, "peer_id": peer_id}
    for key, required in (
        ("port", True),
        ("uploaded", True),
        ("downloaded", True),
        ("left", True),
        ("numwant", False),
    ):
        raw = one(key)
        if raw is None:
            if required:
                return f"missing {key}"
            continue
        try:
            fields[key] = int(raw)
        except ValueError:
            return f"invalid {key}"
        if fields[key] < 0:
            return f"invalid {key}"
    if not 0 < fields["port"] < 65536:
        return "invalid port"
    event_raw = one("event")
    if event_raw is None or event_raw == b"":
        fields["event"] = AnnounceEvent.EMPTY
    else:
        try:
            fields["event"] = AnnounceEvent(event_raw.decode("ascii"))
        except (ValueError, UnicodeDecodeError):
            return "invalid event"
    ip_raw = one("ip")
    fields["ip"] = ip_raw.decode("latin-1") if ip_raw else peer_ip
    fields["compact"] = one("compact") != b"0"
    fields["key"] = one("key")
    return fields


# ============================================================== server


@dataclass
class ServeOptions:
    """(server/tracker.ts:615-630). Port 0 = ephemeral; None disables."""

    http_port: int | None = 8000
    udp_port: int | None = 6969
    host: str = "0.0.0.0"
    interval: int = DEFAULT_ANNOUNCE_INTERVAL
    filter_list: set[bytes] | None = None  # allowed info hashes


class TrackerServer:
    """Async-iterable of validated tracker requests from HTTP + UDP."""

    def __init__(self, opts: ServeOptions):
        self.opts = opts
        self._queue: asyncio.Queue = asyncio.Queue()
        self._http_server: asyncio.AbstractServer | None = None
        self._udp_transport: asyncio.DatagramTransport | None = None
        self._closed = False
        # live counters served by /stats
        self.stats = {"announce": 0, "scrape": 0, "rejected": 0}
        # optional /metrics provider (set by the sharded announce plane:
        # server/shard.run_sharded_tracker wires render_tracker_metrics)
        self.metrics_provider = None
        # optional /v1/health provider (zero-arg → the obs/slo
        # build_health dict; run_sharded_tracker wires pump liveness so
        # the tracker is deployable behind a real load balancer)
        self.health_provider = None
        # UDP connection ids: id → minted_at (server/tracker.ts:512-516)
        self._conn_ids: dict[int, float] = {}

    # ------------------------------------------------------------ startup

    async def start(self):
        if self.opts.http_port is not None:
            self._http_server = await asyncio.start_server(
                self._handle_http, self.opts.host, self.opts.http_port
            )
        if self.opts.udp_port is not None:
            loop = asyncio.get_running_loop()
            self._udp_transport, _ = await loop.create_datagram_endpoint(
                lambda: _UdpListener(self),
                local_addr=(self.opts.host, self.opts.udp_port),
            )
        return self

    @property
    def http_port(self) -> int | None:
        if self._http_server is None:
            return None
        return self._http_server.sockets[0].getsockname()[1]

    @property
    def udp_port(self) -> int | None:
        if self._udp_transport is None:
            return None
        return self._udp_transport.get_extra_info("sockname")[1]

    def close(self):
        self._closed = True
        if self._http_server:
            self._http_server.close()
        if self._udp_transport:
            self._udp_transport.close()
        self._queue.put_nowait(None)  # wake the iterator

    # ------------------------------------------------------------ iterate

    def __aiter__(self):
        return self

    async def __anext__(self):
        if self._closed and self._queue.empty():
            raise StopAsyncIteration
        item = await self._queue.get()
        if item is None:
            raise StopAsyncIteration
        return item

    def drain_nowait(self, max_items: int = 256) -> list:
        """Everything already queued, without awaiting — the sharded
        pump's batch-drain: one cycle picks up a whole burst of parsed
        requests so announces can be processed per shard, not per
        datagram. The close sentinel is put back for the iterator."""
        out: list = []
        while len(out) < max_items:
            try:
                item = self._queue.get_nowait()
            except asyncio.QueueEmpty:
                break
            if item is None:
                self._queue.put_nowait(None)
                break
            out.append(item)
        return out

    # ---------------------------------------------------------------- HTTP

    async def _handle_http(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter):
        try:
            request_line = (await asyncio.wait_for(reader.readline(), 30)).decode("latin-1")
        except (asyncio.TimeoutError, UnicodeDecodeError):
            writer.close()
            return
        parts = request_line.split()
        if len(parts) < 2 or parts[0] != "GET":
            await _http_reply(writer, 400, b"bad request")
            return
        target = parts[1]
        # read headers; honor X-Forwarded-For (server/tracker.ts:348-350)
        peer_ip = writer.get_extra_info("peername", ("", 0))[0]
        while True:
            try:
                line = await asyncio.wait_for(reader.readline(), 30)
            except asyncio.TimeoutError:
                writer.close()
                return
            if line in (b"\r\n", b"\n", b""):
                break
            if line.lower().startswith(b"x-forwarded-for:"):
                peer_ip = line.split(b":", 1)[1].strip().split(b",")[0].decode("latin-1")

        path, _, query = target.partition("?")
        # route on the last path segment (server/tracker.ts:444)
        route = path.rstrip("/").rsplit("/", 1)[-1]
        if route == "announce":
            await self._http_announce(query, peer_ip, writer)
        elif route == "scrape":
            await self._http_scrape(query, writer)
        elif route == "stats":
            body = bencode({k.encode(): v for k, v in sorted(self.stats.items())})
            await _http_reply(writer, 200, body)
        elif route == "health" and self.health_provider is not None:
            # liveness + readiness (obs/slo.build_health): answering at
            # all is liveness; 200 only when ready, 503 with the
            # reasons otherwise — the standard LB probe contract
            try:
                health = self.health_provider()
            except Exception:  # a probe bug must not kill the listener
                await _http_reply(writer, 500, b"health probe failed")
                return
            import json as _json

            await _http_reply(
                writer,
                200 if health.get("ready") else 503,
                _json.dumps(health, sort_keys=True).encode(),
                content_type="application/json",
            )
        elif route == "metrics" and self.metrics_provider is not None:
            try:
                body = self.metrics_provider().encode()
            except Exception:  # a render bug must not kill the listener
                await _http_reply(writer, 500, b"metrics render failed")
                return
            await _http_reply(
                writer, 200, body,
                content_type="text/plain; version=0.0.4; charset=utf-8",
            )
        else:
            await _http_reply(writer, 404, b"not found")

    async def _http_announce(self, query: str, peer_ip: str, writer):
        fields = _validate_announce_params(_parse_query_raw(query), peer_ip)
        if isinstance(fields, str):
            self.stats["rejected"] += 1
            await _http_reply(writer, 200, bencode({b"failure reason": fields}))
            return
        if self.opts.filter_list is not None and fields["info_hash"] not in self.opts.filter_list:
            self.stats["rejected"] += 1
            await _http_reply(
                writer, 200, bencode({b"failure reason": "torrent not in allowlist"})
            )
            return
        self.stats["announce"] += 1
        req = HttpAnnounceRequest(
            info_hash=fields["info_hash"],
            peer_id=fields["peer_id"],
            ip=fields["ip"],
            port=fields["port"],
            uploaded=fields["uploaded"],
            downloaded=fields["downloaded"],
            left=fields["left"],
            event=fields["event"],
            num_want=min(fields.get("numwant", DEFAULT_NUM_WANT), MAX_NUM_WANT),
            compact=fields["compact"],
            key=fields["key"],
            _writer=writer,
        )
        await self._queue.put(req)

    async def _http_scrape(self, query: str, writer):
        params = _parse_query_raw(query)
        hashes = params.get("info_hash", [])
        if any(len(h) != 20 for h in hashes):
            self.stats["rejected"] += 1
            await _http_reply(writer, 200, bencode({b"failure reason": "invalid info_hash"}))
            return
        if self.opts.filter_list is not None:
            hashes = [h for h in hashes if h in self.opts.filter_list]
        self.stats["scrape"] += 1
        await self._queue.put(HttpScrapeRequest(info_hashes=hashes, _writer=writer))

    # ---------------------------------------------------------------- UDP

    def _mint_connection_id(self) -> int:
        now = time.monotonic()
        for cid, t in list(self._conn_ids.items()):
            if now - t > UDP_CONNECTION_TTL:
                del self._conn_ids[cid]
        cid = random.getrandbits(63)
        self._conn_ids[cid] = now
        return cid

    def _connection_id_valid(self, cid: int) -> bool:
        t = self._conn_ids.get(cid)
        return t is not None and time.monotonic() - t <= UDP_CONNECTION_TTL


class _UdpListener(asyncio.DatagramProtocol):
    def __init__(self, server: TrackerServer):
        self.server = server
        self.transport: asyncio.DatagramTransport | None = None

    def connection_made(self, transport):
        self.transport = transport

    def _send_error(self, tid: bytes, reason: str, addr):
        # UDP error packet (server/_helpers.ts:20-36)
        self.server.stats["rejected"] += 1
        self.transport.sendto(
            write_int(UdpTrackerAction.ERROR, 4) + tid + reason.encode(), addr
        )

    def datagram_received(self, data: bytes, addr):
        srv = self.server
        if len(data) < 16:
            return
        action = read_int(data, 4, 8)
        tid = data[12:16]
        if action == UdpTrackerAction.CONNECT:
            if read_int(data, 8, 0) != UDP_CONNECT_MAGIC:
                return  # not a BitTorrent connect; drop silently
            cid = srv._mint_connection_id()
            self.transport.sendto(
                write_int(UdpTrackerAction.CONNECT, 4) + tid + write_int(cid, 8), addr
            )
            return
        if not srv._connection_id_valid(read_int(data, 8, 0)):
            self._send_error(tid, "expired connection id", addr)
            return
        if action == UdpTrackerAction.ANNOUNCE:
            if len(data) < 98:
                self._send_error(tid, "truncated announce", addr)
                return
            event_code = read_int(data, 4, 80)
            event = UDP_CODE_EVENT.get(event_code)
            if event is None:
                self._send_error(tid, "invalid event", addr)
                return
            port = read_int(data, 2, 96)
            if port == 0:
                self._send_error(tid, "invalid port", addr)
                return
            info_hash = data[16:36]
            if srv.opts.filter_list is not None and info_hash not in srv.opts.filter_list:
                self._send_error(tid, "torrent not in allowlist", addr)
                return
            ip_raw = data[84:88]
            ip = (
                ".".join(str(b) for b in ip_raw)
                if ip_raw != b"\x00\x00\x00\x00"
                else addr[0]
            )
            # BEP 15 num_want is signed; -1/any negative means "default".
            # Cap the rest so a compact response always fits one datagram.
            raw_num_want = read_int(data, 4, 92)
            if raw_num_want >= 1 << 31:
                num_want = DEFAULT_NUM_WANT
            else:
                num_want = min(raw_num_want, MAX_NUM_WANT)
            srv.stats["announce"] += 1
            req = UdpAnnounceRequest(
                info_hash=info_hash,
                peer_id=data[36:56],
                ip=ip,
                port=port,
                downloaded=read_int(data, 8, 56),
                left=read_int(data, 8, 64),
                uploaded=read_int(data, 8, 72),
                event=event,
                num_want=num_want,
                key=data[88:92],
                _transport=self.transport,
                _addr=addr,
                _tid=tid,
            )
            srv._queue.put_nowait(req)
        elif action == UdpTrackerAction.SCRAPE:
            body = data[16:]
            if len(body) % 20 != 0:
                self._send_error(tid, "malformed scrape", addr)
                return
            hashes = [body[i : i + 20] for i in range(0, len(body), 20)]
            if srv.opts.filter_list is not None:
                hashes = [h for h in hashes if h in srv.opts.filter_list]
            srv.stats["scrape"] += 1
            srv._queue.put_nowait(
                UdpScrapeRequest(
                    info_hashes=hashes, _transport=self.transport, _addr=addr, _tid=tid
                )
            )
        else:
            self._send_error(tid, "unknown action", addr)


@dataclass
class UdpAnnounceRequest(AnnounceRequest):
    _transport: asyncio.DatagramTransport | None = None
    _addr: tuple = ()
    _tid: bytes = b""

    async def respond(self, interval: int, complete: int, incomplete: int, peers):
        """Announce response packet (server/tracker.ts:187-211)."""
        pkt = (
            write_int(UdpTrackerAction.ANNOUNCE, 4)
            + self._tid
            + write_int(interval, 4)
            + write_int(incomplete, 4)
            + write_int(complete, 4)
            + _pack_peers_compact(peers)
        )
        self._transport.sendto(pkt, self._addr)

    async def reject(self, reason: str):
        self._transport.sendto(
            write_int(UdpTrackerAction.ERROR, 4) + self._tid + reason.encode(), self._addr
        )


@dataclass
class UdpScrapeRequest(ScrapeRequest):
    _transport: asyncio.DatagramTransport | None = None
    _addr: tuple = ()
    _tid: bytes = b""

    async def respond(self, files):
        """Scrape response packet (server/tracker.ts:294-312)."""
        body = b"".join(
            write_int(c, 4) + write_int(d, 4) + write_int(i, 4) for _, c, d, i in files
        )
        self._transport.sendto(
            write_int(UdpTrackerAction.SCRAPE, 4) + self._tid + body, self._addr
        )

    async def reject(self, reason: str):
        self._transport.sendto(
            write_int(UdpTrackerAction.ERROR, 4) + self._tid + reason.encode(), self._addr
        )


async def serve_tracker(opts: ServeOptions | None = None) -> TrackerServer:
    """Bind listeners and return the request stream (server/tracker.ts:633-654)."""
    server = TrackerServer(opts or ServeOptions())
    return await server.start()
