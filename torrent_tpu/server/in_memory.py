"""Reference in-memory tracker (ref: server/in_memory_tracker.ts, 186 LoC).

The policy layer over TrackerServer's transport stream: per-torrent swarm
state, seeder/leecher accounting, random peer selection, idle sweeps.

Deliberate fixes vs the reference (SURVEY §8.13):
- ``random_selection`` cannot loop forever when the pool is exactly the
  requester (in_memory_tracker.ts:42-50) — it samples from a materialized
  candidate list.
- Scrape returns stats for the hashes it knows and zeros for the ones it
  doesn't, instead of rejecting the whole batch when any hash is unknown
  (in_memory_tracker.ts:155-159).
"""

from __future__ import annotations

import asyncio
import random
import time
from dataclasses import dataclass, field

from torrent_tpu.net.constants import DEFAULT_ANNOUNCE_INTERVAL
from torrent_tpu.net.types import AnnounceEvent, AnnouncePeer
from torrent_tpu.server.tracker import (
    AnnounceRequest,
    ScrapeRequest,
    ServeOptions,
    TrackerServer,
    serve_tracker,
)

PEER_TTL = 15 * 60  # evict peers idle > 15 min (in_memory_tracker.ts:16)
SWEEP_INTERVAL = 15 * 60


@dataclass
class PeerState:
    peer_id: bytes
    ip: str
    port: int
    left: int
    last_seen: float = field(default_factory=time.monotonic)

    @property
    def is_seeder(self) -> bool:
        # seeder/leecher classification (in_memory_tracker.ts:23-28)
        return self.left == 0


@dataclass
class FileInfo:
    """Swarm state for one torrent (in_memory_tracker.ts:53-59)."""

    complete: int = 0  # current seeders
    downloaded: int = 0  # lifetime completions
    incomplete: int = 0  # current leechers
    peers: dict[bytes, PeerState] = field(default_factory=dict)


class InMemoryTracker:
    """Tracker policy over in-process maps; drive with handle()."""

    def __init__(self, interval: int = DEFAULT_ANNOUNCE_INTERVAL):
        self.interval = interval
        self.files: dict[bytes, FileInfo] = {}

    # ------------------------------------------------------------ helpers

    def random_selection(self, info: FileInfo, exclude: bytes, n: int) -> list[AnnouncePeer]:
        """Up to n random peers, excluding the requester (in_memory_tracker.ts:30-51)."""
        candidates = [p for pid, p in info.peers.items() if pid != exclude]
        if len(candidates) > n:
            candidates = random.sample(candidates, n)
        return [AnnouncePeer(ip=p.ip, port=p.port, peer_id=p.peer_id) for p in candidates]

    # ------------------------------------------------------------ announce

    async def handle_announce(self, req: AnnounceRequest) -> None:
        """State update + response (in_memory_tracker.ts:79-143)."""
        info = self.files.setdefault(req.info_hash, FileInfo())
        prev = info.peers.get(req.peer_id)

        if req.event == AnnounceEvent.STOPPED:
            if prev is not None:
                del info.peers[req.peer_id]
                if prev.is_seeder:
                    info.complete -= 1
                else:
                    info.incomplete -= 1
            await req.respond(self.interval, info.complete, info.incomplete, [])
            return

        now_seeder = req.left == 0
        if prev is None:
            if now_seeder:
                info.complete += 1
            else:
                info.incomplete += 1
            if req.event == AnnounceEvent.COMPLETED and now_seeder:
                info.downloaded += 1
        else:
            if prev.is_seeder != now_seeder:
                if now_seeder:  # leecher → seeder promotion (:113-125)
                    info.incomplete -= 1
                    info.complete += 1
                    info.downloaded += 1
                else:
                    info.complete -= 1
                    info.incomplete += 1
            elif req.event == AnnounceEvent.COMPLETED and now_seeder:
                info.downloaded += 1

        info.peers[req.peer_id] = PeerState(
            peer_id=req.peer_id, ip=req.ip, port=req.port, left=req.left
        )
        peers = self.random_selection(info, req.peer_id, req.num_want)
        await req.respond(self.interval, info.complete, info.incomplete, peers)

    # ------------------------------------------------------------ scrape

    async def handle_scrape(self, req: ScrapeRequest) -> None:
        """(in_memory_tracker.ts:145-164); unknown hashes scrape as zeros."""
        files = []
        for h in req.info_hashes:
            info = self.files.get(h)
            if info is None:
                files.append((h, 0, 0, 0))
            else:
                files.append((h, info.complete, info.downloaded, info.incomplete))
        await req.respond(files)

    # ------------------------------------------------------------ sweep

    def sweep(self) -> int:
        """Evict idle peers (in_memory_tracker.ts:61-77); returns evictions."""
        cutoff = time.monotonic() - PEER_TTL
        evicted = 0
        for info in self.files.values():
            for pid in [pid for pid, p in info.peers.items() if p.last_seen < cutoff]:
                p = info.peers.pop(pid)
                if p.is_seeder:
                    info.complete -= 1
                else:
                    info.incomplete -= 1
                evicted += 1
        return evicted

    # ------------------------------------------------------------ dispatch

    async def handle(self, req) -> None:
        if isinstance(req, AnnounceRequest):
            await self.handle_announce(req)
        elif isinstance(req, ScrapeRequest):
            await self.handle_scrape(req)


async def run_tracker(opts: ServeOptions | None = None) -> tuple[TrackerServer, asyncio.Task]:
    """Serve + drive an InMemoryTracker (in_memory_tracker.ts:167-181).

    Returns the server (for ports/close) and the pump task. The periodic
    sweep rides the pump loop's timeout rather than a separate timer.
    """
    server = await serve_tracker(opts)
    tracker = InMemoryTracker(interval=(opts.interval if opts else DEFAULT_ANNOUNCE_INTERVAL))

    async def pump():
        last_sweep = time.monotonic()
        it = server.__aiter__()
        while True:
            try:
                req = await asyncio.wait_for(it.__anext__(), timeout=60)
            except asyncio.TimeoutError:
                req = None
            except StopAsyncIteration:
                break
            if req is not None:
                try:
                    await tracker.handle(req)
                except Exception:
                    pass  # one bad request must not kill the tracker
            if time.monotonic() - last_sweep > SWEEP_INTERVAL:
                tracker.sweep()
                last_sweep = time.monotonic()

    task = asyncio.create_task(pump())
    task.tracker = tracker  # expose state for tests/stats
    return server, task


def main(argv=None):  # pragma: no cover - manual entrypoint (in_memory_tracker.ts:183-186)
    import argparse

    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--http-port", type=int, default=8000)
    parser.add_argument(
        "--udp-port", type=int, default=6969, help="negative value disables UDP"
    )
    parser.add_argument("--interval", type=int, default=600)
    args = parser.parse_args(argv)

    async def go():
        server, task = await run_tracker(
            ServeOptions(
                http_port=args.http_port,
                udp_port=args.udp_port if args.udp_port >= 0 else None,
                interval=args.interval,
            )
        )
        print(f"tracker listening: http={server.http_port} udp={server.udp_port}")
        await task

    asyncio.run(go())
    return 0


if __name__ == "__main__":  # pragma: no cover
    main()
