"""Reference in-memory tracker (ref: server/in_memory_tracker.ts, 186 LoC).

The policy layer over TrackerServer's transport stream: per-torrent swarm
state, seeder/leecher accounting, random peer selection, idle sweeps.

Deliberate fixes vs the reference (SURVEY §8.13):
- ``random_selection`` cannot loop forever when the pool is exactly the
  requester (in_memory_tracker.ts:42-50) — it samples from a materialized
  candidate list.
- Scrape returns stats for the hashes it knows and zeros for the ones it
  doesn't, instead of rejecting the whole batch when any hash is unknown
  (in_memory_tracker.ts:155-159).
"""

from __future__ import annotations

import asyncio
import random
import time
from dataclasses import dataclass, field

from torrent_tpu.net.constants import DEFAULT_ANNOUNCE_INTERVAL
from torrent_tpu.net.types import AnnounceEvent, AnnouncePeer
from torrent_tpu.server.tracker import (
    AnnounceRequest,
    ScrapeRequest,
    ServeOptions,
    TrackerServer,
    serve_tracker,
)

PEER_TTL = 15 * 60  # evict peers idle > 15 min (in_memory_tracker.ts:16)
SWEEP_INTERVAL = 15 * 60


@dataclass
class PeerState:
    peer_id: bytes
    ip: str
    port: int
    left: int
    last_seen: float = field(default_factory=time.monotonic)

    @property
    def is_seeder(self) -> bool:
        # seeder/leecher classification (in_memory_tracker.ts:23-28)
        return self.left == 0


@dataclass
class FileInfo:
    """Swarm state for one torrent (in_memory_tracker.ts:53-59)."""

    complete: int = 0  # current seeders
    downloaded: int = 0  # lifetime completions
    incomplete: int = 0  # current leechers
    peers: dict[bytes, PeerState] = field(default_factory=dict)


class InMemoryTracker:
    """Tracker policy over in-process maps; drive with handle()."""

    def __init__(
        self,
        interval: int = DEFAULT_ANNOUNCE_INTERVAL,
        clock=time.monotonic,
        rng: random.Random | None = None,
    ):
        self.interval = interval
        self.files: dict[bytes, FileInfo] = {}
        # determinism seams (same contract as ShardedSwarmStore): all
        # timestamps and peer-selection draws route through these so a
        # scenario replay with virtual clock + seeded rng is bit-stable
        self._clock = clock
        self._rng: random.Random = rng if rng is not None else random  # type: ignore[assignment]

    # ------------------------------------------------------------ helpers

    def random_selection(self, info: FileInfo, exclude: bytes, n: int) -> list[AnnouncePeer]:
        """Up to n random peers, excluding the requester (in_memory_tracker.ts:30-51)."""
        candidates = [p for pid, p in info.peers.items() if pid != exclude]
        if len(candidates) > n:
            candidates = self._rng.sample(candidates, n)
        return [AnnouncePeer(ip=p.ip, port=p.port, peer_id=p.peer_id) for p in candidates]

    # ------------------------------------------------------------ announce

    async def handle_announce(self, req: AnnounceRequest) -> None:
        """State update + response (in_memory_tracker.ts:79-143)."""
        info = self.files.setdefault(req.info_hash, FileInfo())
        prev = info.peers.get(req.peer_id)

        if req.event == AnnounceEvent.STOPPED:
            if prev is not None:
                del info.peers[req.peer_id]
                if prev.is_seeder:
                    info.complete -= 1
                else:
                    info.incomplete -= 1
            await req.respond(self.interval, info.complete, info.incomplete, [])
            return

        now_seeder = req.left == 0
        if prev is None:
            if now_seeder:
                info.complete += 1
            else:
                info.incomplete += 1
            if req.event == AnnounceEvent.COMPLETED and now_seeder:
                info.downloaded += 1
        else:
            if prev.is_seeder != now_seeder:
                if now_seeder:  # leecher → seeder promotion (:113-125)
                    info.incomplete -= 1
                    info.complete += 1
                    info.downloaded += 1
                else:
                    info.complete -= 1
                    info.incomplete += 1
            elif req.event == AnnounceEvent.COMPLETED and now_seeder:
                info.downloaded += 1

        info.peers[req.peer_id] = PeerState(
            peer_id=req.peer_id, ip=req.ip, port=req.port, left=req.left,
            last_seen=self._clock(),
        )
        peers = self.random_selection(info, req.peer_id, req.num_want)
        await req.respond(self.interval, info.complete, info.incomplete, peers)

    # ------------------------------------------------------------ scrape

    async def handle_scrape(self, req: ScrapeRequest) -> None:
        """(in_memory_tracker.ts:145-164); an empty request scrapes every
        tracked torrent (ts:149-152). Unknown hashes scrape as zeros
        rather than rejecting the whole request (deliberate divergence:
        one stale hash in a batched scrape shouldn't void the rest)."""
        hashes = req.info_hashes or list(self.files.keys())
        files = []
        for h in hashes:
            info = self.files.get(h)
            if info is None:
                files.append((h, 0, 0, 0))
            else:
                files.append((h, info.complete, info.downloaded, info.incomplete))
        await req.respond(files)

    # ------------------------------------------------------------ sweep

    def sweep(self) -> int:
        """Evict idle peers (in_memory_tracker.ts:61-77); returns evictions."""
        cutoff = self._clock() - PEER_TTL
        evicted = 0
        for info in self.files.values():
            for pid in [pid for pid, p in info.peers.items() if p.last_seen < cutoff]:
                p = info.peers.pop(pid)
                if p.is_seeder:
                    info.complete -= 1
                else:
                    info.incomplete -= 1
                evicted += 1
        return evicted

    # ------------------------------------------------------------ dispatch

    # -------------------------------------------------------- persistence

    def save_state(self, path: str) -> None:
        """Snapshot swarm state to disk (bencoded) so a tracker restart
        keeps its lifetime counters and live peer lists.

        ``last_seen`` is stored as *age in seconds* — monotonic clocks
        don't survive a process, ages do.
        """
        import os

        from torrent_tpu.codec.bencode import bencode

        now = self._clock()
        files = {}
        for ih, info in self.files.items():
            files[ih] = {
                b"complete": info.complete,
                b"downloaded": info.downloaded,
                b"incomplete": info.incomplete,
                b"peers": {
                    ps.peer_id: {
                        b"ip": ps.ip.encode(),
                        b"port": ps.port,
                        b"left": ps.left,
                        b"age": int(now - ps.last_seen),
                    }
                    for ps in info.peers.values()
                },
            }
        blob = bencode({b"version": 1, b"files": files})
        tmp = f"{path}.tmp"
        with open(tmp, "wb") as f:
            f.write(blob)
        os.replace(tmp, path)  # atomic: no torn state file on crash

    def load_state(self, path: str) -> bool:
        """Restore a ``save_state`` snapshot; False if absent/invalid."""
        from torrent_tpu.codec.bencode import BencodeError, bdecode

        try:
            with open(path, "rb") as f:
                decoded = bdecode(f.read())
        except (OSError, BencodeError):
            return False
        if not isinstance(decoded, dict) or decoded.get(b"version") != 1:
            return False
        files = decoded.get(b"files")
        if not isinstance(files, dict):
            return False
        now = self._clock()
        # Parse fully into a scratch dict first — a snapshot that turns
        # out malformed halfway through must not leave partial state.
        loaded: dict[bytes, FileInfo] = {}
        try:
            for ih, d in files.items():
                if not (isinstance(ih, bytes) and len(ih) == 20 and isinstance(d, dict)):
                    continue
                counters = [d.get(k, 0) for k in (b"complete", b"downloaded", b"incomplete")]
                if not all(isinstance(c, int) for c in counters):
                    continue
                info = FileInfo(
                    complete=counters[0], downloaded=counters[1], incomplete=counters[2]
                )
                peers = d.get(b"peers")
                if isinstance(peers, dict):
                    for pid, p in peers.items():
                        if not (isinstance(pid, bytes) and isinstance(p, dict)):
                            continue
                        ip, port, left = p.get(b"ip"), p.get(b"port"), p.get(b"left")
                        age = p.get(b"age", 0)
                        if not (
                            isinstance(ip, bytes)
                            and isinstance(port, int)
                            and 0 < port < 65536  # compact packing needs u16
                            and isinstance(left, int)
                            and left >= 0
                            and isinstance(age, int)
                            and age >= 0  # a future last_seen never expires
                        ):
                            continue
                        try:
                            info.peers[pid] = PeerState(
                                peer_id=pid,
                                ip=ip.decode(),
                                port=port,
                                left=left,
                                last_seen=now - age,
                            )
                        except UnicodeDecodeError:
                            continue
                # Live counters are derived state — recompute from the
                # peers that actually survived validation so a dropped
                # entry can't leave a phantom seeder/leecher behind.
                info.complete = sum(1 for ps in info.peers.values() if ps.is_seeder)
                info.incomplete = len(info.peers) - info.complete
                loaded[ih] = info
        except (TypeError, ValueError, AttributeError):
            return False
        self.files.update(loaded)
        self.sweep()  # drop peers whose stored age already exceeds the TTL
        return True

    async def handle(self, req) -> None:
        if isinstance(req, AnnounceRequest):
            await self.handle_announce(req)
        elif isinstance(req, ScrapeRequest):
            await self.handle_scrape(req)


async def run_tracker(
    opts: ServeOptions | None = None, state_file: str | None = None
) -> tuple[TrackerServer, asyncio.Task]:
    """Serve + drive an InMemoryTracker (in_memory_tracker.ts:167-181).

    Returns the server (for ports/close) and the pump task. The periodic
    sweep rides the pump loop's timeout rather than a separate timer.
    With ``state_file``, swarm state is restored at startup and saved on
    every sweep and at shutdown — a restart keeps lifetime ``downloaded``
    counters and live peers.
    """
    server = await serve_tracker(opts)
    tracker = InMemoryTracker(interval=(opts.interval if opts else DEFAULT_ANNOUNCE_INTERVAL))
    if state_file:
        tracker.load_state(state_file)

    def _persist():
        if state_file:
            try:
                tracker.save_state(state_file)
            except OSError:
                pass  # persistence is best-effort; serving goes on

    async def pump():
        last_sweep = time.monotonic()
        it = server.__aiter__()
        try:
            while True:
                try:
                    req = await asyncio.wait_for(it.__anext__(), timeout=60)
                except asyncio.TimeoutError:
                    req = None
                except StopAsyncIteration:
                    break
                if req is not None:
                    try:
                        await tracker.handle(req)
                    except Exception:
                        pass  # one bad request must not kill the tracker
                if time.monotonic() - last_sweep > SWEEP_INTERVAL:
                    tracker.sweep()
                    _persist()
                    last_sweep = time.monotonic()
        finally:
            _persist()

    task = asyncio.create_task(pump())
    task.tracker = tracker  # expose state for tests/stats
    return server, task


def main(argv=None):  # pragma: no cover - manual entrypoint (in_memory_tracker.ts:183-186)
    import argparse

    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--http-port", type=int, default=8000)
    parser.add_argument(
        "--udp-port", type=int, default=6969, help="negative value disables UDP"
    )
    parser.add_argument("--interval", type=int, default=600)
    parser.add_argument("--state-file", help="persist swarm state across restarts")
    args = parser.parse_args(argv)

    async def go():
        server, task = await run_tracker(
            ServeOptions(
                http_port=args.http_port,
                udp_port=args.udp_port if args.udp_port >= 0 else None,
                interval=args.interval,
            ),
            state_file=args.state_file,
        )
        print(f"tracker listening: http={server.http_port} udp={server.udp_port}")
        await task

    asyncio.run(go())
    return 0


if __name__ == "__main__":  # pragma: no cover
    main()
