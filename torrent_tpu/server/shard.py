"""Sharded announce plane: the production-scale tracker service.

``server/in_memory.py`` is the reference policy layer — one dict, one
pump, O(swarm) peer-list scans. This module is the scale-out rewrite the
ROADMAP's millions-of-users story needs:

* **Swarm state sharded by info-hash** across N independent shards.
  Each shard owns its swarms behind its own
  ``analysis.sanitizer.named_lock`` — there is NO global lock, and shard
  locks are *leaves* of the lock-order graph: nothing (not even another
  shard's lock) is ever acquired while one is held. Cross-shard
  aggregation (metrics, scrape, sweeps) takes locks strictly
  sequentially.
* **Reservoir-sampled peer lists.** Every swarm keeps a swap-remove
  index array beside its peer dict, so assembling a ``numwant`` reply is
  O(numwant) random draws — never an O(swarm) scan. A two-million-peer
  swarm answers as fast as a two-peer one.
* **Server-side reply bounds.** ``numwant`` is clamped against both a
  hard cap and a compact-reply byte budget (one unfragmented UDP
  datagram), and scrapes are capped per request — a hostile announce can
  never make the tracker assemble an unbounded response.
* **Batched announce processing.** ``announce_batch`` groups a drained
  datagram/request queue by shard and processes each shard's group under
  ONE lock acquisition; ``run_sharded_tracker``'s pump drains the
  transport queue and replies in bulk.
* **Per-shard TTL sweeps.** ``sweep_one`` expires one shard per tick
  (round-robin), so expiry cost is amortized instead of a periodic
  full-store stall.
* **Persistent-tracker seeding.** ``seed_peer`` lets the DHT indexer
  (``net/indexer.py``) feed harvested ``announce_peer`` traffic into the
  store, so the tracker answers for swarms it learned from the DHT —
  the "Persistent BitTorrent Trackers" semantics from PAPERS.md.

Observability: ``metrics_snapshot()`` feeds
``utils.metrics.render_tracker_metrics`` (``torrent_tpu_tracker_*``
series), and the service observes per-announce latency into the shared
log2 histogram registry (family
``torrent_tpu_tracker_announce_seconds``), rendered alongside the other
obs families. The tracker's own HTTP listener serves ``/metrics``.
"""

from __future__ import annotations

import asyncio
import hashlib
import random
import time
from dataclasses import dataclass, field

from torrent_tpu.analysis.sanitizer import guard_attrs, named_lock
from torrent_tpu.net.constants import DEFAULT_ANNOUNCE_INTERVAL, DEFAULT_NUM_WANT
from torrent_tpu.net.types import AnnounceEvent, AnnouncePeer
from torrent_tpu.server.tracker import (
    AnnounceRequest,
    ScrapeRequest,
    ServeOptions,
    TrackerServer,
    serve_tracker,
)
from torrent_tpu.utils.log import get_logger

log = get_logger("server.shard")

DEFAULT_SHARDS = 8
PEER_TTL = 15 * 60  # same idle horizon as the reference tracker
SWEEP_TICK = 60.0  # one shard expired per tick (full cycle = N ticks)
# server-side reply bounds (satellite: never assemble unbounded replies)
MAX_NUM_WANT = 200
# compact-reply peer budget: v6 entries are 18 B and the whole reply must
# stay inside one unfragmented UDP datagram alongside the KRPC/announce
# framing, whatever family mix the sample draws
MAX_REPLY_BYTES = 1200
MAX_SCRAPE_HASHES = 64
MAX_BATCH = 256  # transport-queue drain bound per pump cycle
# /v1/health readiness: the pump stamps every cycle and an idle queue
# wakes it at least every 5 s, so a stamp older than this means the
# drive loop is wedged (not merely idle)
PUMP_MAX_AGE_S = 30.0


class _PeerRec:
    """One swarm member. ``idx`` is its slot in the swarm's swap-remove
    sampling array — removal is O(1), sampling O(numwant)."""

    __slots__ = ("peer_id", "ip", "port", "left", "last_seen", "idx")

    def __init__(self, peer_id: bytes, ip: str, port: int, left: int,
                 last_seen: float, idx: int):
        self.peer_id = peer_id
        self.ip = ip
        self.port = port
        self.left = left
        self.last_seen = last_seen
        self.idx = idx

    @property
    def is_seeder(self) -> bool:
        return self.left == 0


class _Swarm:
    __slots__ = ("complete", "downloaded", "incomplete", "peers", "order",
                 "seeded_from", "last_active")

    def __init__(self):
        self.complete = 0  # current seeders
        self.downloaded = 0  # lifetime completions
        self.incomplete = 0  # current leechers
        self.peers: dict[bytes, _PeerRec] = {}
        self.order: list[bytes] = []  # sampling array (swap-remove)
        self.seeded_from: str | None = None  # "dht" when indexer-created
        self.last_active = 0.0  # last announce/seed (bounds ghost retention)


class _Shard:
    """One independent slice of the swarm space. The lock is a LEAF:
    every critical section below is pure dict/list work — no calls that
    could acquire another lock, no IO, no device work."""

    __slots__ = ("_shard_lock", "swarms", "peers", "announces", "evicted",
                 "indexed", "clamped", "_cells")

    def __init__(self):
        self._shard_lock = named_lock("server.shard._shard_lock")
        # dynamic lockset checking: the shard's whole mutable blob
        # (swarms + counters) is one cell guarded by _shard_lock
        self._cells = guard_attrs("server.shard", "stats")
        self.swarms: dict[bytes, _Swarm] = {}
        # incremental peer count (maintained on insert/remove) so the
        # metrics snapshot never walks all swarms under the shard lock
        self.peers = 0
        self.announces = 0
        self.evicted = 0
        self.indexed = 0  # peers fed by the DHT indexer
        self.clamped = 0  # numwant requests clamped by the reply bounds


@dataclass
class AnnounceOutcome:
    """One processed announce, ready for any transport's ``respond``."""

    interval: int
    complete: int
    incomplete: int
    peers: list[AnnouncePeer] = field(default_factory=list)


class ShardedSwarmStore:
    """Swarm state sharded by info-hash; every method is thread-safe and
    lock-leaf (see module docstring)."""

    def __init__(
        self,
        n_shards: int = DEFAULT_SHARDS,
        interval: int = DEFAULT_ANNOUNCE_INTERVAL,
        peer_ttl: float = PEER_TTL,
        max_numwant: int = MAX_NUM_WANT,
        max_reply_bytes: int = MAX_REPLY_BYTES,
        clock=time.monotonic,
        rng: random.Random | None = None,
    ):
        if n_shards < 1:
            raise ValueError("n_shards must be >= 1")
        self.n_shards = n_shards
        self.interval = interval
        self.peer_ttl = peer_ttl
        self.max_numwant = max_numwant
        self.max_reply_bytes = max_reply_bytes
        # determinism seams: every timestamp (peer last_seen, TTL
        # cutoffs) and every reservoir draw routes through these, so a
        # scenario run with a virtual clock + seeded rng is replayable
        # bit-for-bit (scenario/engine.py); production defaults unchanged
        self._clock = clock
        self._rng: random.Random = rng if rng is not None else random  # type: ignore[assignment]
        # BEP 33 seam: info_hash -> (seed_bloom, peer_bloom) | None,
        # consulted by scrape() for swarms the tracker has never seen an
        # announce for (DHT-harvested knowledge only lives as blooms)
        self._bloom_source = None
        self._shards = [_Shard() for _ in range(n_shards)]
        self._sweep_cursor = 0
        # store-level counters (scrapes/batches span shards); leaf lock,
        # never held while a shard lock is taken or vice versa
        self._stats_lock = named_lock("server.shard._stats_lock")
        self._stats_cells = guard_attrs("server.store", "stats")
        self._scrapes = 0
        self._batches = 0
        self._batched_announces = 0
        self._batch_max = 0

    # ------------------------------------------------------------ routing

    def shard_of(self, info_hash: bytes) -> int:
        """Info-hash → shard index. The hash IS the distribution: BEP 3
        info-hashes are uniform sha1 output, so the top bytes spread
        swarms evenly without rehashing."""
        return int.from_bytes(info_hash[:4], "big") % self.n_shards

    def clamp_numwant(self, numwant: int | None) -> tuple[int, bool]:
        """(effective numwant, was_clamped): negative/absent means the
        BEP default; everything is bounded by the hard cap AND the
        compact-reply byte budget (18 B/peer worst case — v6)."""
        want = DEFAULT_NUM_WANT if numwant is None or numwant < 0 else numwant
        cap = min(self.max_numwant, self.max_reply_bytes // 18)
        return min(want, cap), want > cap

    # ----------------------------------------------------------- announce

    def announce(
        self,
        info_hash: bytes,
        peer_id: bytes,
        ip: str,
        port: int,
        left: int,
        event: AnnounceEvent = AnnounceEvent.EMPTY,
        numwant: int | None = None,
    ) -> AnnounceOutcome:
        shard = self._shards[self.shard_of(info_hash)]
        want, clamped = self.clamp_numwant(numwant)
        now = self._clock()
        with shard._shard_lock:
            shard._cells.write("stats")
            shard.announces += 1
            if clamped:
                shard.clamped += 1
            return self._announce_locked(
                shard, info_hash, peer_id, ip, port, left, event, want, now
            )

    def announce_batch(self, items: list[tuple]) -> list[AnnounceOutcome]:
        """Process many announces with ONE lock acquisition per shard.

        ``items`` are ``(info_hash, peer_id, ip, port, left, event,
        numwant)`` tuples; outcomes come back in input order. This is
        the bulk path the UDP pump drains into: contention cost is paid
        per *shard group*, not per datagram.
        """
        by_shard: dict[int, list[int]] = {}
        for i, it in enumerate(items):
            by_shard.setdefault(self.shard_of(it[0]), []).append(i)
        out: list[AnnounceOutcome | None] = [None] * len(items)
        now = self._clock()
        for si in sorted(by_shard):
            shard = self._shards[si]
            idxs = by_shard[si]
            with shard._shard_lock:
                shard._cells.write("stats")
                shard.announces += len(idxs)
                for i in idxs:
                    ih, pid, ip, port, left, event, numwant = items[i]
                    want, clamped = self.clamp_numwant(numwant)
                    if clamped:
                        shard.clamped += 1
                    out[i] = self._announce_locked(
                        shard, ih, pid, ip, port, left, event, want, now
                    )
        with self._stats_lock:
            self._stats_cells.write("stats")
            self._batches += 1
            self._batched_announces += len(items)
            self._batch_max = max(self._batch_max, len(items))
        return out  # type: ignore[return-value]

    def _announce_locked(
        self, shard: _Shard, info_hash: bytes, peer_id: bytes, ip: str,
        port: int, left: int, event: AnnounceEvent, want: int, now: float,
    ) -> AnnounceOutcome:
        swarm = shard.swarms.get(info_hash)
        if event == AnnounceEvent.STOPPED:
            # never get-or-create on STOPPED: a hostile loop of stops for
            # random hashes must not allocate ghost swarms
            if swarm is None:
                return AnnounceOutcome(self.interval, 0, 0, [])
            prev = swarm.peers.get(peer_id)
            if prev is not None:
                self._remove_locked(swarm, prev)
                shard.peers -= 1
            return AnnounceOutcome(
                self.interval, swarm.complete, swarm.incomplete, []
            )
        if swarm is None:
            swarm = shard.swarms[info_hash] = _Swarm()
        swarm.last_active = now
        prev = swarm.peers.get(peer_id)

        now_seeder = left == 0
        if prev is None:
            rec = _PeerRec(peer_id, ip, port, left, now, len(swarm.order))
            swarm.order.append(peer_id)
            swarm.peers[peer_id] = rec
            shard.peers += 1
            if now_seeder:
                swarm.complete += 1
            else:
                swarm.incomplete += 1
            if event == AnnounceEvent.COMPLETED and now_seeder:
                swarm.downloaded += 1
        else:
            if prev.is_seeder != now_seeder:
                if now_seeder:  # leecher → seeder promotion
                    swarm.incomplete -= 1
                    swarm.complete += 1
                    swarm.downloaded += 1
                else:
                    swarm.complete -= 1
                    swarm.incomplete += 1
            elif event == AnnounceEvent.COMPLETED and now_seeder:
                swarm.downloaded += 1
            prev.ip, prev.port, prev.left, prev.last_seen = ip, port, left, now
        peers = self._sample_locked(swarm, peer_id, want, now)
        return AnnounceOutcome(
            self.interval, swarm.complete, swarm.incomplete, peers
        )

    def _remove_locked(self, swarm: _Swarm, rec: _PeerRec) -> None:
        """O(1) swap-remove from both the dict and the sampling array."""
        last_pid = swarm.order[-1]
        swarm.order[rec.idx] = last_pid
        swarm.peers[last_pid].idx = rec.idx
        swarm.order.pop()
        del swarm.peers[rec.peer_id]
        if rec.is_seeder:
            swarm.complete -= 1
        else:
            swarm.incomplete -= 1

    def _sample_locked(
        self, swarm: _Swarm, exclude: bytes, n: int, now: float
    ) -> list[AnnouncePeer]:
        """Up to ``n`` random peers excluding the requester, O(n) draws
        on the swap-remove array — never a full-swarm scan. Peers past
        the TTL are skipped (not served while they await their shard's
        sweep turn); a draw hitting one simply yields a shorter reply."""
        order = swarm.order
        if n <= 0 or not order:
            return []
        cutoff = now - self.peer_ttl
        extra = 1 if exclude in swarm.peers else 0
        if len(order) <= n + extra:
            return [
                AnnouncePeer(ip=p.ip, port=p.port, peer_id=pid)
                for pid, p in swarm.peers.items()
                if pid != exclude and p.last_seen >= cutoff
            ][:n]
        out: list[AnnouncePeer] = []
        for i in self._rng.sample(range(len(order)), min(len(order), n + extra)):
            pid = order[i]
            if pid == exclude:
                continue
            p = swarm.peers[pid]
            if p.last_seen < cutoff:
                continue
            out.append(AnnouncePeer(ip=p.ip, port=p.port, peer_id=pid))
            if len(out) == n:
                break
        return out

    # ------------------------------------------------------------- scrape

    def attach_bloom_source(self, fn) -> None:
        """Wire a BEP 33 bloom provider (``net.indexer.DhtIndexer
        .blooms_for``): ``fn(info_hash) -> (seed_bloom, peer_bloom) |
        None``. Scrapes for swarms the tracker holds NO peer state for
        fall back to bloom cardinality estimates, so DHT-harvested
        swarms scrape as populations instead of zeros while costing the
        store 0 bytes per swarm. Called OUTSIDE every shard lock (the
        provider owns its own state)."""
        self._bloom_source = fn

    def scrape(self, info_hashes: list[bytes]) -> list[tuple]:
        """(info_hash, complete, downloaded, incomplete) per hash.
        Unknown hashes scrape as zeros — unless a BEP 33 bloom source is
        attached, in which case they scrape as the blooms' cardinality
        estimates (seeders from BFsd, leechers from BFpe); the request
        is CAPPED — an unbounded batch is truncated, and an empty
        scrape returns per-swarm totals only up to the cap."""
        hashes = info_hashes[:MAX_SCRAPE_HASHES]
        if not hashes:
            # empty scrape = "everything": bounded walk, shard by shard.
            # islice, never list(swarms) — materializing a huge shard's
            # key list under its lock would stall every announce on it
            from itertools import islice

            for shard in self._shards:
                with shard._shard_lock:
                    hashes.extend(
                        islice(shard.swarms, MAX_SCRAPE_HASHES - len(hashes))
                    )
                if len(hashes) >= MAX_SCRAPE_HASHES:
                    break
        with self._stats_lock:
            self._stats_cells.write("stats")
            self._scrapes += 1
        out = []
        unknown: list[int] = []  # out-indices to try the bloom source on
        for h in hashes:
            shard = self._shards[self.shard_of(h)]
            with shard._shard_lock:
                swarm = shard.swarms.get(h)
                if swarm is None:
                    unknown.append(len(out))
                    out.append((h, 0, 0, 0))
                else:
                    out.append(
                        (h, swarm.complete, swarm.downloaded, swarm.incomplete)
                    )
        # BEP 33 fallback strictly AFTER the shard-lock walk: the bloom
        # provider is foreign code and must never run under a leaf lock
        if self._bloom_source is not None:
            for i in unknown:
                h = out[i][0]
                blooms = self._bloom_source(h)
                if blooms is None:
                    continue
                seed_bloom, peer_bloom = blooms
                out[i] = (
                    h,
                    int(round(seed_bloom.estimate())),
                    0,
                    int(round(peer_bloom.estimate())),
                )
        return out

    # ----------------------------------------------------- indexer seam

    def seed_peer(
        self, info_hash: bytes, ip: str, port: int, left: int = 0,
        peer_id: bytes | None = None,
    ) -> None:
        """Feed a DHT-harvested peer into the store (persistent-tracker
        semantics): the swarm is created if the tracker has never seen
        an announce for it. DHT announces carry no peer id, so one is
        synthesized deterministically from the address."""
        if peer_id is None:
            peer_id = b"-IX-" + hashlib.sha1(
                f"{ip}:{port}".encode()
            ).digest()[:16]
        shard = self._shards[self.shard_of(info_hash)]
        now = self._clock()
        with shard._shard_lock:
            shard._cells.write("stats")
            shard.indexed += 1
            swarm = shard.swarms.get(info_hash)
            if swarm is None:
                swarm = shard.swarms[info_hash] = _Swarm()
                swarm.seeded_from = "dht"
            # not counted in shard.announces: seeding is harvest, not
            # client announce traffic (it has its own `indexed` counter)
            self._announce_locked(
                shard, info_hash, peer_id, ip, port, left,
                AnnounceEvent.EMPTY, 0, now,
            )

    # -------------------------------------------------------------- sweep

    def _sweep_shard(self, shard: _Shard) -> int:
        cutoff = self._clock() - self.peer_ttl
        evicted = 0
        with shard._shard_lock:
            shard._cells.write("stats")
            for ih in list(shard.swarms):
                swarm = shard.swarms[ih]
                for pid in [
                    pid for pid, p in swarm.peers.items() if p.last_seen < cutoff
                ]:
                    self._remove_locked(swarm, swarm.peers[pid])
                    shard.peers -= 1
                    evicted += 1
                if not swarm.peers and (
                    swarm.downloaded == 0 or swarm.last_active < cutoff
                ):
                    # an empty, never-completed swarm holds no history
                    # worth the memory, and even a completed one is only
                    # kept one TTL past its last announce — a hostile
                    # loop of COMPLETED announces to random hashes must
                    # not allocate permanent ghost swarms
                    del shard.swarms[ih]
            shard.evicted += evicted
        return evicted

    def sweep_one(self) -> int:
        """Expire ONE shard (round-robin) — the amortized form the pump
        calls every tick; a full cycle visits every shard."""
        shard = self._shards[self._sweep_cursor % self.n_shards]
        self._sweep_cursor += 1
        return self._sweep_shard(shard)

    def sweep(self) -> int:
        """Full expiry pass over every shard (sequential, never nested)."""
        return sum(self._sweep_shard(s) for s in self._shards)

    # ------------------------------------------------------------ metrics

    def metrics_snapshot(self) -> dict:
        """Everything ``render_tracker_metrics`` needs: totals plus
        per-shard occupancy. Shard locks are taken strictly one at a
        time (leaf discipline)."""
        per_shard = []
        for shard in self._shards:
            with shard._shard_lock:
                shard._cells.read("stats")
                # O(1) per shard: the peer count is maintained
                # incrementally, never a swarm walk under the lock
                per_shard.append(
                    {
                        "swarms": len(shard.swarms),
                        "peers": shard.peers,
                        "announces": shard.announces,
                        "evicted": shard.evicted,
                        "indexed": shard.indexed,
                        "clamped": shard.clamped,
                    }
                )
        with self._stats_lock:
            self._stats_cells.read("stats")
            batches = {
                "batches": self._batches,
                "announces": self._batched_announces,
                "max": self._batch_max,
            }
            scrapes = self._scrapes
        return {
            "shards": per_shard,
            "n_shards": self.n_shards,
            "announces": sum(s["announces"] for s in per_shard),
            "scrapes": scrapes,
            "swarms": sum(s["swarms"] for s in per_shard),
            "peers": sum(s["peers"] for s in per_shard),
            "evicted": sum(s["evicted"] for s in per_shard),
            "indexed": sum(s["indexed"] for s in per_shard),
            "numwant_clamped": sum(s["clamped"] for s in per_shard),
            "batch": batches,
            "interval": self.interval,
        }


# ================================================================ service


class ShardedTracker:
    """Policy driver speaking ``TrackerServer``'s request objects, with
    announce latency observed into the shared log2 histogram registry
    (outside every lock)."""

    def __init__(self, store: ShardedSwarmStore):
        self.store = store

    @staticmethod
    def _transport(req) -> str:
        return "udp" if type(req).__name__.startswith("Udp") else "http"

    def _observe(self, transport: str, seconds_list: list[float]) -> None:
        from torrent_tpu.obs.hist import histograms

        histograms().get(
            "torrent_tpu_tracker_announce_seconds",
            help="Tracker announce handle latency (receive to reply)",
            transport=transport,
        ).observe_batch(seconds_list)

    async def handle_announce(self, req: AnnounceRequest) -> None:
        t0 = time.perf_counter()
        out = self.store.announce(
            req.info_hash, req.peer_id, req.ip, req.port, req.left,
            req.event, req.num_want,
        )
        await req.respond(out.interval, out.complete, out.incomplete, out.peers)
        self._observe(self._transport(req), [time.perf_counter() - t0])

    async def handle_scrape(self, req: ScrapeRequest) -> None:
        await req.respond(self.store.scrape(req.info_hashes))

    async def handle(self, req) -> None:
        if isinstance(req, AnnounceRequest):
            await self.handle_announce(req)
        elif isinstance(req, ScrapeRequest):
            await self.handle_scrape(req)

    async def handle_batch(self, reqs: list) -> None:
        """The bulk path: announces grouped per shard through
        ``announce_batch`` (one lock acquisition per shard), replies sent
        in bulk afterwards; scrapes handled after the announce burst.

        Latency accounting is per REQUEST: each announce observes the
        time from batch pickup to its OWN reply completing — store work
        plus its reply position in the drain cycle — never the whole
        batch's wall (which would inflate p99 by the batch width)."""
        announces = [r for r in reqs if isinstance(r, AnnounceRequest)]
        if announces:
            t0 = time.perf_counter()
            outcomes = self.store.announce_batch(
                [
                    (r.info_hash, r.peer_id, r.ip, r.port, r.left, r.event,
                     r.num_want)
                    for r in announces
                ]
            )
            by_transport: dict[str, list[float]] = {}
            for req, out in zip(announces, outcomes):
                await req.respond(
                    out.interval, out.complete, out.incomplete, out.peers
                )
                by_transport.setdefault(self._transport(req), []).append(
                    time.perf_counter() - t0
                )
            for transport, lats in by_transport.items():
                self._observe(transport, lats)
        for req in reqs:
            if isinstance(req, ScrapeRequest):
                await self.handle_scrape(req)


async def run_sharded_tracker(
    opts: ServeOptions | None = None,
    n_shards: int = DEFAULT_SHARDS,
    store: ShardedSwarmStore | None = None,
    indexer=None,
) -> tuple[TrackerServer, asyncio.Task]:
    """Serve + drive a :class:`ShardedTracker`.

    Returns the transport server (ports/close) and the pump task. The
    pump drains the request queue each cycle and hands the whole batch
    to ``handle_batch`` — a burst of UDP announces is processed per
    shard, not per datagram — and expires one shard per
    :data:`SWEEP_TICK`. The tracker's HTTP listener serves ``/metrics``
    (``torrent_tpu_tracker_*`` + the latency histogram families).
    ``indexer`` (a ``net.indexer.DhtIndexer``) is only carried for the
    metrics snapshot — its harvest feeds ``store`` directly.
    """
    server = await serve_tracker(opts)
    if store is None:
        store = ShardedSwarmStore(
            n_shards=n_shards,
            interval=(opts.interval if opts else DEFAULT_ANNOUNCE_INTERVAL),
        )
    tracker = ShardedTracker(store)

    def _metrics() -> str:
        from torrent_tpu.obs.hist import histograms
        from torrent_tpu.utils.metrics import render_tracker_metrics

        snap = store.metrics_snapshot()
        if indexer is not None:
            snap["indexer"] = indexer.snapshot()
        return render_tracker_metrics(snap) + histograms().render()

    server.metrics_provider = _metrics

    # pump liveness for GET /v1/health: the pump stamps every cycle
    # (it wakes at least every 5 s on an idle queue), so a stale stamp
    # means the drive loop is wedged and the LB should pull this node
    pump_state = {"tick": time.monotonic()}

    def _health() -> dict:
        from torrent_tpu.obs.slo import armed, build_health

        engine = armed()
        return build_health(
            pump_age_s=time.monotonic() - pump_state["tick"],
            pump_max_age_s=PUMP_MAX_AGE_S,
            slo_report=engine.report() if engine is not None else None,
        )

    server.health_provider = _health

    # sweep enough shards per tick that a full round-robin cycle always
    # completes within one peer TTL, whatever the shard count — with 64
    # shards a one-shard-per-minute cadence would leave dead peers
    # servable for ~an hour
    import math

    shards_per_tick = max(
        1,
        math.ceil(store.n_shards * SWEEP_TICK / max(store.peer_ttl, SWEEP_TICK)),
    )

    async def pump():
        last_sweep = time.monotonic()
        it = server.__aiter__()
        while True:
            pump_state["tick"] = time.monotonic()
            try:
                req = await asyncio.wait_for(it.__anext__(), timeout=5.0)
            except asyncio.TimeoutError:
                req = None
            except StopAsyncIteration:
                break
            batch = ([req] if req is not None else []) + server.drain_nowait(
                MAX_BATCH
            )
            if batch:
                try:
                    await tracker.handle_batch(batch)
                except Exception:
                    log.exception("announce batch failed; tracker continues")
            if time.monotonic() - last_sweep > SWEEP_TICK:
                for _ in range(shards_per_tick):
                    store.sweep_one()
                last_sweep = time.monotonic()

    task = asyncio.create_task(pump())
    task.tracker = tracker  # expose state for tests/stats
    task.store = store
    task.pump_state = pump_state
    return server, task


def main(argv=None) -> int:  # pragma: no cover - manual entrypoint
    import argparse

    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--http-port", type=int, default=8000)
    parser.add_argument(
        "--udp-port", type=int, default=6969, help="negative value disables UDP"
    )
    parser.add_argument("--interval", type=int, default=600)
    parser.add_argument("--shards", type=int, default=DEFAULT_SHARDS)
    args = parser.parse_args(argv)

    async def go():
        server, task = await run_sharded_tracker(
            ServeOptions(
                http_port=args.http_port,
                udp_port=args.udp_port if args.udp_port >= 0 else None,
                interval=args.interval,
            ),
            n_shards=args.shards,
        )
        print(
            f"sharded tracker listening: http={server.http_port} "
            f"udp={server.udp_port} shards={args.shards}"
        )
        await task

    asyncio.run(go())
    return 0


if __name__ == "__main__":  # pragma: no cover
    main()
