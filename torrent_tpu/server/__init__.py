from torrent_tpu.server.tracker import (
    AnnounceRequest,
    HttpAnnounceRequest,
    HttpScrapeRequest,
    ScrapeRequest,
    ServeOptions,
    TrackerServer,
    UdpAnnounceRequest,
    UdpScrapeRequest,
    serve_tracker,
)
from torrent_tpu.server.in_memory import InMemoryTracker, run_tracker
from torrent_tpu.server.shard import (
    AnnounceOutcome,
    ShardedSwarmStore,
    ShardedTracker,
    run_sharded_tracker,
)

__all__ = [
    "AnnounceOutcome",
    "AnnounceRequest",
    "ScrapeRequest",
    "HttpAnnounceRequest",
    "HttpScrapeRequest",
    "UdpAnnounceRequest",
    "UdpScrapeRequest",
    "ServeOptions",
    "ShardedSwarmStore",
    "ShardedTracker",
    "TrackerServer",
    "serve_tracker",
    "run_sharded_tracker",
    "InMemoryTracker",
    "run_tracker",
]
