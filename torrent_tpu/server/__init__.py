from torrent_tpu.server.tracker import (
    AnnounceRequest,
    HttpAnnounceRequest,
    HttpScrapeRequest,
    ScrapeRequest,
    ServeOptions,
    TrackerServer,
    UdpAnnounceRequest,
    UdpScrapeRequest,
    serve_tracker,
)
from torrent_tpu.server.in_memory import InMemoryTracker, run_tracker

__all__ = [
    "AnnounceRequest",
    "ScrapeRequest",
    "HttpAnnounceRequest",
    "HttpScrapeRequest",
    "UdpAnnounceRequest",
    "UdpScrapeRequest",
    "ServeOptions",
    "TrackerServer",
    "serve_tracker",
    "InMemoryTracker",
    "run_tracker",
]
