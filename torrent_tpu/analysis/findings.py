"""Finding model + committed-baseline bookkeeping for the lint plane.

A :class:`Finding` is one violation one pass raised at one site. Its
identity (:attr:`Finding.key`) deliberately excludes the line number:
baselines must survive unrelated edits above a finding, so the key is
``pass::path::symbol::message`` — stable until the finding itself moves
to a different function or changes meaning.

The committed baseline (``torrent_tpu/analysis_baseline.json``, shipped
as package data) records the findings the tree currently carries *on
purpose*, each with a human justification string. The lint gate fails only on findings NOT
in the baseline — new hazards — so the suite stays green while the
debt list stays visible and reviewed.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field


@dataclass(frozen=True)
class Finding:
    """One violation raised by one analysis pass."""

    pass_name: str  # e.g. "lock-order"
    path: str       # repo-relative posix path, e.g. "torrent_tpu/sched/scheduler.py"
    line: int       # 1-based; informational only (not part of the key)
    symbol: str     # enclosing qualname ("Class.method", "<module>")
    message: str    # stable description — no line numbers, no volatile state
    # taint flow: ((path, line, note), ...) source→propagation→sink steps.
    # Informational like ``line`` — rendered as SARIF codeFlows, never
    # part of the key (a flow re-route through the same sink is the
    # same accepted finding).
    flow: tuple = field(default=(), compare=False)

    @property
    def key(self) -> str:
        return f"{self.pass_name}::{self.path}::{self.symbol}::{self.message}"

    def format(self) -> str:
        return f"{self.path}:{self.line}: [{self.pass_name}] {self.message} ({self.symbol})"


@dataclass
class BaselineEntry:
    """One accepted finding with its review justification."""

    pass_name: str
    path: str
    symbol: str
    message: str
    justification: str = ""

    @property
    def key(self) -> str:
        return f"{self.pass_name}::{self.path}::{self.symbol}::{self.message}"


@dataclass
class BaselineDiff:
    new: list = field(default_factory=list)        # Findings not in baseline -> gate fails
    known: list = field(default_factory=list)      # Findings covered by baseline
    stale: list = field(default_factory=list)      # BaselineEntries no current finding matches


def dedupe_findings(findings) -> list:
    """One finding per key — the earliest site. Several sites of one
    hazard share one baseline entry anyway, so extra sites add noise,
    not signal. Output order is deterministic (path, line, message)."""
    best: dict[str, Finding] = {}
    for f in findings:
        prev = best.get(f.key)
        if prev is None or f.line < prev.line:
            best[f.key] = f
    return sorted(best.values(), key=lambda f: (f.path, f.line, f.message))


def load_baseline(path) -> dict[str, BaselineEntry]:
    """Baseline file -> {key: entry}. A missing file is an empty
    baseline (every finding is new), not an error."""
    try:
        with open(path) as f:
            doc = json.load(f)
    except FileNotFoundError:
        return {}
    entries = {}
    for raw in doc.get("findings", []):
        e = BaselineEntry(
            pass_name=raw["pass"],
            path=raw["path"],
            symbol=raw["symbol"],
            message=raw["message"],
            justification=raw.get("justification", ""),
        )
        entries[e.key] = e
    return entries


def diff_baseline(findings, baseline: dict[str, BaselineEntry]) -> BaselineDiff:
    diff = BaselineDiff()
    seen: set[str] = set()
    for f in findings:
        seen.add(f.key)
        (diff.known if f.key in baseline else diff.new).append(f)
    diff.stale = [e for k, e in baseline.items() if k not in seen]
    return diff


def save_baseline(findings, path, keep: dict[str, BaselineEntry] | None = None) -> None:
    """Write the baseline for ``findings``, preserving justification
    strings from ``keep`` (the previous baseline) where keys match."""
    keep = keep or {}
    out, emitted = [], set()
    for f in sorted(findings, key=lambda f: (f.path, f.pass_name, f.symbol, f.message)):
        if f.key in emitted:  # two sites of the same finding share one entry
            continue
        emitted.add(f.key)
        prev = keep.get(f.key)
        out.append(
            {
                "pass": f.pass_name,
                "path": f.path,
                "symbol": f.symbol,
                "message": f.message,
                "justification": prev.justification if prev else "TODO: justify or fix",
            }
        )
    with open(path, "w") as fh:
        json.dump({"version": 1, "findings": out}, fh, indent=2)
        fh.write("\n")
