"""``python -m torrent_tpu.analysis`` — the lint gate."""

from torrent_tpu.analysis.lint import main

raise SystemExit(main())
