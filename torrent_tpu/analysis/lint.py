"""``torrent-tpu lint`` / ``python -m torrent_tpu.analysis`` — the gate.

Runs the four analysis passes over the package and compares the
findings against the committed baseline (``torrent_tpu/
analysis_baseline.json``): exit 0 when every finding is baselined (each baseline
entry carries a reviewed justification), exit 1 on any NEW finding.
Stale baseline entries (the finding was fixed) are reported but do not
fail — refresh with ``--update-baseline``.

    torrent-tpu lint                      # gate against the baseline
    torrent-tpu lint --json               # machine-readable findings
    torrent-tpu lint --graph              # dump the lock-order graph
    torrent-tpu lint --update-baseline    # re-baseline (keeps justifications)
    torrent-tpu lint --no-baseline        # raw findings, exit 1 if any
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

from torrent_tpu.analysis.findings import (
    diff_baseline,
    load_baseline,
    save_baseline,
)
from torrent_tpu.analysis.passes import ALL_PASS_NAMES, run_passes
from torrent_tpu.analysis.passes import lock_order as _lock_order


def default_root() -> Path:
    import torrent_tpu

    return Path(torrent_tpu.__file__).resolve().parent


def default_baseline(root: Path) -> Path:
    # inside the package (shipped as package data), so the gate works
    # on pip installs as well as source checkouts
    return root / "analysis_baseline.json"


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="torrent-tpu lint",
        description="concurrency/invariant static analysis over torrent_tpu",
    )
    ap.add_argument(
        "--root", default=None,
        help="package directory to lint (default: the installed torrent_tpu)",
    )
    ap.add_argument(
        "--baseline", default=None,
        help="baseline JSON path (default: analysis_baseline.json inside the package)",
    )
    ap.add_argument(
        "--passes", default=None, metavar="A,B",
        help=f"comma-separated subset of: {', '.join(ALL_PASS_NAMES)}",
    )
    ap.add_argument(
        "--no-baseline", action="store_true",
        help="ignore the baseline: report raw findings, exit 1 if any",
    )
    ap.add_argument(
        "--update-baseline", action="store_true",
        help="rewrite the baseline from current findings (justifications "
        "on unchanged entries are preserved; new entries get a TODO)",
    )
    ap.add_argument("--json", action="store_true", help="JSON findings report")
    ap.add_argument(
        "--graph", action="store_true",
        help="also dump the static lock-acquisition graph",
    )
    args = ap.parse_args(argv)

    root = Path(args.root) if args.root else default_root()
    if not root.is_dir():
        print(f"error: {root} is not a directory", file=sys.stderr)
        return 2
    baseline_path = Path(args.baseline) if args.baseline else default_baseline(root)
    pass_names = (
        [p.strip() for p in args.passes.split(",") if p.strip()]
        if args.passes
        else None
    )
    try:
        findings, index = run_passes(root, pass_names)
    except (SyntaxError, ValueError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2

    if args.graph:
        print("# static lock-acquisition graph")
        print(_lock_order.render_graph(index) or "(no edges)")
        print()

    if args.update_baseline:
        if pass_names is not None:
            # a subset run only produced a subset of findings — writing
            # it would silently delete every other pass's entries (and
            # their reviewed justifications)
            print(
                "error: --update-baseline requires a full run "
                "(drop --passes)",
                file=sys.stderr,
            )
            return 2
        prev = load_baseline(baseline_path)
        save_baseline(findings, baseline_path, keep=prev)
        print(f"baseline written: {baseline_path} ({len(findings)} findings)")
        return 0

    baseline = {} if args.no_baseline else load_baseline(baseline_path)
    diff = diff_baseline(findings, baseline)

    if args.json:
        print(
            json.dumps(
                {
                    "ok": not diff.new,
                    "new": [f.__dict__ for f in diff.new],
                    "baselined": [f.__dict__ for f in diff.known],
                    "stale_baseline": [e.__dict__ for e in diff.stale],
                }
            )
        )
        return 1 if diff.new else 0

    for f in diff.new:
        print(f"NEW  {f.format()}")
    if diff.stale:
        for e in diff.stale:
            print(f"stale baseline entry (fixed?): {e.key}")
    print(
        f"lint: {len(findings)} finding(s) — {len(diff.known)} baselined, "
        f"{len(diff.new)} new, {len(diff.stale)} stale baseline entr"
        f"{'y' if len(diff.stale) == 1 else 'ies'}"
    )
    return 1 if diff.new else 0


if __name__ == "__main__":  # pragma: no cover - module entrypoint
    raise SystemExit(main())
