"""``torrent-tpu lint`` / ``python -m torrent_tpu.analysis`` — the gate.

Runs the eight analysis passes over the package and compares the
findings against the committed baseline (``torrent_tpu/
analysis_baseline.json``): exit 0 when every finding is baselined (each baseline
entry carries a reviewed justification), exit 1 on any NEW finding.
Stale baseline entries (the finding was fixed) are reported but do not
fail — refresh with ``--update-baseline`` or drop just them with
``--prune-stale``. Taint findings (wire-taint) carry their full
source→propagation→sink flow, emitted as SARIF ``codeFlows``.

    torrent-tpu lint                      # gate against the baseline
    torrent-tpu lint --json               # machine-readable findings
    torrent-tpu lint --graph              # lock-order graph + attr->guard map
    torrent-tpu lint --sarif out.sarif    # SARIF 2.1.0 report (CI annotations)
    torrent-tpu lint --update-baseline    # re-baseline (keeps justifications)
    torrent-tpu lint --prune-stale        # drop baseline entries nothing matches
    torrent-tpu lint --no-baseline        # raw findings, exit 1 if any
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

from torrent_tpu.analysis.findings import (
    diff_baseline,
    load_baseline,
    save_baseline,
)
from torrent_tpu.analysis.passes import ALL_PASS_NAMES, PASSES, run_passes
from torrent_tpu.analysis.passes import guarded_state as _guarded_state
from torrent_tpu.analysis.passes import lock_order as _lock_order

SARIF_VERSION = "2.1.0"
SARIF_SCHEMA = (
    "https://raw.githubusercontent.com/oasis-tcs/sarif-spec/master/"
    "Schemata/sarif-schema-2.1.0.json"
)


def _pass_rule(name: str) -> dict:
    """One SARIF reportingDescriptor per analysis pass, described from
    the pass module's own docstring headline."""
    mod = PASSES[name]
    doc = (mod.__doc__ or "").strip().splitlines()
    head = doc[0].split("—", 1)[-1].strip() if doc else name
    return {
        "id": name,
        "name": name,
        "shortDescription": {"text": head or name},
    }


def sarif_report(findings, baseline) -> dict:
    """SARIF 2.1.0 document for ALL findings. Baselined findings carry
    an ``external`` suppression with the reviewed justification, so CI
    diff annotators show only the new ones while the full debt list
    stays machine-readable."""
    results = []
    for f in findings:
        entry = baseline.get(f.key)
        # URIs stay repo-relative with no uriBaseId: consumers (GitHub
        # code scanning et al.) resolve them against the checkout root,
        # which is exactly where "torrent_tpu/..." paths live
        result = {
            "ruleId": f.pass_name,
            "level": "error",
            "message": {"text": f"{f.message} ({f.symbol})"},
            "locations": [
                {
                    "physicalLocation": {
                        "artifactLocation": {"uri": f.path},
                        "region": {"startLine": max(1, f.line)},
                    }
                }
            ],
            "partialFingerprints": {"torrentTpuFindingKey": f.key},
        }
        if f.flow:
            # dataflow findings are attack paths, not line numbers: one
            # threadFlow from the decode boundary through every
            # propagation hop to the sink
            result["codeFlows"] = [
                {
                    "threadFlows": [
                        {
                            "locations": [
                                {
                                    "location": {
                                        "physicalLocation": {
                                            "artifactLocation": {"uri": path},
                                            "region": {
                                                "startLine": max(1, line)
                                            },
                                        },
                                        "message": {"text": note},
                                    }
                                }
                                for (path, line, note) in f.flow
                            ]
                        }
                    ]
                }
            ]
        if entry is not None:
            result["suppressions"] = [
                {
                    "kind": "external",
                    "justification": entry.justification,
                }
            ]
        results.append(result)
    return {
        "$schema": SARIF_SCHEMA,
        "version": SARIF_VERSION,
        "runs": [
            {
                "tool": {
                    "driver": {
                        "name": "torrent-tpu-lint",
                        "informationUri": "https://github.com/rclarey/torrent",
                        "rules": [_pass_rule(n) for n in ALL_PASS_NAMES],
                    }
                },
                "results": results,
            }
        ],
    }


def default_root() -> Path:
    import torrent_tpu

    return Path(torrent_tpu.__file__).resolve().parent


def default_baseline(root: Path) -> Path:
    # inside the package (shipped as package data), so the gate works
    # on pip installs as well as source checkouts
    return root / "analysis_baseline.json"


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="torrent-tpu lint",
        description="concurrency/invariant static analysis over torrent_tpu",
    )
    ap.add_argument(
        "--root", default=None,
        help="package directory to lint (default: the installed torrent_tpu)",
    )
    ap.add_argument(
        "--baseline", default=None,
        help="baseline JSON path (default: analysis_baseline.json inside the package)",
    )
    ap.add_argument(
        "--passes", default=None, metavar="A,B",
        help=f"comma-separated subset of: {', '.join(ALL_PASS_NAMES)}",
    )
    ap.add_argument(
        "--no-baseline", action="store_true",
        help="ignore the baseline: report raw findings, exit 1 if any",
    )
    ap.add_argument(
        "--update-baseline", action="store_true",
        help="rewrite the baseline from current findings (justifications "
        "on unchanged entries are preserved; new entries get a TODO)",
    )
    ap.add_argument(
        "--prune-stale", action="store_true",
        help="rewrite the baseline WITHOUT entries no current finding "
        "matches (fixed debt); justifications on live entries are kept",
    )
    ap.add_argument("--json", action="store_true", help="JSON findings report")
    ap.add_argument(
        "--graph", action="store_true",
        help="also dump the static lock-acquisition graph and the "
        "inferred attr->guard map",
    )
    ap.add_argument(
        "--sarif", default=None, metavar="PATH",
        help="also write findings as SARIF 2.1.0 (baselined findings "
        "carry their justification as a suppression)",
    )
    args = ap.parse_args(argv)

    root = Path(args.root) if args.root else default_root()
    if not root.is_dir():
        print(f"error: {root} is not a directory", file=sys.stderr)
        return 2
    baseline_path = Path(args.baseline) if args.baseline else default_baseline(root)
    pass_names = (
        [p.strip() for p in args.passes.split(",") if p.strip()]
        if args.passes
        else None
    )
    try:
        findings, index = run_passes(root, pass_names)
    except (SyntaxError, ValueError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2

    if args.graph:
        print("# static lock-acquisition graph")
        print(_lock_order.render_graph(index) or "(no edges)")
        print()
        print("# inferred attribute guards (guarded-state pass)")
        print(_guarded_state.render_guard_map(index) or "(no guarded attributes)")
        print()

    if args.update_baseline:
        if pass_names is not None:
            # a subset run only produced a subset of findings — writing
            # it would silently delete every other pass's entries (and
            # their reviewed justifications)
            print(
                "error: --update-baseline requires a full run "
                "(drop --passes)",
                file=sys.stderr,
            )
            return 2
        prev = load_baseline(baseline_path)
        save_baseline(findings, baseline_path, keep=prev)
        print(f"baseline written: {baseline_path} ({len(findings)} findings)")
        if args.sarif:
            # suppressions come from the baseline just written, so the
            # artifact and the gate agree
            doc = sarif_report(findings, load_baseline(baseline_path))
            with open(args.sarif, "w") as fh:
                json.dump(doc, fh, indent=2)
                fh.write("\n")
            print(
                f"sarif written: {args.sarif} ({len(findings)} results)",
                file=sys.stderr,
            )
        return 0

    if args.prune_stale:
        if pass_names is not None:
            # a subset run can't tell "fixed" from "pass not run": every
            # entry of a skipped pass would look stale and be deleted
            print(
                "error: --prune-stale requires a full run (drop --passes)",
                file=sys.stderr,
            )
            return 2
        prev = load_baseline(baseline_path)
        diff = diff_baseline(findings, prev)
        if not diff.stale:
            print("baseline has no stale entries — nothing to prune")
            return 0
        live = {k: e for k, e in prev.items()
                if k not in {e.key for e in diff.stale}}
        with open(baseline_path, "w") as fh:
            json.dump(
                {
                    "version": 1,
                    "findings": [
                        {
                            "pass": e.pass_name,
                            "path": e.path,
                            "symbol": e.symbol,
                            "message": e.message,
                            "justification": e.justification,
                        }
                        for e in live.values()
                    ],
                },
                fh,
                indent=2,
            )
            fh.write("\n")
        for e in diff.stale:
            print(f"pruned: {e.key}")
        print(
            f"baseline written: {baseline_path} "
            f"({len(live)} entries, {len(diff.stale)} pruned)"
        )
        return 0

    baseline = {} if args.no_baseline else load_baseline(baseline_path)
    diff = diff_baseline(findings, baseline)

    if args.sarif:
        doc = sarif_report(findings, baseline)
        with open(args.sarif, "w") as fh:
            json.dump(doc, fh, indent=2)
            fh.write("\n")
        # stderr: --sarif composes with --json, whose stdout is a document
        print(
            f"sarif written: {args.sarif} ({len(findings)} results)",
            file=sys.stderr,
        )

    if args.json:
        print(
            json.dumps(
                {
                    "ok": not diff.new,
                    "new": [f.__dict__ for f in diff.new],
                    "baselined": [f.__dict__ for f in diff.known],
                    "stale_baseline": [e.__dict__ for e in diff.stale],
                }
            )
        )
        return 1 if diff.new else 0

    for f in diff.new:
        print(f"NEW  {f.format()}")
    if diff.stale:
        for e in diff.stale:
            print(f"stale baseline entry (fixed?): {e.key}")
    print(
        f"lint: {len(findings)} finding(s) — {len(diff.known)} baselined, "
        f"{len(diff.new)} new, {len(diff.stale)} stale baseline entr"
        f"{'y' if len(diff.stale) == 1 else 'ies'}"
    )
    return 1 if diff.new else 0


if __name__ == "__main__":  # pragma: no cover - module entrypoint
    raise SystemExit(main())
