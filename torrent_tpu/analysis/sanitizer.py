"""tsan-lite: the runtime concurrency sanitizer (``TORRENT_TPU_TSAN=1``).

The static passes under-approximate (ambiguous call names are not
traversed); this is the dynamic complement. When enabled, every lock the
package creates through :func:`named_lock` is a :class:`SanitizedLock`:
a plain ``threading.Lock`` plus, on each acquisition,

* **lock-order recording** — the acquiring thread's held-set becomes
  edges in a dynamic acquisition graph; a new edge that closes a cycle
  is an observed ABBA hazard, recorded (and asserted zero by
  ``tests/conftest.py`` at session end, so the whole tier-1 suite
  doubles as a concurrency test);
* **wait/hold accounting** — per-lock total wait seconds, max hold
  seconds, acquisition and contention counts, exported through
  ``utils/metrics.py`` ``render_tsan_metrics`` → ``/metrics``;
* **hold-time watchdog** — a daemon thread flags any lock held longer
  than ``TORRENT_TPU_TSAN_HOLD_S`` (default 10 s) while it is still
  held, naming the lock and the owning thread.

Independent of locks, enabling also installs an **event-loop stall
monitor**: ``asyncio``'s callback runner is wrapped so any single
callback exceeding ``TORRENT_TPU_TSAN_STALL_S`` (default 0.5 s) —
sync IO or jit dispatch on the serving loop, the blocking-in-async
hazard class at runtime — increments a stall counter with the max
observed stall.

**Dynamic lockset checking (Eraser).** The static ``guarded-state``
pass cannot see cross-object mutations or ambiguous calls; the
:func:`guard_attrs` / :func:`guarded_cell` registration API is its
runtime complement. A *cell* is one logical piece of shared state
(breaker state, a staging free list, a slab refcount, a shard's stat
counters, the ledger's stage table); instrumented call sites report
reads/writes and the cell runs Eraser's state machine —

    virgin → exclusive(first thread) → shared / shared-modified

— initializing its candidate lockset from the per-thread held-set
:class:`SanitizedLock` already maintains when a second thread arrives,
and intersecting it on every subsequent access. A shared-modified cell
whose lockset empties is an observed data race: logged, counted
(``lockset_races`` in :func:`snapshot`,
``torrent_tpu_lockset_races_total`` on ``/metrics``), dumped to the
flight recorder once, and turned into a failed session by
``tests/conftest.py`` exactly like a lock-order cycle. When TSAN is
off, ``guard_attrs`` returns a shared no-op group — zero state, zero
behavior change.

Node identity in the dynamic graph is the lock's *name* (the
:func:`named_lock` annotation, e.g. ``"sched.lane.build_lock"``), not
the instance: all lanes' build locks are one node, which is what lock
*ordering* is about. Same-name self-edges are counted separately
(``same_name_nesting``) rather than reported as cycles — two distinct
instances of one class's lock may legally nest.

When TSAN is off, :func:`named_lock` returns a plain
``threading.Lock`` — zero overhead, zero behavior change.
"""

from __future__ import annotations

import os
import threading
import time

from torrent_tpu.utils.log import get_logger

log = get_logger("analysis.tsan")

_TSAN_ENV = "TORRENT_TPU_TSAN"
_HOLD_ENV = "TORRENT_TPU_TSAN_HOLD_S"
_STALL_ENV = "TORRENT_TPU_TSAN_STALL_S"

_enabled = False


def tsan_env_set() -> bool:
    return os.environ.get(_TSAN_ENV, "") in ("1", "true")


def is_enabled() -> bool:
    return _enabled or tsan_env_set()


def _hold_threshold() -> float:
    try:
        return float(os.environ.get(_HOLD_ENV, "") or 10.0)
    except ValueError:
        return 10.0


def _stall_threshold() -> float:
    try:
        return float(os.environ.get(_STALL_ENV, "") or 0.5)
    except ValueError:
        return 0.5


class _LockStats:
    __slots__ = ("acquisitions", "contended", "wait_total", "hold_max")

    def __init__(self):
        self.acquisitions = 0
        self.contended = 0
        self.wait_total = 0.0
        self.hold_max = 0.0


class _CellStats:
    """Per-cell-NAME aggregate (instances come and go with their owning
    objects; the name-level counters persist for metrics)."""

    __slots__ = ("instances", "races")

    def __init__(self):
        self.instances = 0
        self.races = 0


class _Cell:
    """One guarded memory cell's Eraser state. Owned by its
    :class:`CellGroup` (and thus by the instrumented object), so cell
    state is garbage-collected with the object; only the name-level
    aggregates live in :class:`TsanState`."""

    __slots__ = ("name", "state", "owner", "lockset", "raced", "_lock")

    def __init__(self, name: str):
        self.name = name
        self.state = "virgin"  # -> exclusive -> shared[-modified]
        self.owner: int | None = None
        self.lockset: set[str] | None = None
        self.raced = False
        # plain per-cell lock: accesses normally arrive already
        # serialized by the guard under test, but racy code (the point)
        # must not corrupt the checker itself
        self._lock = threading.Lock()


# bound on retained race descriptions (the counter keeps counting)
_MAX_RACES = 100


class TsanState:
    """All sanitizer state. One module-global instance backs the
    process; tests may construct private ones and hand them to
    :class:`SanitizedLock` directly."""

    def __init__(self):
        # the meta lock guards everything below; it is a PLAIN lock
        # (sanitizing the sanitizer would recurse) and is only ever
        # held for dict updates — never across user code
        self._meta = threading.Lock()
        self._tls = threading.local()
        self.edges: dict[str, set[str]] = {}
        self.cycles: list[tuple[str, ...]] = []
        self._cycle_keys: set[tuple[str, ...]] = set()
        self.locks: dict[str, _LockStats] = {}
        self.same_name_nesting = 0
        self.long_holds = 0
        self.loop_stalls = 0
        self.loop_stall_max = 0.0
        # id(lock) -> (name, thread name, since) for the hold watchdog
        self._held_registry: dict[int, tuple[str, str, float]] = {}
        self._watchdog_flagged: set[int] = set()
        # Eraser: per-cell-name aggregates + observed races
        self.cells: dict[str, _CellStats] = {}
        self.lockset_races: list[str] = []
        self.lockset_race_count = 0

    # ------------------------------------------------------- lock hooks

    def _stack(self) -> list:
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = self._tls.stack = []
        return stack

    def on_acquired(self, lock, name: str, waited: float) -> None:
        stack = self._stack()
        now = time.monotonic()
        new_cycle: tuple[str, ...] | None = None
        with self._meta:
            st = self.locks.get(name)
            if st is None:
                st = self.locks[name] = _LockStats()
            st.acquisitions += 1
            st.wait_total += waited
            if waited > 1e-3:
                st.contended += 1
            for held_name, _held_id in stack:
                if held_name == name:
                    self.same_name_nesting += 1
                    continue
                cyc = self._add_edge(held_name, name)
                if cyc is not None:
                    new_cycle = cyc
            self._held_registry[id(lock)] = (
                name,
                threading.current_thread().name,
                now,
            )
        stack.append((name, id(lock)))
        if new_cycle is not None:
            # flight-recorder trigger OUTSIDE the meta lock: the dump
            # itself acquires (sanitized) obs locks and re-takes meta
            # for its tsan snapshot
            _notify_cycle(self, new_cycle)

    def on_released(self, lock, name: str) -> None:
        now = time.monotonic()
        stack = self._stack()
        # releases may be out of LIFO order: drop the newest matching entry
        for i in range(len(stack) - 1, -1, -1):
            if stack[i][1] == id(lock):
                del stack[i]
                break
        with self._meta:
            entry = self._held_registry.pop(id(lock), None)
            self._watchdog_flagged.discard(id(lock))
            if entry is not None:
                st = self.locks.get(name)
                if st is not None:
                    st.hold_max = max(st.hold_max, now - entry[2])

    def _add_edge(self, frm: str, to: str) -> tuple[str, ...] | None:
        """Record frm -> to (held while acquiring); detect a new cycle.
        Caller holds the meta lock. Returns the normalized cycle when
        this edge closed a NEW one (the caller notifies the flight
        recorder after releasing meta), else None."""
        outs = self.edges.setdefault(frm, set())
        if to in outs:
            return None
        outs.add(to)
        # does `frm` become reachable from `to` now? DFS on a small graph
        seen = set()
        path = self._find_path(to, frm, seen)
        if path is not None:
            cyc = tuple(path)
            k = cyc.index(min(cyc))
            norm = cyc[k:] + cyc[:k]
            if norm not in self._cycle_keys:
                self._cycle_keys.add(norm)
                self.cycles.append(norm)
                log.error(
                    "tsan: lock-order cycle observed: %s",
                    " -> ".join(norm + (norm[0],)),
                )
                return norm
        return None

    def _find_path(self, start: str, goal: str, seen: set) -> list | None:
        if start == goal:
            return [start]
        seen.add(start)
        for nxt in self.edges.get(start, ()):
            if nxt in seen:
                continue
            sub = self._find_path(nxt, goal, seen)
            if sub is not None:
                return [start] + sub
        return None

    # -------------------------------------------------- lockset checking

    def register_cell(self, name: str) -> _Cell:
        with self._meta:
            st = self.cells.get(name)
            if st is None:
                st = self.cells[name] = _CellStats()
            st.instances += 1
        return _Cell(name)

    def on_cell_access(self, cell: _Cell, write: bool) -> None:
        """Eraser's per-access step: advance the cell's state machine and
        refine its candidate lockset with the locks this thread holds."""
        held = {name for name, _lid in self._stack()}
        tid = threading.get_ident()
        race: str | None = None
        with cell._lock:
            if cell.state == "virgin":
                cell.state = "exclusive"
                cell.owner = tid
            elif cell.state == "exclusive":
                if tid != cell.owner:
                    # second thread: start lockset tracking here (the
                    # initialization-then-handoff idiom stays silent)
                    cell.state = "shared_modified" if write else "shared"
                    cell.lockset = set(held)
                    if write and not cell.lockset and not cell.raced:
                        cell.raced = True
                        race = self._race_msg(cell, write)
            else:
                if write and cell.state == "shared":
                    cell.state = "shared_modified"
                cell.lockset &= held
                if (
                    cell.state == "shared_modified"
                    and not cell.lockset
                    and not cell.raced
                ):
                    cell.raced = True
                    race = self._race_msg(cell, write)
        if race is not None:
            with self._meta:
                st = self.cells.get(cell.name)
                if st is not None:
                    st.races += 1
                self.lockset_race_count += 1
                if len(self.lockset_races) < _MAX_RACES:
                    self.lockset_races.append(race)
            log.error("tsan: %s", race)
            _notify_race(self, race)

    @staticmethod
    def _race_msg(cell: _Cell, write: bool) -> str:
        return (
            f"lockset race on cell {cell.name}: candidate lockset emptied "
            f"on a {'write' if write else 'read'} by thread "
            f"{threading.current_thread().name} (state {cell.state})"
        )

    # ------------------------------------------------- watchdog / stalls

    def watchdog_scan(self) -> None:
        threshold = _hold_threshold()
        now = time.monotonic()
        with self._meta:
            for key, (name, thread, since) in list(self._held_registry.items()):
                if now - since > threshold and key not in self._watchdog_flagged:
                    self._watchdog_flagged.add(key)
                    self.long_holds += 1
                    log.warning(
                        "tsan: lock %s held %.1fs by thread %s (threshold %.1fs)",
                        name, now - since, thread, threshold,
                    )

    def on_stall(self, seconds: float) -> None:
        with self._meta:
            self.loop_stalls += 1
            self.loop_stall_max = max(self.loop_stall_max, seconds)
            log.warning("tsan: event-loop callback stalled %.3fs", seconds)

    # ----------------------------------------------------------- output

    def snapshot(self) -> dict:
        with self._meta:
            return {
                "enabled": is_enabled(),
                "locks": {
                    name: {
                        "acquisitions": st.acquisitions,
                        "contended": st.contended,
                        "wait_total_s": st.wait_total,
                        "hold_max_s": st.hold_max,
                    }
                    for name, st in sorted(self.locks.items())
                },
                "edges": sum(len(v) for v in self.edges.values()),
                "cycles": [list(c) for c in self.cycles],
                "same_name_nesting": self.same_name_nesting,
                "long_holds": self.long_holds,
                "loop_stalls": self.loop_stalls,
                "loop_stall_max_s": self.loop_stall_max,
                "cells": {
                    name: {"instances": st.instances, "races": st.races}
                    for name, st in sorted(self.cells.items())
                },
                "lockset_races": list(self.lockset_races),
                "lockset_race_count": self.lockset_race_count,
            }


_state = TsanState()


def _notify_cycle(state: "TsanState", cycle: tuple[str, ...]) -> None:
    """One black-box dump per newly observed lock-order cycle. Global
    state only: tests drive private TsanState instances through
    deliberate cycles and must not pollute the process recorder. Lazy
    import — obs depends on this module for named_lock."""
    if state is not _state:
        return
    try:
        from torrent_tpu.obs.recorder import flight_recorder

        flight_recorder().trigger(
            "tsan_cycle", detail={"cycle": list(cycle)}
        )
    except Exception:  # the sanitizer must never take the process down
        log.exception("tsan cycle flight-recorder dump failed")


def _notify_race(state: "TsanState", race: str) -> None:
    """One black-box dump per observed lockset race (global state only,
    same contract as :func:`_notify_cycle`)."""
    if state is not _state:
        return
    try:
        from torrent_tpu.obs.recorder import flight_recorder

        flight_recorder().trigger("tsan_lockset_race", detail={"race": race})
    except Exception:  # the sanitizer must never take the process down
        log.exception("tsan lockset-race flight-recorder dump failed")


def global_state() -> TsanState:
    return _state


def snapshot() -> dict:
    return _state.snapshot()


# --------------------------------------------------------- guarded cells


class CellGroup:
    """A bundle of guarded cells owned by one object.

    ``guard_attrs("sched.breaker", "state")`` at construction, then
    ``self._cells.write("state")`` at each mutation site and
    ``self._cells.read("state")`` at each cross-thread read site —
    always placed INSIDE the critical section that claims to guard the
    cell, so the held-set the checker samples is the one the access
    actually ran under."""

    __slots__ = ("_cells", "_state")

    def __init__(self, owner: str, names, state: TsanState):
        self._state = state
        self._cells = {n: state.register_cell(f"{owner}.{n}") for n in names}

    def read(self, cell: str) -> None:
        self._state.on_cell_access(self._cells[cell], False)

    def write(self, cell: str) -> None:
        self._state.on_cell_access(self._cells[cell], True)


class _NullCells:
    """TSAN-off stand-in: one shared instance, no state, no overhead
    beyond a no-op method call at instrumented sites."""

    __slots__ = ()

    def read(self, cell: str) -> None:
        pass

    def write(self, cell: str) -> None:
        pass


_NULL_CELLS = _NullCells()


def guard_attrs(owner: str, *cells: str, state: TsanState | None = None):
    """Register ``cells`` (logical shared-state members of ``owner``)
    for dynamic lockset checking. Returns a :class:`CellGroup` when the
    sanitizer is on (or an explicit ``state`` is given — tests), else
    the shared no-op group. Name convention mirrors :func:`named_lock`:
    ``<area>.<owner>`` + the cell name, e.g.
    ``guard_attrs("sched.slab", "refs")`` → cell ``sched.slab.refs``."""
    if state is not None:
        return CellGroup(owner, cells, state)
    if is_enabled():
        _autoenable()
        return CellGroup(owner, cells, _state)
    return _NULL_CELLS


class _SingleCell:
    __slots__ = ("_cell", "_state")

    def __init__(self, cell: _Cell, state: TsanState):
        self._cell = cell
        self._state = state

    def read(self) -> None:
        self._state.on_cell_access(self._cell, False)

    def write(self) -> None:
        self._state.on_cell_access(self._cell, True)


class _NullCell:
    __slots__ = ()

    def read(self) -> None:
        pass

    def write(self) -> None:
        pass


_NULL_CELL = _NullCell()


def guarded_cell(name: str, state: TsanState | None = None):
    """Single-cell form of :func:`guard_attrs` for module-level shared
    state: ``_cell = guarded_cell("native.engine")``; then
    ``_cell.read()`` / ``_cell.write()`` at access sites."""
    if state is not None:
        return _SingleCell(state.register_cell(name), state)
    if is_enabled():
        _autoenable()
        return _SingleCell(_state.register_cell(name), _state)
    return _NULL_CELL


class SanitizedLock:
    """``threading.Lock`` with acquisition-order + timing recording."""

    __slots__ = ("_name", "_lock", "_state")

    def __init__(self, name: str, state: TsanState | None = None):
        self._name = name
        self._lock = threading.Lock()
        self._state = state or _state

    @property
    def name(self) -> str:
        return self._name

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        t0 = time.monotonic()
        ok = self._lock.acquire(blocking, timeout)
        if ok:
            self._state.on_acquired(self, self._name, time.monotonic() - t0)
        return ok

    def release(self) -> None:
        self._state.on_released(self, self._name)
        self._lock.release()

    def locked(self) -> bool:
        return self._lock.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False


def named_lock(name: str):
    """The package's lock constructor: a plain ``threading.Lock`` when
    TSAN is off, a :class:`SanitizedLock` recording under ``name`` when
    on. Name convention: ``<area>.<owner>.<attr>`` with the attribute
    name last (``"sched.lane.build_lock"``), so dynamic nodes map back
    to the static pass's canonical lock names."""
    if is_enabled():
        _autoenable()
        return SanitizedLock(name)
    return threading.Lock()


# ------------------------------------------------------------- enabling

_watchdog_started = False
_loop_patched = False


def _watchdog_main() -> None:  # pragma: no cover - timing-dependent
    while True:
        time.sleep(max(0.05, _hold_threshold() / 4))
        _state.watchdog_scan()


def _start_watchdog() -> None:
    global _watchdog_started
    if _watchdog_started:
        return
    _watchdog_started = True
    t = threading.Thread(target=_watchdog_main, name="tsan-watchdog", daemon=True)
    t.start()


def _install_loop_monitor() -> None:
    """Wrap asyncio's callback runner so any single callback exceeding
    the stall threshold is counted — the runtime form of the
    blocking-in-async pass."""
    global _loop_patched
    if _loop_patched:
        return
    _loop_patched = True
    import asyncio.events as events

    orig = events.Handle._run

    def _run(self):
        t0 = time.monotonic()
        try:
            return orig(self)
        finally:
            dt = time.monotonic() - t0
            if dt > _stall_threshold():
                _state.on_stall(dt)

    events.Handle._run = _run


def _autoenable() -> None:
    global _enabled
    if not _enabled:
        _enabled = True
        _start_watchdog()
        _install_loop_monitor()


def enable() -> None:
    """Turn the sanitizer on programmatically (tests/conftest). Locks
    created BEFORE this call stay plain; enable as early as possible —
    before importing the modules whose locks you want instrumented."""
    _autoenable()
