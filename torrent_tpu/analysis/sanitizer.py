"""tsan-lite: the runtime concurrency sanitizer (``TORRENT_TPU_TSAN=1``).

The static passes under-approximate (ambiguous call names are not
traversed); this is the dynamic complement. When enabled, every lock the
package creates through :func:`named_lock` is a :class:`SanitizedLock`:
a plain ``threading.Lock`` plus, on each acquisition,

* **lock-order recording** — the acquiring thread's held-set becomes
  edges in a dynamic acquisition graph; a new edge that closes a cycle
  is an observed ABBA hazard, recorded (and asserted zero by
  ``tests/conftest.py`` at session end, so the whole tier-1 suite
  doubles as a concurrency test);
* **wait/hold accounting** — per-lock total wait seconds, max hold
  seconds, acquisition and contention counts, exported through
  ``utils/metrics.py`` ``render_tsan_metrics`` → ``/metrics``;
* **hold-time watchdog** — a daemon thread flags any lock held longer
  than ``TORRENT_TPU_TSAN_HOLD_S`` (default 10 s) while it is still
  held, naming the lock and the owning thread.

Independent of locks, enabling also installs an **event-loop stall
monitor**: ``asyncio``'s callback runner is wrapped so any single
callback exceeding ``TORRENT_TPU_TSAN_STALL_S`` (default 0.5 s) —
sync IO or jit dispatch on the serving loop, the blocking-in-async
hazard class at runtime — increments a stall counter with the max
observed stall.

Node identity in the dynamic graph is the lock's *name* (the
:func:`named_lock` annotation, e.g. ``"sched.lane.build_lock"``), not
the instance: all lanes' build locks are one node, which is what lock
*ordering* is about. Same-name self-edges are counted separately
(``same_name_nesting``) rather than reported as cycles — two distinct
instances of one class's lock may legally nest.

When TSAN is off, :func:`named_lock` returns a plain
``threading.Lock`` — zero overhead, zero behavior change.
"""

from __future__ import annotations

import os
import threading
import time

from torrent_tpu.utils.log import get_logger

log = get_logger("analysis.tsan")

_TSAN_ENV = "TORRENT_TPU_TSAN"
_HOLD_ENV = "TORRENT_TPU_TSAN_HOLD_S"
_STALL_ENV = "TORRENT_TPU_TSAN_STALL_S"

_enabled = False


def tsan_env_set() -> bool:
    return os.environ.get(_TSAN_ENV, "") in ("1", "true")


def is_enabled() -> bool:
    return _enabled or tsan_env_set()


def _hold_threshold() -> float:
    try:
        return float(os.environ.get(_HOLD_ENV, "") or 10.0)
    except ValueError:
        return 10.0


def _stall_threshold() -> float:
    try:
        return float(os.environ.get(_STALL_ENV, "") or 0.5)
    except ValueError:
        return 0.5


class _LockStats:
    __slots__ = ("acquisitions", "contended", "wait_total", "hold_max")

    def __init__(self):
        self.acquisitions = 0
        self.contended = 0
        self.wait_total = 0.0
        self.hold_max = 0.0


class TsanState:
    """All sanitizer state. One module-global instance backs the
    process; tests may construct private ones and hand them to
    :class:`SanitizedLock` directly."""

    def __init__(self):
        # the meta lock guards everything below; it is a PLAIN lock
        # (sanitizing the sanitizer would recurse) and is only ever
        # held for dict updates — never across user code
        self._meta = threading.Lock()
        self._tls = threading.local()
        self.edges: dict[str, set[str]] = {}
        self.cycles: list[tuple[str, ...]] = []
        self._cycle_keys: set[tuple[str, ...]] = set()
        self.locks: dict[str, _LockStats] = {}
        self.same_name_nesting = 0
        self.long_holds = 0
        self.loop_stalls = 0
        self.loop_stall_max = 0.0
        # id(lock) -> (name, thread name, since) for the hold watchdog
        self._held_registry: dict[int, tuple[str, str, float]] = {}
        self._watchdog_flagged: set[int] = set()

    # ------------------------------------------------------- lock hooks

    def _stack(self) -> list:
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = self._tls.stack = []
        return stack

    def on_acquired(self, lock, name: str, waited: float) -> None:
        stack = self._stack()
        now = time.monotonic()
        new_cycle: tuple[str, ...] | None = None
        with self._meta:
            st = self.locks.get(name)
            if st is None:
                st = self.locks[name] = _LockStats()
            st.acquisitions += 1
            st.wait_total += waited
            if waited > 1e-3:
                st.contended += 1
            for held_name, _held_id in stack:
                if held_name == name:
                    self.same_name_nesting += 1
                    continue
                cyc = self._add_edge(held_name, name)
                if cyc is not None:
                    new_cycle = cyc
            self._held_registry[id(lock)] = (
                name,
                threading.current_thread().name,
                now,
            )
        stack.append((name, id(lock)))
        if new_cycle is not None:
            # flight-recorder trigger OUTSIDE the meta lock: the dump
            # itself acquires (sanitized) obs locks and re-takes meta
            # for its tsan snapshot
            _notify_cycle(self, new_cycle)

    def on_released(self, lock, name: str) -> None:
        now = time.monotonic()
        stack = self._stack()
        # releases may be out of LIFO order: drop the newest matching entry
        for i in range(len(stack) - 1, -1, -1):
            if stack[i][1] == id(lock):
                del stack[i]
                break
        with self._meta:
            entry = self._held_registry.pop(id(lock), None)
            self._watchdog_flagged.discard(id(lock))
            if entry is not None:
                st = self.locks.get(name)
                if st is not None:
                    st.hold_max = max(st.hold_max, now - entry[2])

    def _add_edge(self, frm: str, to: str) -> tuple[str, ...] | None:
        """Record frm -> to (held while acquiring); detect a new cycle.
        Caller holds the meta lock. Returns the normalized cycle when
        this edge closed a NEW one (the caller notifies the flight
        recorder after releasing meta), else None."""
        outs = self.edges.setdefault(frm, set())
        if to in outs:
            return None
        outs.add(to)
        # does `frm` become reachable from `to` now? DFS on a small graph
        seen = set()
        path = self._find_path(to, frm, seen)
        if path is not None:
            cyc = tuple(path)
            k = cyc.index(min(cyc))
            norm = cyc[k:] + cyc[:k]
            if norm not in self._cycle_keys:
                self._cycle_keys.add(norm)
                self.cycles.append(norm)
                log.error(
                    "tsan: lock-order cycle observed: %s",
                    " -> ".join(norm + (norm[0],)),
                )
                return norm
        return None

    def _find_path(self, start: str, goal: str, seen: set) -> list | None:
        if start == goal:
            return [start]
        seen.add(start)
        for nxt in self.edges.get(start, ()):
            if nxt in seen:
                continue
            sub = self._find_path(nxt, goal, seen)
            if sub is not None:
                return [start] + sub
        return None

    # ------------------------------------------------- watchdog / stalls

    def watchdog_scan(self) -> None:
        threshold = _hold_threshold()
        now = time.monotonic()
        with self._meta:
            for key, (name, thread, since) in list(self._held_registry.items()):
                if now - since > threshold and key not in self._watchdog_flagged:
                    self._watchdog_flagged.add(key)
                    self.long_holds += 1
                    log.warning(
                        "tsan: lock %s held %.1fs by thread %s (threshold %.1fs)",
                        name, now - since, thread, threshold,
                    )

    def on_stall(self, seconds: float) -> None:
        with self._meta:
            self.loop_stalls += 1
            self.loop_stall_max = max(self.loop_stall_max, seconds)
            log.warning("tsan: event-loop callback stalled %.3fs", seconds)

    # ----------------------------------------------------------- output

    def snapshot(self) -> dict:
        with self._meta:
            return {
                "enabled": is_enabled(),
                "locks": {
                    name: {
                        "acquisitions": st.acquisitions,
                        "contended": st.contended,
                        "wait_total_s": st.wait_total,
                        "hold_max_s": st.hold_max,
                    }
                    for name, st in sorted(self.locks.items())
                },
                "edges": sum(len(v) for v in self.edges.values()),
                "cycles": [list(c) for c in self.cycles],
                "same_name_nesting": self.same_name_nesting,
                "long_holds": self.long_holds,
                "loop_stalls": self.loop_stalls,
                "loop_stall_max_s": self.loop_stall_max,
            }


_state = TsanState()


def _notify_cycle(state: "TsanState", cycle: tuple[str, ...]) -> None:
    """One black-box dump per newly observed lock-order cycle. Global
    state only: tests drive private TsanState instances through
    deliberate cycles and must not pollute the process recorder. Lazy
    import — obs depends on this module for named_lock."""
    if state is not _state:
        return
    try:
        from torrent_tpu.obs.recorder import flight_recorder

        flight_recorder().trigger(
            "tsan_cycle", detail={"cycle": list(cycle)}
        )
    except Exception:  # the sanitizer must never take the process down
        log.exception("tsan cycle flight-recorder dump failed")


def global_state() -> TsanState:
    return _state


def snapshot() -> dict:
    return _state.snapshot()


class SanitizedLock:
    """``threading.Lock`` with acquisition-order + timing recording."""

    __slots__ = ("_name", "_lock", "_state")

    def __init__(self, name: str, state: TsanState | None = None):
        self._name = name
        self._lock = threading.Lock()
        self._state = state or _state

    @property
    def name(self) -> str:
        return self._name

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        t0 = time.monotonic()
        ok = self._lock.acquire(blocking, timeout)
        if ok:
            self._state.on_acquired(self, self._name, time.monotonic() - t0)
        return ok

    def release(self) -> None:
        self._state.on_released(self, self._name)
        self._lock.release()

    def locked(self) -> bool:
        return self._lock.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False


def named_lock(name: str):
    """The package's lock constructor: a plain ``threading.Lock`` when
    TSAN is off, a :class:`SanitizedLock` recording under ``name`` when
    on. Name convention: ``<area>.<owner>.<attr>`` with the attribute
    name last (``"sched.lane.build_lock"``), so dynamic nodes map back
    to the static pass's canonical lock names."""
    if is_enabled():
        _autoenable()
        return SanitizedLock(name)
    return threading.Lock()


# ------------------------------------------------------------- enabling

_watchdog_started = False
_loop_patched = False


def _watchdog_main() -> None:  # pragma: no cover - timing-dependent
    while True:
        time.sleep(max(0.05, _hold_threshold() / 4))
        _state.watchdog_scan()


def _start_watchdog() -> None:
    global _watchdog_started
    if _watchdog_started:
        return
    _watchdog_started = True
    t = threading.Thread(target=_watchdog_main, name="tsan-watchdog", daemon=True)
    t.start()


def _install_loop_monitor() -> None:
    """Wrap asyncio's callback runner so any single callback exceeding
    the stall threshold is counted — the runtime form of the
    blocking-in-async pass."""
    global _loop_patched
    if _loop_patched:
        return
    _loop_patched = True
    import asyncio.events as events

    orig = events.Handle._run

    def _run(self):
        t0 = time.monotonic()
        try:
            return orig(self)
        finally:
            dt = time.monotonic() - t0
            if dt > _stall_threshold():
                _state.on_stall(dt)

    events.Handle._run = _run


def _autoenable() -> None:
    global _enabled
    if not _enabled:
        _enabled = True
        _start_watchdog()
        _install_loop_monitor()


def enable() -> None:
    """Turn the sanitizer on programmatically (tests/conftest). Locks
    created BEFORE this call stay plain; enable as early as possible —
    before importing the modules whose locks you want instrumented."""
    _autoenable()
