"""Concurrency sanitizer & invariant lint plane.

Two halves, one contract — the concurrency invariants the hash plane is
built on are machine-checked, not review-enforced:

* **Static** (``analysis/passes/``): four AST passes over the package —
  ``lock-order`` (acquisition-graph cycles + the documented partial
  order ``build_lock → lock → _device_lock``, ``_counter_lock`` leaf),
  ``blocking-in-async`` (no sync stalls on serving loops),
  ``device-under-lock`` (only ``_device_lock`` guards plane entry),
  ``determinism`` (bit-stable bytes where fabric processes must agree).
  Gated by ``torrent-tpu lint`` against ``analysis_baseline.json``.
* **Dynamic** (``analysis/sanitizer.py``): tsan-lite. Under
  ``TORRENT_TPU_TSAN=1`` every :func:`named_lock` is instrumented —
  dynamic lock-order graph with cycle detection, wait/hold accounting
  (→ ``/metrics``), a hold-time watchdog, and an event-loop stall
  monitor. ``tests/conftest.py`` wires it into the whole suite.
"""

# The sanitizer is imported by every module that creates a named_lock,
# so this package __init__ must stay a leaf: the AST pass machinery
# (Finding, run_passes, ALL_PASS_NAMES) is loaded lazily on first
# attribute access (PEP 562), never at runtime-lock-construction time.
from torrent_tpu.analysis.sanitizer import (
    SanitizedLock,
    enable as enable_tsan,
    is_enabled as tsan_is_enabled,
    named_lock,
    snapshot as tsan_snapshot,
)

__all__ = [
    "ALL_PASS_NAMES",
    "Finding",
    "SanitizedLock",
    "enable_tsan",
    "named_lock",
    "run_passes",
    "tsan_is_enabled",
    "tsan_snapshot",
]

_LAZY = {
    "Finding": ("torrent_tpu.analysis.findings", "Finding"),
    "run_passes": ("torrent_tpu.analysis.passes", "run_passes"),
    "ALL_PASS_NAMES": ("torrent_tpu.analysis.passes", "ALL_PASS_NAMES"),
}


def __getattr__(name: str):
    try:
        module, attr = _LAZY[name]
    except KeyError:
        raise AttributeError(name) from None
    import importlib

    return getattr(importlib.import_module(module), attr)
