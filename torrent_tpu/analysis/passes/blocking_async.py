"""``blocking-in-async`` — no synchronous stalls on the event loop.

The PR 3 / PR 4 hazard class: a coroutine that calls ``time.sleep``,
does sync file or socket IO, blocks on a ``Future.result()``, probes
``jax.devices()`` (can hang for minutes behind a wedged device tunnel)
or dispatches jitted work stalls the WHOLE serving loop — every
concurrent connection, heartbeat, and deadline timer stops with it.

Scope: ``async def`` bodies in the packages that run event loops —
``bridge/``, ``session/``, ``fabric/``, ``net/``. Nested synchronous
``def``s inside a coroutine are exempt: that is exactly the
``asyncio.to_thread(worker)`` idiom the rule wants work moved into.
"""

from __future__ import annotations

import ast

from torrent_tpu.analysis.findings import Finding
from torrent_tpu.analysis.passes.common import (
    PackageIndex,
    dotted_name,
    tail_name,
)

PASS_NAME = "blocking-in-async"

SCOPE_DIRS = frozenset({"bridge", "session", "fabric", "net"})

# full dotted names that block
BLOCKING_DOTTED = frozenset(
    {
        "time.sleep",
        "jax.devices",
        "socket.socket",
        "socket.create_connection",
        "socket.getaddrinfo",
        "socket.gethostbyname",
        "subprocess.run",
        "subprocess.check_output",
        "subprocess.check_call",
        "subprocess.call",
        "os.system",
    }
)
# attribute tails that block regardless of receiver. ".result" is
# flagged only on zero-argument calls (the Future.result() shape) —
# domain methods named result(args...) are not futures.
BLOCKING_TAILS = frozenset({"block_until_ready"})
# builtins that block
BLOCKING_BUILTINS = frozenset({"open", "input"})
# jit dispatch: any call rooted at jnp enqueues device work synchronously
BLOCKING_ROOTS = ("jnp",)


def _in_scope(path: str) -> bool:
    parts = path.split("/")
    # repo-relative: torrent_tpu/<dir>/... (fixtures: <pkg>/<dir>/...)
    return len(parts) >= 3 and parts[1] in SCOPE_DIRS


def _blocking_token(call: ast.Call) -> str | None:
    dn = dotted_name(call.func)
    if dn:
        if dn in BLOCKING_DOTTED:
            return dn
        if dn.split(".", 1)[0] in BLOCKING_ROOTS:
            return dn
    if isinstance(call.func, ast.Name) and call.func.id in BLOCKING_BUILTINS:
        return call.func.id
    tail = tail_name(call.func)
    if tail in BLOCKING_TAILS:
        return f".{tail}()"
    if tail == "result" and not call.args and not call.keywords:
        return ".result()"
    return None


class _CoroWalker(ast.NodeVisitor):
    """Visits one coroutine body, not descending into nested defs."""

    def __init__(self):
        self.hits: list[tuple[str, int]] = []

    def visit_FunctionDef(self, node):  # nested sync def: to_thread idiom
        pass

    def visit_AsyncFunctionDef(self, node):  # nested coroutine: own entry
        pass

    def visit_Lambda(self, node):
        pass

    def visit_Call(self, node):
        token = _blocking_token(node)
        if token:
            self.hits.append((token, node.lineno))
        self.generic_visit(node)


def run(index: PackageIndex, files=None) -> list[Finding]:
    findings: list[Finding] = []
    for fn in index.functions:
        if not fn.is_async or not _in_scope(fn.module):
            continue
        w = _CoroWalker()
        for stmt in fn.node.body:
            w.visit(stmt)
        for token, line in w.hits:
            findings.append(
                Finding(
                    PASS_NAME,
                    fn.module,
                    line,
                    fn.qualname,
                    f"blocking call {token} in coroutine",
                )
            )
    return findings
