"""Pass registry: named analysis passes over a parsed package.

Each pass module exposes ``PASS_NAME`` and ``run(index, files) ->
list[Finding]``. ``run_passes`` is the one entry point: it parses the
package once, builds the shared :class:`~.common.PackageIndex`, and
runs the requested passes over it.
"""

from __future__ import annotations

import ast
import os
from pathlib import Path

from torrent_tpu.analysis.passes import (
    blocking_async,
    bounded_state,
    determinism,
    device_under_lock,
    guarded_state,
    lifecycle,
    lock_order,
    wire_taint,
)
from torrent_tpu.analysis.passes.common import ModuleFile, PackageIndex

PASSES = {
    lock_order.PASS_NAME: lock_order,
    blocking_async.PASS_NAME: blocking_async,
    device_under_lock.PASS_NAME: device_under_lock,
    determinism.PASS_NAME: determinism,
    guarded_state.PASS_NAME: guarded_state,
    lifecycle.PASS_NAME: lifecycle,
    wire_taint.PASS_NAME: wire_taint,
    bounded_state.PASS_NAME: bounded_state,
}

ALL_PASS_NAMES = tuple(PASSES)


def load_package(root) -> PackageIndex:
    """Parse every ``*.py`` under ``root`` into a PackageIndex. Paths
    are recorded relative to ``root``'s parent ("torrent_tpu/…"), the
    stable form baseline keys use."""
    root = Path(root)
    base = root.parent
    files: list[ModuleFile] = []
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = sorted(d for d in dirnames if d != "__pycache__")
        for name in sorted(filenames):
            if not name.endswith(".py"):
                continue
            path = Path(dirpath) / name
            rel = path.relative_to(base).as_posix()
            source = path.read_text()
            try:
                tree = ast.parse(source, filename=str(path))
            except SyntaxError as e:  # a broken file is its own problem
                raise SyntaxError(f"{rel}: {e}") from e
            files.append(ModuleFile(rel, tree, source))
    return PackageIndex(files)


def run_passes(root, pass_names=None):
    """Run the named passes (default: all) over the package at ``root``.
    Returns (findings, index)."""
    names = list(pass_names or ALL_PASS_NAMES)
    for n in names:
        if n not in PASSES:
            raise ValueError(
                f"unknown pass {n!r} (known: {', '.join(ALL_PASS_NAMES)})"
            )
    index = load_package(root)
    findings = []
    for n in names:
        findings.extend(PASSES[n].run(index, index.files))
    return findings, index
