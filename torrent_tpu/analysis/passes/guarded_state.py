"""``guarded-state`` — Eraser-style lockset inference for shared state.

The lock-order pass gates *how* locks nest and device-under-lock gates
*what runs under them*; nothing checked that shared mutable state is
guarded at all. This pass closes that hole statically, per class that
constructs a lock:

1. every ``self.<attr>`` read/write site is collected together with the
   lock set held there (``common.AttrSite`` — the same held-set
   machinery the other passes use), with held-sets propagated into
   private helpers through the resolved call graph: a ``_helper`` whose
   every intra-class call site holds ``_lock`` effectively runs under
   ``_lock`` (the ``*_locked`` convention, verified instead of trusted);
2. each attribute's **guard** is inferred as the intersection of locks
   held across its post-``__init__`` mutation sites (Eraser's C(v)
   rule applied statically);
3. findings:

   * **unguarded mutation** — the attribute has a non-empty inferred
     (or annotated) guard, but this mutation site holds none of it:
     the lockset has emptied, the classic Eraser report;
   * **mixed guards** — every mutation site is locked but no single
     lock is common to all of them (two locks each "guarding" half the
     sites guard nothing);
   * **unguarded read** — a read of a guard-mutated attribute holding
     no part of the guard, in a function reachable from a thread or
     coroutine entry point (``async def``, a ``Thread(target=…)`` /
     ``to_thread`` / executor-submit target, or any public callable —
     i.e. somewhere a second thread can actually be).

Exemptions (what keeps the pass precise enough to gate):

* ``__init__``/``__post_init__``/``__new__``/``__del__`` bodies —
  publication: the object is not shared yet (or no longer);
* immutable-after-start — attributes never mutated outside the exempt
  methods have nothing to guard;
* loop-confined state — attributes never mutated under ANY lock carry
  no inferred guard and stay silent (the event-loop single-writer
  discipline is the blocking-in-async pass's domain, not this one's);
* annotations — a ``# guarded-by: <lock>`` comment on any assignment
  line of the attribute pins its guard (mutations/reads are checked
  against the declaration instead of the inference), and
  ``# guarded-by: none`` declares the attribute deliberately unguarded
  (documented loop-confinement / benign monotonic flag) and exempts it
  entirely. Annotations are themselves checked: one naming a lock the
  class never constructs, or sitting on a line no attribute write
  occupies, is a finding — a typo'd declaration must not silently
  disable the check.

Like every static pass here this under-approximates: cross-object
mutations (``lane.x += 1`` from the scheduler) and ambiguous calls are
not traversed — the dynamic lockset checker in ``analysis/sanitizer.py``
(``guard_attrs``) is the runtime complement on exactly those seams.
"""

from __future__ import annotations

import ast
import re

from torrent_tpu.analysis.findings import Finding, dedupe_findings
from torrent_tpu.analysis.passes.common import (
    AttrSite,
    FunctionInfo,
    PackageIndex,
)

PASS_NAME = "guarded-state"

# publication scopes: the object is not yet (or no longer) shared
EXEMPT_METHODS = frozenset({"__init__", "__post_init__", "__new__", "__del__"})

# annotation syntax: "# guarded-by: <lock-attr>" or "# guarded-by: none"
# (end-anchored so prose mentions wrapped in ``...`` don't parse)
_ANNOTATION_RE = re.compile(
    r"#\s*guarded-by:\s*([A-Za-z_][A-Za-z_0-9]*|none)\s*$"
)

# call shapes whose function-valued argument runs on another thread
_THREAD_HANDOFF_TAILS = frozenset(
    {"to_thread", "submit", "run_in_executor", "call_soon_threadsafe",
     "start_new_thread"}
)

# fixpoint sentinel: "called only from contexts we have not resolved yet"
_TOP = None


def _annotations(source: str) -> dict[int, str]:
    """{lineno: guard-name} for every ``# guarded-by:`` comment line."""
    out: dict[int, str] = {}
    for i, line in enumerate(source.splitlines(), start=1):
        m = _ANNOTATION_RE.search(line)
        if m:
            out[i] = m.group(1)
    return out


def _class_locks(fns: list[FunctionInfo]) -> set[str]:
    """Lock attributes this class constructs: ``self.<x>lock = <call>``
    anywhere in its methods (``named_lock(…)``, ``threading.Lock()`` —
    the constructor call is the signal; storing ``None`` or a borrowed
    lock does not make the class a lock owner)."""
    locks: set[str] = set()
    for fn in fns:
        for node in ast.walk(fn.node):
            if not isinstance(node, ast.Assign) or not isinstance(
                node.value, ast.Call
            ):
                continue
            for tgt in node.targets:
                if (
                    isinstance(tgt, ast.Attribute)
                    and isinstance(tgt.value, ast.Name)
                    and tgt.value.id == "self"
                    and tgt.attr.lower().endswith("lock")
                ):
                    locks.add(tgt.attr)
    return locks


def _thread_target_names(index: PackageIndex) -> set[str]:
    """Bare/tail names of callables handed to another thread anywhere in
    the package: ``Thread(target=f)``, ``asyncio.to_thread(f, …)``,
    ``pool.submit(f, …)``, ``loop.run_in_executor(None, f)`` …"""
    names: set[str] = set()

    def _callable_name(arg) -> str | None:
        if isinstance(arg, ast.Name):
            return arg.id
        if isinstance(arg, ast.Attribute):
            return arg.attr
        return None

    for mf in index.files:
        for node in ast.walk(mf.tree):
            if not isinstance(node, ast.Call):
                continue
            for kw in node.keywords:
                if kw.arg == "target":
                    n = _callable_name(kw.value)
                    if n:
                        names.add(n)
            tail = (
                node.func.attr
                if isinstance(node.func, ast.Attribute)
                else node.func.id
                if isinstance(node.func, ast.Name)
                else None
            )
            if tail in _THREAD_HANDOFF_TAILS:
                for arg in node.args:
                    n = _callable_name(arg)
                    if n:
                        names.add(n)
    return names


def _entry_reachable(index: PackageIndex) -> set[int]:
    """ids of FunctionInfos reachable (via resolved calls) from a
    thread/coroutine entry point: coroutines, thread-handoff targets,
    dunders, and public callables (a second thread can start at any of
    them)."""
    targets = _thread_target_names(index)
    reach: set[int] = set()
    for fn in index.functions:
        if (
            fn.is_async
            or not fn.name.startswith("_")
            or (fn.name.startswith("__") and fn.name.endswith("__"))
            or fn.name in targets
        ):
            reach.add(id(fn))
    changed = True
    while changed:
        changed = False
        for fn in index.functions:
            if id(fn) not in reach:
                continue
            for site in fn.calls:
                callee = index.resolve(fn, site)
                if callee is not None and id(callee) not in reach:
                    reach.add(id(callee))
                    changed = True
    return reach


# cap on tracked caller contexts per method; past it, collapse to the
# single intersection context (precision degrades, soundness direction
# preserved: the intersection holds in EVERY context)
_MAX_CONTEXTS = 8


def _caller_contexts(
    index: PackageIndex, fns: list[FunctionInfo]
) -> dict[int, frozenset[frozenset[str]]]:
    """Per-method set of caller lock contexts.

    Public methods and dunders get ``{∅}`` (anyone may call them bare).
    A private method accumulates one context per intra-class call chain:
    the locks held at the call site ∪ each of the caller's own contexts,
    iterated to a fixpoint — so an access inside ``_helper`` is checked
    once per distinct way the class reaches ``_helper``. This is what
    both *verifies* the ``_locked``-suffix convention (every context
    holds the lock) and *catches* the lockset-empties-via-call hazard
    (one locked context, one bare context → the intersection is empty).
    Private methods with no resolved intra-class callers get ``{∅}``
    (they may be callbacks handed elsewhere)."""
    ids = {id(fn) for fn in fns}
    bare = frozenset([frozenset()])
    ctxs: dict[int, frozenset[frozenset[str]] | None] = {}
    pinned: set[int] = set()  # public/dunder: always callable bare
    for fn in fns:
        public = not fn.name.startswith("_") or (
            fn.name.startswith("__") and fn.name.endswith("__")
        )
        ctxs[id(fn)] = bare if public else _TOP
        if public:
            pinned.add(id(fn))
    # intra-class call edges: callee id -> [(caller id, held at site)]
    callers: dict[int, list[tuple[int, frozenset[str]]]] = {}
    for fn in fns:
        for site in fn.calls:
            callee = index.resolve(fn, site)
            if callee is None or id(callee) not in ids:
                continue
            callers.setdefault(id(callee), []).append(
                (id(fn), frozenset(site.held))
            )
    for _ in range(len(fns) + 2):
        changed = False
        for fn in fns:
            k = id(fn)
            if k in pinned:
                continue
            contributions: set[frozenset[str]] = set()
            unresolved = False
            for caller_id, held in callers.get(k, ()):
                c = ctxs.get(caller_id, bare)
                if c is _TOP:
                    unresolved = True
                    continue
                contributions.update(held | cc for cc in c)
            if not contributions:
                if k in callers and unresolved:
                    continue  # only unresolved (cyclic) callers so far
                # no intra-class callers at all: may be a callback
                new: frozenset[frozenset[str]] | None = bare
            else:
                if len(contributions) > _MAX_CONTEXTS:
                    meet = None
                    for c in contributions:
                        meet = c if meet is None else (meet & c)
                    contributions = {meet}
                new = frozenset(contributions)
            if new != ctxs[k]:
                ctxs[k] = new
                changed = True
        if not changed:
            break
    # anything still TOP is only reachable through unresolved cycles
    return {k: (bare if v is _TOP else v) for k, v in ctxs.items()}


def _class_groups(
    index: PackageIndex,
) -> dict[tuple[str, str], list[FunctionInfo]]:
    groups: dict[tuple[str, str], list[FunctionInfo]] = {}
    for fn in index.functions:
        if fn.cls is not None:
            groups.setdefault((fn.module, fn.cls), []).append(fn)
    return groups


class AttrGuard:
    """Inference result for one class attribute (``render_guard_map``
    and the finding logic share it)."""

    __slots__ = ("cls", "attr", "guard", "source", "module")

    def __init__(self, cls: str, attr: str, guard: frozenset[str],
                 source: str, module: str):
        self.cls = cls
        self.attr = attr
        self.guard = guard      # empty = no guard
        self.source = source    # 'inferred' | 'annotated' | 'annotated-none'
                                # | 'mixed' | 'unguarded'
        self.module = module

    @property
    def guard_str(self) -> str:
        return "+".join(sorted(self.guard)) if self.guard else "none"


def _declared_guards(
    fns: list[FunctionInfo], ann: dict[int, str]
) -> dict[str, tuple[str, int, str]]:
    """{attr: (declared guard, line, qualname)} from ``# guarded-by:``
    comments sitting on the attribute's write lines."""
    out: dict[str, tuple[str, int, str]] = {}
    if not ann:
        return out
    for fn in fns:
        for site in fn.attrs:
            if site.write and site.line in ann:
                out[site.attr] = (ann[site.line], site.line, fn.qualname)
    return out


def _analyze_class(
    index: PackageIndex,
    module: str,
    cls: str,
    fns: list[FunctionInfo],
    ann: dict[int, str],
    reachable: set[int],
    findings: list[Finding],
    guards_out: list[AttrGuard] | None = None,
    consumed: set[int] | None = None,
) -> None:
    declared = _declared_guards(fns, ann)
    if consumed is not None:
        consumed.update(line for _, line, _ in declared.values())
    locks = _class_locks(fns)
    if not locks:
        # no locks means nothing to check against — but a declaration
        # naming a guard here is already wrong, not merely unchecked
        for attr, (name, line, qual) in sorted(declared.items()):
            if name != "none":
                findings.append(
                    Finding(
                        PASS_NAME, module, line, qual,
                        f"guarded-by names {name!r}, but {cls} "
                        "constructs no locks — fix the annotation or "
                        "add the lock",
                    )
                )
        return
    ctxs = _caller_contexts(index, fns)

    # per-attr post-publication access sites, each expanded to one
    # virtual site per caller context: effs = {local held ∪ c}
    Sites = dict[str, list[tuple[FunctionInfo, AttrSite, list[frozenset[str]]]]]
    writes: Sites = {}
    reads: Sites = {}
    for fn in fns:
        if fn.name in EXEMPT_METHODS:
            continue
        for site in fn.attrs:
            held = frozenset(site.held)
            effs = [held | c for c in ctxs[id(fn)]]
            (writes if site.write else reads).setdefault(site.attr, []).append(
                (fn, site, effs)
            )

    for attr in sorted(set(writes) | set(declared)):
        decl = declared.get(attr)
        if decl is not None and decl[0] == "none":
            if guards_out is not None:
                guards_out.append(
                    AttrGuard(cls, attr, frozenset(), "annotated-none", module)
                )
            continue
        if decl is not None and decl[0] not in locks:
            # a declared guard that names no lock of the class is a
            # typo or a survivor of a rename: every mutation site
            # that trusts it is silently unchecked
            name, dline, dqual = decl
            findings.append(
                Finding(
                    PASS_NAME, module, dline, dqual,
                    f"guarded-by names {name!r}, which is not a lock "
                    f"of {cls} ({', '.join(sorted(locks))}) — fix "
                    "the annotation or add the lock",
                )
            )
            continue
        w = writes.get(attr, [])
        if not w:
            continue  # immutable after publication
        if decl is not None:
            guard = frozenset({decl[0]})
            source = "annotated"
        else:
            locked = [
                eff for _, _, effs in w for eff in effs if eff
            ]
            if not locked:
                # never mutated under any lock: loop-confined by
                # discipline, no guard to enforce
                if guards_out is not None:
                    guards_out.append(
                        AttrGuard(cls, attr, frozenset(), "unguarded", module)
                    )
                continue
            guard = locked[0]
            for eff in locked[1:]:
                guard = guard & eff
            if not guard:
                fn0, s0, _ = min(w, key=lambda t: t[1].line)
                findings.append(
                    Finding(
                        PASS_NAME, module, s0.line, fn0.qualname,
                        f"{cls}.{attr} has mixed guards: no lock is common "
                        "to all of its mutation sites",
                    )
                )
                if guards_out is not None:
                    guards_out.append(
                        AttrGuard(cls, attr, frozenset(), "mixed", module)
                    )
                continue
            source = "inferred"
        if guards_out is not None:
            guards_out.append(AttrGuard(cls, attr, guard, source, module))
        for fn, s, effs in w:
            if any(not (eff & guard) for eff in effs):
                findings.append(
                    Finding(
                        PASS_NAME, module, s.line, fn.qualname,
                        f"mutation of {cls}.{attr} outside its guard "
                        f"{'+'.join(sorted(guard))} empties the lockset",
                    )
                )
        for fn, s, effs in reads.get(attr, []):
            if id(fn) not in reachable:
                continue
            if any(not (eff & guard) for eff in effs):
                findings.append(
                    Finding(
                        PASS_NAME, module, s.line, fn.qualname,
                        f"unguarded read of {cls}.{attr} (guard "
                        f"{'+'.join(sorted(guard))}) reachable from a "
                        "thread/coroutine entry",
                    )
                )


def run(index: PackageIndex, files=None) -> list[Finding]:
    findings: list[Finding] = []
    reachable = _entry_reachable(index)
    ann_by_module = {mf.path: _annotations(mf.source) for mf in index.files}
    consumed_by_module: dict[str, set[int]] = {
        path: set() for path in ann_by_module
    }
    for (module, cls), fns in sorted(_class_groups(index).items()):
        _analyze_class(
            index, module, cls, fns, ann_by_module.get(module, {}),
            reachable, findings,
            consumed=consumed_by_module.setdefault(module, set()),
        )
    # an annotation no attribute write consumed is stale or misplaced —
    # it documents a guard discipline the checker never sees
    for module, ann in ann_by_module.items():
        for line in sorted(set(ann) - consumed_by_module[module]):
            findings.append(
                Finding(
                    PASS_NAME, module, line, "guarded-by annotation",
                    f"guarded-by: {ann[line]} sits on no attribute "
                    "write — move it onto a self.<attr> assignment "
                    "or delete it",
                )
            )
    return dedupe_findings(findings)


def guard_map(index: PackageIndex) -> list[AttrGuard]:
    """The inferred attr→guard table (``lint --graph`` and docs)."""
    guards: list[AttrGuard] = []
    reachable = _entry_reachable(index)
    ann_by_module = {mf.path: _annotations(mf.source) for mf in index.files}
    scratch: list[Finding] = []
    for (module, cls), fns in sorted(_class_groups(index).items()):
        _analyze_class(
            index, module, cls, fns, ann_by_module.get(module, {}),
            reachable, scratch, guards_out=guards,
        )
    return guards


def render_guard_map(index: PackageIndex) -> str:
    """Human-readable attr→guard dump, one line per guarded attribute."""
    lines = []
    for g in guard_map(index):
        lines.append(
            f"{g.cls}.{g.attr} -> {g.guard_str}  [{g.source}] {g.module}"
        )
    return "\n".join(lines)
