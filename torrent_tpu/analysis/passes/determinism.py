"""``determinism`` — bit-stable bytes where processes must agree.

The fabric's correctness rests on every process computing identical
answers from identical inputs: ``fabric/plan.py`` fingerprints the
shard assignment to prove plan agreement, and the heartbeat exchange's
coverage/adoption rules assume each process evaluates the same state.
Wall-clock reads, randomness, and unordered ``set``/``dict`` iteration
are the three ways nondeterminism leaks into those bytes.

Scope is explicit (``SCOPE``): all of ``fabric/plan.py``, the executor
functions that build, merge, or consume exchanged heartbeat state, and
the obs-plane helpers whose output rides those heartbeats
(``obs/tracer.py``'s span-context builders — trace ids and span
payloads exchanged between processes must be as bit-stable as the
verdicts they annotate). Within scope, the pass flags:

* wall-clock reads (``time.time``, ``datetime.now`` …) — cross-host
  clock skew turns these into divergent values;
* randomness (``random.*``, ``os.urandom``, ``uuid.*``, ``hash()`` —
  the latter is PYTHONHASHSEED-dependent);
* iteration over sets or ``dict.items()/keys()/values()`` whose order
  feeds the output, unless the iteration is consumed by an
  order-insensitive sink (``sorted``, ``min``, ``max``, ``sum``,
  ``any``, ``all``, ``len``, ``set``, ``frozenset``).

Set-typed attributes are recognized from ``self.x: set[...] = ...``
annotations in the class ``__init__``.
"""

from __future__ import annotations

import ast

from torrent_tpu.analysis.findings import Finding
from torrent_tpu.analysis.passes.common import (
    PackageIndex,
    dotted_name,
    tail_name,
)

PASS_NAME = "determinism"

# path suffix -> function names in scope ("*" = every function)
SCOPE: dict[str, frozenset[str]] = {
    "fabric/plan.py": frozenset({"*"}),
    # the Byzantine receipt plane: Merkle commitments, audit-sample
    # draws, and proof verification are ALL exchanged (or replayed)
    # bytes — pure by contract, so the whole module is in scope
    "fabric/receipts.py": frozenset({"*"}),
    # _own_bits is deliberately NOT in scope: its dict order provably
    # never reaches exchanged bytes (the payload sorts own.items() and
    # _published_done is a set)
    "fabric/executor.py": frozenset(
        {
            "_heartbeat_once",
            "_build_obs_digest",
            "_rebalance_offers",
            "bitfields",
            "pack_bits",
            "unpack_bits",
            "plan_payload_bytes",
            # Byzantine receipt builders: roots/evidence ride the
            # heartbeat, and the quorum grouping/need rules decide the
            # symmetric coverage every process must agree on
            "_receipt_payload",
            "_unit_root",
            "_quorum_groups",
            "_unit_need",
        }
    ),
    # the scheduler autopilot's decision core: decisions are pure
    # functions of snapshot deltas — the same sequence of snapshots
    # must always produce the same sequence of actuator moves (and the
    # rebalance offers ride the heartbeat exchange), so the decision
    # functions are held to the exchanged-bytes rules
    "sched/control.py": frozenset(
        {
            "decide",
            "build_inputs",
            "initial_state",
            "decision_summary",
            "_confirmed_stage",
            "_lane_decisions",
            "_admission_decision",
            "_backend_decisions",
        }
    ),
    # span context carried in fabric heartbeat payloads: the obs plane's
    # contribution to exchanged bytes must obey the same rules
    "obs/tracer.py": frozenset({"fabric_trace_id", "heartbeat_span_context"}),
    # the fleet obs digest rides the same heartbeats: every builder that
    # shapes exchanged digest bytes is held to the same bit-stability
    # rules (monotonic-only, no randomness, sorted iteration)
    "obs/fleet.py": frozenset(
        {
            "build_obs_digest",
            "clamp_digest",
            "digest_bytes",
            "obs_digest",
            "_digest_stages",
            "_digest_hist",
            "_digest_sched",
        }
    ),
    # the scenario plane's spec and verdict builders are pure by
    # contract: a spec must parse/serialize bit-identically and a
    # verdict is the artifact two same-seed replays are diffed on —
    # wall-clock reads, randomness, or unordered iteration anywhere in
    # these modules would break the doctor --scenario bit-identity gate
    "scenario/spec.py": frozenset({"*"}),
    "scenario/verdict.py": frozenset({"*"}),
    # the seeder plane's snapshot builders: the serve snapshot rides
    # /v1/swarm and the bench seed record (banked artifacts diffed
    # across runs), so the rollup must be bit-stable over equal raws
    "serve_plane/telemetry.py": frozenset(
        {
            "build_serve_snapshot",
            "_serve_peer_entry",
            "_serve_fold_entries",
        }
    ),
    # the SLO evaluators are pure functions over timeline samples (the
    # same determinism contract as decide() and the digest builders):
    # the same sample ring must always produce the same burn-rate
    # verdicts, breach transitions, and health strings — and the
    # digest_summary rides the heartbeat exchange
    "obs/slo.py": frozenset(
        {
            "evaluate_slo",
            "digest_summary",
            "build_health",
            "_counter_objective",
            "_eval_availability",
            "_eval_latency",
            "_eval_throughput",
            "_eval_integrity",
            "_eval_swarm_availability",
            "_eval_swarm_throughput",
            "_avail_counters",
            "_swarm_avail_counters",
            "_swarm_throughput_intervals",
            "_window_delta",
            "_hist_window",
            "_hist_errors",
            "_p99_estimate",
            "_throughput_intervals",
            "_integrity_counters_of",
            "_tail",
        }
    ),
    # the swarm wire plane's pure rollup builders (obs/swarm): the
    # snapshot feeds /v1/swarm, /metrics, bench records, and flight
    # dumps — same sorted-iteration / no-clock / no-randomness contract
    # as the digest builders (the registry finalizes every duration
    # BEFORE these run)
    "obs/swarm.py": frozenset(
        {
            "build_swarm_snapshot",
            "_peer_entry",
            "_fold_entries",
            "_rtt_summary",
        }
    ),
    # timeline sample builders + the offline replay attributor: samples
    # are dumped/replayed bytes (and the builders feed the digest-shaped
    # encodings), so they obey the same rules — the monotonic capture
    # instant is PASSED IN by the sampler, never read inside
    "obs/timeline.py": frozenset(
        {
            "build_sample",
            "replay_report",
            "_sample_sched",
            "_integrity_counters",
            "_sample_to_ledger",
        }
    ),
}

WALL_CLOCK = frozenset(
    {"time.time", "time.time_ns", "time.ctime", "datetime.now", "datetime.utcnow"}
)
RANDOM_ROOTS = ("random", "uuid", "secrets")
RANDOM_DOTTED = frozenset({"os.urandom"})
UNORDERED_METHODS = frozenset({"items", "keys", "values"})
ORDER_INSENSITIVE_SINKS = frozenset(
    {"sorted", "min", "max", "sum", "any", "all", "len", "set", "frozenset"}
)


def _scope_functions(path: str) -> frozenset[str] | None:
    for suffix, names in SCOPE.items():
        if path.endswith(suffix):
            return names
    return None


def _set_typed_attrs(tree: ast.Module) -> set[str]:
    """Attribute names annotated ``self.x: set[...]`` in any __init__."""
    attrs: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.AnnAssign) and isinstance(node.target, ast.Attribute):
            tgt = node.target
            ann = node.annotation
            base = ann.value if isinstance(ann, ast.Subscript) else ann
            if (
                isinstance(tgt.value, ast.Name)
                and tgt.value.id == "self"
                and isinstance(base, ast.Name)
                and base.id in ("set", "frozenset")
            ):
                attrs.add(tgt.attr)
    return attrs


class _DetWalker(ast.NodeVisitor):
    def __init__(self, set_attrs: set[str]):
        self.set_attrs = set_attrs
        self.hits: list[tuple[str, int]] = []
        self._sink_depth = 0

    # ------------------------------------------------------------ calls

    def visit_Call(self, node: ast.Call):
        dn = dotted_name(node.func)
        if dn:
            if dn in WALL_CLOCK:
                self.hits.append((f"wall-clock {dn}()", node.lineno))
            elif dn in RANDOM_DOTTED or dn.split(".", 1)[0] in RANDOM_ROOTS:
                self.hits.append((f"randomness {dn}()", node.lineno))
        if isinstance(node.func, ast.Name) and node.func.id == "hash":
            self.hits.append(
                ("PYTHONHASHSEED-dependent hash()", node.lineno)
            )
        if (
            isinstance(node.func, ast.Name)
            and node.func.id in ORDER_INSENSITIVE_SINKS
        ):
            self._sink_depth += 1
            self.generic_visit(node)
            self._sink_depth -= 1
            return
        self.generic_visit(node)

    # -------------------------------------------------------- iteration

    def _unordered_iter(self, expr) -> str | None:
        if isinstance(expr, ast.Call):
            tail = tail_name(expr.func)
            if tail in UNORDERED_METHODS and isinstance(expr.func, ast.Attribute):
                return f".{tail}()"
            if isinstance(expr.func, ast.Name) and expr.func.id in (
                "set",
                "frozenset",
            ):
                return f"{expr.func.id}(...)"
        if isinstance(expr, (ast.Set, ast.SetComp)):
            return "set literal"
        if (
            isinstance(expr, ast.Attribute)
            and isinstance(expr.value, ast.Name)
            and expr.value.id == "self"
            and expr.attr in self.set_attrs
        ):
            return f"self.{expr.attr} (set-typed)"
        return None

    def _check_iter(self, expr, line: int) -> None:
        if self._sink_depth:
            return
        what = self._unordered_iter(expr)
        if what:
            self.hits.append((f"unordered iteration over {what}", line))

    def visit_For(self, node: ast.For):
        self._check_iter(node.iter, node.lineno)
        self.generic_visit(node)

    def _visit_comp(self, node):
        for gen in node.generators:
            self._check_iter(gen.iter, node.lineno)
        self.generic_visit(node)

    visit_ListComp = _visit_comp
    visit_SetComp = _visit_comp
    visit_DictComp = _visit_comp
    visit_GeneratorExp = _visit_comp


def run(index: PackageIndex, files=None) -> list[Finding]:
    findings: list[Finding] = []
    set_attrs_by_module: dict[str, set[str]] = {}
    for mf in index.files:
        set_attrs_by_module[mf.path] = _set_typed_attrs(mf.tree)
    for fn in index.functions:
        names = _scope_functions(fn.module)
        if names is None or ("*" not in names and fn.name not in names):
            continue
        w = _DetWalker(set_attrs_by_module.get(fn.module, set()))
        for stmt in fn.node.body:
            w.visit(stmt)
        for what, line in w.hits:
            findings.append(
                Finding(
                    PASS_NAME,
                    fn.module,
                    line,
                    fn.qualname,
                    f"{what} in deterministic scope",
                )
            )
    return findings
