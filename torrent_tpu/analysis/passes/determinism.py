"""``determinism`` — bit-stable bytes where processes must agree.

The fabric's correctness rests on every process computing identical
answers from identical inputs: ``fabric/plan.py`` fingerprints the
shard assignment to prove plan agreement, and the heartbeat exchange's
coverage/adoption rules assume each process evaluates the same state.
Wall-clock reads, randomness, and unordered ``set``/``dict`` iteration
are the three ways nondeterminism leaks into those bytes.

Scope is declared IN the code it governs, by marker comment, and
discovered from the PackageIndex (for five PRs the scope lived here as
a hand-grown module list — which meant a new heartbeat/digest builder
silently dodged the pass until someone remembered to edit the linter):

* ``# determinism-scope: module`` anywhere in a file (conventionally
  right under the module docstring) puts every function of that module
  in scope — for modules that are pure by contract end to end
  (``fabric/plan.py``, ``fabric/receipts.py``, ``scenario/spec.py``,
  ``scenario/verdict.py``);
* ``# determinism-scope`` on a ``def`` line, or on the line directly
  above it, puts that one function in scope — for modules where only
  the exchanged-bytes builders are held to the contract.

A marker that governs nothing (not ``: module``, not attached to any
``def``) is itself a finding — a misplaced marker must not silently
drop a builder from scope. Within scope, the pass flags:

* wall-clock reads (``time.time``, ``datetime.now`` …) — cross-host
  clock skew turns these into divergent values;
* randomness (``random.*``, ``os.urandom``, ``uuid.*``, ``hash()`` —
  the latter is PYTHONHASHSEED-dependent);
* iteration over sets or ``dict.items()/keys()/values()`` whose order
  feeds the output, unless the iteration is consumed by an
  order-insensitive sink (``sorted``, ``min``, ``max``, ``sum``,
  ``any``, ``all``, ``len``, ``set``, ``frozenset``).

Set-typed attributes are recognized from ``self.x: set[...] = ...``
annotations in the class ``__init__``.
"""

from __future__ import annotations

import ast
import re

from torrent_tpu.analysis.findings import Finding
from torrent_tpu.analysis.passes.common import (
    PackageIndex,
    dotted_name,
    tail_name,
)

PASS_NAME = "determinism"

# ``# determinism-scope`` (function) / ``# determinism-scope: module``
_MARKER_RE = re.compile(r"#\s*determinism-scope(?::\s*(module))?\s*$")

WALL_CLOCK = frozenset(
    {"time.time", "time.time_ns", "time.ctime", "datetime.now", "datetime.utcnow"}
)
RANDOM_ROOTS = ("random", "uuid", "secrets")
RANDOM_DOTTED = frozenset({"os.urandom"})
UNORDERED_METHODS = frozenset({"items", "keys", "values"})
ORDER_INSENSITIVE_SINKS = frozenset(
    {"sorted", "min", "max", "sum", "any", "all", "len", "set", "frozenset"}
)


def _module_markers(source: str) -> tuple[bool, set[int]]:
    """Scan one module for scope markers.

    Returns ``(module_wide, lines)``: whether a ``: module`` marker puts
    the whole file in scope, and the 1-based lines of bare per-function
    markers (each must sit on a ``def`` line or directly above one).
    """
    module_wide = False
    lines: set[int] = set()
    for i, text in enumerate(source.splitlines(), start=1):
        m = _MARKER_RE.search(text)
        if m is None:
            continue
        if m.group(1) == "module":
            module_wide = True
        else:
            lines.add(i)
    return module_wide, lines


def _set_typed_attrs(tree: ast.Module) -> set[str]:
    """Attribute names annotated ``self.x: set[...]`` in any __init__."""
    attrs: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.AnnAssign) and isinstance(node.target, ast.Attribute):
            tgt = node.target
            ann = node.annotation
            base = ann.value if isinstance(ann, ast.Subscript) else ann
            if (
                isinstance(tgt.value, ast.Name)
                and tgt.value.id == "self"
                and isinstance(base, ast.Name)
                and base.id in ("set", "frozenset")
            ):
                attrs.add(tgt.attr)
    return attrs


class _DetWalker(ast.NodeVisitor):
    def __init__(self, set_attrs: set[str]):
        self.set_attrs = set_attrs
        self.hits: list[tuple[str, int]] = []
        self._sink_depth = 0

    # ------------------------------------------------------------ calls

    def visit_Call(self, node: ast.Call):
        dn = dotted_name(node.func)
        if dn:
            if dn in WALL_CLOCK:
                self.hits.append((f"wall-clock {dn}()", node.lineno))
            elif dn in RANDOM_DOTTED or dn.split(".", 1)[0] in RANDOM_ROOTS:
                self.hits.append((f"randomness {dn}()", node.lineno))
        if isinstance(node.func, ast.Name) and node.func.id == "hash":
            self.hits.append(
                ("PYTHONHASHSEED-dependent hash()", node.lineno)
            )
        if (
            isinstance(node.func, ast.Name)
            and node.func.id in ORDER_INSENSITIVE_SINKS
        ):
            self._sink_depth += 1
            self.generic_visit(node)
            self._sink_depth -= 1
            return
        self.generic_visit(node)

    # -------------------------------------------------------- iteration

    def _unordered_iter(self, expr) -> str | None:
        if isinstance(expr, ast.Call):
            tail = tail_name(expr.func)
            if tail in UNORDERED_METHODS and isinstance(expr.func, ast.Attribute):
                return f".{tail}()"
            if isinstance(expr.func, ast.Name) and expr.func.id in (
                "set",
                "frozenset",
            ):
                return f"{expr.func.id}(...)"
        if isinstance(expr, (ast.Set, ast.SetComp)):
            return "set literal"
        if (
            isinstance(expr, ast.Attribute)
            and isinstance(expr.value, ast.Name)
            and expr.value.id == "self"
            and expr.attr in self.set_attrs
        ):
            return f"self.{expr.attr} (set-typed)"
        return None

    def _check_iter(self, expr, line: int) -> None:
        if self._sink_depth:
            return
        what = self._unordered_iter(expr)
        if what:
            self.hits.append((f"unordered iteration over {what}", line))

    def visit_For(self, node: ast.For):
        self._check_iter(node.iter, node.lineno)
        self.generic_visit(node)

    def _visit_comp(self, node):
        for gen in node.generators:
            self._check_iter(gen.iter, node.lineno)
        self.generic_visit(node)

    visit_ListComp = _visit_comp
    visit_SetComp = _visit_comp
    visit_DictComp = _visit_comp
    visit_GeneratorExp = _visit_comp


def run(index: PackageIndex, files=None) -> list[Finding]:
    findings: list[Finding] = []
    set_attrs_by_module: dict[str, set[str]] = {}
    markers: dict[str, tuple[bool, set[int]]] = {}
    for mf in index.files:
        set_attrs_by_module[mf.path] = _set_typed_attrs(mf.tree)
        markers[mf.path] = _module_markers(mf.source)
    # per-function marker lines that actually attached to a def
    governing: dict[str, set[int]] = {path: set() for path in markers}
    for fn in index.functions:
        module_wide, lines = markers.get(fn.module, (False, set()))
        # fn.node.lineno is the ``def`` line even when decorated
        attached = {fn.node.lineno, fn.node.lineno - 1} & lines
        governing[fn.module] |= attached
        if not (module_wide or attached):
            continue
        w = _DetWalker(set_attrs_by_module.get(fn.module, set()))
        for stmt in fn.node.body:
            w.visit(stmt)
        for what, line in w.hits:
            findings.append(
                Finding(
                    PASS_NAME,
                    fn.module,
                    line,
                    fn.qualname,
                    f"{what} in deterministic scope",
                )
            )
    # a bare marker that attached to no def is stale: the function it
    # once governed moved or was renamed, and is now silently unchecked
    for path, (_, lines) in markers.items():
        for line in sorted(lines - governing[path]):
            findings.append(
                Finding(
                    PASS_NAME,
                    path,
                    line,
                    "determinism-scope marker",
                    "determinism-scope marker governs no function "
                    "(not on a def line or the line above one) — "
                    "move it or delete it",
                )
            )
    return findings
