"""``lock-order`` — the static lock-acquisition graph and its rules.

Builds the package-wide digraph of "lock B acquired while lock A is
held" edges: direct nesting (``with a: with b:``, linear
``acquire()``/``release()`` scopes) plus edges propagated through
resolved calls (holding ``a`` while calling a function that —
transitively — acquires ``b``). Then checks three rules:

1. **No cycles.** Any strongly-connected component (including a
   self-edge between same-named locks) is a potential ABBA deadlock.
2. **Documented partial order.** The project's lock hierarchy is
   ``build_lock → lock → _device_lock`` (lane plane construction,
   breaker state, device serialization — see ARCHITECTURE.md
   "Concurrency invariants"). An edge that acquires a lower-ranked
   lock while holding a higher-ranked one inverts the hierarchy.
3. **Leaf locks.** ``_counter_lock`` (cross-lane metrics counters) is
   documented leaf-only: nothing may be acquired while holding it.

Call resolution is conservative (see ``common.py``): ambiguous names
are not traversed, so this pass under-approximates — the runtime
sanitizer covers the dynamic remainder.
"""

from __future__ import annotations

from dataclasses import dataclass

from torrent_tpu.analysis.findings import Finding
from torrent_tpu.analysis.passes.common import FunctionInfo, PackageIndex

PASS_NAME = "lock-order"

# The documented partial order, outermost first. Locks not listed are
# unconstrained relative to these except through the cycle rule.
DOCUMENTED_ORDER = ("build_lock", "lock", "_device_lock")
# Locks nothing else may be acquired under.
LEAF_LOCKS = frozenset({"_counter_lock"})


@dataclass(frozen=True)
class Edge:
    held: str
    acquired: str
    module: str
    line: int
    symbol: str
    via_call: bool  # propagated through a resolved call, not direct nesting


def build_edges(index: PackageIndex) -> list[Edge]:
    edges: list[Edge] = []
    for fn in index.functions:
        for site in fn.acquires:
            for held in site.held:
                edges.append(
                    Edge(held, site.lock, fn.module, site.line, fn.qualname, False)
                )
        for site in fn.calls:
            if not site.held:
                continue
            callee = index.resolve(fn, site)
            if callee is None:
                continue
            for lock in sorted(index.transitive_acquires(callee)):
                for held in site.held:
                    edges.append(
                        Edge(held, lock, fn.module, site.line, fn.qualname, True)
                    )
    return edges


def _cycles(edges: list[Edge]) -> list[tuple[str, ...]]:
    """All elementary cycles reachable in the (small) lock graph,
    deduplicated by rotation-normalized node tuple."""
    graph: dict[str, set[str]] = {}
    for e in edges:
        graph.setdefault(e.held, set()).add(e.acquired)
    seen: set[tuple[str, ...]] = set()
    out: list[tuple[str, ...]] = []

    def dfs(start: str, node: str, path: list[str]) -> None:
        for nxt in sorted(graph.get(node, ())):
            if nxt == start:
                cyc = tuple(path)
                # rotate so the lexicographically smallest node leads
                k = cyc.index(min(cyc))
                norm = cyc[k:] + cyc[:k]
                if norm not in seen:
                    seen.add(norm)
                    out.append(norm)
            elif nxt not in path and len(path) < 8:
                dfs(start, nxt, path + [nxt])

    for node in sorted(graph):
        dfs(node, node, [node])
    return out


def run(index: PackageIndex, files=None) -> list[Finding]:
    edges = build_edges(index)
    findings: list[Finding] = []

    # one representative site per (held, acquired) pair for reporting
    rep: dict[tuple[str, str], Edge] = {}
    for e in edges:
        rep.setdefault((e.held, e.acquired), e)

    for cyc in _cycles(edges):
        chain = " -> ".join(cyc + (cyc[0],))
        # anchor the finding at the edge closing the cycle
        e = rep.get((cyc[-1], cyc[0])) or rep.get((cyc[0], cyc[1 % len(cyc)]))
        findings.append(
            Finding(
                PASS_NAME,
                e.module,
                e.line,
                e.symbol,
                f"lock-order cycle: {chain}",
            )
        )

    rank = {name: i for i, name in enumerate(DOCUMENTED_ORDER)}
    for (held, acquired), e in sorted(rep.items()):
        if held in rank and acquired in rank and rank[held] > rank[acquired]:
            findings.append(
                Finding(
                    PASS_NAME,
                    e.module,
                    e.line,
                    e.symbol,
                    f"acquisition {held} -> {acquired} inverts the documented "
                    f"order {' -> '.join(DOCUMENTED_ORDER)}",
                )
            )
        if held in LEAF_LOCKS:
            findings.append(
                Finding(
                    PASS_NAME,
                    e.module,
                    e.line,
                    e.symbol,
                    f"{held} is a leaf lock but {acquired} is acquired under it",
                )
            )
    return findings


def render_graph(index: PackageIndex) -> str:
    """Human-readable dump of the acquisition graph (``lint --graph``)."""
    edges = build_edges(index)
    rep: dict[tuple[str, str], Edge] = {}
    for e in edges:
        rep.setdefault((e.held, e.acquired), e)
    lines = []
    for (held, acquired), e in sorted(rep.items()):
        kind = "via-call" if e.via_call else "direct"
        lines.append(
            f"{held} -> {acquired}  [{kind}] {e.module}:{e.line} ({e.symbol})"
        )
    return "\n".join(lines)
