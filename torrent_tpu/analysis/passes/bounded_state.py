"""bounded-state: remote-keyed collections must have a visible cap.

The repo's cardinality discipline — tenant eviction, MAX_FLEET_PIDS,
histogram label caps, per-trace span caps, the per-IP accept clamp —
has been enforced by hand, one incident at a time. This pass turns it
into a gate: any instance collection (dict/set/list/defaultdict/
OrderedDict/deque) that *grows* under a key or value derived from the
remote (peer IP/id, info-hash, origin, tenant, trace id — by name, or
wire-tainted per the dataflow engine) must show one of:

* a **len-guard**: a ``len(self.attr)`` comparison anywhere in the
  class (the ``if len(self._hashes) >= self.max_hashes: evict`` idiom);
* a **deque maxlen** at construction;
* a **slice truncation** (``del self.attr[n:]`` / ``self.attr[n:] = []``);
* a ``# bounded-by: <cap>`` annotation on the construction or growth
  line, naming the symbol that bounds it out-of-band.

Plain per-key ``del``/``.pop`` (TTL expiry) is deliberately NOT
accepted: expiring old entries does not bound how many fresh keys an
attacker can mint inside one TTL window — exactly the bug class this
pass exists to catch. An annotation naming a cap symbol that does not
exist in the module/class is itself a finding.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field

from torrent_tpu.analysis.findings import Finding
from torrent_tpu.analysis.passes.common import MUTATING_METHODS, PackageIndex
from torrent_tpu.analysis.passes.dataflow import Registries, TaintAnalysis, _base_path

PASS_NAME = "bounded-state"

# substrings that mark a name as remote-derived (attacker-mintable).
# Deliberately concrete: generic names ("key", "token", "target",
# "host") false-positive on every internal map — a peer-keyed map that
# hides behind a generic name needs the taint engine to catch it, or a
# reviewer; this list is the *name* channel only.
REMOTE_KEY_MARKERS = (
    "info_hash", "infohash", "peer_id", "peer", "addr", "ip_",
    "origin", "tenant", "trace_id", "node_id", "sender",
)
# exact names (short forms too risky for substring matching)
REMOTE_KEY_EXACT = frozenset({"ih", "ip", "addr"})

GROW_METHODS = frozenset(
    {"setdefault", "add", "append", "appendleft", "insert", "extend",
     "extendleft", "update"}
)

_COLLECTION_CALLS = frozenset(
    {"dict", "set", "list", "defaultdict", "OrderedDict", "Counter", "deque"}
)

_BOUNDED_RE = re.compile(r"#\s*bounded-by:\s*([A-Za-z_][\w.]*)")


@dataclass
class _Collection:
    cls: str
    attr: str
    module: str
    line: int                      # construction line in __init__
    capped: bool = False           # len-guard / maxlen / truncation seen
    growth: list = field(default_factory=list)  # (line, fn, key_remote?)


def _collection_ctor(value) -> bool:
    """Is this __init__ RHS an empty/growable collection?"""
    if isinstance(value, (ast.Dict, ast.Set, ast.List)):
        return True
    if isinstance(value, ast.Call):
        from torrent_tpu.analysis.passes.common import tail_name

        name = tail_name(value.func)
        if name in _COLLECTION_CALLS:
            if name == "deque":
                for kw in value.keywords:
                    if kw.arg == "maxlen" and not (
                        isinstance(kw.value, ast.Constant)
                        and kw.value.value is None
                    ):
                        return False  # bounded by construction
            return True
    return False


def _names_in(expr) -> set[str]:
    out: set[str] = set()
    for node in ast.walk(expr):
        if isinstance(node, ast.Name):
            out.add(node.id)
        elif isinstance(node, ast.Attribute):
            out.add(node.attr)
        elif isinstance(node, ast.arg):
            out.add(node.arg)
    return out


def _looks_remote(expr) -> bool:
    for name in _names_in(expr):
        low = name.lower()
        if low in REMOTE_KEY_EXACT:
            return True
        if any(m in low for m in REMOTE_KEY_MARKERS):
            return True
    return False


def _is_tainted(expr, taint_engine) -> bool:
    if taint_engine is None:
        return False
    for node in ast.walk(expr):
        if isinstance(node, (ast.Name, ast.Attribute)):
            if taint_engine.trace_of(_base_path(node)) is not None:
                return True
    return False


def _self_attr_of(expr) -> str | None:
    if (
        isinstance(expr, ast.Attribute)
        and isinstance(expr.value, ast.Name)
        and expr.value.id == "self"
    ):
        return expr.attr
    return None


def taint_analysis_for(index: PackageIndex, regs: Registries) -> TaintAnalysis:
    """Memoized on the index: wire-taint and bounded-state share one
    interprocedural run when driven from the same ``run_passes``."""
    cached = getattr(index, "_taint_cache", None)
    if cached is None:
        cached = TaintAnalysis(index, regs)
        index._taint_cache = cached
    return cached


def annotations_by_line(source: str) -> dict[int, str]:
    out: dict[int, str] = {}
    for i, text in enumerate(source.splitlines(), start=1):
        m = _BOUNDED_RE.search(text)
        if m:
            out[i] = m.group(1)
    return out


def _module_symbols(tree: ast.Module, cls_name: str | None) -> set[str]:
    """Names a ``# bounded-by: <cap>`` annotation may legally cite:
    module globals, imports, class attributes, self attributes and
    parameters of the class's methods."""
    syms: set[str] = set()
    for node in tree.body:
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    syms.add(t.id)
        elif isinstance(node, ast.AnnAssign) and isinstance(node.target, ast.Name):
            syms.add(node.target.id)
        elif isinstance(node, (ast.Import, ast.ImportFrom)):
            for a in node.names:
                syms.add((a.asname or a.name).split(".")[0])
        elif isinstance(node, ast.ClassDef):
            syms.add(node.name)
            if cls_name is not None and node.name != cls_name:
                continue
            for sub in ast.walk(node):
                if isinstance(sub, ast.Assign):
                    for t in sub.targets:
                        if isinstance(t, ast.Name):
                            syms.add(t.id)
                        else:
                            a = _self_attr_of(t)
                            if a:
                                syms.add(a)
                elif isinstance(sub, ast.AnnAssign):
                    if isinstance(sub.target, ast.Name):
                        syms.add(sub.target.id)
                    else:
                        a = _self_attr_of(sub.target)
                        if a:
                            syms.add(a)
                elif isinstance(sub, ast.arg):
                    syms.add(sub.arg)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            syms.add(node.name)
    return syms


def run(index, files) -> list[Finding]:
    from torrent_tpu.analysis.passes import wire_taint

    analysis = taint_analysis_for(index, wire_taint.registries())
    trees = {mf.path: mf.tree for mf in files}
    ann = {mf.path: annotations_by_line(mf.source) for mf in files}

    # -- collect per-class collections from __init__
    colls: dict[tuple[str, str, str], _Collection] = {}
    for fn in index.functions:
        if fn.cls is None or fn.name != "__init__":
            continue
        for node in ast.walk(fn.node):
            if isinstance(node, ast.Assign):
                targets = node.targets
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                targets = [node.target]
            else:
                continue
            for tgt in targets:
                attr = _self_attr_of(tgt)
                if attr and _collection_ctor(node.value):
                    colls[(fn.module, fn.cls, attr)] = _Collection(
                        fn.cls, attr, fn.module, node.lineno
                    )

    # -- scan every method of those classes for growth + cap evidence
    engines: dict[int, object] = {}
    for fn in index.functions:
        if fn.cls is None:
            continue
        relevant = [c for (m, c_, a), c in colls.items()
                    if m == fn.module and c_ == fn.cls]
        if not relevant:
            continue
        by_attr = {c.attr: c for c in relevant}
        lazy_engine = [None]

        def engine():
            if lazy_engine[0] is None:
                if id(fn) not in engines:
                    engines[id(fn)] = analysis.function_taint(fn)
                lazy_engine[0] = engines[id(fn)]
            return lazy_engine[0]

        for node in ast.walk(fn.node):
            # len(self.attr) compared against anything => capacity-aware
            # (covers ``len(self.peers) + len(self._dialing) >= cap`` too)
            if isinstance(node, ast.Compare):
                for sub in ast.walk(node):
                    if (
                        isinstance(sub, ast.Call)
                        and isinstance(sub.func, ast.Name)
                        and sub.func.id == "len"
                        and sub.args
                    ):
                        a = _self_attr_of(sub.args[0])
                        if a in by_attr:
                            by_attr[a].capped = True
            # del self.attr[n:] / self.attr[n:] = ... truncation
            elif isinstance(node, ast.Delete):
                for t in node.targets:
                    if isinstance(t, ast.Subscript) and isinstance(
                        t.slice, ast.Slice
                    ):
                        a = _self_attr_of(t.value)
                        if a in by_attr:
                            by_attr[a].capped = True
            elif isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = (
                    node.targets if isinstance(node, ast.Assign)
                    else [node.target]
                )
                for t in targets:
                    if isinstance(t, ast.Subscript):
                        if isinstance(t.slice, ast.Slice):
                            a = _self_attr_of(t.value)
                            if a in by_attr:
                                by_attr[a].capped = True
                            continue
                        # self.attr[key] = value / += delta — growth
                        a = _self_attr_of(t.value)
                        if a in by_attr:
                            remote = _looks_remote(t.slice) or _is_tainted(
                                t.slice, engine()
                            )
                            by_attr[a].growth.append(
                                (node.lineno, fn.qualname, remote)
                            )
            elif isinstance(node, ast.Call):
                f = node.func
                if (
                    isinstance(f, ast.Attribute)
                    and f.attr in GROW_METHODS
                    and f.attr in MUTATING_METHODS
                ):
                    a = _self_attr_of(f.value)
                    if a in by_attr:
                        probe = ast.Tuple(
                            elts=list(node.args)
                            + [kw.value for kw in node.keywords],
                            ctx=ast.Load(),
                        )
                        remote = _looks_remote(probe) or _is_tainted(
                            probe, engine()
                        )
                        by_attr[a].growth.append(
                            (node.lineno, fn.qualname, remote)
                        )

    # -- report
    findings: list[Finding] = []
    for (module, cls, attr), coll in sorted(colls.items()):
        remote_growth = [(ln, fn_q) for (ln, fn_q, r) in coll.growth if r]
        if not remote_growth or coll.capped:
            continue
        lines = ann.get(module, {})
        cap = lines.get(coll.line)
        grow_line, grow_fn = remote_growth[0]
        if cap is None:
            for ln, _fn_q in remote_growth:
                if ln in lines:
                    cap = lines[ln]
                    break
        symbol = f"{cls}.{attr}"
        if cap is not None:
            syms = _module_symbols(trees[module], cls)
            if cap.split(".")[-1] in syms or cap in syms:
                continue  # bounded out-of-band by a real symbol
            findings.append(
                Finding(
                    PASS_NAME,
                    module,
                    coll.line,
                    symbol,
                    f"bounded-by names nonexistent cap {cap!r} — the "
                    f"annotation is inert; name a real symbol or add an "
                    f"eviction path",
                )
            )
            continue
        findings.append(
            Finding(
                PASS_NAME,
                module,
                grow_line,
                symbol,
                f"remote-keyed collection grows in {grow_fn} with no "
                f"statically visible cap (no len-guard, maxlen, or "
                f"truncation; TTL expiry does not bound fresh keys) — "
                f"add eviction or # bounded-by: <cap>",
            )
        )
    return findings
