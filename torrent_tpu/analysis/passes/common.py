"""Shared AST machinery for the analysis passes.

The passes all reason about the same few facts, so they are computed
once per lint run in a :class:`PackageIndex`:

* **who acquires what** — every ``with <lock>`` / ``<lock>.acquire()``
  site, with the set of locks already held at that point (nested
  ``with`` scopes plus linear ``acquire()``/``release()`` tracking);
* **who calls whom** — every call site, with the held-set at the call,
  resolved conservatively (see below) so lock acquisitions and device
  entries propagate through one level of indirection and beyond via a
  fixpoint;
* **who enters the device** — calls that dispatch compiled work
  (``digest_batch``, the pallas kernels, ``jnp.*`` / ``jax.*`` rooted
  calls, collectives);
* **who touches what state** — every ``self.<attr>`` read/write site
  with the held-set at that point (direct stores, container stores,
  and known mutating method calls all count as writes), the raw
  material of the guarded-state lockset pass.

Lock identity is the *attribute name* (``_device_lock``,
``build_lock``, ``_counter_lock`` …): instances of a lane's
``build_lock`` are interchangeable for ordering purposes, and the
documented partial order is written in exactly these names.
Anything whose name ends in ``lock`` (case-insensitive) is a lock;
``async with`` items are asyncio locks — a different (loop-confined)
discipline — and are excluded from the thread-lock graph.

Call resolution is deliberately conservative: ``self.m()`` resolves
within the enclosing class; a bare ``Name()`` call resolves to a
same-module function, a package-unique function, or a class's
``__init__``; any other attribute call resolves only if the method
name is unique across the package. Ambiguous names (``run``, ``set``…)
are NOT traversed — the static pass under-approximates there and the
runtime sanitizer (``analysis/sanitizer.py``) is the ground truth.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field

# Calls that enter the device plane (jit dispatch / kernel launch /
# collective). Tail-name matches; plus any call rooted at jnp./jax.
DEVICE_CALL_NAMES = {
    "digest_batch",
    "verify_batch",
    "sha256_pieces_pallas",
    "sha1_pieces_pallas",
    "hash_pieces",
    "process_allgather",
    "block_until_ready",
    "device_put",
}
DEVICE_ROOTS = ("jnp", "jax")


def dotted_name(expr) -> str | None:
    """Full dotted chain of a Name/Attribute expr ('jax.devices'), or
    None when the chain bottoms out in a call/subscript."""
    parts = []
    while isinstance(expr, ast.Attribute):
        parts.append(expr.attr)
        expr = expr.value
    if not isinstance(expr, ast.Name):
        return None
    parts.append(expr.id)
    return ".".join(reversed(parts))


def tail_name(expr) -> str | None:
    if isinstance(expr, ast.Name):
        return expr.id
    if isinstance(expr, ast.Attribute):
        return expr.attr
    return None


def lock_name_of(expr) -> str | None:
    """Canonical lock name of a with-item / acquire receiver, or None."""
    name = tail_name(expr)
    if name and name.lower().endswith("lock"):
        return name
    return None


def is_device_call(call: ast.Call) -> str | None:
    """A token naming the device entry this call performs, or None."""
    tail = tail_name(call.func)
    if tail in DEVICE_CALL_NAMES:
        return tail
    dn = dotted_name(call.func)
    if dn and dn.split(".", 1)[0] in DEVICE_ROOTS:
        return dn
    return None


@dataclass
class AcquireSite:
    lock: str
    held: tuple[str, ...]  # locks already held when this one is taken
    line: int


@dataclass
class CallSite:
    func: ast.expr          # the call's func node (for resolution)
    held: tuple[str, ...]
    line: int


@dataclass
class DeviceSite:
    token: str
    held: tuple[str, ...]
    line: int


@dataclass
class AttrSite:
    """One ``self.<attr>`` access with the locks held at that point.

    ``write`` covers direct stores (``self.x = …``, ``self.x += …``,
    ``del self.x``), container stores through the attribute
    (``self.x[k] = v``, ``del self.x[k]``), and calls of known mutating
    methods on the attribute (``self.x.append(…)``); everything else is
    a read. Attributes whose own name looks like a lock are not
    recorded — they are the guards, not the guarded."""

    attr: str
    held: tuple[str, ...]
    line: int
    write: bool


# container/collection methods that mutate their receiver: a call
# ``self.x.<m>(…)`` with m here is a WRITE of x for lockset purposes
MUTATING_METHODS = frozenset(
    {
        "append", "appendleft", "extend", "extendleft", "insert",
        "add", "update", "setdefault", "pop", "popleft", "popitem",
        "remove", "discard", "clear", "sort", "reverse",
        "move_to_end",
    }
)


def self_attr(expr) -> str | None:
    """``x`` when ``expr`` is exactly ``self.x`` (and x is not itself a
    lock name), else None."""
    if (
        isinstance(expr, ast.Attribute)
        and isinstance(expr.value, ast.Name)
        and expr.value.id == "self"
        and not expr.attr.lower().endswith("lock")
    ):
        return expr.attr
    return None


@dataclass
class FunctionInfo:
    module: str             # repo-relative posix path
    cls: str | None
    name: str
    node: ast.AST
    is_async: bool
    acquires: list[AcquireSite] = field(default_factory=list)
    calls: list[CallSite] = field(default_factory=list)
    device: list[DeviceSite] = field(default_factory=list)
    attrs: list[AttrSite] = field(default_factory=list)

    @property
    def qualname(self) -> str:
        return f"{self.cls}.{self.name}" if self.cls else self.name


class _FnWalker:
    """Walks one function body tracking the held-lock set.

    Nested ``def``/``class`` bodies are skipped — they get their own
    FunctionInfo and do not run where they are defined. ``lambda``
    bodies run inline often enough (sort keys) that their calls are
    recorded under the current held-set.
    """

    def __init__(self, info: FunctionInfo):
        self.info = info

    def walk(self) -> None:
        self._stmts(self.info.node.body, ())

    # ------------------------------------------------------- statements

    def _stmts(self, body, held) -> None:
        held = list(held)
        for stmt in body:
            held = self._stmt(stmt, held)

    def _stmt(self, stmt, held: list) -> list:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            return held  # separate FunctionInfo; doesn't run here
        if isinstance(stmt, ast.With):
            inner = list(held)
            for item in stmt.items:
                self._expr(item.context_expr, tuple(inner))
                lock = lock_name_of(item.context_expr)
                if lock:
                    self.info.acquires.append(
                        AcquireSite(lock, tuple(inner), item.context_expr.lineno)
                    )
                    inner.append(lock)
            self._stmts(stmt.body, tuple(inner))
            return held
        if isinstance(stmt, ast.AsyncWith):
            # asyncio locks: excluded from the thread-lock graph, but
            # the body still runs under the current (thread) held-set
            for item in stmt.items:
                self._expr(item.context_expr, tuple(held))
            self._stmts(stmt.body, tuple(held))
            return held
        # linear acquire()/release() tracking within a statement list
        if isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Call):
            call = stmt.value
            if isinstance(call.func, ast.Attribute) and call.func.attr in (
                "acquire",
                "release",
            ):
                lock = lock_name_of(call.func.value)
                if lock:
                    if call.func.attr == "acquire":
                        self.info.acquires.append(
                            AcquireSite(lock, tuple(held), call.lineno)
                        )
                        return held + [lock]
                    out = list(held)
                    if lock in out:  # drop the most recent acquisition
                        out.reverse()
                        out.remove(lock)
                        out.reverse()
                    return out
        # generic statement: visit child expressions + statement lists
        for _field, value in ast.iter_fields(stmt):
            if isinstance(value, list):
                if value and isinstance(value[0], ast.stmt):
                    self._stmts(value, tuple(held))
                else:
                    for v in value:
                        if isinstance(v, ast.ExceptHandler):
                            self._stmts(v.body, tuple(held))
                        elif isinstance(v, ast.expr):
                            self._expr(v, tuple(held))
            elif isinstance(value, ast.expr):
                self._expr(value, tuple(held))
        return held

    # ------------------------------------------------------ expressions

    def _expr(self, expr, held: tuple[str, ...]) -> None:
        # receivers of a mutation recorded as writes below; their own
        # Load node must not double-record as a read
        consumed: set[int] = set()
        nodes = list(ast.walk(expr))
        for node in nodes:
            if isinstance(node, ast.Call):
                self.info.calls.append(CallSite(node.func, held, node.lineno))
                token = is_device_call(node)
                if token:
                    self.info.device.append(DeviceSite(token, held, node.lineno))
                if (
                    isinstance(node.func, ast.Attribute)
                    and node.func.attr in MUTATING_METHODS
                ):
                    attr = self_attr(node.func.value)
                    if attr:
                        consumed.add(id(node.func.value))
                        self.info.attrs.append(
                            AttrSite(attr, held, node.lineno, True)
                        )
            elif isinstance(node, ast.Subscript) and isinstance(
                node.ctx, (ast.Store, ast.Del)
            ):
                attr = self_attr(node.value)
                if attr:
                    consumed.add(id(node.value))
                    self.info.attrs.append(AttrSite(attr, held, node.lineno, True))
        for node in nodes:
            if isinstance(node, ast.Attribute) and id(node) not in consumed:
                attr = self_attr(node)
                if attr:
                    self.info.attrs.append(
                        AttrSite(
                            attr,
                            held,
                            node.lineno,
                            isinstance(node.ctx, (ast.Store, ast.Del)),
                        )
                    )


# ------------------------------------------------------------- indexing


@dataclass
class ModuleFile:
    path: str        # repo-relative posix path
    tree: ast.Module
    source: str


class PackageIndex:
    """All functions of the linted package, with call resolution and
    the transitive acquire/device fixpoint."""

    def __init__(self, files: list[ModuleFile]):
        self.files = files
        self.functions: list[FunctionInfo] = []
        self.by_bare_name: dict[str, list[FunctionInfo]] = {}
        self.by_module_func: dict[tuple[str, str], FunctionInfo] = {}
        self.by_class_method: dict[tuple[str, str], list[FunctionInfo]] = {}
        self.class_init: dict[str, list[FunctionInfo]] = {}
        for mf in files:
            self._index_module(mf)
        for fn in self.functions:
            _FnWalker(fn).walk()
        self._resolved: dict = {}  # id(CallSite) | ("expr", id(node)) -> fn
        self._trans_acquires: dict[int, frozenset[str]] = {}
        self._trans_device: dict[int, bool] = {}
        self._fixpoint()

    # -------------------------------------------------------- structure

    def _index_module(self, mf: ModuleFile) -> None:
        def add(node, cls: str | None):
            info = FunctionInfo(
                module=mf.path,
                cls=cls,
                name=node.name,
                node=node,
                is_async=isinstance(node, ast.AsyncFunctionDef),
            )
            self.functions.append(info)
            self.by_bare_name.setdefault(node.name, []).append(info)
            if cls is None:
                self.by_module_func.setdefault((mf.path, node.name), info)
            else:
                self.by_class_method.setdefault((cls, node.name), []).append(info)
                if node.name == "__init__":
                    self.class_init.setdefault(cls, []).append(info)
            # nested defs get their own entries (resolution by unique
            # bare name may still reach them)
            for child in ast.walk(node):
                if child is node:
                    continue
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    nested = FunctionInfo(
                        module=mf.path,
                        cls=cls,
                        name=child.name,
                        node=child,
                        is_async=isinstance(child, ast.AsyncFunctionDef),
                    )
                    self.functions.append(nested)
                    self.by_bare_name.setdefault(child.name, []).append(nested)

        for node in mf.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                add(node, None)
            elif isinstance(node, ast.ClassDef):
                for sub in node.body:
                    if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                        add(sub, node.name)

    # ------------------------------------------------------- resolution

    def resolve(self, caller: FunctionInfo, site: CallSite) -> FunctionInfo | None:
        key = id(site)
        if key in self._resolved:
            return self._resolved[key]
        out = self._resolve_uncached(caller, site)
        self._resolved[key] = out
        return out

    def resolve_call(self, caller: FunctionInfo, func_expr) -> FunctionInfo | None:
        """Resolve a call by its ``func`` expression node. Unlike
        :meth:`resolve`, safe for ad-hoc queries: AST nodes live as long
        as the index, so the cache key cannot be reused the way the id
        of a thrown-away CallSite can."""
        key = ("expr", id(func_expr))
        if key in self._resolved:
            return self._resolved[key]
        out = self._resolve_uncached(caller, CallSite(func_expr, (), 0))
        self._resolved[key] = out
        return out

    def _resolve_uncached(self, caller, site) -> FunctionInfo | None:
        f = site.func
        if isinstance(f, ast.Attribute):
            recv = f.value
            if (
                isinstance(recv, ast.Name)
                and recv.id in ("self", "cls")
                and caller.cls is not None
            ):
                methods = self.by_class_method.get((caller.cls, f.attr))
                if methods:
                    same = [m for m in methods if m.module == caller.module]
                    return same[0] if same else methods[0]
            cands = self.by_bare_name.get(f.attr, [])
            return cands[0] if len(cands) == 1 else None
        if isinstance(f, ast.Name):
            inits = self.class_init.get(f.id, [])
            if len(inits) == 1:
                return inits[0]
            same_mod = self.by_module_func.get((caller.module, f.id))
            if same_mod is not None:
                return same_mod
            cands = [fn for fn in self.by_bare_name.get(f.id, []) if fn.cls is None]
            return cands[0] if len(cands) == 1 else None
        return None

    # --------------------------------------------------------- fixpoint

    def _fixpoint(self) -> None:
        acq = {
            id(fn): {a.lock for a in fn.acquires} for fn in self.functions
        }
        dev = {id(fn): bool(fn.device) for fn in self.functions}
        edges: dict[int, list[int]] = {}
        for fn in self.functions:
            outs = []
            for site in fn.calls:
                callee = self.resolve(fn, site)
                if callee is not None:
                    outs.append(id(callee))
            edges[id(fn)] = outs
        changed = True
        while changed:
            changed = False
            for fn in self.functions:
                k = id(fn)
                for callee in edges[k]:
                    before = len(acq[k])
                    acq[k] |= acq[callee]
                    if len(acq[k]) != before:
                        changed = True
                    if dev[callee] and not dev[k]:
                        dev[k] = True
                        changed = True
        self._trans_acquires = {k: frozenset(v) for k, v in acq.items()}
        self._trans_device = dev

    def transitive_acquires(self, fn: FunctionInfo) -> frozenset[str]:
        return self._trans_acquires[id(fn)]

    def transitive_device(self, fn: FunctionInfo) -> bool:
        return self._trans_device[id(fn)]
