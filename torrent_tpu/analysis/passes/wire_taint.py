"""wire-taint: untrusted swarm input must be validated before it sizes,
indexes, seeks, paths, loops, or charges anything.

Sources are the decode boundaries where attacker bytes become Python
values: bencode decoding, peer-wire message parsing, handshake reads,
raw datagram handlers. Sinks are the places a remote-supplied number or
name becomes dangerous: allocation sizes, staging-slab geometry, IO
offsets+lengths, file-path construction, loop bounds, DRR charge
amounts. A flow from source to sink must pass a registered validation
**barrier** (:data:`BARRIERS`) — piece-geometry checks, server-side
clamps, the structural ``if x > CAP: raise`` idiom — or carry a
``# sanitized-by: <barrier>`` annotation on the sink line naming the
out-of-band check that covers it. Annotations naming a barrier that is
not registered are themselves findings (a typo'd suppression must not
silently disable the gate).

Each finding carries the full machine-traced flow (source →
propagation → sink); the lint CLI emits it as SARIF ``codeFlows`` so a
finding reads as an attack path, not a line number.
"""

from __future__ import annotations

import re

from torrent_tpu.analysis.findings import Finding
from torrent_tpu.analysis.passes.dataflow import Registries, TaintAnalysis

PASS_NAME = "wire-taint"

# ---------------------------------------------------------------- model

# calls whose RETURN VALUE is attacker-controlled wire data
SOURCE_CALLS: dict[str, str] = {
    "bdecode": "bencode decode",
    "bdecode_prefix": "bencode decode",
    "bdecode_with_info_span": "bencode decode",
    "decode_message": "peer-wire message decode",
    "read_message": "peer-wire message read",
    "read_handshake_head": "peer handshake",
    "read_handshake_peer_id": "peer handshake",
}

# functions whose PARAMETERS arrive straight off the wire
SOURCE_PARAMS: dict[str, frozenset[str]] = {
    "DHTNode._on_datagram": frozenset({"data"}),
    "LSDResponder._on_datagram": frozenset({"data"}),
    "_on_datagram": frozenset({"data"}),
}

# registered validation barriers: calling one of these sanitizes its
# arguments (guard barriers) / returns a clean value (value barriers).
# ``# sanitized-by:`` annotations must name an entry here.
BARRIERS: frozenset[str] = frozenset(
    {
        "validate_requested_block",
        "validate_received_block",
        "clamp_numwant",
        "clamp_digest",
        "check",          # codec/valid.py combinator verdicts
        "parse_info",     # metainfo validation funnels
        "parse_v2_info_dict",
        "hex",            # hex-encode: output alphabet is [0-9a-f] —
                          # cannot traverse paths, cannot act as a size
        "min",            # the clamp builtin (value barrier)
        # annotation-only vocabulary (hyphenated names never match a
        # call; they exist for # sanitized-by on sites the engine can't
        # judge structurally):
        "len-guard",      # structural: if len(x) > CAP / if x > CAP: raise
        "bounded-copy",   # bytearray/bytes copy of an already-received
                          # buffer — allocation bounded by that buffer
    }
)

# sink calls by bare/tail name: name -> (kind, positional arg idxs|None=all)
SINK_CALLS: dict[str, tuple[str, tuple[int, ...] | None]] = {
    "bytearray": ("allocation size", (0,)),
    "range": ("loop bound", None),
    "read_batch": ("batched IO geometry", None),
    "preadv": ("vectored read offset/length", None),
    "pread": ("read offset/length", None),
    "read_into": ("read offset/length", None),
    "readexactly": ("read length", (0,)),
    "checkout_staging": ("staging slab geometry", (0, 1)),
    "enqueue_staged": ("staged submit geometry", None),
    "seek": ("file offset", (0,)),
    "joinpath": ("file-path construction", None),
    "truncate": ("file size", (0,)),
    "charge": ("DRR charge amount", (1,)),
}

# sink calls by dotted name (module-qualified callables)
SINK_DOTTED: dict[str, tuple[str, tuple[int, ...] | None]] = {
    "os.path.join": ("file-path construction", None),
    "os.pread": ("read offset/length", (1, 2)),
    "os.preadv": ("vectored read offset", None),
}

_SANITIZED_RE = re.compile(r"#\s*sanitized-by:\s*([A-Za-z_][\w.-]*)")


def registries() -> Registries:
    return Registries(
        source_calls=dict(SOURCE_CALLS),
        source_params=dict(SOURCE_PARAMS),
        barrier_calls=frozenset(b for b in BARRIERS if b.isidentifier()),
        sink_calls=dict(SINK_CALLS),
        sink_dotted=dict(SINK_DOTTED),
    )


def annotations_by_line(source: str) -> dict[int, str]:
    """``# sanitized-by: <barrier>`` annotations, keyed by 1-based line."""
    out: dict[int, str] = {}
    for i, text in enumerate(source.splitlines(), start=1):
        m = _SANITIZED_RE.search(text)
        if m:
            out[i] = m.group(1)
    return out


def run(index, files) -> list[Finding]:
    analysis = TaintAnalysis(index, registries())
    ann: dict[str, dict[int, str]] = {
        mf.path: annotations_by_line(mf.source) for mf in files
    }

    findings: list[Finding] = []
    consumed: set[tuple[str, int]] = set()
    seen: set[tuple[str, str, str]] = set()
    for hit in analysis.hits:
        barrier = ann.get(hit.module, {}).get(hit.line)
        if barrier is not None:
            consumed.add((hit.module, hit.line))
            if barrier in BARRIERS:
                continue  # deliberate, named, registered — suppressed
            findings.append(
                Finding(
                    PASS_NAME,
                    hit.module,
                    hit.line,
                    hit.sink_note,
                    f"sanitized-by names unregistered barrier "
                    f"{barrier!r} (not in BARRIERS) — suppression is "
                    f"inert; register the barrier or fix the flow",
                )
            )
            continue
        source_note = hit.trace.steps[0].note if hit.trace.steps else "wire input"
        key = (hit.module, hit.sink_note, source_note)
        if key in seen:
            continue  # one finding per (module, sink, source) family
        seen.add(key)
        findings.append(
            Finding(
                PASS_NAME,
                hit.module,
                hit.line,
                hit.sink_note,
                f"{source_note} reaches {hit.kind} sink {hit.sink_note} "
                f"without a registered validation barrier "
                f"(# sanitized-by: <barrier> for deliberate exceptions)",
                flow=tuple(s.as_tuple() for s in hit.trace.steps),
            )
        )

    # a sanitized-by annotation nothing consumed is stale or misplaced —
    # it suggests a validated flow that the engine does not even see
    for path, lines in ann.items():
        for line, barrier in lines.items():
            if (path, line) in consumed:
                continue
            if barrier not in BARRIERS:
                findings.append(
                    Finding(
                        PASS_NAME,
                        path,
                        line,
                        "annotation",
                        f"sanitized-by names unregistered barrier "
                        f"{barrier!r} (not in BARRIERS)",
                    )
                )
    return findings
