"""``device-under-lock`` — only ``_device_lock`` may guard plane entry.

PR 2's intermittent deadlock was exactly this shape: two worker threads
entering the same compiled executable concurrently wedged the XLA
runtime, and the fix was one designated lock (``_device_lock``) whose
ONLY job is serializing device entry. Holding any *other* lock across a
jit dispatch / kernel launch / collective couples that lock's hold time
to device latency (seconds of compile, minutes behind a wedged tunnel)
and recreates the hazard: whoever contends that lock is now blocked on
the device.

Flags any device-entry call (``common.DEVICE_CALL_NAMES``, ``jnp.*`` /
``jax.*`` rooted calls) made — directly or through resolved calls —
while a lock other than ``_device_lock`` is held.
"""

from __future__ import annotations

from torrent_tpu.analysis.findings import Finding
from torrent_tpu.analysis.passes.common import PackageIndex

PASS_NAME = "device-under-lock"

ALLOWED = frozenset({"_device_lock"})


def _bad_held(held) -> list[str]:
    return [h for h in held if h not in ALLOWED]


def run(index: PackageIndex, files=None) -> list[Finding]:
    findings: list[Finding] = []
    for fn in index.functions:
        for site in fn.device:
            for lock in _bad_held(site.held):
                findings.append(
                    Finding(
                        PASS_NAME,
                        fn.module,
                        site.line,
                        fn.qualname,
                        f"device entry {site.token} while holding {lock}",
                    )
                )
        for site in fn.calls:
            bad = _bad_held(site.held)
            if not bad:
                continue
            callee = index.resolve(fn, site)
            if callee is None or not index.transitive_device(callee):
                continue
            for lock in bad:
                findings.append(
                    Finding(
                        PASS_NAME,
                        fn.module,
                        site.line,
                        fn.qualname,
                        f"call to {callee.qualname} enters the device "
                        f"while holding {lock}",
                    )
                )
    return findings
