"""``lifecycle`` — acquire/release pairing on refcounted seams.

Lockset analysis can prove an access is guarded; it cannot prove a
checked-out resource is returned. The zero-copy ingest plane (PR 8)
runs on exactly such seams: a staging slot checked out of
``_StagingSlots`` and never checked back in permanently shrinks the
pool, a leaked :class:`~torrent_tpu.sched.scheduler.StagedSlab`
reference keeps its slot out of circulation forever, and both leak
silently — throughput degrades launch by launch with no error. This
pass checks the pairing statically, per function:

* **checkout pairing** — a call to ``checkout()`` / ``checkout_staging()``
  whose result stays in the function (not returned, not stored on
  ``self``) must be protected by an exception edge: the paired release
  (``checkin``/``release``) has to appear inside a ``finally`` block or
  an ``except`` handler. A release only in straight-line code leaks the
  slot the first time the body raises; no release at all leaks it every
  time.
* **ownership transfer** is exempt: a checkout inside a ``return``
  expression, or whose result is assigned to ``self.<attr>``, hands the
  obligation to the caller / the object lifetime (``checkout_staging``
  itself does both — the docstring contract passes the release duty to
  the reader).
* **context-manager discipline** — ``pipeline_ledger().track(…)`` and
  ``tracer().span(…)`` return context managers whose ``__exit__`` IS
  the accounting: calling either outside a ``with`` item opens a stage
  entry / span that never closes (the ledger's occupancy counts drift
  up, the span never lands in the ring). Both must appear as the
  context expression of a ``with`` statement.

Like the other passes this is deliberately shallow on aliasing: it
reasons per function over names, and the dynamic leak counters
(``_StagingSlots.outstanding``, asserted by tests, plus the sanitizer's
guarded cells) cover what escapes it.
"""

from __future__ import annotations

import ast

from torrent_tpu.analysis.findings import Finding, dedupe_findings
from torrent_tpu.analysis.passes.common import PackageIndex, tail_name

PASS_NAME = "lifecycle"

# acquire tail-names and the release tail-names of the resource family.
# Pairing accepts any release tail of the family (the APIs alias: a raw
# slot checkout pairs with checkin, but a checkout wrapped in a
# StagedSlab — or reached through a `checkout = getattr(...)` alias —
# pairs with the wrapper's release), BUT the release must reference the
# checked-out variable: an unrelated `sem.release()` in a finally must
# not mask a slot leak.
ACQUIRE_TAILS = frozenset({"checkout", "checkout_staging"})
RELEASE_TAILS = frozenset({"checkin", "release"})

# context-manager-only calls: tail name -> receiver tails that identify
# the real API (``.track(`` on anything else is not the ledger)
CM_ONLY: dict[str, frozenset[str]] = {
    "track": frozenset({"ledger", "_ledger", "pipeline_ledger"}),
    "span": frozenset({"tracer", "_tracer"}),
}


def _receiver_tail(call: ast.Call) -> str | None:
    """Tail name of the call's receiver: ``ledger`` for
    ``self.ledger.track(…)``, ``pipeline_ledger`` for
    ``pipeline_ledger().track(…)``."""
    if not isinstance(call.func, ast.Attribute):
        return None
    recv = call.func.value
    if isinstance(recv, ast.Call):
        return tail_name(recv.func)
    return tail_name(recv)


class _FnScan(ast.NodeVisitor):
    """One pass over a function body (nested defs excluded — they get
    their own FunctionInfo) collecting every fact the rules need."""

    def __init__(self):
        # (api, line, result var name or None)
        self.acquires: list[tuple[str, int, str | None]] = []
        self.transferred: set[int] = set()             # id() of exempt calls
        self.acquire_vars: dict[int, str] = {}         # id(call) -> bound name
        # (tail, names the call touches, protected?) — names are the
        # receiver tail plus any bare-Name arguments, so `slot` pairs
        # with both `pool.checkin(slot)` and `slot.release()`
        self.releases: list[tuple[str, frozenset[str], bool]] = []
        self.with_items: set[int] = set()              # id() of with context exprs
        self.cm_calls: list[tuple[str, int, int]] = [] # (api, id, line)
        self._protected = 0

    # ------------------------------------------------------- structure

    def visit_FunctionDef(self, node):
        pass

    def visit_AsyncFunctionDef(self, node):
        pass

    def visit_Lambda(self, node):
        pass

    def visit_Try(self, node):
        for stmt in node.body + node.orelse:
            self.visit(stmt)
        self._protected += 1
        for handler in node.handlers:
            for stmt in handler.body:
                self.visit(stmt)
        for stmt in node.finalbody:
            self.visit(stmt)
        self._protected -= 1

    def _visit_with(self, node):
        for item in node.items:
            self.with_items.add(id(item.context_expr))
            self.visit(item.context_expr)
            if item.optional_vars is not None:
                self.visit(item.optional_vars)
        for stmt in node.body:
            self.visit(stmt)

    visit_With = _visit_with
    visit_AsyncWith = _visit_with

    def visit_Return(self, node):
        # ownership transfer: the caller receives the resource (and the
        # checkout_staging contract, its release duty)
        if node.value is not None:
            for sub in ast.walk(node.value):
                if isinstance(sub, ast.Call):
                    self.transferred.add(id(sub))
        self.generic_visit(node)

    def visit_Assign(self, node):
        # self.<attr> = <...checkout()...> escapes to the object lifetime
        escapes = any(
            isinstance(t, ast.Attribute)
            and isinstance(t.value, ast.Name)
            and t.value.id == "self"
            for t in node.targets
        )
        bound = (
            node.targets[0].id
            if len(node.targets) == 1 and isinstance(node.targets[0], ast.Name)
            else None
        )
        for sub in ast.walk(node.value):
            if isinstance(sub, ast.Call):
                if escapes:
                    self.transferred.add(id(sub))
                elif bound is not None:
                    # `slot = pool.checkout()` and wrapper shapes like
                    # `slab = StagedSlab(pool, pool.checkout(), …)`:
                    # the bound name is what a release must reference
                    self.acquire_vars[id(sub)] = bound
        self.generic_visit(node)

    # ------------------------------------------------------------ calls

    def visit_Call(self, node: ast.Call):
        tail = tail_name(node.func)
        if tail in ACQUIRE_TAILS and id(node) not in self.transferred:
            self.acquires.append(
                (tail, node.lineno, self.acquire_vars.get(id(node)))
            )
        if tail in CM_ONLY and isinstance(node.func, ast.Attribute):
            recv = _receiver_tail(node)
            if recv in CM_ONLY[tail]:
                self.cm_calls.append((tail, id(node), node.lineno))
        if tail in RELEASE_TAILS and isinstance(node.func, ast.Attribute):
            names = set()
            recv = tail_name(node.func.value)
            if recv is not None:
                names.add(recv)
            for arg in node.args:
                if isinstance(arg, ast.Name):
                    names.add(arg.id)
            self.releases.append(
                (tail, frozenset(names), bool(self._protected))
            )
        self.generic_visit(node)


def run(index: PackageIndex, files=None) -> list[Finding]:
    findings: list[Finding] = []
    for fn in index.functions:
        # the resource APIs themselves are the pairing's implementation,
        # not its clients
        if fn.name in ACQUIRE_TAILS or fn.name in RELEASE_TAILS:
            continue
        scan = _FnScan()
        for stmt in fn.node.body:
            scan.visit(stmt)
        for api, line, var in scan.acquires:
            matching = [
                (names, protected)
                for _tail, names, protected in scan.releases
                if var is None or var in names
            ]
            if any(protected for _names, protected in matching):
                continue
            if matching:
                findings.append(
                    Finding(
                        PASS_NAME, fn.module, line, fn.qualname,
                        f"{api}() released only on the happy path — leaks "
                        "the slot on an exception edge (release belongs in "
                        "a finally/except)",
                    )
                )
            else:
                findings.append(
                    Finding(
                        PASS_NAME, fn.module, line, fn.qualname,
                        f"{api}() result is never released on any path",
                    )
                )
        for api, node_id, line in scan.cm_calls:
            if node_id in scan.with_items:
                continue
            what = (
                "pipeline_ledger().track()" if api == "track"
                else "tracer().span()"
            )
            findings.append(
                Finding(
                    PASS_NAME, fn.module, line, fn.qualname,
                    f"{what} must be the context expression of a with "
                    "statement (the exit IS the accounting)",
                )
            )
    return dedupe_findings(findings)
