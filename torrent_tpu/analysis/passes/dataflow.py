"""Forward interprocedural taint dataflow over the PackageIndex.

Everything this system serves first arrives as attacker-controlled wire
input — bencode frames, peer-wire messages, DHT packets, tracker
announces. The concurrency passes gate *when* code runs; nothing gated
*where remote bytes flow*. This engine closes that hole: a forward
abstract interpretation of each function (assignments, calls, returns,
match-case destructuring), field-sensitive for decoded message
dicts/dataclasses, composed interprocedurally through function
summaries iterated to a fixpoint over the same conservatively-resolved
call graph the lockset pass uses.

Abstract state per function: a map of **taint paths** — ``("msg",)``
for a whole decoded message, ``("msg", "length")`` for one field — to
the :class:`FlowTrace` that explains *how* the value got tainted (the
raw material of SARIF ``codeFlows``). A path is tainted when it or any
prefix is in the map, unless the exact path has been *sanitized* by a
registered validation barrier.

Three registries (owned by the ``wire-taint`` pass, passed in):

* **sources** — calls whose return value is wire bytes
  (``bdecode``, ``decode_message`` …) and functions whose *parameters*
  arrive tainted (datagram handlers, bridge request bodies);
* **barriers** — validation choke points. Two shapes: a *value barrier*
  returns a clean version of its argument (``min(x, CAP)``); a *guard
  barrier* is called for effect (``validate_requested_block(...)``) and
  sanitizes the argument paths for the rest of the function. The
  clamp idiom ``if x > CAP: raise`` is recognized structurally: a
  comparison of a tainted path against anything inside an ``if`` whose
  body unconditionally escapes (raise/return/continue/break) sanitizes
  that path afterward.
* **sinks** — calls where a remote-sized value becomes dangerous:
  allocation sizes, slab/row indices, IO offsets+lengths, file-path
  construction, loop bounds, admission charges.

Soundness direction: like every static pass here this
**under-approximates** — loops are walked once, branches union into one
state, cross-object attribute flows and unresolvable calls are not
traversed. A clean report is not a proof; a finding is a real,
machine-traced attack path from a decode boundary to a sink.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field

from torrent_tpu.analysis.passes.common import (
    FunctionInfo,
    PackageIndex,
    dotted_name,
    tail_name,
)

# taint paths: ("var",) or ("var", "field") — one level of field
# sensitivity is enough to tell msg.length from msg.index
Path = tuple[str, ...]

# calls that return a value bounded by data the process already holds
# (len of a received buffer can never exceed the buffer), or an
# intrinsically clean scalar — implicit value barriers
_CLEAN_CALLS = frozenset({"len", "bool", "id", "hash", "isinstance", "type"})

# a value pushed through these keeps its provenance
_IDENTITY_CALLS = frozenset({"int", "float", "str", "abs", "bytes", "bytearray",
                             "list", "tuple", "dict", "set", "frozenset",
                             "sorted", "reversed", "enumerate", "zip", "iter",
                             "next", "repr", "ord", "chr", "sum", "max"})
# NB: ``min`` is deliberately NOT identity — min(x, CAP) is the clamp
# idiom, a value barrier. ``max`` stays identity (max raises the value).
_VALUE_BARRIER_CALLS = frozenset({"min"})


@dataclass(frozen=True)
class FlowStep:
    """One hop of a taint flow (== one SARIF threadFlow location)."""

    path: str   # repo-relative module path
    line: int
    note: str   # human-readable: what happened at this hop

    def as_tuple(self) -> tuple:
        return (self.path, self.line, self.note)


@dataclass(frozen=True)
class FlowTrace:
    """Provenance of one tainted value: source step + propagation steps.

    ``root`` distinguishes true wire sources ("source") from the
    all-params-tainted summary runs (the param's name), so summary
    consumers know which parameter a flow entered through.
    """

    root: str
    steps: tuple[FlowStep, ...]

    def extend(self, step: FlowStep) -> "FlowTrace":
        # bound the trace: a pathological chain must not OOM the linter;
        # keep the source and the most recent hops
        steps = self.steps
        if len(steps) >= 12:
            steps = steps[:1] + steps[-10:]
        return FlowTrace(self.root, steps + (step,))


@dataclass(frozen=True)
class SinkHit:
    """A tainted value reaching a sink inside some function."""

    kind: str            # sink family ("allocation size", "loop bound" …)
    sink_note: str       # what the sink call is
    module: str
    line: int
    trace: FlowTrace     # full flow: source … propagation … (sink appended)


@dataclass
class Summary:
    """Interprocedural behavior of one function, fixpointed."""

    returns_source: bool = False          # return is wire-tainted outright
    param_to_return: set[str] = field(default_factory=set)
    # param name -> sink hits a tainted argument would cause inside
    param_sinks: dict[str, list[SinkHit]] = field(default_factory=dict)
    # trace explaining returns_source (for codeFlows through helpers)
    return_trace: FlowTrace | None = None


class Registries:
    """The wire-taint pass's source/sink/barrier model, decoupled from
    the engine so fixtures can run with a tiny synthetic model."""

    def __init__(
        self,
        source_calls: dict[str, str],          # tail/dotted name -> note
        source_params: dict[str, frozenset[str]],  # fn qualname tail -> params
        barrier_calls: frozenset[str],         # tail names (guard barriers)
        sink_calls: dict[str, tuple[str, tuple[int, ...] | None]],
        sink_dotted: dict[str, tuple[str, tuple[int, ...] | None]],
    ):
        self.source_calls = source_calls
        self.source_params = source_params
        self.barrier_calls = barrier_calls
        self.sink_calls = sink_calls        # tail name -> (kind, arg idxs|None=all)
        self.sink_dotted = sink_dotted      # dotted name -> same


def _base_path(expr) -> Path | None:
    """Taint path of an expression that *names* a value: a local
    ``x`` -> ("x",); ``x.f`` -> ("x","f"); ``self.f`` -> ("self","f");
    ``x[k]``/``x.f[k]`` collapse to their base path (container taint is
    per-container, element reads inherit it)."""
    if isinstance(expr, ast.Name):
        return (expr.id,)
    if isinstance(expr, ast.Attribute):
        base = _base_path(expr.value)
        if base is None:
            return None
        if len(base) >= 2:          # one level of field sensitivity
            return base
        return base + (expr.attr,)
    if isinstance(expr, ast.Subscript):
        return _base_path(expr.value)
    return None


class _Engine:
    """One analysis run of one function body."""

    def __init__(
        self,
        index: PackageIndex,
        fn: FunctionInfo,
        regs: Registries,
        summaries: dict[int, Summary],
        taint_params: bool,
    ):
        self.index = index
        self.fn = fn
        self.regs = regs
        self.summaries = summaries
        self.taint: dict[Path, FlowTrace] = {}
        self.sanitized: set[Path] = set()
        self.hits: list[SinkHit] = []
        self.returns: list[FlowTrace] = []
        node = fn.node
        params: list[str] = []
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            args = node.args
            for a in (
                list(args.posonlyargs) + list(args.args)
                + list(args.kwonlyargs)
                + ([args.vararg] if args.vararg else [])
                + ([args.kwarg] if args.kwarg else [])
            ):
                if a.arg not in ("self", "cls"):
                    params.append(a.arg)
        self.params = params
        if taint_params:
            for p in params:
                self.taint[(p,)] = FlowTrace(
                    p,
                    (FlowStep(fn.module, node.lineno,
                              f"parameter {p} of {fn.qualname}"),),
                )
        else:
            # declared param sources: handlers whose arguments ARE the wire
            for key, names in regs.source_params.items():
                if fn.qualname == key or fn.name == key:
                    for p in params:
                        if p in names:
                            self.taint[(p,)] = FlowTrace(
                                "source",
                                (FlowStep(
                                    fn.module, node.lineno,
                                    f"untrusted wire input: parameter {p} "
                                    f"of {fn.qualname}"),),
                            )

    # ---------------------------------------------------------- queries

    def trace_of(self, path: Path | None) -> FlowTrace | None:
        if path is None:
            return None
        if path in self.sanitized:
            return None
        for n in range(len(path), 0, -1):
            pre = path[:n]
            if pre in self.sanitized:
                return None
            t = self.taint.get(pre)
            if t is not None:
                return t
        return None

    def _sanitize(self, path: Path | None, line: int) -> None:
        if path is None:
            return
        self.sanitized.add(path)
        # sanitizing a whole variable also clears its fields
        if len(path) == 1:
            for p in list(self.taint):
                if p[0] == path[0]:
                    self.taint.pop(p)
            self.taint.pop(path, None)

    # ------------------------------------------------------- expressions

    def eval(self, expr) -> FlowTrace | None:
        """Taint trace of an expression's value, or None when clean."""
        if expr is None or isinstance(expr, ast.Constant):
            return None
        if isinstance(expr, (ast.Name, ast.Attribute, ast.Subscript)):
            t = self.trace_of(_base_path(expr))
            if t is not None and isinstance(expr, ast.Attribute):
                base = _base_path(expr.value)
                if base is not None and self.trace_of(base) is t:
                    return t.extend(FlowStep(
                        self.fn.module, expr.lineno,
                        f"field read .{expr.attr}"))
            if isinstance(expr, ast.Subscript):
                # index taint matters too: d[tainted] as a VALUE is
                # whatever the container held; not propagated here
                pass
            return t
        if isinstance(expr, ast.Call):
            return self._eval_call(expr)
        if isinstance(expr, ast.BinOp):
            return self.eval(expr.left) or self.eval(expr.right)
        if isinstance(expr, ast.UnaryOp):
            return self.eval(expr.operand)
        if isinstance(expr, ast.BoolOp):
            for v in expr.values:
                t = self.eval(v)
                if t:
                    return t
            return None
        if isinstance(expr, ast.Compare):
            return None  # a bool is not a size
        if isinstance(expr, ast.IfExp):
            return self.eval(expr.body) or self.eval(expr.orelse)
        if isinstance(expr, (ast.Tuple, ast.List, ast.Set)):
            for e in expr.elts:
                t = self.eval(e)
                if t:
                    return t
            return None
        if isinstance(expr, ast.Dict):
            for e in list(expr.keys) + list(expr.values):
                t = self.eval(e)
                if t:
                    return t
            return None
        if isinstance(expr, ast.Starred):
            return self.eval(expr.value)
        if isinstance(expr, ast.JoinedStr):
            for v in expr.values:
                t = self.eval(v)
                if t:
                    return t
            return None
        if isinstance(expr, ast.FormattedValue):
            return self.eval(expr.value)
        if isinstance(expr, ast.Slice):
            return self.eval(expr.lower) or self.eval(expr.upper) or self.eval(expr.step)
        if isinstance(expr, (ast.ListComp, ast.SetComp, ast.GeneratorExp)):
            t = None
            for gen in expr.generators:
                gt = self.eval(gen.iter)
                if gt:
                    tgt = _base_path(gen.target)
                    if tgt:
                        self.taint[tgt] = gt.extend(FlowStep(
                            self.fn.module, expr.lineno, "iteration element"))
                    t = t or gt
            return t or self.eval(expr.elt if hasattr(expr, "elt") else None)
        if isinstance(expr, ast.DictComp):
            for gen in expr.generators:
                t = self.eval(gen.iter)
                if t:
                    return t
            return self.eval(expr.key) or self.eval(expr.value)
        if isinstance(expr, ast.Await):
            return self.eval(expr.value)
        if isinstance(expr, ast.NamedExpr):
            t = self.eval(expr.value)
            tgt = _base_path(expr.target)
            if tgt is not None:
                if t:
                    self.taint[tgt] = t
                else:
                    self.taint.pop(tgt, None)
            return t
        return None

    def _call_name(self, call: ast.Call) -> tuple[str | None, str | None]:
        return tail_name(call.func), dotted_name(call.func)

    def _eval_call(self, call: ast.Call) -> FlowTrace | None:
        tail, dn = self._call_name(call)
        args = list(call.args) + [kw.value for kw in call.keywords]
        arg_traces = [self.eval(a) for a in args]

        # ---- sinks first: the call consumes the value as-is
        self._check_sink(call, tail, dn, args, arg_traces)

        # ---- barriers
        if tail in self.regs.barrier_calls or (dn and dn in self.regs.barrier_calls):
            for a in args:
                self._sanitize(_base_path(a), call.lineno)
            return None
        if tail in _VALUE_BARRIER_CALLS:
            return None
        if tail in _CLEAN_CALLS:
            return None

        # ---- sources
        note = None
        if dn and dn in self.regs.source_calls:
            note = self.regs.source_calls[dn]
        elif tail in self.regs.source_calls:
            note = self.regs.source_calls[tail]
        if note is not None:
            return FlowTrace("source", (FlowStep(
                self.fn.module, call.lineno, f"untrusted wire input: {note}"),))

        # ---- interprocedural: resolved callee summary
        callee = self.index.resolve_call(self.fn, call.func)
        if callee is not None:
            summ = self.summaries.get(id(callee))
            if summ is not None:
                # param-position mapping: positional args only (methods
                # drop self in the summary's param list)
                names = _callee_params(callee)
                for i, (a, t) in enumerate(zip(call.args, arg_traces)):
                    if t is None or i >= len(names):
                        continue
                    pname = names[i]
                    for hit in summ.param_sinks.get(pname, ()):
                        self.hits.append(SinkHit(
                            hit.kind, hit.sink_note, hit.module, hit.line,
                            _splice(t, self.fn, call, callee, hit),
                        ))
                for kw in call.keywords:
                    t = self.eval(kw.value)
                    if t is None or kw.arg is None:
                        continue
                    for hit in summ.param_sinks.get(kw.arg, ()):
                        self.hits.append(SinkHit(
                            hit.kind, hit.sink_note, hit.module, hit.line,
                            _splice(t, self.fn, call, callee, hit),
                        ))
                if summ.returns_source:
                    base = summ.return_trace or FlowTrace("source", ())
                    return base.extend(FlowStep(
                        self.fn.module, call.lineno,
                        f"returned by {callee.qualname}()"))
                ret_params = summ.param_to_return
                for i, (a, t) in enumerate(zip(call.args, arg_traces)):
                    if t is not None and i < len(names) and names[i] in ret_params:
                        return t.extend(FlowStep(
                            self.fn.module, call.lineno,
                            f"flows through {callee.qualname}()"))
                for kw in call.keywords:
                    if kw.arg in ret_params:
                        t = self.eval(kw.value)
                        if t is not None:
                            return t.extend(FlowStep(
                                self.fn.module, call.lineno,
                                f"flows through {callee.qualname}()"))
                return None

        # ---- unresolved call: identity builtins propagate, methods on a
        # tainted receiver stay tainted (payload.split(), d.get(k) …)
        if tail in _IDENTITY_CALLS:
            for t in arg_traces:
                if t is not None:
                    return t
            return None
        if isinstance(call.func, ast.Attribute):
            t = self.trace_of(_base_path(call.func.value))
            if t is not None:
                return t.extend(FlowStep(
                    self.fn.module, call.lineno, f"via .{call.func.attr}()"))
        return None

    def _check_sink(self, call, tail, dn, args, arg_traces) -> None:
        spec = None
        if dn and dn in self.regs.sink_dotted:
            spec = self.regs.sink_dotted[dn]
        elif tail in self.regs.sink_calls:
            spec = self.regs.sink_calls[tail]
        if spec is None:
            return
        kind, idxs = spec
        for i, (a, t) in enumerate(zip(args, arg_traces)):
            if t is None:
                continue
            if idxs is not None and i not in idxs:
                continue
            name = dn or tail or "?"
            self.hits.append(SinkHit(
                kind, f"{name}()", self.fn.module, call.lineno,
                t.extend(FlowStep(
                    self.fn.module, call.lineno,
                    f"reaches {kind} sink {name}()")),
            ))

    # -------------------------------------------------------- statements

    def run(self) -> None:
        self._stmts(self.fn.node.body)

    def _stmts(self, body) -> None:
        for stmt in body:
            self._stmt(stmt)

    def _escapes(self, body) -> bool:
        return any(
            isinstance(s, (ast.Raise, ast.Return, ast.Continue, ast.Break))
            for s in body
        )

    def _stmt(self, stmt) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            return  # separate FunctionInfo
        if isinstance(stmt, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            value = stmt.value
            t = self.eval(value)
            targets = (
                stmt.targets if isinstance(stmt, ast.Assign) else [stmt.target]
            )
            for tgt in targets:
                self._assign(tgt, t, stmt, aug=isinstance(stmt, ast.AugAssign))
            return
        if isinstance(stmt, ast.If):
            self._clamp_guard(stmt)
            self.eval(stmt.test)
            self._stmts(stmt.body)
            self._stmts(stmt.orelse)
            return
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            it = stmt.iter
            # range(tainted) loop bound is checked by eval's sink pass
            t = self.eval(it)
            if t is not None:
                tgt = _base_path(stmt.target)
                if tgt is not None:
                    self.taint[tgt] = t.extend(FlowStep(
                        self.fn.module, stmt.lineno, "iteration element"))
                elif isinstance(stmt.target, ast.Tuple):
                    for e in stmt.target.elts:
                        p = _base_path(e)
                        if p is not None:
                            self.taint[p] = t.extend(FlowStep(
                                self.fn.module, stmt.lineno,
                                "iteration element"))
            self._stmts(stmt.body)
            self._stmts(stmt.orelse)
            return
        if isinstance(stmt, ast.While):
            self.eval(stmt.test)
            self._stmts(stmt.body)
            self._stmts(stmt.orelse)
            return
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                t = self.eval(item.context_expr)
                if item.optional_vars is not None:
                    p = _base_path(item.optional_vars)
                    if p is not None and t is not None:
                        self.taint[p] = t
            self._stmts(stmt.body)
            return
        if isinstance(stmt, ast.Try):
            self._stmts(stmt.body)
            for h in stmt.handlers:
                self._stmts(h.body)
            self._stmts(stmt.orelse)
            self._stmts(stmt.finalbody)
            return
        if isinstance(stmt, ast.Match):
            subj = self.eval(stmt.subject)
            for case in stmt.cases:
                if subj is not None:
                    for name, line in _pattern_bindings(case.pattern):
                        self.taint[(name,)] = subj.extend(FlowStep(
                            self.fn.module, line,
                            f"destructured into {name}"))
                self._stmts(case.body)
            return
        if isinstance(stmt, ast.Return):
            t = self.eval(stmt.value)
            if t is not None:
                self.returns.append(t)
            return
        if isinstance(stmt, ast.Expr):
            self.eval(stmt.value)
            return
        if isinstance(stmt, (ast.Raise, ast.Assert)):
            if isinstance(stmt, ast.Assert):
                self._assert_guard(stmt)
            return
        if isinstance(stmt, ast.Delete):
            for tgt in stmt.targets:
                p = _base_path(tgt)
                if p is not None:
                    self.taint.pop(p, None)
            return
        # Global/Nonlocal/Pass/Import...: nothing to do

    def _assign(self, tgt, t: FlowTrace | None, stmt, aug: bool = False) -> None:
        if isinstance(tgt, ast.Tuple):
            for e in tgt.elts:
                self._assign(e, t, stmt, aug)
            return
        p = _base_path(tgt)
        if p is None:
            return
        if t is not None:
            self.taint[p] = t.extend(FlowStep(
                self.fn.module, stmt.lineno,
                f"assigned to {'.'.join(p)}"))
            self.sanitized.discard(p)
        elif not aug and isinstance(tgt, ast.Name):
            # a clean re-assignment kills the old taint (linear walk)
            for q in list(self.taint):
                if q[0] == p[0]:
                    self.taint.pop(q)

    def _clamp_guard(self, stmt: ast.If) -> None:
        """``if <tainted cmp …>: raise/return/continue/break`` sanitizes
        the tainted comparison operand afterward — the repo's clamp
        idiom (``if length > MAX_MESSAGE_LEN: raise``)."""
        test = stmt.test
        if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
            test = test.operand
        if not isinstance(test, ast.Compare):
            return
        if not self._escapes(stmt.body):
            return
        for side in [test.left] + list(test.comparators):
            p = _base_path(side)
            if p is not None and self.trace_of(p) is not None:
                self._sanitize(p, stmt.lineno)
            # ``if not 0 <= x < cap: raise`` with x inside a len() etc.
            if isinstance(side, ast.Call):
                for a in side.args:
                    q = _base_path(a)
                    if q is not None and self.trace_of(q) is not None:
                        self._sanitize(q, stmt.lineno)

    def _assert_guard(self, stmt: ast.Assert) -> None:
        test = stmt.test
        if isinstance(test, ast.Compare):
            for side in [test.left] + list(test.comparators):
                p = _base_path(side)
                if p is not None and self.trace_of(p) is not None:
                    self._sanitize(p, stmt.lineno)


def _pattern_bindings(pattern) -> list[tuple[str, int]]:
    """Names a match-case pattern binds from the subject."""
    out: list[tuple[str, int]] = []
    for node in ast.walk(pattern):
        if isinstance(node, ast.MatchAs) and node.name:
            out.append((node.name, node.lineno))
        elif isinstance(node, ast.MatchStar) and node.name:
            out.append((node.name, node.lineno))
        elif isinstance(node, ast.MatchMapping) and node.rest:
            out.append((node.rest, node.lineno))
    return out


def _callee_params(fn: FunctionInfo) -> list[str]:
    node = fn.node
    if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return []
    names = [a.arg for a in list(node.args.posonlyargs) + list(node.args.args)]
    if names and names[0] in ("self", "cls"):
        names = names[1:]
    return names


def _splice(caller_trace: FlowTrace, fn: FunctionInfo, call: ast.Call,
            callee: FunctionInfo, hit: SinkHit) -> FlowTrace:
    """Join a caller-side trace to a callee-side sink trace: source …
    call-site hop … the callee's own propagation steps."""
    t = caller_trace.extend(FlowStep(
        fn.module, call.lineno, f"passed into {callee.qualname}()"))
    # drop the callee trace's synthetic "parameter" root step, keep the rest
    inner = tuple(s for s in hit.trace.steps[1:])
    steps = t.steps + inner
    if len(steps) > 16:
        steps = steps[:3] + steps[-13:]
    return FlowTrace(caller_trace.root, steps)


# ---------------------------------------------------------------- driver


class TaintAnalysis:
    """Whole-package run: summaries to fixpoint, then source-mode hits."""

    def __init__(self, index: PackageIndex, regs: Registries):
        self.index = index
        self.regs = regs
        self.summaries: dict[int, Summary] = {
            id(fn): Summary() for fn in index.functions
        }
        self._fixpoint()
        self.hits: list[SinkHit] = self._collect()

    def _summarize(self, fn: FunctionInfo) -> Summary:
        eng = _Engine(self.index, fn, self.regs, self.summaries,
                      taint_params=True)
        eng.run()
        s = Summary()
        for t in eng.returns:
            if t.root == "source":
                s.returns_source = True
                if s.return_trace is None:
                    s.return_trace = t
            else:
                s.param_to_return.add(t.root)
        for hit in eng.hits:
            if hit.trace.root == "source":
                continue  # a true source flow; reported by _collect
            s.param_sinks.setdefault(hit.trace.root, []).append(hit)
        # a function that CALLS a source and returns it is itself a
        # source; handled because eval tags those traces root="source"
        return s

    def _fixpoint(self) -> None:
        # iterate until summaries stabilize; depth of real call chains
        # here is small — cap the rounds to stay linter-fast
        for _ in range(6):
            changed = False
            for fn in self.index.functions:
                new = self._summarize(fn)
                old = self.summaries[id(fn)]
                if (
                    new.returns_source != old.returns_source
                    or new.param_to_return != old.param_to_return
                    or {k: len(v) for k, v in new.param_sinks.items()}
                    != {k: len(v) for k, v in old.param_sinks.items()}
                ):
                    changed = True
                self.summaries[id(fn)] = new
            if not changed:
                break

    def _collect(self) -> list[SinkHit]:
        hits: list[SinkHit] = []
        for fn in self.index.functions:
            eng = _Engine(self.index, fn, self.regs, self.summaries,
                          taint_params=False)
            eng.run()
            hits.extend(h for h in eng.hits if h.trace.root == "source")
        return hits

    def function_taint(self, fn: FunctionInfo) -> "_Engine":
        """Re-run one function in source mode and return the engine (the
        bounded-state pass reads its final taint map)."""
        eng = _Engine(self.index, fn, self.regs, self.summaries,
                      taint_params=False)
        eng.run()
        return eng
