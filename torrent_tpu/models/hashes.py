"""BEP 52 merkle hash transfer: serve and verify ``hash request`` data.

v2/hybrid swarms exchange per-file merkle subtrees on the wire
(messages 21-23, net/protocol.py) so a downloader can verify 16 KiB
blocks against the ``pieces root`` in the info dict without trusting
the sender. This module is the math behind both sides:

- ``HashTreeCache.serve`` answers a request from a file's *piece
  layer* (what a `.torrent`'s ``piece layers`` dict carries): the
  requested run of hashes plus the uncle hashes that chain its subtree
  root up to ``pieces root``.
- ``verify_hash_response`` replays that chain and accepts only if it
  lands exactly on the expected root — the client-side check.

Layer numbering follows the BEP: layer 0 is the 16 KiB leaf layer and
grows upward, so a file's piece layer sits at
``log2(piece_length / 16384)``. The served layer is padded to a power
of two with zero-subtree roots of matching height (the same padding
rule the file root itself is computed with, models/merkle.py).
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass

from torrent_tpu.codec.metainfo_v2 import BLOCK
from torrent_tpu.models.merkle import zero_chain

# DoS bound: the longest hash run a single request may ask for (16 KiB
# of digests); every validation on a request lives in serve() itself
MAX_RUN = 512


@dataclass(frozen=True)
class HashRequestFields:
    """The five fields shared by request/response/reject (BEP 52)."""

    pieces_root: bytes
    base_layer: int
    index: int
    length: int
    proof_layers: int


def _layer_height(piece_length: int) -> int:
    """Piece layer number = log2(piece_length / BLOCK)."""
    return (piece_length // BLOCK).bit_length() - 1


class HashTreeCache:
    """Per-torrent cache of reconstructed upper merkle layers.

    Built lazily per ``pieces_root`` from the piece layer; a layer of n
    hashes reconstructs ``log2(n)`` upper levels of 32-byte digests —
    a 100k-piece file costs ~6.4 MB once, then every request is a
    slice + a handful of sibling lookups.
    """

    def __init__(self, piece_layers: dict[bytes, tuple[bytes, ...]], piece_length: int):
        self.piece_layers = piece_layers
        self.piece_length = piece_length
        self.base = _layer_height(piece_length)
        self._trees: dict[bytes, list[list[bytes]]] = {}
        self._single_roots: set[bytes] = set()
        # serve() runs in worker threads (session offloads the first
        # build); one lock bounds a pipelined burst of requests for the
        # same root to a single tree construction
        from torrent_tpu.analysis.sanitizer import named_lock

        self._build_lock = named_lock("models.hashes._build_lock")

    def _tree_for(self, root: bytes) -> list[list[bytes]] | None:
        with self._build_lock:
            return self._tree_for_locked(root)

    def _tree_for_locked(self, root: bytes) -> list[list[bytes]] | None:
        tree = self._trees.get(root)
        if tree is not None:
            return tree
        layer = self.piece_layers.get(root)
        if layer is None:
            # single-piece files carry no piece-layers entry: their root
            # IS the only piece hash, a one-node base layer — but only
            # for roots the owner registered (anything else is unknown)
            if root not in self._known_single_roots():
                return None
            layer = (root,)
        padded = 1 << max(0, (len(layer) - 1).bit_length())
        zero = zero_chain(self.base)[self.base]
        level = list(layer) + [zero] * (padded - len(layer))
        levels = [level]
        while len(level) > 1:
            level = [
                hashlib.sha256(level[i] + level[i + 1]).digest()
                for i in range(0, len(level), 2)
            ]
            levels.append(level)
        if levels[-1][0] != root:
            return None  # corrupt layer; never serve from it
        self._trees[root] = levels
        return levels

    def _known_single_roots(self) -> set[bytes]:
        return self._single_roots

    def add_single_piece_roots(self, roots) -> None:
        """Register roots of files that fit in one piece (no layer entry)."""
        self._single_roots = set(roots)

    def serve(self, req: HashRequestFields) -> list[bytes] | None:
        """→ ``length + proof_layers`` hashes, or None (reject).

        Requests below the piece layer need file data we don't index
        here; requests above it are equivalent to a shorter piece-layer
        request, so both are rejected — real clients ask at the piece
        layer (libtorrent does exactly this for seeding from metadata).
        """
        if (
            req.base_layer != self.base
            or req.length < 1
            or req.length > MAX_RUN
            or req.length & (req.length - 1)
            or req.index % req.length
            or req.index < 0
            or req.proof_layers < 0
        ):
            return None
        levels = self._tree_for(req.pieces_root)
        if levels is None or req.index >= len(levels[0]):
            return None
        # levels[0] is already zero-padded to a power of two, and the
        # proof-availability check below rejects any span past it
        run = levels[0][req.index : req.index + req.length]
        # the span [index, index+length) reduces to one node this many
        # levels up; proofs are that node's successive siblings
        span_level = req.length.bit_length() - 1
        avail = len(levels) - 1 - span_level
        if req.proof_layers > avail:
            return None
        proofs = []
        pos = req.index >> span_level
        for k in range(req.proof_layers):
            level = levels[span_level + k]
            proofs.append(level[pos ^ 1])
            pos >>= 1
        return run + proofs


def verify_hash_response(req: HashRequestFields, hashes: list[bytes]) -> bool:
    """Client-side acceptance: the run + proofs must chain to pieces_root.

    ``proof_layers`` must cover the whole distance to the root (the
    normal request shape); anything shorter reduces to an unverifiable
    midpoint and is refused — a partial proof proves nothing without a
    trusted intermediate digest.
    """
    if (
        req.length < 1
        or req.length & (req.length - 1)
        or req.index < 0
        or req.proof_layers < 0
        or len(hashes) != req.length + req.proof_layers
    ):
        return False  # malformed geometry can't verify (and must not raise)
    run, proofs = hashes[: req.length], hashes[req.length :]
    level = list(run)
    while len(level) > 1:
        level = [
            hashlib.sha256(level[i] + level[i + 1]).digest()
            for i in range(0, len(level), 2)
        ]
    node = level[0]
    pos = req.index >> (req.length.bit_length() - 1)
    for sibling in proofs:
        pair = (sibling + node) if pos & 1 else (node + sibling)
        node = hashlib.sha256(pair).digest()
        pos >>= 1
    return pos == 0 and node == req.pieces_root
