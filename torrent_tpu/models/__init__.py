from torrent_tpu.models.verifier import TPUVerifier

__all__ = ["TPUVerifier"]
