"""BitTorrent v2 hashing/verify pipeline — batched SHA-256 + merkle.

Authoring and resume-recheck for BEP 52 torrents on the TPU hash plane:

- ``hash_file_v2``    — one file's bytes → (pieces_root, piece layer)
- ``build_v2``        — author a pure-v2 torrent from (path, reader)s
- ``verify_v2``       — recheck files against piece layers; returns a
                        per-piece bool array for every file (the v2
                        analogue of the v1 bitfield)

Leaves are uniform 16 KiB blocks → one padded batch through the SHA-256
plane; every merkle level above them is a single ``sha256_pairs`` call
(``models/merkle.py``). ``hasher='cpu'`` hashes leaves with hashlib (the
dominant cost — the merkle reduction above them always runs on the
device plane); the independent spec oracle lives in tests/test_v2.py.
"""

from __future__ import annotations

import hashlib

import numpy as np

from torrent_tpu.codec.metainfo_v2 import BLOCK, InfoDictV2, MetainfoV2, V2File
from torrent_tpu.models.merkle import (
    digests_to_words32,
    file_root_from_piece_roots,
    merkle_root,
    pad_leaves,
    piece_roots_from_leaves,
    small_file_root,
    words32_to_digests,
    zero_chain,
)
from torrent_tpu.ops.padding import alloc_padded, pad_in_place
from torrent_tpu.ops.sha256_jax import make_sha256_fn

# Leaf blocks hashed per device launch: 4096 × 16 KiB = 64 MiB staging.
LEAF_BATCH = 4096

# A "source" is either resident bytes or a filesystem path (str) that is
# streamed in LEAF_BATCH-block chunks — a 60 GiB file never holds more
# than one ~64 MiB chunk in memory.


def source_len(source) -> int:
    if isinstance(source, (bytes, bytearray, memoryview)):
        return len(source)
    import os

    return os.path.getsize(source)


def _iter_source(source, chunk_bytes: int):
    """Yield ``chunk_bytes``-sized slices of the source (last may be short).

    Path sources go through the native C++ pread pool when it's built
    (striped parallel reads per chunk — the same engine behind
    ``Storage.read_batch``); plain buffered reads otherwise.
    """
    if isinstance(source, (bytes, bytearray, memoryview)):
        mv = memoryview(source)
        for off in range(0, len(mv), chunk_bytes):
            yield bytes(mv[off : off + chunk_bytes])
        return
    from torrent_tpu.native.io_engine import get_engine

    engine = get_engine()
    total = source_len(source)
    if engine is not None and total > 0:
        path = str(source)
        buf = np.empty(chunk_bytes, dtype=np.uint8)
        stripes = 4
        for off in range(0, total, chunk_bytes):
            n = min(chunk_bytes, total - off)
            step = -(-n // stripes)
            segs = [
                (0, off + s, s, min(step, n - s)) for s in range(0, n, step)
            ]
            engine.read_segments([path], segs, buf[:n])
            yield buf[:n].tobytes()
        return
    with open(source, "rb") as f:
        while True:
            chunk = f.read(chunk_bytes)
            if not chunk:
                return
            yield chunk


def _leaf_words_device(source, backend: str) -> np.ndarray:
    """SHA-256 leaf hashes for a file source → ``u32[n_blocks, 8]``.

    Batch rows are pow-2 bucketed (floor 16, cap LEAF_BATCH) so arbitrary
    file sizes share a handful of compiled executables instead of one per
    block count; sentinel rows carry ``nblocks=0`` and never run.
    """
    import jax

    total = source_len(source)
    n = max(1, -(-total // BLOCK))
    b = min(LEAF_BATCH, max(16, 1 << (n - 1).bit_length()))
    if backend == "auto":
        # the pallas kernel pads launches to TILE rows and only compiles
        # for real (non-interpret) on TPU-kind devices — anywhere else
        # (CPU, GPU, or a jax without pallas at all) the scan backend wins
        try:
            from torrent_tpu.ops.sha1_pallas import TILE, _auto_interpret

            backend = "pallas" if b % TILE == 0 and not _auto_interpret() else "jax"
        except ImportError:
            backend = "jax"
    fn = make_sha256_fn(backend)
    out = np.zeros((n, 8), dtype=np.uint32)
    padded, view = alloc_padded(b, BLOCK)
    start = 0
    for chunk in _iter_source(source, b * BLOCK):
        k = -(-len(chunk) // BLOCK)
        lengths = np.zeros(b, dtype=np.int64)
        padded[:] = 0
        flat = np.frombuffer(chunk, dtype=np.uint8)
        full, rem = divmod(len(chunk), BLOCK)
        view[:full] = flat[: full * BLOCK].reshape(full, BLOCK)
        lengths[:full] = BLOCK
        if rem:
            view[full, :rem] = flat[full * BLOCK :]
            lengths[full] = rem
        nblocks = pad_in_place(padded, lengths)
        nblocks[k:] = 0
        words = np.asarray(fn(jax.numpy.asarray(padded), jax.numpy.asarray(nblocks)))
        out[start : start + k] = words[:k]
        start += k
    if total == 0:  # empty source: single zero-length leaf
        lengths = np.zeros(b, dtype=np.int64)
        padded[:] = 0
        nblocks = pad_in_place(padded, lengths)
        nblocks[1:] = 0
        out[0] = np.asarray(fn(jax.numpy.asarray(padded), jax.numpy.asarray(nblocks)))[0]
    return out


def _leaf_words_cpu(source) -> np.ndarray:
    digs = []
    for chunk in _iter_source(source, LEAF_BATCH * BLOCK):
        for i in range(0, len(chunk), BLOCK):
            digs.append(hashlib.sha256(chunk[i : i + BLOCK]).digest())
    if not digs:
        digs.append(hashlib.sha256(b"").digest())
    return digests_to_words32(digs)


def hash_file_v2(
    source, piece_length: int, hasher: str = "tpu"
) -> tuple[bytes, tuple[bytes, ...]]:
    """One file source (bytes or filesystem path) → (pieces_root, layer).

    The layer is empty for files of at most one piece (BEP 52 publishes
    piece layers only for multi-piece files). Path sources stream in
    bounded chunks — memory is independent of file size.
    """
    total = source_len(source)
    if total == 0:
        return b"\x00" * 32, ()
    if hasher == "cpu":
        leaves = _leaf_words_cpu(source)
    else:
        leaves = _leaf_words_device(source, "auto")
    if total <= piece_length:
        return small_file_root(leaves), ()
    lpp = piece_length // BLOCK
    roots = piece_roots_from_leaves(leaves, lpp)
    layer = tuple(words32_to_digests(roots))
    return file_root_from_piece_roots(roots, lpp), layer


def build_v2(
    files: list[tuple[tuple[str, ...], "bytes | str"]],
    name: str,
    piece_length: int,
    hasher: str = "tpu",
    announce: str | None = None,
    private: bool = False,
    comment: str | None = None,
    announce_list: list[list[str]] | None = None,
    web_seeds: list[str] | None = None,
) -> MetainfoV2:
    """Author a pure-v2 torrent from (path, source) entries.

    Sources are bytes or filesystem paths (streamed — a 60 GiB corpus
    never holds more than one leaf chunk resident).
    """
    if piece_length < BLOCK or piece_length & (piece_length - 1):
        raise ValueError("piece_length must be a power of two >= 16 KiB")
    from torrent_tpu.codec.metainfo_v2 import valid_path_component

    for path, _ in files:
        for part in path:
            if not valid_path_component(part):
                raise ValueError(
                    f"path component {part!r} cannot appear in a v2 file tree "
                    "(separator/traversal/non-UTF-8 names are not encodable)"
                )
    v2files: list[V2File] = []
    layers: dict[bytes, tuple[bytes, ...]] = {}
    for path, source in sorted(files, key=lambda e: e[0]):
        root, layer = hash_file_v2(source, piece_length, hasher)
        v2files.append(V2File(path=path, length=source_len(source), pieces_root=root))
        if layer:
            layers[root] = layer
    info = InfoDictV2(
        name=name, piece_length=piece_length, files=tuple(v2files), private=private
    )
    from torrent_tpu.codec.metainfo_v2 import encode_metainfo_v2, parse_metainfo_v2

    encoded = encode_metainfo_v2(
        info, layers, announce,
        comment=comment, announce_list=announce_list, web_seeds=web_seeds,
    )
    parsed = parse_metainfo_v2(encoded)
    assert parsed is not None, "authored v2 metainfo failed its own parse"
    return parsed


def verify_v2(
    read_file,
    meta: MetainfoV2,
    hasher: str = "tpu",
) -> dict[tuple[str, ...], np.ndarray]:
    """Recheck every file against its pieces_root / piece layer.

    ``read_file(path_tuple) -> bytes | path-str | None`` supplies each
    file's source (None = missing; a path source streams in bounded
    chunks). Returns ``{path: bool[n_pieces]}`` — the v2 analogue of the
    v1 resume-recheck bitfield, per file.
    """
    plen = meta.info.piece_length
    lpp = plen // BLOCK
    results: dict[tuple[str, ...], np.ndarray] = {}
    for f in meta.info.files:
        n_pieces = f.num_pieces(plen)
        ok = np.zeros(max(1, n_pieces), dtype=bool)
        source = read_file(f.path)
        if source is None or (source_len(source) != f.length):
            results[f.path] = ok if f.length else np.ones(0, dtype=bool)
            continue
        if f.length == 0:
            results[f.path] = np.ones(0, dtype=bool)
            continue
        if hasher == "cpu":
            leaves = _leaf_words_cpu(source)
        else:
            leaves = _leaf_words_device(source, "auto")
        if f.length <= plen:
            ok[0] = small_file_root(leaves) == f.pieces_root
            results[f.path] = ok
            continue
        roots = piece_roots_from_leaves(leaves, lpp)
        layer = meta.piece_layers.get(f.pieces_root, ())
        # metadata self-consistency: the published layer must merkle up to
        # the published root (a hostile layer otherwise localizes damage
        # to the wrong pieces). Data corruption must NOT trip this — the
        # per-piece comparison below is what localizes it.
        if (
            len(layer) != n_pieces
            or file_root_from_piece_roots(digests_to_words32(layer), lpp) != f.pieces_root
        ):
            results[f.path] = ok
            continue
        got = words32_to_digests(roots)
        for i in range(n_pieces):
            ok[i] = got[i] == layer[i]
        results[f.path] = ok
    return results
