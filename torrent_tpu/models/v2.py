"""BitTorrent v2 hashing/verify pipeline — batched SHA-256 + merkle.

Authoring and resume-recheck for BEP 52 torrents on the TPU hash plane:

- ``hash_file_v2``    — one file's bytes → (pieces_root, piece layer)
- ``build_v2``        — author a pure-v2 torrent from (path, reader)s
- ``verify_v2``       — recheck files against piece layers; returns a
                        per-piece bool array for every file (the v2
                        analogue of the v1 bitfield)

Leaves are uniform 16 KiB blocks → one padded batch through the SHA-256
plane; the merkle levels above them reduce one ``sha256_pairs`` dispatch
per level per shape group across ALL files (``roots_batched``).
``hasher='cpu'`` is device-free END TO END — hashlib leaves AND hashlib
merkle folds (``_root_cpu``) — so an explicitly-CPU author/verify never
touches the jax backend (on hosts whose default device is remote or
wedged, the first dispatch would hang). The independent spec oracle
lives in tests/test_v2.py.
"""

from __future__ import annotations

import functools
import hashlib

import numpy as np

from torrent_tpu.codec.metainfo_v2 import BLOCK, InfoDictV2, MetainfoV2, V2File
from torrent_tpu.models.merkle import (
    digests_to_words32,
    file_root_from_piece_roots,
    merkle_root,
    pad_leaves,
    piece_roots_from_leaves,
    small_file_root,
    words32_to_digests,
    zero_chain,
)
from torrent_tpu.ops.padding import alloc_padded, pad_in_place
from torrent_tpu.ops.sha256_jax import make_sha256_fn
from torrent_tpu.utils.env import env_int

# Leaf blocks hashed per device launch: 32768 × 16 KiB = 512 MiB
# staging. Dispatch size is the dominant throughput knob on a remote
# device (a ~55 ms fixed per-dispatch cost swamps 64 MiB launches —
# measured 1.9 GiB/s at 4096 leaves vs the kernel's much higher
# sustained rate); memory-constrained hosts can dial it back via the
# env knob.
LEAF_BATCH = env_int("TORRENT_TPU_LEAF_BATCH", 32768)

# A "source" is either resident bytes or a filesystem path (str) that is
# streamed in LEAF_BATCH-block chunks — a 60 GiB file never holds more
# than one chunk (LEAF_BATCH x 16 KiB) in memory.


def source_len(source) -> int:
    if isinstance(source, (bytes, bytearray, memoryview)):
        return len(source)
    import os

    return os.path.getsize(source)


def _iter_source(source, chunk_bytes: int):
    """Yield ``chunk_bytes``-sized slices of the source (last may be short).

    Path sources go through the native C++ pread pool when it's built
    (striped parallel reads per chunk — the same engine behind
    ``Storage.read_batch``); plain buffered reads otherwise.
    """
    if isinstance(source, (bytes, bytearray, memoryview)):
        mv = memoryview(source)
        for off in range(0, len(mv), chunk_bytes):
            yield bytes(mv[off : off + chunk_bytes])
        return
    from torrent_tpu.native.io_engine import get_engine

    engine = get_engine()
    total = source_len(source)
    if engine is not None and total > 0:
        path = str(source)
        buf = np.empty(chunk_bytes, dtype=np.uint8)
        stripes = 4
        for off in range(0, total, chunk_bytes):
            n = min(chunk_bytes, total - off)
            step = -(-n // stripes)
            segs = [
                (0, off + s, s, min(step, n - s)) for s in range(0, n, step)
            ]
            engine.read_segments([path], segs, buf[:n])
            yield buf[:n].tobytes()
        return
    with open(source, "rb") as f:
        while True:
            chunk = f.read(chunk_bytes)
            if not chunk:
                return
            yield chunk


def _make_leaf_fn(b: int, backend: str):
    """SHA-256 fn for a ``b``-row leaf batch; ``auto`` prefers Pallas.

    The pallas kernel pads launches to a ``tile_sub*128``-row multiple and
    only compiles for real (non-interpret) on TPU-kind devices — anywhere
    else (CPU, GPU, or a jax without pallas at all) the scan backend
    wins. tile_sub is a call parameter now, so any 1024-row-multiple
    batch qualifies: pick the largest sublane count that divides ``b``
    (pow-2 bucketed batches of 1024/2048 rows keep the fast path at
    tile_sub 8/16 instead of silently falling back to the scan backend).
    """
    if backend == "auto":
        try:
            from torrent_tpu.ops.sha1_pallas import _auto_interpret

            backend = "jax"
            if not _auto_interpret():
                from torrent_tpu.ops import sha256_pallas as sp256

                # try the tuned TORRENT_TPU_SHA256_TILE_SUB first — the
                # knob must actually reach this hot path or the sweep
                # tool's winner would be a no-op here
                for ts in dict.fromkeys((sp256.TILE_SUB, 32, 16, 8)):
                    if b % (ts * 128) == 0:
                        return lambda d, nb, _ts=ts: sp256.sha256_pieces_pallas(
                            d, nb, tile_sub=_ts
                        )
        except ImportError:
            backend = "jax"
    return make_sha256_fn(backend)


def _leaf_words_from_chunks(chunks, total: int, backend: str) -> np.ndarray:
    """SHA-256 leaf hashes from an iterator of block-aligned chunks
    → ``u32[n_blocks, 8]``.

    Batch rows are pow-2 bucketed (floor 16, cap LEAF_BATCH) so arbitrary
    file sizes share a handful of compiled executables instead of one per
    block count; sentinel rows carry ``nblocks=0`` and never run.
    """
    import jax

    n = max(1, -(-total // BLOCK))
    b = min(LEAF_BATCH, max(16, 1 << (n - 1).bit_length()))
    fn = _make_leaf_fn(b, backend)
    out = np.zeros((n, 8), dtype=np.uint32)
    padded, view = alloc_padded(b, BLOCK)
    start = 0
    for chunk in chunks:
        k = -(-len(chunk) // BLOCK)
        lengths = np.zeros(b, dtype=np.int64)
        padded[:] = 0
        flat = np.frombuffer(chunk, dtype=np.uint8)
        full, rem = divmod(len(chunk), BLOCK)
        view[:full] = flat[: full * BLOCK].reshape(full, BLOCK)
        lengths[:full] = BLOCK
        if rem:
            view[full, :rem] = flat[full * BLOCK :]
            lengths[full] = rem
        nblocks = pad_in_place(padded, lengths)
        nblocks[k:] = 0
        words = np.asarray(fn(jax.numpy.asarray(padded), jax.numpy.asarray(nblocks)))
        out[start : start + k] = words[:k]
        start += k
    if total == 0:  # empty source: single zero-length leaf
        lengths = np.zeros(b, dtype=np.int64)
        padded[:] = 0
        nblocks = pad_in_place(padded, lengths)
        nblocks[1:] = 0
        out[0] = np.asarray(fn(jax.numpy.asarray(padded), jax.numpy.asarray(nblocks)))[0]
    return out


def _leaf_words_device(source, backend: str) -> np.ndarray:
    total = source_len(source)
    n = max(1, -(-total // BLOCK))
    b = min(LEAF_BATCH, max(16, 1 << (n - 1).bit_length()))
    return _leaf_words_from_chunks(_iter_source(source, b * BLOCK), total, backend)


def _leaf_words_cpu_from_chunks(chunks) -> np.ndarray:
    digs = []
    for chunk in chunks:
        for i in range(0, len(chunk), BLOCK):
            digs.append(hashlib.sha256(chunk[i : i + BLOCK]).digest())
    if not digs:
        digs.append(hashlib.sha256(b"").digest())
    return digests_to_words32(digs)


def _leaf_words_cpu(source) -> np.ndarray:
    return _leaf_words_cpu_from_chunks(_iter_source(source, LEAF_BATCH * BLOCK))


def _root_cpu(words: np.ndarray, pad_to: int, pad_digest: bytes = b"\x00" * 32) -> bytes:
    """hashlib pair-fold of ``u32[n, 8]`` leaf/node words padded to
    ``pad_to`` with ``pad_digest`` — the device-free merkle reduction the
    ``hasher='cpu'`` paths use (a pure-CPU run must never touch the jax
    backend: on hosts where the default device is remote or wedged, a
    'cpu' author/verify would otherwise hang on the first dispatch)."""
    nodes = list(words32_to_digests(words)) + [pad_digest] * (pad_to - words.shape[0])
    while len(nodes) > 1:
        nodes = [
            hashlib.sha256(nodes[i] + nodes[i + 1]).digest()
            for i in range(0, len(nodes), 2)
        ]
    return nodes[0]


def roots_batched(
    entries: "list[tuple[int, np.ndarray]]", piece_length: int, device: bool = True
) -> list[tuple[bytes, tuple[bytes, ...]]]:
    """(pieces_root, layer) for MANY files from precomputed leaf words,
    with ONE pair-reduction dispatch per tree level per shape group
    instead of one reduction chain per file (round-2 verdict #3: the
    per-file merkle levels were many small dispatches).

    ``entries`` is ``[(length, leaf_words u32[n,8]), ...]``. Three
    batched stages, numerically identical to hash_file_v2:

    1. small files (≤1 piece) group by their pow2 leaf-pad target; each
       group stacks to ``[k, target, 8]`` and reduces together (the
       leading axis of ``merkle_root`` flattens into the pair batch);
    2. big files' leaf grids concatenate to ``[total_pieces, lpp, 8]``
       — every piece root of every file in log2(lpp) dispatches;
    3. per-file piece-root layers pad with the zero-piece-subtree root,
       group by padded length, and reduce stacked the same way.
    """
    lpp = piece_length // BLOCK
    out: list = [None] * len(entries)

    # stage 1: single-piece files, grouped by pad target
    small_groups: dict[int, list[int]] = {}
    for i, (length, leaves) in enumerate(entries):
        if length == 0:
            out[i] = (b"\x00" * 32, ())
        elif length <= piece_length:
            n = leaves.shape[0]
            target = max(1, 1 << max(0, (n - 1).bit_length()))
            small_groups.setdefault(target, []).append(i)
    for target, idxs in small_groups.items():
        if device:
            stacked = np.stack(
                [pad_leaves(entries[i][1], target) for i in idxs]
            )  # [k, target, 8]
            roots = words32_to_digests(merkle_root(stacked))
        else:
            roots = [_root_cpu(entries[i][1], target) for i in idxs]
        for i, r in zip(idxs, roots):
            out[i] = (r, ())

    # stage 2: all big files' piece roots in one reduction chain
    big = [i for i, (length, _) in enumerate(entries) if length > piece_length]
    if big:
        counts = [-(-entries[i][0] // piece_length) for i in big]
        if device:
            grid = np.zeros((sum(counts), lpp, 8), dtype=np.uint32)
            pos = 0
            for i, n_pieces in zip(big, counts):
                leaves = entries[i][1]
                grid.reshape(-1, 8)[pos * lpp : pos * lpp + leaves.shape[0]] = leaves
                pos += n_pieces
            all_roots = merkle_root(grid)  # [sum_pieces, 8]
        else:
            rows = []
            for i, n_pieces in zip(big, counts):
                leaves = entries[i][1]
                for p in range(n_pieces):
                    rows.append(
                        digests_to_words32(
                            [_root_cpu(leaves[p * lpp : (p + 1) * lpp], lpp)]
                        )[0]
                    )
            all_roots = np.stack(rows)

        # stage 3: file roots from the piece-root layers, grouped by
        # padded layer length (zero-piece-subtree padding, BEP 52)
        height = lpp.bit_length() - 1
        zero_root = zero_chain(height)[height]
        zero_root_words = digests_to_words32([zero_root])[0]
        layer_groups: dict[int, list[tuple[int, np.ndarray]]] = {}
        pos = 0
        for i, n_pieces in zip(big, counts):
            roots_i = all_roots[pos : pos + n_pieces]
            pos += n_pieces
            padded_n = 1 << max(0, (n_pieces - 1).bit_length())
            layer_groups.setdefault(padded_n, []).append((i, roots_i))
        for padded_n, group in layer_groups.items():
            if device:
                stacked = np.tile(zero_root_words, (len(group), padded_n, 1))
                for g, (_, roots_i) in enumerate(group):
                    stacked[g, : roots_i.shape[0]] = roots_i
                file_roots = words32_to_digests(merkle_root(stacked))
            else:
                file_roots = [
                    _root_cpu(roots_i, padded_n, pad_digest=zero_root)
                    for _, roots_i in group
                ]
            for (i, roots_i), fr in zip(group, file_roots):
                out[i] = (fr, tuple(words32_to_digests(roots_i)))
    return out


# Leaf-word window for the batched reduction passes: flush once this
# many leaves (32 B each) are resident. The default bounds leaf RAM at
# ~64 MB (covering ~32 GiB of payload per window) — batching still
# collapses reductions to one dispatch per level per shape group WITHIN
# a window, without the corpus-proportional residency of an unbounded
# pass.
LEAF_WINDOW = env_int("TORRENT_TPU_LEAF_WINDOW", 1 << 21)


def roots_batched_windowed(
    entry_iter, piece_length: int, window: int | None = None, device: bool = True
) -> list[tuple[bytes, tuple[bytes, ...]]]:
    """Windowed driver for :func:`roots_batched`: consumes an iterator of
    ``(length, leaf_words)`` and flushes whenever the resident leaf count
    reaches ``window`` (default ``LEAF_WINDOW``), so memory stays bounded
    no matter how large the corpus is. Results keep input order."""
    window = window or LEAF_WINDOW
    out: list[tuple[bytes, tuple[bytes, ...]]] = []
    buf: list[tuple[int, np.ndarray]] = []
    acc = 0
    for entry in entry_iter:
        buf.append(entry)
        acc += entry[1].shape[0]
        if acc >= window:
            out.extend(roots_batched(buf, piece_length, device=device))
            buf, acc = [], 0
    if buf:
        out.extend(roots_batched(buf, piece_length, device=device))
    return out


def hash_file_v2(
    source, piece_length: int, hasher: str = "tpu"
) -> tuple[bytes, tuple[bytes, ...]]:
    """One file source (bytes or filesystem path) → (pieces_root, layer).

    The layer is empty for files of at most one piece (BEP 52 publishes
    piece layers only for multi-piece files). Path sources stream in
    bounded chunks — memory is independent of file size.
    """
    total = source_len(source)
    if total == 0:
        return b"\x00" * 32, ()
    if hasher == "cpu":
        leaves = _leaf_words_cpu(source)
        # device=False keeps a 'cpu' run off the jax backend entirely
        # (on hosts with a remote/wedged default device the first
        # dispatch would hang an explicitly-CPU author/verify)
        return roots_batched([(total, leaves)], piece_length, device=False)[0]
    leaves = _leaf_words_device(source, "auto")
    if total <= piece_length:
        return small_file_root(leaves), ()
    lpp = piece_length // BLOCK
    roots = piece_roots_from_leaves(leaves, lpp)
    layer = tuple(words32_to_digests(roots))
    return file_root_from_piece_roots(roots, lpp), layer


def build_v2(
    files: list[tuple[tuple[str, ...], "bytes | str"]],
    name: str,
    piece_length: int,
    hasher: str = "tpu",
    announce: str | None = None,
    private: bool = False,
    comment: str | None = None,
    announce_list: list[list[str]] | None = None,
    web_seeds: list[str] | None = None,
) -> MetainfoV2:
    """Author a pure-v2 torrent from (path, source) entries.

    Sources are bytes or filesystem paths (streamed — a 60 GiB corpus
    never holds more than one leaf chunk resident).
    """
    if piece_length < BLOCK or piece_length & (piece_length - 1):
        raise ValueError("piece_length must be a power of two >= 16 KiB")
    from torrent_tpu.codec.metainfo_v2 import valid_path_component

    for path, _ in files:
        for part in path:
            if not valid_path_component(part):
                raise ValueError(
                    f"path component {part!r} cannot appear in a v2 file tree "
                    "(separator/traversal/non-UTF-8 names are not encodable)"
                )
    # phase 1: leaf words per file (streaming — bounded by the chunk
    # size, not file size); phase 2: batched reduction passes across
    # files (roots_batched_windowed: one dispatch per level per shape
    # group within each bounded-residency window, not a chain per file)
    ordered = sorted(files, key=lambda e: e[0])
    lengths = [source_len(source) for _, source in ordered]

    def leaf_entries():
        for (_, source), total in zip(ordered, lengths):
            if total == 0:
                yield 0, np.zeros((0, 8), dtype=np.uint32)
            elif hasher == "cpu":
                yield total, _leaf_words_cpu(source)
            else:
                yield total, _leaf_words_device(source, "auto")

    reduced = roots_batched_windowed(
        leaf_entries(), piece_length, device=hasher != "cpu"
    )
    v2files: list[V2File] = []
    layers: dict[bytes, tuple[bytes, ...]] = {}
    for (path, _), total, (root, layer) in zip(ordered, lengths, reduced):
        v2files.append(V2File(path=path, length=total, pieces_root=root))
        if layer:
            layers[root] = layer
    info = InfoDictV2(
        name=name, piece_length=piece_length, files=tuple(v2files), private=private
    )
    from torrent_tpu.codec.metainfo_v2 import encode_metainfo_v2, parse_metainfo_v2

    encoded = encode_metainfo_v2(
        info, layers, announce,
        comment=comment, announce_list=announce_list, web_seeds=web_seeds,
    )
    parsed = parse_metainfo_v2(encoded)
    assert parsed is not None, "authored v2 metainfo failed its own parse"
    return parsed


@functools.lru_cache(maxsize=4)
def _piece_verifier(plen: int):
    """One SHA-1 hash-plane verifier per piece geometry (a fresh one per
    file would recompile the same executable over and over)."""
    from torrent_tpu.models.verifier import TPUVerifier

    return TPUVerifier(piece_length=plen, batch_size=256)


def _hybrid_hash_file(
    source, plen: int, hasher: str, pad_tail: bool
) -> tuple[bytes, tuple[bytes, ...], list[bytes]]:
    """One streaming pass → (v2 pieces_root, v2 layer, v1 piece digests).

    Both hash families consume the same chunk iterator, so hybrid
    authoring reads each file from disk exactly once. ``pad_tail`` zero-
    extends the final v1 piece to full length (BEP 47 — the pad bytes are
    part of the hashed piece). Chunk size is the leaf bucket (a power-of-
    two multiple of BLOCK, hence of ``plen`` whenever plen ≤ chunk), so
    the v1 carry is only ever the file's final partial piece.
    """
    total = source_len(source)
    if total == 0:
        return b"\x00" * 32, (), []
    n = max(1, -(-total // BLOCK))
    bkt = min(LEAF_BATCH, max(16, 1 << (n - 1).bit_length()))
    chunk_bytes = bkt * BLOCK

    if hasher == "cpu":
        import hashlib as _hl

        hash_batch = lambda ps: [_hl.sha1(p).digest() for p in ps]
    else:
        hash_batch = _piece_verifier(plen).hash_pieces

    v1_digs: list[bytes] = []
    state = {"carry": b""}

    def feed_sha1(chunk: bytes) -> None:
        buf = state["carry"] + chunk
        full = len(buf) // plen
        if full:
            v1_digs.extend(hash_batch([buf[i * plen : (i + 1) * plen] for i in range(full)]))
        state["carry"] = buf[full * plen :]

    def tee():
        for chunk in _iter_source(source, chunk_bytes):
            feed_sha1(chunk)
            yield chunk

    if hasher == "cpu":
        leaves = _leaf_words_cpu_from_chunks(tee())
    else:
        leaves = _leaf_words_from_chunks(tee(), total, "auto")
    tail = state["carry"]
    if tail:
        v1_digs.extend(hash_batch([tail.ljust(plen, b"\x00") if pad_tail else tail]))

    # device=False for 'cpu' keeps explicitly-CPU hybrid authoring off
    # the jax backend (same remote/wedged-device hazard as hash_file_v2)
    root, layer = roots_batched([(total, leaves)], plen, device=hasher != "cpu")[0]
    return root, layer, v1_digs


def build_hybrid(
    files: list[tuple[tuple[str, ...], "bytes | str"]],
    name: str,
    piece_length: int,
    hasher: str = "tpu",
    announce: str | None = None,
    private: bool = False,
    comment: str | None = None,
    announce_list: list[list[str]] | None = None,
    web_seeds: list[str] | None = None,
) -> tuple[bytes, MetainfoV2]:
    """Author a hybrid v1+v2 torrent (BEP 52 upgrade path).

    Every file except the last is padded to a piece boundary with a
    BEP 47 pad file (``.pad/N``, attr ``p``) so v1 pieces never span
    files — which is exactly what lets the v1 piece hashes and the v2
    per-file merkle trees describe the same bytes. Returns the bencoded
    torrent and its parsed v2 view (``parse_metainfo`` reads the same
    blob for the v1 view).
    """
    if piece_length < BLOCK or piece_length & (piece_length - 1):
        raise ValueError("piece_length must be a power of two >= 16 KiB")
    from torrent_tpu.codec.metainfo_v2 import (
        encode_metainfo_v2,
        parse_metainfo_v2,
        valid_path_component,
    )

    for path, _ in files:
        for part in path:
            if not valid_path_component(part):
                raise ValueError(f"path component {part!r} not encodable in a file tree")

    entries = sorted(files, key=lambda e: e[0])
    v2files: list[V2File] = []
    layers: dict[bytes, tuple[bytes, ...]] = {}
    v1_pieces: list[bytes] = []
    v1_files: list[dict] = []
    single = len(entries) == 1 and entries[0][0] == (name,)
    for idx, (path, source) in enumerate(entries):
        last = idx == len(entries) - 1
        root, layer, digs = _hybrid_hash_file(
            source, piece_length, hasher, pad_tail=not last
        )
        length = source_len(source)
        v2files.append(V2File(path=path, length=length, pieces_root=root))
        if layer:
            layers[root] = layer
        v1_pieces.extend(digs)
        v1_files.append({b"length": length, b"path": [p.encode() for p in path]})
        pad = (-length) % piece_length
        if not last and pad:
            v1_files.append(
                {b"length": pad, b"path": [b".pad", str(pad).encode()], b"attr": b"p"}
            )
    info = InfoDictV2(
        name=name, piece_length=piece_length, files=tuple(v2files), private=private
    )
    encoded = encode_metainfo_v2(
        info,
        layers,
        announce=announce,
        comment=comment,
        announce_list=announce_list,
        web_seeds=web_seeds,
        v1_pieces=v1_pieces,
        v1_files=None if single else v1_files,
        v1_length=source_len(entries[0][1]) if single else None,
    )
    parsed = parse_metainfo_v2(encoded)
    assert parsed is not None, "authored hybrid failed its own v2 parse"
    return encoded, parsed


def verify_v2(
    read_file,
    meta: MetainfoV2,
    hasher: str = "tpu",
) -> dict[tuple[str, ...], np.ndarray]:
    """Recheck every file against its pieces_root / piece layer.

    ``read_file(path_tuple) -> bytes | path-str | None`` supplies each
    file's source (None = missing; a path source streams in bounded
    chunks). Returns ``{path: bool[n_pieces]}`` — the v2 analogue of the
    v1 resume-recheck bitfield, per file.
    """
    plen = meta.info.piece_length
    lpp = plen // BLOCK
    results: dict[tuple[str, ...], np.ndarray] = {}
    # phase 1: select present, size-matching files (stashing the source —
    # calling read_file again later could observe a concurrently deleted
    # or resized file and crash instead of marking it missing); phase 2:
    # windowed batched reduction passes (one dispatch per level per shape
    # group within each bounded-residency window, not a chain per file)
    todo: list[tuple[V2File, object]] = []  # (file, source)
    for f in meta.info.files:
        n_pieces = f.num_pieces(plen)
        source = read_file(f.path)
        if source is None or (source_len(source) != f.length):
            results[f.path] = (
                np.zeros(max(1, n_pieces), dtype=bool)
                if f.length
                else np.ones(0, dtype=bool)
            )
            continue
        if f.length == 0:
            results[f.path] = np.ones(0, dtype=bool)
            continue
        todo.append((f, source))

    def leaf_entries():
        for f, source in todo:
            try:
                if hasher == "cpu":
                    yield f.length, _leaf_words_cpu(source)
                else:
                    yield f.length, _leaf_words_device(source, "auto")
            except OSError:
                # a path source deleted between phases: zero leaf words
                # can't match any real root, so every piece of this file
                # lands False — same verdict as a missing file
                yield f.length, np.zeros(
                    (max(1, -(-f.length // BLOCK)), 8), dtype=np.uint32
                )

    reduced = roots_batched_windowed(leaf_entries(), plen, device=hasher != "cpu")
    for ei, (f, _) in enumerate(todo):
        n_pieces = f.num_pieces(plen)
        ok = np.zeros(max(1, n_pieces), dtype=bool)
        got_root, got_layer = reduced[ei]
        if f.length <= plen:
            ok[0] = got_root == f.pieces_root
            results[f.path] = ok
            continue
        layer = meta.piece_layers.get(f.pieces_root, ())
        # metadata self-consistency: the published layer must merkle up to
        # the published root (a hostile layer otherwise localizes damage
        # to the wrong pieces). Data corruption must NOT trip this — the
        # per-piece comparison below is what localizes it. The cpu hasher
        # folds with hashlib (device-free guarantee).
        if len(layer) != n_pieces:
            results[f.path] = ok
            continue
        if hasher == "cpu":
            height = lpp.bit_length() - 1
            padded_n = 1 << max(0, (n_pieces - 1).bit_length())
            layer_root = _root_cpu(
                digests_to_words32(layer), padded_n,
                pad_digest=zero_chain(height)[height],
            )
        else:
            layer_root = file_root_from_piece_roots(digests_to_words32(layer), lpp)
        if layer_root != f.pieces_root:
            results[f.path] = ok
            continue
        for i in range(n_pieces):
            ok[i] = got_layer[i] == layer[i]
        results[f.path] = ok
    return results
