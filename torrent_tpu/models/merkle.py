"""Batched merkle trees over SHA-256 — the BEP 52 (BitTorrent v2) plane.

v2 hashes files as merkle trees with 16 KiB leaf blocks: leaves are
SHA-256 of each block, interior nodes are SHA-256 of the 64-byte
concatenation of their children, a file's ``pieces root`` is the tree
root, and for files larger than one piece the per-piece subtree roots
are published as the ``piece layers`` (BEP 52 "file tree" / "piece
layers"). The reference predates v2 — this subsystem is beyond-parity.

TPU mapping: digests never leave word form. Leaves come out of the
SHA-256 plane as ``u32[N, 8]`` big-endian words; each merkle level is
one batched compression of the 16-word pair concatenation plus a
constant padding block (message length is always exactly 64 bytes), so
a whole level is ``sha256_pairs: u32[M, 16] → u32[M/2, 8]`` — no byte
swizzling anywhere above the leaves.
"""

from __future__ import annotations

import functools
import hashlib

import jax
import jax.numpy as jnp
import numpy as np

from torrent_tpu.ops.sha256_jax import _IV256, _compress256


@jax.jit
def sha256_pairs(words: jax.Array) -> jax.Array:
    """One merkle level: ``u32[M, 16]`` child-pair words → ``u32[M, 8]``.

    The 64-byte message is exactly one block; the second (padding) block
    is the constant ``0x80 || zeros || bitlen=512``.
    """
    m = words.shape[0]
    state = tuple(jnp.full((m,), v, dtype=jnp.uint32) for v in _IV256)
    state = _compress256(state, [words[:, i] for i in range(16)])
    pad = (
        [jnp.full((m,), 0x80000000, dtype=jnp.uint32)]
        + [jnp.zeros((m,), dtype=jnp.uint32)] * 14
        + [jnp.full((m,), 512, dtype=jnp.uint32)]
    )
    state = _compress256(state, pad)
    return jnp.stack(state, axis=1)


@functools.partial(jax.jit, static_argnames=("levels",))
def _merkle_reduce_fused(words: jax.Array, levels: int) -> jax.Array:
    """``u32[B, 2**levels, 8]`` → roots ``u32[B, 8]``: EVERY pair level
    in one dispatch. The per-level host wrapper (``merkle_level``) paid
    a device round-trip per level — log2(L) dispatches and transfers per
    reduction, which on the relay-tunneled chip is log2(L) × ~55 ms of
    fixed cost. Here intermediates never leave the device (round-2
    verdict #3's "fuse levels" option)."""
    for _ in range(levels):
        b, m, _ = words.shape
        pairs = words.reshape(b * (m // 2), 16)
        # nested jit traces inline: still ONE dispatch for all levels
        words = sha256_pairs(pairs).reshape(b, m // 2, 8)
    return words[:, 0, :]


def merkle_level(words: np.ndarray) -> np.ndarray:
    """Host wrapper: ``u32[..., M, 8]`` → ``u32[..., M/2, 8]``.

    Leading batch axes are flattened into the pair batch so one call
    reduces a whole level of MANY trees at once.
    """
    *lead, m, _ = words.shape
    if m % 2:
        raise ValueError("merkle level must have an even node count")
    pairs = np.ascontiguousarray(words).reshape(-1, 16)
    out = np.asarray(sha256_pairs(jnp.asarray(pairs)))
    return out.reshape(*lead, m // 2, 8)


def merkle_root(words: np.ndarray) -> np.ndarray:
    """``u32[..., L, 8]`` (L a power of two) → root ``u32[..., 8]``.

    Backend-keyed: on an accelerator all levels fuse into ONE dispatch
    (each per-level host hop costs ~55 ms of fixed relay/dispatch
    overhead — log2(L) of them per reduction); on the CPU backend the
    per-level loop wins instead, because dispatch is free there and the
    fused program's levels×-larger XLA graph makes compile time dominate
    real work (measured 2× on the v2 suite)."""
    *lead, l, _ = words.shape
    if l & (l - 1):
        raise ValueError("leaf count must be a power of two")
    if l == 1:
        return np.asarray(words)[..., 0, :]
    if jax.default_backend() == "cpu":
        out = words
        while out.shape[-2] > 1:
            out = merkle_level(out)
        return out[..., 0, :]
    flat = np.ascontiguousarray(words).reshape(-1, l, 8)
    out = np.asarray(_merkle_reduce_fused(jnp.asarray(flat), l.bit_length() - 1))
    return out.reshape(*lead, 8)


@functools.lru_cache(maxsize=None)
def zero_chain(levels: int) -> tuple[bytes, ...]:
    """``zero_chain(k)[i]`` = root digest of a full zero-leaf subtree of
    height ``i`` (index 0 = the 32-byte zero leaf itself), up to height
    ``levels``. Host-side hashlib — computed once per geometry."""
    out = [b"\x00" * 32]
    for _ in range(levels):
        out.append(hashlib.sha256(out[-1] + out[-1]).digest())
    return tuple(out)


def digests_to_words32(digests) -> np.ndarray:
    """32-byte SHA-256 digests → ``u32[N, 8]`` big-endian words."""
    from torrent_tpu.ops.padding import digests_to_words

    return digests_to_words(digests, words=8)


# width follows the array; the shared converter handles both planes
from torrent_tpu.ops.padding import words_to_digests as words32_to_digests  # noqa: E402


def pad_leaves(leaf_words: np.ndarray, target: int) -> np.ndarray:
    """Pad ``u32[n, 8]`` leaf words with zero-hash leaves up to ``target``."""
    n = leaf_words.shape[0]
    if n == target:
        return leaf_words
    padded = np.zeros((target, 8), dtype=np.uint32)
    padded[:n] = leaf_words
    return padded


def piece_roots_from_leaves(leaf_words: np.ndarray, leaves_per_piece: int) -> np.ndarray:
    """Leaf words ``u32[n_leaves, 8]`` → per-piece roots ``u32[n_pieces, 8]``.

    The final piece's missing leaves are zero-hash-padded (BEP 52). All
    pieces reduce together: one device call per tree level.
    """
    if leaves_per_piece & (leaves_per_piece - 1):
        raise ValueError("leaves_per_piece must be a power of two")
    n = leaf_words.shape[0]
    n_pieces = -(-n // leaves_per_piece)
    grid = np.zeros((n_pieces, leaves_per_piece, 8), dtype=np.uint32)
    grid.reshape(-1, 8)[:n] = leaf_words
    return merkle_root(grid)


def file_root_from_piece_roots(piece_root_words: np.ndarray, leaves_per_piece: int) -> bytes:
    """Piece roots → the file's ``pieces root`` digest.

    The piece-root layer is padded to the next power of two with the root
    of an all-zero piece subtree (NOT the zero leaf — BEP 52's "remaining
    leaf hashes ... set to zero" composes upward through the full-height
    zero subtree).
    """
    n = piece_root_words.shape[0]
    target = 1 << max(0, (n - 1).bit_length())
    if target != n:
        height = leaves_per_piece.bit_length() - 1
        zero_root = zero_chain(height)[height]
        pad = np.tile(digests_to_words32([zero_root]), (target - n, 1))
        piece_root_words = np.concatenate([piece_root_words, pad], axis=0)
    return words32_to_digests(merkle_root(piece_root_words)[None, :])[0]


def small_file_root(leaf_words: np.ndarray) -> bytes:
    """Root for a file no larger than one piece: leaves zero-padded to the
    next power of two of the file's own block count."""
    n = leaf_words.shape[0]
    target = max(1, 1 << max(0, (n - 1).bit_length()))
    return words32_to_digests(merkle_root(pad_leaves(leaf_words, target))[None, :])[0]


def piece_root_cpu(data: bytes, pad_leaves: int) -> bytes:
    """Merkle root of one piece's data: SHA-256 16 KiB leaf hashes padded
    with ZERO digests (BEP 52 "remaining leaf hashes ... set to zero" —
    the pad is the zero VALUE, not the hash of zero bytes) up to
    ``pad_leaves`` (a power of two), pairs folded to the root.

    ``pad_leaves`` is blocks-per-piece for pieces of multi-piece files,
    or the file's own next-power-of-two block count for single-piece
    files — the per-piece expected digest in session/v2.py either way.
    Host-side hashlib: one piece is at most 64 leaves (1 MiB pieces), so
    the batched device planes only pay off across MANY pieces (see
    piece_roots_from_leaves / parallel/verify.py).
    """
    from torrent_tpu.codec.metainfo_v2 import BLOCK

    if pad_leaves < 1 or pad_leaves & (pad_leaves - 1):
        raise ValueError("pad_leaves must be a power of two >= 1")
    leaves = [
        hashlib.sha256(data[i : i + BLOCK]).digest()
        for i in range(0, len(data), BLOCK)
    ] or [hashlib.sha256(b"").digest()]
    if len(leaves) > pad_leaves:
        raise ValueError(f"piece has {len(leaves)} leaves > pad target {pad_leaves}")
    leaves += [b"\x00" * 32] * (pad_leaves - len(leaves))
    while len(leaves) > 1:
        leaves = [
            hashlib.sha256(leaves[i] + leaves[i + 1]).digest()
            for i in range(0, len(leaves), 2)
        ]
    return leaves[0]
