"""TPUVerifier — the flagship pipeline of the framework.

One object owning the compiled hash plane for a given piece geometry:

- ``verify_storage``  — full resume-recheck of a torrent (BASELINE
  configs 1, 2, 4): disk → ``Storage.read_batch`` → pad → device →
  masked SHA1 chain → on-device digest compare → ``bool`` bitfield.
  Disk IO for batch *i+1* overlaps device compute for batch *i*.
- ``hash_pieces`` / ``hash_bytes`` — authoring-side digests (BASELINE
  config 3; replaces tools/make_torrent.ts:28-32's per-piece WebCrypto).
- ``verify_batch`` — the raw jitted step, used by the HTTP bridge and by
  ``__graft_entry__`` for compile checks.

Shapes are static per (piece_length, batch_size): ragged batches are
padded to ``batch_size`` rows with ``nblocks=0`` sentinel rows, so the
whole session reuses one XLA executable. The batch axis is sharded
``(hosts, dp)`` over the mesh (parallel/mesh.py); everything up to the
final per-piece bool is embarrassingly parallel, so the only cross-chip
traffic is output gathering.
"""

from __future__ import annotations


from torrent_tpu.analysis.sanitizer import named_lock
import time
from collections import deque
from concurrent.futures import ThreadPoolExecutor

import jax
import jax.numpy as jnp
import numpy as np

from torrent_tpu.codec.metainfo import InfoDict
from torrent_tpu.ops.padding import (
    alloc_padded,
    digests_to_words,
    pad_in_place,
    pad_pieces,
    padded_len_for,
    words_to_digests,
)
from torrent_tpu.ops.sha1_jax import make_sha1_fn
from torrent_tpu.parallel.mesh import (
    batch_sharding,
    make_mesh,
    round_up_to_multiple,
)
from torrent_tpu.parallel.verify import VerifyResult
from torrent_tpu.utils.env import env_int
from torrent_tpu.storage.storage import Storage


class TPUVerifier:
    def __init__(
        self,
        piece_length: int,
        batch_size: int = 1024,
        backend: str = "jax",
        mesh=None,
        devices=None,
    ):
        if piece_length <= 0:
            raise ValueError("piece_length must be positive")
        self.piece_length = piece_length
        self.mesh = mesh if mesh is not None else make_mesh(devices)
        self.batch_size = round_up_to_multiple(max(batch_size, self.mesh.size), self.mesh.size)
        self.padded_len = padded_len_for(piece_length)
        self.backend = backend
        sha1_fn = make_sha1_fn(backend)
        self.tile_sub = None
        if backend == "pallas":
            # A pallas_call has no SPMD partitioning rule, so on a >1-device
            # mesh we shard it explicitly: each device runs the kernel on its
            # local piece sub-batch (embarrassingly parallel, no collectives).
            # Per-device sub-batches must be tile-aligned or every
            # launch pads with wasted sentinel rows.
            from jax.sharding import PartitionSpec as P

            from torrent_tpu.parallel.mesh import compat_shard_map

            shard_map, _sm_kw = compat_shard_map()

            from torrent_tpu.ops.sha1_pallas import TILE_SUB, sha1_pieces_pallas

            # Adaptive tiling: one tile row (tile_sub*128 pieces) is the
            # kernel's swizzle/launch granularity, and its temporaries are
            # ~2x the tile slab. Big pieces shrink the sublane count so a
            # tile stays ~1 GiB regardless of piece size (the sweep's
            # measured-best regime; at 4096x1 MiB a whole-batch slab OOMs
            # a 16 GB chip outright).
            budget = env_int("TORRENT_TPU_TILE_BYTES", 1_342_177_280)  # 1.25 GiB
            ts = TILE_SUB
            # step by 8s, not halving: the env default may be any multiple
            # of 8 (halving 24 would land on 12 and crash _check_tiling)
            while ts > 8 and ts * 128 * self.padded_len > budget:
                ts -= 8
            self.tile_sub = ts
            tile = ts * 128

            def sha1_fn(data, nblocks, _ts=ts):
                return sha1_pieces_pallas(data, nblocks, tile_sub=_ts)

            if self.mesh.size > 1:
                spec = P(tuple(self.mesh.axis_names))
                sha1_fn = shard_map(
                    sha1_fn,
                    mesh=self.mesh,
                    in_specs=(spec, spec),
                    out_specs=spec,
                    **_sm_kw,
                )
            self.batch_size = round_up_to_multiple(self.batch_size, tile * self.mesh.size)
        shard = batch_sharding(self.mesh)

        def _digests(data_u8, nblocks):
            return sha1_fn(data_u8, nblocks)

        def _verify(data_u8, nblocks, expected):
            words = sha1_fn(data_u8, nblocks)
            return jnp.all(words == expected, axis=1)

        self._digest_step = jax.jit(
            _digests, in_shardings=(shard, shard), out_shardings=shard
        )
        self._verify_step = jax.jit(
            _verify, in_shardings=(shard, shard, shard), out_shardings=shard
        )

        # Fast single-device upload path: row-block 2-D chunks put in
        # parallel, joined with one axis-0 concat on device. padded_len is
        # 128-byte aligned (ops/padding.py), so a 2-D put is a straight
        # memcpy (measured at full wire speed on both PCIe and this
        # image's tunnel). The earlier flatten→concat→reshape design is
        # gone for a reason: XLA's AOT lowering of the big 1-D→2-D
        # reshape materializes a (4,1)-subtiled intermediate padded 32x —
        # a 16 GiB allocation at 512 KiB pieces. Multi-device meshes keep
        # the sharded 2-D path (dryrun/tests, upload speed irrelevant).
        # Chunks arrive as host-order u32 (ndarray.view is free and a
        # u8→u32 bitcast on TPU lowers through a 4x-widened convert
        # fusion — the pallas kernel consumes u32 directly). The scan
        # backend still wants u8 rows; the bitcast back is cheap there
        # (CPU/GPU lower it as a real reinterpret).
        pallas = backend == "pallas"

        def _join(chunks):
            data = jnp.concatenate(chunks, axis=0)
            if not pallas:
                data = jax.lax.bitcast_convert_type(data, jnp.uint8).reshape(
                    data.shape[0], -1
                )
            return data

        def _verify_flat(chunks, nblocks, expected):
            words = sha1_fn(_join(chunks), nblocks)
            return jnp.all(words == expected, axis=1)

        def _digests_flat(chunks, nblocks):
            return sha1_fn(_join(chunks), nblocks)

        # Donate the uploaded chunks on real accelerators: the launch
        # consumes them exactly once, so freeing the device input buffer
        # as the kernel runs lets the NEXT batch's H2D reuse that memory
        # — the double-buffered ingest contract the scheduler's sha1
        # plane relies on. XLA-CPU refuses donation (it would only emit
        # a warning per launch), so it stays off there.
        _platform_cpu = next(iter(self.mesh.devices.flat)).platform == "cpu"
        _donate = () if _platform_cpu else (0,)
        self._verify_step_flat = jax.jit(_verify_flat, donate_argnums=_donate)
        self._digest_step_flat = jax.jit(_digests_flat, donate_argnums=_donate)
        # the sharded twin of the donated digest step, for upload_batch
        # on a >1-device mesh (compiled only if that path runs)
        self._digest_step_donated = jax.jit(
            _digests, in_shardings=(shard, shard), out_shardings=shard,
            donate_argnums=_donate,
        )
        # 4 concurrent streams saturate both a local PCIe path and this
        # image's relay tunnel; 8+ makes the tunnel collapse (measured
        # ~190 MiB/s vs ~1.7 GiB/s at 4 on the raw path).
        self._upload_chunks = env_int("TORRENT_TPU_UPLOAD_CHUNKS", 4)
        self._upload_pool: ThreadPoolExecutor | None = None
        # verify_batch/digest_batch may be called from several threads on a
        # shared verifier (the bridge does); first-use pool init must not race
        self._upload_pool_lock = named_lock("models.verifier._upload_pool_lock")
        # On the CPU backend device_put can zero-copy an aligned numpy
        # view — the "device" array then aliases the staging buffer, and
        # reusing the buffer while a batch is still in flight would
        # corrupt it. Force a real copy there (still done in the upload
        # worker threads, so it's parallel).
        self._upload_must_copy = _platform_cpu
        self._shard = shard
        # A mesh spanning >1 process (parallel/distributed.py) cannot be
        # fed global numpy arrays — each process only holds its
        # addressable shard. verify/digest then take this process's
        # LOCAL rows (batch_size / process_count of them) and convert
        # via make_array_from_process_local_data.
        self._mesh_processes = len(
            {d.process_index for d in self.mesh.devices.flat}
        )

    def _use_flat(self, padded: np.ndarray) -> bool:
        return (
            self.mesh.size == 1
            and isinstance(padded, np.ndarray)
            and padded.shape == (self.batch_size, self.padded_len)
        )

    def _put_flat(self, padded: np.ndarray) -> list[jax.Array]:
        """Upload ``uint8[B, padded_len]`` as concurrent row-block chunks.

        Blocks until every chunk is resident so the caller may reuse the
        staging buffer immediately.
        """
        with self._upload_pool_lock:
            if self._upload_pool is None:
                self._upload_pool = ThreadPoolExecutor(max_workers=self._upload_chunks)
            pool = self._upload_pool
        rows = padded.shape[0]
        step = -(-rows // self._upload_chunks)
        views = [
            padded[i : i + step].view(np.uint32) for i in range(0, rows, step)
        ]
        if self._upload_must_copy:
            put = lambda v: jax.device_put(v.copy())
        else:
            put = jax.device_put
        chunks = list(pool.map(put, views))
        for c in chunks:
            c.block_until_ready()
        return chunks

    # ------------------------------------------------------------ raw steps

    def _put_global(self, padded, nblocks, expected_words=None):
        """Multi-process input path: build global batch-sharded Arrays
        from this process's local rows (parallel/distributed.py)."""
        from torrent_tpu.parallel.distributed import global_batch

        args = [global_batch(self._shard, np.asarray(padded)),
                global_batch(self._shard, np.asarray(nblocks))]
        if expected_words is not None:
            args.append(global_batch(self._shard, np.asarray(expected_words)))
        return args

    def _put_local_sharded(self, *arrays):
        """On a multi-process CLUSTER even a fully-addressable local
        mesh can't take numpy args through a jit with non-trivial
        in_shardings ("Passing non-trivial shardings for numpy inputs
        is not allowed") — e.g. each pod host bulk-validating its
        library shard on its own devices (verify_library_distributed).
        Put them explicitly with the batch sharding; a no-op wrapper on
        single-process runs."""
        if jax.process_count() == 1:
            return arrays
        return tuple(jax.device_put(a, self._shard) for a in arrays)

    def verify_batch_global(
        self, padded: np.ndarray, nblocks: np.ndarray, expected_words: np.ndarray
    ):
        """Multi-process verify: inputs are this process's LOCAL rows
        (``batch_size / process_count`` of them); returns
        ``(ok_local, ok_global)`` — the local bool rows plus the global
        sharded device array for collective stats (psum_valid_count)."""
        from torrent_tpu.parallel.distributed import local_values

        ok_global = self._verify_step(
            *self._put_global(padded, nblocks, expected_words)
        )
        return local_values(ok_global), ok_global

    def verify_batch(
        self, padded: np.ndarray, nblocks: np.ndarray, expected_words: np.ndarray
    ) -> np.ndarray:
        """bool[B]: does each padded row hash to its expected digest words.

        On a multi-process mesh the inputs are this process's local rows
        and the returned bools are for those rows only."""
        from torrent_tpu.utils.trace import maybe_profile_batch

        with maybe_profile_batch("sha1_verify_batch"):
            if self._mesh_processes > 1:
                return self.verify_batch_global(padded, nblocks, expected_words)[0]
            if self._use_flat(padded):
                chunks = self._put_flat(padded)
                return np.asarray(self._verify_step_flat(chunks, nblocks, expected_words))
            return np.asarray(
                self._verify_step(
                    *self._put_local_sharded(padded, nblocks, expected_words)
                )
            )

    def digest_batch(self, padded: np.ndarray, nblocks: np.ndarray) -> np.ndarray:
        """uint32[B, 5] big-endian digest words for each row (local rows
        on a multi-process mesh, as in verify_batch)."""
        from torrent_tpu.utils.trace import maybe_profile_batch

        with maybe_profile_batch("sha1_digest_batch"):
            if self._mesh_processes > 1:
                from torrent_tpu.parallel.distributed import local_values

                return local_values(
                    self._digest_step(*self._put_global(padded, nblocks))
                )
            if self._use_flat(padded):
                chunks = self._put_flat(padded)
                return np.asarray(self._digest_step_flat(chunks, nblocks))
            return np.asarray(
                self._digest_step(*self._put_local_sharded(padded, nblocks))
            )

    def upload_supported(self, padded) -> bool:
        """Whether :meth:`upload_batch` can take this batch — checked
        BEFORE opening an ``h2d`` ledger span, so a fused fallback never
        charges transfer bytes to a near-zero-duration span."""
        if self._mesh_processes > 1:
            return False
        if self._use_flat(padded):
            return True
        return (
            isinstance(padded, np.ndarray)
            and padded.ndim == 2
            and padded.shape[0] % self.mesh.size == 0
        )

    def upload_batch(self, padded: np.ndarray):
        """Explicit H2D for the scheduler's split-stage accounting.

        Single-device meshes take the chunked concurrent upload of
        ``digest_batch``'s flat path; >1-device single-process meshes an
        explicit batch-sharded ``device_put``. Returns an opaque handle
        for :meth:`digest_uploaded`, or ``None`` when neither form can
        take this batch (multi-process mesh, odd geometry) — callers
        then fall back to the fused :meth:`digest_batch`. Blocks until
        the batch is device-resident, so the staging buffer may be
        reused immediately.
        """
        if not self.upload_supported(padded):
            return None
        if self._use_flat(padded):
            return ("flat", self._put_flat(padded))
        dev = jax.device_put(padded, self._shard)
        dev.block_until_ready()
        return ("sharded", dev)

    def digest_uploaded(self, handle, nblocks: np.ndarray):
        """Async digest dispatch on an :meth:`upload_batch` handle.

        Returns the device words array WITHOUT fetching — the caller's
        ``np.asarray`` is the D2H boundary (the scheduler accounts it as
        the ledger's ``digest`` stage). The handle is donated to the
        launch on real accelerators; it must not be reused.
        """
        kind, data = handle
        if kind == "flat":
            return self._digest_step_flat(data, nblocks)
        dev_n = jax.device_put(np.asarray(nblocks), self._shard)
        return self._digest_step_donated(data, dev_n)

    # ------------------------------------------------------------ authoring

    def hash_pieces(self, pieces: list[bytes]) -> list[bytes]:
        """SHA1 digests for a ragged list of pieces (authoring path).

        Chunks into fixed ``batch_size`` launches so one executable serves
        any piece count; rows are padded with ``nblocks=0`` sentinels.
        """
        if not pieces:
            return []
        if any(len(p) > self.piece_length for p in pieces):
            raise ValueError("piece longer than verifier piece_length")
        out: list[bytes] = []
        b = self.batch_size
        for start in range(0, len(pieces), b):
            chunk = pieces[start : start + b]
            padded, view = alloc_padded(b, self.piece_length)
            lengths = np.zeros(b, dtype=np.int64)
            for i, p in enumerate(chunk):
                view[i, : len(p)] = np.frombuffer(p, dtype=np.uint8)
                lengths[i] = len(p)
            nblocks = pad_in_place(padded, lengths)
            nblocks[len(chunk) :] = 0  # sentinel rows: skip entirely
            words = self.digest_batch(padded, nblocks)
            out.extend(words_to_digests(words[: len(chunk)]))
        return out

    # ------------------------------------------------------------ recheck

    def verify_storage(
        self,
        storage: Storage,
        info: InfoDict,
        progress_cb=None,
        io_threads: int = 4,
    ) -> np.ndarray:
        """Full recheck → bool[n_pieces]. Disk reads overlap device compute."""
        if info.piece_length != self.piece_length:
            raise ValueError(
                f"verifier compiled for piece_length={self.piece_length}, "
                f"torrent has {info.piece_length}"
            )
        n = info.num_pieces
        bitfield = np.zeros(n, dtype=bool)
        if n == 0:
            return bitfield
        expected_all = digests_to_words(info.pieces)
        b = self.batch_size
        plen = self.piece_length

        # Two staging buffers: the IO threads fill one while the device
        # consumes the other (the TPU analogue of the reference's
        # Promise.all hashing pipeline, tools/make_torrent.ts:96-111).
        # ``io_threads`` stripes each batch's disk reads in parallel.
        staging = [alloc_padded(b, plen) for _ in range(2)]
        stripes = max(1, io_threads)
        io_pool = ThreadPoolExecutor(max_workers=stripes) if stripes > 1 else None

        def load(slot: int, start: int):
            padded, view = staging[slot]
            idxs = range(start, min(start + b, n))
            k = len(idxs)
            if io_pool is not None and k > stripes:
                step = (k + stripes - 1) // stripes
                futs = [
                    io_pool.submit(
                        storage.read_batch,
                        idxs[s : s + step],
                        out=view[s : min(s + step, k)],
                    )
                    for s in range(0, k, step)
                ]
                for f in futs:
                    f.result()
            else:
                storage.read_batch(idxs, out=view[:k])
            padded[:, plen:] = 0  # clear pad tail (stale 0x80/bitlen bytes)
            if k < b:
                padded[k:] = 0
            lengths = np.zeros(b, dtype=np.int64)
            for i, idx in enumerate(idxs):
                lengths[i] = min(plen, info.length - idx * plen)
            nblocks = pad_in_place(padded, lengths)
            if k < b:
                nblocks[k:] = 0
            expected = np.zeros((b, 5), dtype=np.uint32)
            expected[:k] = expected_all[start : start + k]
            return padded, nblocks, expected, k

        # Three overlapped stages: disk reads (loader thread) ahead of
        # uploads (chunked concurrent puts) ahead of device compute
        # (async dispatch). The async window is ONE batch — see the
        # drain loop below for why it must not be widened.
        flat_path = self.mesh.size == 1
        inflight: deque = deque()

        def drain_one():
            start_i, k_i, ok_dev = inflight.popleft()
            ok = np.asarray(ok_dev)
            bitfield[start_i : start_i + k_i] = ok[:k_i]
            if progress_cb:
                progress_cb(min(start_i + b, n), n)

        t0 = time.perf_counter()
        try:
            with ThreadPoolExecutor(max_workers=1) as pool:
                fut = pool.submit(load, 0, 0)
                start = 0
                slot = 0
                while start < n:
                    padded, nblocks, expected, k = fut.result()
                    next_start = start + b
                    if next_start < n:
                        slot = 1 - slot
                        fut = pool.submit(load, slot, next_start)
                    if flat_path:
                        chunks = self._put_flat(padded)
                        ok_dev = self._verify_step_flat(chunks, nblocks, expected)
                        inflight.append((start, k, ok_dev))
                        # Window of 1: upload/compute of batch i+1 overlap
                        # the result fetch of batch i, nothing more. On
                        # remote-relay backends block_until_ready/asarray
                        # provide the ONLY real backpressure, and a wider
                        # window lets the client queue unbounded upload
                        # copies in host RAM (a 100 GiB recheck ate 123 GB
                        # before being stopped).
                        while len(inflight) > 1:
                            drain_one()
                    else:
                        ok = self.verify_batch(padded, nblocks, expected)
                        bitfield[start : start + k] = ok[:k]
                        if progress_cb:
                            progress_cb(min(next_start, n), n)
                    start = next_start
                while inflight:
                    drain_one()
        finally:
            if io_pool is not None:
                io_pool.shutdown(wait=False)
        self.last_result = VerifyResult(
            bitfield=bitfield,
            n_pieces=n,
            n_valid=int(bitfield.sum()),
            bytes_hashed=info.length,
            seconds=time.perf_counter() - t0,
        )
        return bitfield

    # ------------------------------------------------------------ misc

    def hash_bytes(self, data: bytes) -> bytes:
        """Single-message SHA1 on device (bridge convenience)."""
        padded, nblocks = pad_pieces([data])
        fn = make_sha1_fn(self.backend)
        words = np.asarray(fn(padded, nblocks))
        return words_to_digests(words)[0]
