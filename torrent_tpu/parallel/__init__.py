from torrent_tpu.parallel.mesh import make_mesh, batch_sharding, replicated_sharding
from torrent_tpu.parallel.verify import verify_pieces, VerifyResult
from torrent_tpu.parallel.bulk import verify_library, LibraryResult
from torrent_tpu.parallel.distributed import (
    initialize as init_distributed,
    verify_library_distributed,
    verify_storage_distributed,
)

__all__ = [
    "make_mesh",
    "batch_sharding",
    "replicated_sharding",
    "verify_pieces",
    "VerifyResult",
    "verify_library",
    "LibraryResult",
    "init_distributed",
    "verify_library_distributed",
    "verify_storage_distributed",
]
