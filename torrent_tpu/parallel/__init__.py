from torrent_tpu.parallel.mesh import make_mesh, batch_sharding, replicated_sharding
from torrent_tpu.parallel.verify import verify_pieces, verify_pieces_sched, VerifyResult
from torrent_tpu.parallel.bulk import (
    verify_library,
    verify_library_fabric,
    verify_library_sched,
    LibraryResult,
)
from torrent_tpu.parallel.distributed import (
    initialize as init_distributed,
    verify_library_distributed,
    verify_storage_distributed,
)

__all__ = [
    "make_mesh",
    "batch_sharding",
    "replicated_sharding",
    "verify_pieces",
    "verify_pieces_sched",
    "VerifyResult",
    "verify_library",
    "verify_library_fabric",
    "verify_library_sched",
    "LibraryResult",
    "init_distributed",
    "verify_library_distributed",
    "verify_storage_distributed",
]
