from torrent_tpu.parallel.mesh import make_mesh, batch_sharding, replicated_sharding
from torrent_tpu.parallel.verify import verify_pieces, VerifyResult
from torrent_tpu.parallel.bulk import verify_library, LibraryResult

__all__ = [
    "make_mesh",
    "batch_sharding",
    "replicated_sharding",
    "verify_pieces",
    "VerifyResult",
    "verify_library",
    "LibraryResult",
]
