"""Device mesh + sharding helpers for the hash plane.

The reference's only parallelism is async concurrency on one event loop
(SURVEY §2); the TPU build's parallelism is SPMD over a
``jax.sharding.Mesh``:

- axis ``"dp"`` — pieces (data parallel; the batch axis of every kernel)
- axis ``"hosts"`` — multi-host fan-out over DCN for pod-scale bulk
  verification (BASELINE config 5); piece batches shard over
  ``hosts × dp`` so collectives ride ICI within a host and only the final
  few-byte bitfield reductions cross DCN. On a real multi-process
  cluster (``jax.distributed``) the host rows are process-aligned and
  inputs enter as per-process local shards — see
  ``parallel/distributed.py``; the live 2-process path is exercised by
  ``tests/test_distributed.py``.

SHA1's block chain is inherently serial *within* a piece, so there is no
tensor/sequence-parallel axis to shard — all scale-out is across pieces,
which is exactly what ICI is worst-case-free at: the verify step is
embarrassingly parallel until the final ``psum`` of match counts.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DP_AXIS = "dp"
HOST_AXIS = "hosts"


def compat_shard_map():
    """``(shard_map, kwargs)`` for whichever jax this is: ~0.5 moved
    ``shard_map`` out of ``jax.experimental`` and renamed its
    replication-check kwarg ``check_rep`` → ``check_vma``. Every mesh
    call site splats the returned kwargs instead of carrying its own
    version probe."""
    try:
        from jax import shard_map

        return shard_map, {"check_vma": False}
    except ImportError:
        from jax.experimental.shard_map import shard_map

        return shard_map, {"check_rep": False}


def make_mesh(devices=None, n_hosts: int | None = None) -> Mesh:
    """Build a ``(hosts, dp)`` mesh over ``devices`` (default: all).

    ``n_hosts`` defaults to ``jax.process_count()`` so a single-host run
    gets a ``(1, n_chips)`` mesh and a pod run gets ``(n_hosts, chips)`` —
    the per-host sub-batches never need cross-DCN data movement.
    """
    if devices is None:
        devices = jax.devices()
    if n_hosts is None:
        n_hosts = jax.process_count()
    if jax.process_count() > 1 and n_hosts == jax.process_count():
        # Real multi-process mesh (parallel/distributed.py): row p MUST
        # be process p's local devices, so the batch rows a process
        # feeds via make_array_from_process_local_data are the rows its
        # own devices hold — piece bytes stay on-host, only bitfield /
        # stats reductions cross DCN. jax.devices() order is not a
        # contract; group explicitly.
        rows = [
            [d for d in devices if d.process_index == p]
            for p in range(n_hosts)
        ]
        width = len(rows[0])
        if width == 0 or any(len(r) != width for r in rows):
            raise ValueError(
                "devices are not evenly spread over processes: "
                + str([len(r) for r in rows])
            )
        return Mesh(np.array(rows, dtype=object), (HOST_AXIS, DP_AXIS))
    devices = np.asarray(devices)
    if devices.size % n_hosts != 0:
        raise ValueError(f"{devices.size} devices not divisible by {n_hosts} hosts")
    grid = devices.reshape(n_hosts, devices.size // n_hosts)
    return Mesh(grid, (HOST_AXIS, DP_AXIS))


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Shard the leading (piece-batch) axis over every mesh axis."""
    return NamedSharding(mesh, P((HOST_AXIS, DP_AXIS),))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def round_up_to_multiple(n: int, k: int) -> int:
    return ((n + k - 1) // k) * k
