"""Multi-host (DCN) support for the verify/bulk hash planes.

SURVEY §5/§7 names the split: XLA ICI collectives (``shard_map`` +
``psum``) within a host, and **DCN via ``jax.distributed`` only for
pod-scale bulk verification** (BASELINE config 5). Until round 5 the
``hosts`` mesh axis was a single-process fiction: ``verify_storage`` /
``verify_library`` fed whole *global* numpy arrays into ``jax.jit`` —
single-controller style that a real multi-process mesh rejects, because
each process only holds its addressable shard of a global array.

This module is the process-boundary glue, testable on CPU with two real
processes (tests/test_distributed.py spawns them; no TPU pod needed):

- :func:`initialize` — ``jax.distributed.initialize`` wrapper.
- :func:`global_batch` / :func:`local_values` — per-process local rows
  ↔ global sharded ``jax.Array`` (``make_array_from_process_local_data``
  on the way in, addressable-shard reassembly on the way out).
- :func:`psum_valid_count` — the bulk-validate stats reduction (psum
  over ``(hosts, dp)``) on a live multi-process mesh.
- :func:`verify_storage_distributed` — the pod-scale recheck: each
  process reads its own slice of every global batch, all processes
  enter the same jitted verify step, and the per-piece bitfield is
  assembled with a process allgather. Every process returns the same
  global bitfield.

Mesh layout contract: row ``p`` of the ``(hosts, dp)`` mesh is exactly
process ``p``'s local devices (``make_mesh`` groups by
``process_index`` when ``jax.process_count() > 1``), so the batch rows
a process feeds are the rows its devices own — data never crosses DCN;
only the few-byte stats/bitfield reductions do.
"""

from __future__ import annotations

import functools as _functools
import math
from concurrent.futures import ThreadPoolExecutor

import numpy as np

from torrent_tpu.parallel.mesh import DP_AXIS, HOST_AXIS


def initialize(
    coordinator_address: str, num_processes: int, process_id: int
) -> None:
    """``jax.distributed.initialize`` with an idempotence guard.

    Call before the first use of ``jax.devices()``. On CPU test rigs set
    ``jax.config.update("jax_platforms", "cpu")`` and
    ``jax.config.update("jax_num_cpu_devices", k)`` first so each
    process contributes ``k`` virtual devices to the global mesh.
    """
    import jax

    try:  # private in some jax versions; fall back to is_initialized
        from jax._src.distributed import global_state as _state

        if getattr(_state, "client", None) is not None:
            return
    except ImportError:
        if getattr(jax.distributed, "is_initialized", lambda: False)():
            return
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )


def global_batch(sharding, local: np.ndarray):
    """Build the global batch-sharded ``jax.Array`` from this process's
    local rows.

    ``local`` is this process's contiguous row-slice; the global leading
    dim is ``local.shape[0] * process_count`` (every process must pass
    the same local row count — pad ragged tails before calling).
    """
    import jax

    global_shape = (
        local.shape[0] * jax.process_count(),
        *local.shape[1:],
    )
    return jax.make_array_from_process_local_data(
        sharding, np.ascontiguousarray(local), global_shape
    )


def local_values(arr) -> np.ndarray:
    """This process's rows of a batch-sharded global array, in global
    row order (the inverse of :func:`global_batch`)."""
    shards = sorted(
        arr.addressable_shards, key=lambda s: s.index[0].start or 0
    )
    return np.concatenate([np.asarray(s.data) for s in shards])


@_functools.lru_cache(maxsize=8)
def _count_fn(mesh):
    """One compiled psum-count program per mesh (Mesh is hashable);
    rebuilding the jit closure per call would recompile the collective
    on every batch of the recheck hot loop."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from torrent_tpu.parallel.mesh import compat_shard_map

    shard_map, sm_kw = compat_shard_map()
    spec = P((HOST_AXIS, DP_AXIS))

    def _count(ok_local):
        return jax.lax.psum(
            jnp.sum(ok_local.astype(jnp.int32)), (HOST_AXIS, DP_AXIS)
        )

    return jax.jit(
        shard_map(_count, mesh=mesh, in_specs=(spec,), out_specs=P(), **sm_kw)
    )


def psum_valid_count(mesh, ok_global) -> int:
    """Total True count of a batch-sharded bool array, reduced on-device
    with ``psum`` over both mesh axes — the bulk-validate stats
    reduction (BASELINE config 5) riding ICI within a host and DCN
    across hosts. Every process returns the same total."""
    return int(_count_fn(mesh)(ok_global))


def allgather_bitfield(local_contrib: np.ndarray) -> np.ndarray:
    """OR-assemble per-process disjoint bitfield contributions into the
    global bitfield (identical on every process). A few bytes per piece
    — the only payload that crosses DCN in the whole recheck."""
    from jax.experimental import multihost_utils

    gathered = multihost_utils.process_allgather(
        local_contrib.astype(np.uint8), tiled=False
    )
    return np.asarray(gathered).any(axis=0)


def verify_storage_distributed(
    storage,
    info,
    batch_size: int = 1024,
    backend: str = "jax",
    mesh=None,
    progress_cb=None,
    io_threads: int = 4,
):
    """Pod-scale resume-recheck: every process verifies its slice of
    each global batch through one shared jitted step, then the bitfield
    is assembled over DCN. Returns ``(bitfield, n_valid)`` — identical
    on every process; ``n_valid`` comes from the on-device psum stats
    reduction, not a host-side sum, so the collective path is exercised
    on every call.

    Row layout per global batch ``g`` of size ``B`` over ``P``
    processes: process ``p`` loads pieces
    ``[g*B + p*(B/P), g*B + (p+1)*(B/P))`` — matching the mesh's
    process-aligned host rows, so piece bytes never cross a process
    boundary.
    """
    import jax

    from torrent_tpu.models.verifier import TPUVerifier
    from torrent_tpu.ops.padding import (
        alloc_padded,
        digests_to_words,
        pad_in_place,
    )

    nproc = jax.process_count()
    pid = jax.process_index()
    verifier = TPUVerifier(
        piece_length=info.piece_length,
        batch_size=batch_size,
        backend=backend,
        mesh=mesh,
    )
    B = verifier.batch_size
    if B % nproc:
        raise ValueError(f"batch_size {B} not divisible by {nproc} processes")
    L = B // nproc
    n = info.num_pieces
    plen = info.piece_length
    expected_all = digests_to_words(info.pieces)
    local_contrib = np.zeros(n, dtype=bool)
    n_valid = 0
    n_batches = math.ceil(n / B)

    # Same shape as TPUVerifier.verify_storage: two staging buffers, a
    # loader thread reading global batch g+1 (this process's contiguous
    # slice, striped over io_threads) while the device verifies batch g.
    staging = [alloc_padded(L, plen) for _ in range(2)]
    stripes = max(1, io_threads)
    io_pool = ThreadPoolExecutor(max_workers=stripes) if stripes > 1 else None

    def load(slot: int, g: int):
        padded, view = staging[slot]
        base = g * B + pid * L
        idxs = range(base, min(base + L, n))
        k = len(idxs)
        if k:
            if io_pool is not None and k > stripes:
                step = (k + stripes - 1) // stripes
                futs = [
                    io_pool.submit(
                        storage.read_batch,
                        idxs[s : s + step],
                        out=view[s : min(s + step, k)],
                    )
                    for s in range(0, k, step)
                ]
                for f in futs:
                    f.result()
            else:
                storage.read_batch(idxs, out=view[:k])
        padded[:, plen:] = 0  # clear pad tail (stale 0x80/bitlen bytes)
        if k < L:
            padded[k:] = 0
        lengths = np.zeros(L, dtype=np.int64)
        expected = np.zeros((L, 5), dtype=np.uint32)
        for r, idx in enumerate(idxs):
            lengths[r] = min(plen, info.length - idx * plen)
            expected[r] = expected_all[idx]
        nblocks = pad_in_place(padded, lengths)
        nblocks[k:] = 0
        return padded, nblocks, expected, list(idxs)

    try:
        with ThreadPoolExecutor(max_workers=1) as pool:
            fut = pool.submit(load, 0, 0)
            slot = 0
            for g in range(n_batches):
                padded, nblocks, expected, idxs = fut.result()
                if g + 1 < n_batches:
                    slot = 1 - slot
                    fut = pool.submit(load, slot, g + 1)
                # verify_batch_global copies rows into device shards
                # before returning, so reusing the staging buffer for
                # the next load cannot race the in-flight batch
                ok_local, ok_global = verifier.verify_batch_global(
                    padded, nblocks, expected
                )
                for r, idx in enumerate(idxs):
                    local_contrib[idx] = bool(ok_local[r])
                # on-device DCN+ICI stats reduction. Sentinel /
                # out-of-range rows carry expected=0, which no SHA1
                # digest ever equals, so they can never inflate the
                # count — n_valid == popcount(bitfield).
                n_valid += psum_valid_count(verifier.mesh, ok_global)
                if progress_cb:
                    progress_cb(min((g + 1) * B, n), n)
    finally:
        if io_pool is not None:
            io_pool.shutdown(wait=False)
    bitfield = allgather_bitfield(local_contrib)
    return bitfield, n_valid


def verify_pieces_v2_distributed(
    storage,
    info,
    batch_size: int = 256,
    progress_cb=None,
) -> np.ndarray:
    """Pod-scale BEP 52 (merkle) recheck: pieces are verified
    independently, so each process takes its round-robin stride of the
    piece index space through the ordinary per-host v2 device plane
    (leaf hashing + fused pair reduction on LOCAL devices — v2 batches
    are pad-grouped and never need a global mesh), and the disjoint
    bitfield contributions are OR-assembled over one DCN allgather.
    Returns the identical full bitfield on every process.

    SPMD contract: every process must call this collectively on the
    same torrent (the allgather blocks until all arrive). For a
    host-local-only recheck on a cluster call
    ``verify_pieces_v2_tpu`` directly.
    """
    import jax

    from torrent_tpu.parallel.verify import verify_pieces_v2_tpu

    nproc = jax.process_count()
    pid = jax.process_index()
    local = verify_pieces_v2_tpu(
        storage,
        info,
        batch_size=batch_size,
        progress_cb=progress_cb,
        indices=range(pid, info.num_pieces, nproc),
    )
    return allgather_bitfield(local)


def verify_library_distributed(
    items,
    batch_size: int = 1024,
    backend: str = "jax",
    io_threads: int = 4,
    progress_cb=None,
):
    """Pod-scale bulk library validation (BASELINE config 5): the
    torrent-level DCN parallelism `parallel/bulk.py` documents — each
    host runs :func:`verify_library` over its round-robin shard of the
    library on its LOCAL device mesh (no cross-host piece movement),
    then the per-torrent bitfields are assembled over one packed DCN
    allgather. Returns ``(bitfields, n_valid)``, identical on every
    process; ``n_valid`` counts valid pieces library-wide.

    ``items``: ``list[(Storage, InfoDict)]`` — the SAME list, in the
    same order, on every process (each host opens its own storage
    handles; only the round-robin slice is actually read).

    ``progress_cb`` reports THIS process's shard progress —
    ``(pieces_done_local, shard_pieces_total)`` — not library-wide
    progress: hosts advance independently and cross-host progress
    would cost a collective per batch. Only the RETURN values are
    identical on every process.
    """
    import jax

    from torrent_tpu.parallel.bulk import verify_library
    from torrent_tpu.parallel.mesh import make_mesh

    nproc = jax.process_count()
    pid = jax.process_index()
    # round-robin, not contiguous: libraries are often sorted by size,
    # and striding spreads the big torrents evenly across hosts
    mine = list(range(pid, len(items), nproc))
    local_mesh = make_mesh(jax.local_devices(), n_hosts=1)
    result = verify_library(
        [items[i] for i in mine],
        hasher="tpu",
        batch_size=batch_size,
        backend=backend,
        mesh=local_mesh,
        io_threads=io_threads,
        progress_cb=progress_cb,
    )
    # pack every torrent's bitfield into one flat disjoint-contribution
    # vector: this process fills only its torrents' spans, the OR-
    # allgather assembles the global view on every host
    offsets = np.zeros(len(items) + 1, dtype=np.int64)
    for i, (_, info) in enumerate(items):
        offsets[i + 1] = offsets[i] + info.num_pieces
    flat = np.zeros(int(offsets[-1]), dtype=bool)
    for j, i in enumerate(mine):
        flat[offsets[i] : offsets[i + 1]] = result.bitfields[j]
    flat = allgather_bitfield(flat)
    bitfields = [
        flat[offsets[i] : offsets[i + 1]].copy() for i in range(len(items))
    ]
    return bitfields, int(flat.sum())
