"""Batched piece verification — the resume-recheck / authoring hash plane.

This is the subsystem the reference *lacks* (SURVEY §8.3: downloaded
pieces are never SHA1-checked; resume-recheck is an unchecked roadmap
item, README.md:34) and the BASELINE north star: ``verify_pieces(storage,
info)`` reads pieces in large batches (``Storage.read_batch``), pads them
on host, and hashes them on device — pieces sharded ``(hosts, dp)`` over
the mesh, digests compared on device, one bool per piece returned.

Pipeline shape (per batch of B pieces):

    disk → read_batch → pad_in_place → device put (sharded) ┐
                                    sha1 chain (scan)       │ overlapped:
                                    compare vs expected     │ next batch's
                                    psum-free bool[B] ──────┘ disk read runs
                                                              on a host thread

The CPU path (``hasher="cpu"``) is streaming hashlib — the measured
baseline the TPU path is benchmarked against (BASELINE.md configs 1-2).
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass
from typing import Callable

import numpy as np

from torrent_tpu.codec.metainfo import InfoDict
from torrent_tpu.storage.piece import piece_length
from torrent_tpu.storage.storage import Storage, StorageError


@dataclass
class VerifyResult:
    """Outcome of a full verify pass."""

    bitfield: np.ndarray  # bool[n_pieces]
    n_pieces: int
    n_valid: int
    bytes_hashed: int
    seconds: float

    @property
    def complete(self) -> bool:
        return self.n_valid == self.n_pieces

    @property
    def pieces_per_sec(self) -> float:
        return self.n_pieces / self.seconds if self.seconds > 0 else float("inf")

    @property
    def gib_per_sec(self) -> float:
        return self.bytes_hashed / self.seconds / 2**30 if self.seconds > 0 else float("inf")


ProgressCb = Callable[[int, int], None]  # (pieces_done, pieces_total)


def verify_pieces_cpu(
    storage: Storage, info: InfoDict, progress_cb: ProgressCb | None = None
) -> np.ndarray:
    """Streaming hashlib recheck — the measured CPU baseline."""
    n = info.num_pieces
    bitfield = np.zeros(n, dtype=bool)
    for idx in range(n):
        try:
            data = storage.read_piece(idx)
        except (StorageError, OSError):
            continue  # unreadable = failed piece, keep checking the rest
        if len(data) == piece_length(info, idx) and hashlib.sha1(data).digest() == info.pieces[idx]:
            bitfield[idx] = True
        if progress_cb and (idx + 1) % 256 == 0:
            progress_cb(idx + 1, n)
    if progress_cb:
        progress_cb(n, n)
    return bitfield


def verify_pieces_tpu(
    storage: Storage,
    info: InfoDict,
    batch_size: int = 1024,
    backend: str = "jax",
    mesh=None,
    progress_cb: ProgressCb | None = None,
    io_threads: int = 4,
) -> np.ndarray:
    """Batched device recheck; overlaps disk reads with device hashing.

    On a multi-process (``jax.distributed``) cluster this routes to the
    DCN path automatically: every process verifies its shard of each
    global batch and all return the identical global bitfield
    (parallel/distributed.py; proven by tests/test_distributed.py).
    """
    import jax

    # Route on the MESH's process span, not bare process_count(): a
    # caller on a multi-process cluster may pass a local-only mesh
    # (make_mesh(jax.local_devices(), n_hosts=1)) for a per-host
    # recheck, which must take the ordinary single-controller path.
    if jax.process_count() > 1:
        span_mesh = mesh
        if span_mesh is None:
            from torrent_tpu.parallel.mesh import make_mesh

            span_mesh = make_mesh()
        if len({d.process_index for d in span_mesh.devices.flat}) > 1:
            from torrent_tpu.parallel.distributed import (
                verify_storage_distributed,
            )

            bitfield, _ = verify_storage_distributed(
                storage,
                info,
                batch_size=batch_size,
                backend=backend,
                mesh=span_mesh,
                progress_cb=progress_cb,
                io_threads=io_threads,
            )
            return bitfield
        mesh = span_mesh

    from torrent_tpu.models.verifier import TPUVerifier

    verifier = TPUVerifier(
        piece_length=info.piece_length,
        batch_size=batch_size,
        backend=backend,
        mesh=mesh,
    )
    return verifier.verify_storage(
        storage, info, progress_cb=progress_cb, io_threads=io_threads
    )


def verify_pieces_v2_cpu(
    storage: Storage, info, progress_cb: ProgressCb | None = None
) -> np.ndarray:
    """Streaming per-piece merkle recheck (session/v2.py geometry)."""
    from torrent_tpu.models.merkle import piece_root_cpu

    n = info.num_pieces
    bitfield = np.zeros(n, dtype=bool)
    for idx in range(n):
        try:
            data = storage.read_piece(idx)
        except (StorageError, OSError):
            continue  # unreadable = failed piece, keep checking the rest
        if (
            len(data) == info.piece_sizes[idx]
            and piece_root_cpu(data, info.piece_pad_leaves[idx]) == info.pieces[idx]
        ):
            bitfield[idx] = True
        if progress_cb and (idx + 1) % 256 == 0:
            progress_cb(idx + 1, n)
    if progress_cb:
        progress_cb(n, n)
    return bitfield


def verify_pieces_v2_tpu(
    storage: Storage,
    info,
    batch_size: int = 256,
    progress_cb: ProgressCb | None = None,
    indices=None,
    **_ignored,
) -> np.ndarray:
    """Batched device merkle recheck: SHA-256 16 KiB leaves on the hash
    plane, then one batched pair-reduction per tree level across the
    whole piece batch (models/merkle.py).

    ``indices``: optional subset of piece indices to recheck (the
    multi-host path gives each process its stride); the returned
    bitfield is always full length, False outside the subset.
    """
    from torrent_tpu.codec.metainfo_v2 import BLOCK
    from torrent_tpu.models.merkle import merkle_root, words32_to_digests
    from torrent_tpu.models.v2 import _make_leaf_fn
    from torrent_tpu.ops.padding import alloc_padded, pad_in_place

    import jax.numpy as jnp

    n = info.num_pieces
    bitfield = np.zeros(n, dtype=bool)
    if n == 0:
        return bitfield
    todo = range(n) if indices is None else indices
    # group pieces by leaf-pad target: multi-piece files all share
    # blocks-per-piece, single-piece files use their own pow2 count
    by_pad: dict[int, list[int]] = {}
    for idx in todo:
        by_pad.setdefault(info.piece_pad_leaves[idx], []).append(idx)
    n_todo = sum(len(v) for v in by_pad.values())
    leaf_rows = 1024  # device rows per leaf dispatch (pow2-bucketed fn)
    fn = _make_leaf_fn(leaf_rows, "auto")
    padded, view = alloc_padded(leaf_rows, BLOCK)
    done = 0
    for pad, group in by_pad.items():
        for bstart in range(0, len(group), batch_size):
            batch = group[bstart : bstart + batch_size]
            buf, lengths = storage.read_batch(batch)
            ok_len = np.array(
                [lengths[i] == info.piece_sizes[p] for i, p in enumerate(batch)]
            )
            m = len(batch)
            grid = np.zeros((m, pad, 8), dtype=np.uint32)
            # flatten every real block of the batch into leaf-plane rows
            blocks: list[tuple[int, int, int]] = []  # (piece_i, block_i, blen)
            for i in range(m):
                ln = int(lengths[i])
                for bi in range(-(-ln // BLOCK) if ln else 0):
                    blocks.append((i, bi, min(BLOCK, ln - bi * BLOCK)))
                if ln == 0 and info.piece_sizes[batch[i]] == 0:
                    blocks.append((i, 0, 0))
            for rstart in range(0, len(blocks), leaf_rows):
                chunk = blocks[rstart : rstart + leaf_rows]
                padded[:] = 0
                row_len = np.zeros(leaf_rows, dtype=np.int64)
                for r, (i, bi, blen) in enumerate(chunk):
                    view[r, :blen] = buf[i, bi * BLOCK : bi * BLOCK + blen]
                    row_len[r] = blen
                nblocks = pad_in_place(padded, row_len)
                nblocks[len(chunk) :] = 0
                words = np.asarray(fn(jnp.asarray(padded), jnp.asarray(nblocks)))
                for r, (i, bi, _blen) in enumerate(chunk):
                    grid[i, bi] = words[r]
            roots = words32_to_digests(merkle_root(grid))
            for i, p in enumerate(batch):
                bitfield[p] = bool(ok_len[i]) and roots[i] == info.pieces[p]
            done += m
            if progress_cb:
                progress_cb(done, n_todo)
    return bitfield


def read_pieces_chunk(storage: Storage, info: InfoDict, idxs):
    """Read a chunk of pieces with mark-and-continue semantics.

    Returns ``(payloads, expected, keep)`` — a torn/unreadable/short
    piece is skipped (stays False in the caller's bitfield) instead of
    aborting, the same contract as ``verify_pieces_cpu``; OSError too,
    because a backend that leaks a raw errno (file truncated between
    open and pread) must not kill the pass. The ONE implementation of
    the read/filter/keep contract, shared by the scheduler sessions
    here and the fabric executor (``torrent_tpu/fabric``) — which also
    makes it the pipeline ledger's ``read`` stage boundary for every
    scheduler-fed path."""
    from torrent_tpu.obs.ledger import pipeline_ledger

    payloads, exps, keep = [], [], []
    with pipeline_ledger().track("read") as tracked:
        for i in idxs:
            try:
                data = storage.read_piece(i)
            except (StorageError, OSError):
                continue
            tracked.add(len(data))
            if len(data) != piece_length(info, i):
                continue
            payloads.append(data)
            exps.append(info.pieces[i])
            keep.append(i)
    return payloads, exps, keep


def read_pieces_into(storage: Storage, info: InfoDict, idxs, scheduler):
    """Zero-copy sibling of :func:`read_pieces_chunk`.

    Checks a staging slab out of the scheduler's ingest pool
    (``sched._StagingSlots`` via ``checkout_staging``) FIRST, then has
    ``Storage.read_batch`` — the native ``io_engine.read_into`` pread
    pool when available, the pure-Python backend walk otherwise — land
    the reads directly in the slab's row-strided view and pads the rows
    in place. No intermediate per-piece ``bytes``, no ``np.frombuffer``
    row copy, no ``_StagingSlots.stage`` pass later: the slab IS the
    launch buffer.

    Mark-and-continue semantics are preserved: a torn/short/unreadable
    piece becomes an ``nblocks=0`` sentinel row, is dropped from the
    returned ticket rows, and stays False in the caller's bitfield —
    the same contract as ``read_pieces_chunk`` (differential-tested in
    tests/test_ingest.py, native engine present and absent).

    Returns ``(slab, rows, expected, keep)`` — the caller holds one
    slab reference and must ``slab.release()`` after hand-off (or on
    abort) — or ``None`` when this scheduler/geometry can't take
    pre-staged submissions (callers fall back to the byte path). Any
    read-path failure checks the slab back in before returning, so a
    mid-batch ``NativeIOError`` can never leak a slot.
    """
    checkout = getattr(scheduler, "checkout_staging", None)
    if checkout is None:
        return None
    idxs = list(idxs)
    slab = checkout(info.piece_length, len(idxs), algo="sha1")
    if slab is None:
        return None
    try:
        n = len(idxs)
        slab.prepare([piece_length(info, i) for i in idxs])
        ok = np.zeros(n, dtype=bool)
        storage.read_batch(
            idxs,
            out=slab.padded[:n, : info.piece_length],
            row_status=ok,
            zero_fill=False,
        )
        slab.finalize(ok)
    except Exception:
        # whatever broke (engine fault, backend bug): return the slot —
        # callers retry through the byte path, which re-reads cleanly
        slab.release()
        return None
    rows = [i for i in range(n) if ok[i]]
    expected = [info.pieces[idxs[i]] for i in rows]
    keep = [idxs[i] for i in rows]
    return slab, rows, expected, keep


class _SchedChunk:
    """One read chunk ready for scheduler submission — staged (slab)
    or byte form, behind one enqueue/discard surface so every
    scheduler-fed read loop (torrent rechecks, library sweeps, the
    fabric executor) shares the zero-copy-with-fallback contract."""

    __slots__ = ("slab", "rows", "payloads", "expected", "keep", "piece_length")

    def __init__(self, slab, rows, payloads, expected, keep, piece_length):
        self.slab = slab
        self.rows = rows
        self.payloads = payloads
        self.expected = expected
        self.keep = keep
        self.piece_length = piece_length

    @property
    def empty(self) -> bool:
        return not self.keep

    @property
    def nbytes(self) -> int:
        if self.slab is not None:
            return int(self.slab.lengths[list(self.rows)].sum())
        return sum(len(p) for p in self.payloads)

    async def enqueue(self, scheduler, tenant: str, wait: bool = True):
        """Submit and hand ownership over: the creator's slab reference
        is released on EVERY path (tickets keep the slab alive through
        demux; a shed releases everything)."""
        if self.slab is not None:
            slab, self.slab = self.slab, None
            try:
                return await scheduler.enqueue_staged(
                    tenant, slab, self.rows, expected=self.expected, wait=wait
                )
            finally:
                slab.release()
        return await scheduler.enqueue(
            tenant,
            self.payloads,
            expected=self.expected,
            algo="sha1",
            piece_length=self.piece_length,
            wait=wait,
        )

    def discard(self) -> None:
        """Abandon without submitting (empty chunk, caller abort)."""
        if self.slab is not None:
            self.slab.release()
            self.slab = None


def read_chunk_for_sched(
    storage: Storage, info: InfoDict, idxs, scheduler
) -> _SchedChunk:
    """Read one chunk for scheduler submission, zero-copy when the
    scheduler's ingest pool can take it, ``read_pieces_chunk`` bytes
    otherwise. Runs in a worker thread (both read paths block)."""
    staged = read_pieces_into(storage, info, idxs, scheduler)
    if staged is not None:
        slab, rows, expected, keep = staged
        if not keep:  # nothing readable: give the slot straight back
            slab.release()
            return _SchedChunk(None, None, [], [], [], info.piece_length)
        return _SchedChunk(slab, rows, None, expected, keep, info.piece_length)
    payloads, exps, keep = read_pieces_chunk(storage, info, idxs)
    return _SchedChunk(None, None, payloads, exps, keep, info.piece_length)


async def enqueue_torrent_sched(
    storage: Storage,
    info: InfoDict,
    scheduler,
    tenant: str,
    chunk_pieces: int | None = None,
) -> list[tuple]:
    """Read a torrent's pieces off-thread and enqueue them on the shared
    hash-plane scheduler WITHOUT awaiting results.

    Returns ``[(future, keep_indices), ...]`` — each future resolves to
    ok-bytes for the pieces in ``keep_indices`` (rows that failed to read
    or were short are skipped and stay False in the caller's bitfield).
    Submissions use blocking admission (``wait=True``): a full queue
    pauses the disk read loop instead of buffering without bound. Shared
    by ``verify_pieces_sched`` and ``verify_library_sched`` so the read /
    filter / keep-demux contract lives in one place.

    Chunks go zero-copy whenever the scheduler's ingest pool covers the
    geometry (:func:`read_pieces_into` → ``enqueue_staged``): reads for
    chunk *k+1* land in a second slab while chunk *k*'s H2D/launch runs
    — the read→h2d→launch overlap the pipeline ledger's occupancy
    series makes visible.
    """
    import asyncio

    chunk = chunk_pieces or scheduler.chunk_for(info.piece_length)

    futs: list[tuple] = []
    for start in range(0, info.num_pieces, chunk):
        idxs = list(range(start, min(start + chunk, info.num_pieces)))
        ck = await asyncio.to_thread(
            read_chunk_for_sched, storage, info, idxs, scheduler
        )
        if ck.empty:
            ck.discard()
            continue
        fut = await ck.enqueue(scheduler, tenant, wait=True)
        futs.append((fut, ck.keep))
    return futs


async def verify_pieces_sched(
    storage: Storage,
    info: InfoDict,
    scheduler,
    tenant: str = "verify",
    chunk_pieces: int | None = None,
    progress_cb: ProgressCb | None = None,
) -> np.ndarray:
    """Recheck through the shared hash-plane scheduler (v1/sha1 infos).

    Instead of owning a private ``TPUVerifier`` batch loop, pieces are
    read off-thread and submitted to ``scheduler``
    (``torrent_tpu.sched.HashPlaneScheduler``): the scheduler coalesces
    them with every other caller's traffic into full device launches and
    keeps the geometry-grouped compile cache across sessions. Reads
    pipeline against launches — submissions are enqueued with blocking
    admission (``wait=True``), so a full queue pauses the disk read
    loop instead of buffering without bound.

    A launch failure that outlives the scheduler's retry/bisection
    (``SchedLaunchError``) marks its pieces unverified (False — retried
    on the next recheck or re-downloaded) instead of aborting the whole
    pass: one poisoned piece must not discard every verified one.

    v2 (merkle) infos don't map onto the flat digest plane; use
    ``verify_pieces`` for those.
    """
    from torrent_tpu.sched import SchedLaunchError
    from torrent_tpu.utils.log import get_logger

    if getattr(info, "v2", False):
        raise ValueError("scheduler sessions are sha1/v1-only; use verify_pieces")
    n = info.num_pieces
    bitfield = np.zeros(n, dtype=bool)
    if n == 0:
        return bitfield
    futs = await enqueue_torrent_sched(storage, info, scheduler, tenant, chunk_pieces)
    done = 0
    for fut, keep in futs:
        try:
            ok = await fut
        except SchedLaunchError as e:
            get_logger("parallel.verify").warning(
                "recheck: %d pieces unverified (hash launch failed: %s)",
                len(keep), e,
            )
            done += len(keep)  # stay False in the bitfield: retry later
            if progress_cb:
                progress_cb(min(done, n), n)
            continue
        for j, i in enumerate(keep):
            bitfield[i] = bool(ok[j])
        done += len(keep)
        if progress_cb:
            progress_cb(min(done, n), n)
    return bitfield


def verify_pieces(
    storage: Storage,
    info: InfoDict,
    hasher: str = "cpu",
    progress_cb: ProgressCb | None = None,
    **tpu_kwargs,
) -> np.ndarray:
    """Recheck every piece; returns ``bool[n_pieces]``.

    ``hasher`` mirrors the BASELINE API contract: ``"cpu"`` (default,
    streaming hashlib — the reference's std/crypto analogue) or ``"tpu"``
    (batched device path; on CPU-only hosts XLA still runs it, so the flag
    selects *strategy*, not hardware availability). v2 session infos
    (session/v2.py) route to the merkle recheck automatically.
    """
    if info.num_pieces == 0:
        return np.zeros(0, dtype=bool)
    v2 = getattr(info, "v2", False)
    if hasher == "cpu":
        fn = verify_pieces_v2_cpu if v2 else verify_pieces_cpu
        return fn(storage, info, progress_cb)
    if hasher == "tpu":
        if v2:
            import jax

            # v2 batches are pad-grouped per host (no global mesh), so
            # the DCN route keys on process_count alone: on a cluster
            # every process calls collectively and gets the identical
            # bitfield; for host-local-only semantics call
            # verify_pieces_v2_tpu directly. An explicit caller subset
            # (indices=...) is host-local by definition — the
            # distributed stride would silently override it, so it
            # always takes the local path.
            if jax.process_count() > 1 and "indices" not in tpu_kwargs:
                from torrent_tpu.parallel.distributed import (
                    verify_pieces_v2_distributed,
                )

                return verify_pieces_v2_distributed(
                    storage,
                    info,
                    batch_size=tpu_kwargs.get("batch_size", 256),
                    progress_cb=progress_cb,
                )
            return verify_pieces_v2_tpu(
                storage, info, progress_cb=progress_cb, **tpu_kwargs
            )
        return verify_pieces_tpu(
            storage, info, progress_cb=progress_cb, **tpu_kwargs
        )
    raise ValueError(f"unknown hasher {hasher!r}")
