"""Bulk library validation — BASELINE config 5 (1000 torrents × ~1 GiB).

Verifying a library torrent-by-torrent wastes device time twice: one
compile + ragged tail batch per torrent. Here torrents are grouped by
piece geometry (one compiled executable per piece length) and their
pieces are flattened into a single work list, so every device batch is
full — pieces from different torrents ride the same launch — and only
the library's final batch is ragged.

On a multi-host pod each host runs verify_library over its shard of the
library (torrent-level DCN parallelism; no cross-host piece movement) —
implemented by ``parallel/distributed.verify_library_distributed`` and
proven with two real processes in ``tests/test_distributed.py``.
``verify_library_fabric`` composes that sharding WITH the shared
scheduler: each process's shard feeds its local continuous-batching
queue (``torrent_tpu/fabric``), so pod-scale rechecks coalesce with
foreground verify traffic instead of competing for the plane.
"""

from __future__ import annotations

import time
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass

import numpy as np

from torrent_tpu.codec.metainfo import InfoDict
from torrent_tpu.ops.padding import alloc_padded, digests_to_words, pad_in_place
from torrent_tpu.parallel.verify import verify_pieces_cpu
from torrent_tpu.storage.storage import Storage


@dataclass
class LibraryResult:
    bitfields: list[np.ndarray]
    n_pieces: int
    bytes_hashed: int
    seconds: float

    @property
    def pieces_per_sec(self) -> float:
        return self.n_pieces / self.seconds if self.seconds > 0 else float("inf")

    @property
    def gib_per_sec(self) -> float:
        return self.bytes_hashed / self.seconds / 2**30 if self.seconds > 0 else float("inf")


def verify_library(
    items: list[tuple[Storage, InfoDict]],
    hasher: str = "tpu",
    batch_size: int = 1024,
    backend: str = "jax",
    mesh=None,
    io_threads: int = 4,
    progress_cb=None,
    verifier=None,
) -> LibraryResult:
    """Recheck every torrent; returns per-torrent bitfields in order.

    ``verifier``: reuse a compiled ``TPUVerifier`` across calls (its
    geometry must match every torrent's piece length) — repeated library
    sweeps then skip recompilation entirely.
    """
    t0 = time.perf_counter()
    bitfields = [np.zeros(info.num_pieces, dtype=bool) for _, info in items]
    total_pieces = sum(info.num_pieces for _, info in items)
    total_bytes = sum(info.length for _, info in items)

    if hasher == "cpu":
        done_pieces = 0
        for i, (storage, info) in enumerate(items):
            bitfields[i] = verify_pieces_cpu(storage, info)
            done_pieces += info.num_pieces
            if progress_cb:
                # same (pieces_done, pieces_total) contract as the tpu path
                # and parallel/verify.py's ProgressCb
                progress_cb(done_pieces, total_pieces)
        return LibraryResult(
            bitfields, total_pieces, total_bytes, time.perf_counter() - t0
        )
    if hasher != "tpu":
        raise ValueError(f"unknown hasher {hasher!r}")

    from torrent_tpu.models.verifier import TPUVerifier

    # Group torrents by piece length: one executable per geometry.
    groups: dict[int, list[int]] = {}
    for idx, (_, info) in enumerate(items):
        groups.setdefault(info.piece_length, []).append(idx)

    done = 0
    for plen, group in groups.items():
        if verifier is not None:
            if verifier.piece_length != plen:
                raise ValueError(
                    f"shared verifier is compiled for piece_length="
                    f"{verifier.piece_length}, library has {plen}"
                )
            group_verifier = verifier
        else:
            group_verifier = TPUVerifier(
                piece_length=plen, batch_size=batch_size, backend=backend, mesh=mesh
            )
        b = group_verifier.batch_size
        # Flattened torrent-major work list: rows of one batch that belong
        # to the same torrent are contiguous, so loads stay batched reads.
        work: list[tuple[int, int]] = [
            (ti, pi) for ti in group for pi in range(items[ti][1].num_pieces)
        ]
        expected = {
            ti: digests_to_words(items[ti][1].pieces) for ti in group
        }
        staging = [alloc_padded(b, plen) for _ in range(2)]
        stripes = max(1, io_threads)
        io_pool = ThreadPoolExecutor(max_workers=stripes) if stripes > 1 else None

        def load(slot: int, start: int):
            padded, view = staging[slot]
            rows = work[start : start + b]
            k = len(rows)
            lengths = np.zeros(b, dtype=np.int64)
            exp = np.zeros((b, 5), dtype=np.uint32)
            # contiguous per-torrent runs → one read_batch per run
            futs = []
            row = 0
            while row < k:
                ti = rows[row][0]
                run_end = row
                while run_end < k and rows[run_end][0] == ti:
                    run_end += 1
                idxs = [pi for _, pi in rows[row:run_end]]
                storage, info = items[ti]
                out_view = view[row:run_end]
                if io_pool is not None:
                    futs.append(io_pool.submit(storage.read_batch, idxs, out=out_view))
                else:
                    storage.read_batch(idxs, out=out_view)
                for j, pi in enumerate(idxs):
                    lengths[row + j] = min(plen, info.length - pi * plen)
                    exp[row + j] = expected[ti][pi]
                row = run_end
            for f in futs:
                f.result()
            padded[:, plen:] = 0
            if k < b:
                padded[k:] = 0
            nblocks = pad_in_place(padded, lengths)
            if k < b:
                nblocks[k:] = 0
            return padded, nblocks, exp, rows

        try:
            with ThreadPoolExecutor(max_workers=1) as pool:
                fut = pool.submit(load, 0, 0)
                start = 0
                slot = 0
                while start < len(work):
                    padded, nblocks, exp, rows = fut.result()
                    nxt = start + b
                    if nxt < len(work):
                        slot = 1 - slot
                        fut = pool.submit(load, slot, nxt)
                    ok = group_verifier.verify_batch(padded, nblocks, exp)
                    for j, (ti, pi) in enumerate(rows):
                        bitfields[ti][pi] = ok[j]
                    done += len(rows)
                    if progress_cb:
                        progress_cb(done, total_pieces)
                    start = nxt
        finally:
            if io_pool is not None:
                io_pool.shutdown(wait=False)

    return LibraryResult(bitfields, total_pieces, total_bytes, time.perf_counter() - t0)


async def verify_library_sched(
    items: list[tuple[Storage, InfoDict]],
    scheduler,
    tenant: str = "bulk",
    progress_cb=None,
) -> LibraryResult:
    """Bulk validation as a scheduler session.

    The sync ``verify_library`` owns its own batch loop; this variant
    submits every torrent's pieces to the shared hash-plane scheduler
    (``torrent_tpu.sched``) instead. Cross-torrent coalescing then falls
    out of the queue itself — the tail of one torrent and the head of
    the next ride the same device launch, and pieces from *other*
    concurrent callers (bridge clients, CLI verifies) fill the batch
    too, with the scheduler's DRR keeping them fair. Geometry grouping
    is the scheduler's lane map, so the compile cache is shared with
    every other consumer rather than per-call.

    Per-piece hash failures (``SchedLaunchError`` after the scheduler's
    retry/bisection) leave those pieces unverified (False) and the sweep
    continues — a poisoned piece in torrent 3 must not abort the other
    997 torrents' results.
    """
    from torrent_tpu.parallel.verify import enqueue_torrent_sched
    from torrent_tpu.sched import SchedLaunchError
    from torrent_tpu.utils.log import get_logger

    t0 = time.perf_counter()
    bitfields = [np.zeros(info.num_pieces, dtype=bool) for _, info in items]
    total_pieces = sum(info.num_pieces for _, info in items)
    total_bytes = sum(info.length for _, info in items)

    # enqueue the WHOLE library before awaiting any result: the ragged
    # tail of torrent i is still queued when torrent i+1's head arrives,
    # so they share a launch instead of each paying a deadline flush
    pending: list[tuple] = []
    for ti, (storage, info) in enumerate(items):
        for fut, keep in await enqueue_torrent_sched(storage, info, scheduler, tenant):
            pending.append((fut, ti, keep))
    done = 0
    for fut, ti, keep in pending:
        try:
            ok = await fut
        except SchedLaunchError as e:
            get_logger("parallel.bulk").warning(
                "library sweep: %d pieces of torrent %d unverified "
                "(hash launch failed: %s)", len(keep), ti, e,
            )
            done += len(keep)  # stay False: recheck later
            if progress_cb:
                progress_cb(min(done, total_pieces), total_pieces)
            continue
        for j, pi in enumerate(keep):
            bitfields[ti][pi] = bool(ok[j])
        done += len(keep)
        if progress_cb:
            progress_cb(min(done, total_pieces), total_pieces)
    return LibraryResult(bitfields, total_pieces, total_bytes, time.perf_counter() - t0)


async def verify_library_fabric(
    items: list[tuple[Storage, InfoDict]],
    scheduler,
    nproc: int | None = None,
    pid: int | None = None,
    heartbeat_dir: str | None = None,
    transport=None,
    fabric_config=None,
    unit_bytes: int | None = None,
    progress_cb=None,
    executor_out: list | None = None,
) -> LibraryResult:
    """Pod-scale bulk validation THROUGH each process's scheduler —
    the composition of ``verify_library_sched`` (cross-tenant
    coalescing) and ``verify_library_distributed`` (process sharding).

    A deterministic byte-weight shard plan is computed identically on
    every process (``torrent_tpu.fabric.plan`` — no coordinator RPC);
    each process feeds its shard of (torrent, piece-range) units into
    its LOCAL scheduler as a low-priority ``"fabric"`` tenant, so bulk
    recheck launches coalesce with foreground verify traffic instead of
    competing with it. A periodic few-byte heartbeat carries progress
    and verdict bits; survivors adopt orphaned units from lapsed or
    breaker-degraded processes with a sentinel cross-check per adopted
    unit (see ``torrent_tpu.fabric.executor``).

    ``items``: the SAME list, in the same order, on every process (each
    host opens its own storage handles; only the shard is read).
    ``nproc``/``pid`` default to the live ``jax.distributed`` cluster;
    pass them explicitly (with ``heartbeat_dir`` for the shared-
    filesystem heartbeat transport) to run without ``jax.distributed``.
    ``executor_out``: optional list the executor is appended to, so
    callers can poll ``metrics_snapshot()`` while the sweep runs.

    Returns a :class:`LibraryResult` whose bitfields are identical on
    every process. ``progress_cb`` reports THIS process's verified
    pieces against the library-wide total.
    """
    from torrent_tpu.fabric import DEFAULT_UNIT_BYTES, build_fabric_executor

    t0 = time.perf_counter()
    ex = build_fabric_executor(
        items,
        scheduler,
        nproc=nproc,
        pid=pid,
        heartbeat_dir=heartbeat_dir,
        transport=transport,
        config=fabric_config,
        unit_bytes=unit_bytes or DEFAULT_UNIT_BYTES,
        progress_cb=progress_cb,
    )
    if executor_out is not None:
        executor_out.append(ex)
    await ex.run()
    total_pieces = sum(info.num_pieces for _, info in items)
    total_bytes = sum(info.length for _, info in items)
    return LibraryResult(
        ex.bitfields(), total_pieces, total_bytes, time.perf_counter() - t0
    )
