"""Scheduler autopilot — attribution-driven adaptive control.

PRs 6–10 built every sensor the verify pipeline has (per-stage ledger,
bottleneck attributor, lane-fill gauges, queue-wait histograms, fleet
digests); this module closes the observe→act loop: a periodic
controller whose **decisions are pure functions of snapshot deltas**
(no wall clock, no randomness — the decision core sits in the analysis
plane's determinism pass, exactly like the heartbeat payload builders)
drives four actuators, each individually config-gated:

* **Adaptive per-lane batch targets + flush deadlines**
  (``adapt_batch``): when attribution names a stage whose cost is paid
  per *launch* (``read``/``h2d``/``launch``) and the lane is flushing
  full (fill ≥ ``fill_high``) with queue waits that show backlog, grow
  the lane's flush target (×2 per decision, bounded by the staging
  budget and ``target_max_factor`` × the planned target) so fewer,
  bigger launches amortize the fixed cost; the flush deadline follows
  so partial flushes have time to fill. When demand falls (fill <
  ``fill_low``) the target returns toward the static plan. Applied
  targets snap to what the built plane actually stages via its
  existing ``launch_geometry`` hook — a pallas lane's grown target is
  always a tile multiple.
* **Admission budgets that follow the limiting stage**
  (``adapt_admission``): when a bottleneck is confirmed, stop admitting
  faster than it drains — the effective global queue budget becomes
  ``achieved_bps × drain_window_s`` (floored at ``admission_floor`` ×
  the configured budget). The existing shed/429 and blocking-
  backpressure machinery does the rest; when the bottleneck clears the
  budget recovers (×2 per decision) back to the configured value.
* **Backend steering** (``adapt_backend``): a lane persistently
  limited by its ``launch`` stage trials the alternative backend
  (pallas ↔ scan for sha256 lanes; device → cpu for sha1 — the same
  hashlib floor the breaker degrades to). The trial is hysteresis-
  guarded: it starts only after ``hysteresis_ticks`` consecutive
  identical verdicts, is evaluated one cooldown later against the
  pre-switch achieved launch rate, reverts if it did not improve by
  ``backend_improve``, and then **pins** the lane — a flapping verdict
  can never oscillate a lane between backends.
* **Fleet work rebalancing** (``FabricConfig.rebalance``, implemented
  in ``fabric/executor.py``): when the fleet rollup names this process
  a straggler for ``rebalance_after`` consecutive heartbeats, its
  *unstarted* units are offered to peers with headroom over the
  existing heartbeat/adoption channel — reusing the yield/reclaim and
  sentinel re-hash + distrust rules, so rebalancing cannot weaken the
  fabric's trust model.

**Hysteresis.** Every actuator requires the bottleneck verdict to
persist ``hysteresis_ticks`` consecutive decisions before acting, and
backs off ``cooldown_ticks`` after acting. An attribution verdict that
flaps between two stages therefore never confirms, and the actuators
hold still — the property the flapping test pins.

**Controller-off is bit-identical.** With no autopilot attached (or
``ControlConfig(enabled=False)``) every actuator keeps its static
value: lane targets/deadlines come from ``SchedulerConfig``, the
admission factor stays 1.0 (the budget comparison short-circuits), and
backends are the lane plan's. ``decide`` still runs in disabled mode
(the decision is observable) but nothing is applied.

Surfaces: ``GET /v1/control`` (last decision + inputs + actuator
values), ``torrent_tpu_control_*`` on both ``/metrics`` endpoints, a
decision line in ``torrent-tpu top``, ``doctor --control``, and the
``bench controller`` A/B rung (controller-on vs controller-off under a
``sched/faults.py`` throttle, banked).
"""

from __future__ import annotations

import asyncio
from dataclasses import dataclass

from torrent_tpu.utils.log import get_logger

log = get_logger("sched.control")

__all__ = [
    "ControlConfig",
    "SchedulerAutopilot",
    "build_inputs",
    "decide",
    "decision_summary",
    "initial_state",
]

# the queue-wait histogram family the controller reads (obs/hist):
# backlog evidence for the grow law, merged across lanes
QUEUE_WAIT_FAMILY = "torrent_tpu_sched_queue_wait_seconds"

# stages whose cost is paid per LAUNCH: a bigger batch amortizes them
# (verdict/stage are per-piece host work a bigger batch cannot help)
BATCH_AMORTIZED_STAGES = ("read", "h2d", "launch")

# backend-steering alternatives. "cpu" has no entry on purpose: the
# hashlib plane is the degradation floor — climbing back up is the
# breaker's half-open job, not the controller's.
ALT_BACKEND = {"pallas": "scan", "scan": "pallas", "device": "cpu"}


@dataclass
class ControlConfig:
    """Autopilot knobs. Defaults are deliberately conservative: the
    controller only moves an actuator on a persistent, high-confidence
    verdict, and every law is bounded on both sides."""

    # master switch: False = decisions are computed (observable via
    # /v1/control) but never applied — bit-identical static behavior
    enabled: bool = True
    # seconds between controller ticks when run as a background loop
    interval_s: float = 1.0
    # actuator gates, individually testable
    adapt_batch: bool = True
    adapt_admission: bool = True
    adapt_backend: bool = True
    # consecutive identical bottleneck verdicts before any actuator may
    # move (a flapping verdict never confirms)
    hysteresis_ticks: int = 2
    # decisions an actuator sits out after moving (per lane)
    cooldown_ticks: int = 2
    # a stage must own this share of the interval's wall to count
    util_threshold: float = 0.6
    # demanded/achieved must exceed this for the verdict to be worth
    # acting on (None headroom — only one active stage — passes)
    headroom_threshold: float = 1.5
    # lane-fill thresholds for the batch actuator
    fill_high: float = 0.85
    fill_low: float = 0.4
    # lane targets may grow to this multiple of the planned target
    target_max_factor: int = 8
    # flush deadlines may grow to this multiple of the configured one
    deadline_max_factor: float = 8.0
    # admission budget floor as a fraction of the configured budget
    admission_floor: float = 0.25
    # seconds of limiting-stage drain the admission budget may hold
    drain_window_s: float = 2.0
    # a backend trial must improve achieved launch B/s by this factor
    # to be kept; otherwise it reverts (and the lane pins either way)
    backend_improve: float = 1.1


# ----------------------------------------------------------- pure core
# (analysis determinism pass scope: decisions must be bit-stable given
# the same snapshot sequence — no wall clock, no randomness, every
# dict iteration sorted)


# determinism-scope
def initial_state() -> dict:
    """The controller's fold state: tick counter, bottleneck streak,
    the last tick the admission shrink condition confirmed, per-lane
    cooldowns and backend-trial records."""
    return {
        "tick": 0,
        "bn_stage": None,
        "bn_streak": 0,
        "adm_confirmed_tick": 0,
        "lanes": {},
    }


# determinism-scope
def build_inputs(
    led_snap: dict,
    prev_led: dict | None,
    surface: dict,
    prev_surface: dict | None,
    qw_snap=None,
    prev_qw=None,
) -> dict:
    """Assemble one decision's inputs from already-taken snapshots:
    delta attribution over the ledger, per-lane launch/fill deltas over
    the scheduler's control surface, and the queue-wait mean over the
    histogram family delta. Pure: no clocks, no globals."""
    from torrent_tpu.obs.attrib import attribute

    rep = attribute(led_snap, prev=prev_led)
    wall = float(rep.get("wall_s") or 0.0)
    lanes: dict = {}
    psurf = (prev_surface or {}).get("lanes") or {}
    for name in sorted((surface or {}).get("lanes") or {}):
        lane = surface["lanes"][name]
        prev = psurf.get(name) or {}
        d_launches = int(lane.get("launches", 0)) - int(prev.get("launches", 0))
        d_fill = float(lane.get("fill_sum", 0.0)) - float(prev.get("fill_sum", 0.0))
        bucket = int(lane.get("bucket", 0))
        target = int(lane.get("target", 1))
        lanes[name] = {
            "backend": lane.get("backend"),
            "bucket": bucket,
            "granule": max(1, int(lane.get("granule", 1))),
            "target": target,
            "base_target": int(lane.get("base_target", target)),
            "afford": int(lane.get("afford", target)),
            "deadline": float(lane.get("deadline", 0.0)),
            "base_deadline": float(lane.get("base_deadline", lane.get("deadline", 0.0))),
            "pending": int(lane.get("pending", 0)),
            "launches": max(0, d_launches),
            "fill": (d_fill / d_launches) if d_launches > 0 else None,
            # THIS lane's approximate launch throughput over the interval
            # (fill × target × bucket ≈ bytes per launch) — the backend
            # trial must judge a lane's steer against the lane's own
            # rate, never the ledger-global launch aggregate another
            # lane's traffic can inflate
            "launch_bps": (
                (d_fill * target * bucket) / wall
                if d_launches > 0 and wall > 1e-9 and bucket
                else None
            ),
        }
    qw_mean = None
    if qw_snap is not None:
        _, c1, s1 = qw_snap
        c0, s0 = 0, 0.0
        if prev_qw is not None:
            _, c0, s0 = prev_qw
        if c1 > c0:
            qw_mean = max(0.0, (float(s1) - float(s0)) / (int(c1) - int(c0)))
    return {
        "attribution": rep,
        "lanes": lanes,
        "queue_wait_mean_s": qw_mean,
        "admission": dict((surface or {}).get("admission") or {}),
    }


# determinism-scope
def _confirmed_stage(inputs: dict, state: dict, cfg: ControlConfig):
    """(stage, streak, confirmed): the bottleneck verdict gated by the
    utilization/headroom thresholds, its consecutive-tick streak, and
    whether hysteresis has confirmed it."""
    rep = inputs.get("attribution") or {}
    bn = rep.get("bottleneck")
    stage = None
    if bn and float(bn.get("utilization") or 0.0) >= cfg.util_threshold:
        hr = bn.get("headroom")
        if hr is None or float(hr) >= cfg.headroom_threshold:
            stage = bn.get("stage")
    if stage is not None and stage == state.get("bn_stage"):
        streak = int(state.get("bn_streak", 0)) + 1
    else:
        streak = 1 if stage is not None else 0
    confirmed = stage is not None and streak >= cfg.hysteresis_ticks
    return stage, streak, confirmed


# determinism-scope
def _lane_decisions(inputs, state, cfg, stage, streak, confirmed) -> list[dict]:
    """Batch-target + flush-deadline actions (per lane, hysteresis- and
    cooldown-guarded). Grow when a confirmed per-launch-cost stage
    limits a full-flushing lane with backlog; shrink back toward the
    static plan when fill collapses."""
    actions: list[dict] = []
    tick = state["tick"]
    qw = inputs.get("queue_wait_mean_s")
    lanes = inputs.get("lanes") or {}
    for name in sorted(lanes):
        lane = lanes[name]
        ls = state["lanes"].setdefault(name, {})
        if tick < int(ls.get("batch_cooldown", 0)):
            continue
        if not lane["launches"] or lane["fill"] is None:
            continue  # no traffic this interval: nothing to learn
        cap = min(lane["afford"], lane["base_target"] * cfg.target_max_factor)
        # snap the cap DOWN to the launch granule: proposing a target
        # the scheduler's snap would round back forever is pure chatter
        granule = max(1, int(lane.get("granule", 1)))
        if granule > 1 and cap >= granule:
            cap = cap // granule * granule
        backlogged = qw is None or qw >= lane["deadline"] * 0.25
        if (
            confirmed
            and stage in BATCH_AMORTIZED_STAGES
            and lane["fill"] >= cfg.fill_high
            and lane["target"] < cap
            and backlogged
        ):
            to = min(lane["target"] * 2, cap)
            actions.append({
                "actuator": "batch_target", "lane": name,
                "from": lane["target"], "to": to,
                "reason": (
                    f"{stage} limiting x{streak}, fill "
                    f"{lane['fill']:.2f}: amortize per-launch cost"
                ),
            })
            dl_to = min(
                lane["deadline"] * 2.0,
                lane["base_deadline"] * cfg.deadline_max_factor,
            )
            if dl_to > lane["deadline"]:
                actions.append({
                    "actuator": "flush_deadline", "lane": name,
                    "from": round(lane["deadline"], 6), "to": round(dl_to, 6),
                    "reason": "deadline follows the grown target",
                })
            ls["batch_cooldown"] = tick + cfg.cooldown_ticks + 1
        elif lane["fill"] < cfg.fill_low and lane["target"] > lane["base_target"]:
            to = max(lane["base_target"], lane["target"] // 2)
            actions.append({
                "actuator": "batch_target", "lane": name,
                "from": lane["target"], "to": to,
                "reason": (
                    f"fill {lane['fill']:.2f} under {cfg.fill_low}: "
                    "return toward the static plan"
                ),
            })
            dl_to = max(lane["base_deadline"], lane["deadline"] / 2.0)
            if dl_to < lane["deadline"]:
                actions.append({
                    "actuator": "flush_deadline", "lane": name,
                    "from": round(lane["deadline"], 6), "to": round(dl_to, 6),
                    "reason": "deadline follows the shrunk target",
                })
            ls["batch_cooldown"] = tick + cfg.cooldown_ticks + 1
    return actions


# determinism-scope
def _admission_decision(inputs, state, cfg, stage, confirmed) -> list[dict]:
    """Admission-budget action: while a bottleneck is confirmed, admit
    no faster than it drains; recover the budget once the shrink
    condition has not re-confirmed for a cooldown. Recovery keys on the
    LAST CONFIRMED tick, not on `stage is None` — a flapping verdict
    (stage set every tick but never confirming) must not leave the
    budget stuck at the floor forever; it recovers to the static 1.0
    and rests there, which is the stable endpoint the flapping test
    demands."""
    tick = state["tick"]
    adm = inputs.get("admission") or {}
    factor = float(adm.get("factor", 1.0))
    maxq = int(adm.get("max_queue_bytes", 0) or 0)
    rep = inputs.get("attribution") or {}
    bn = rep.get("bottleneck") or {}
    if confirmed and stage != "verdict" and maxq > 0:
        state["adm_confirmed_tick"] = tick
        achieved = bn.get("achieved_bps")
        if achieved:
            want = max(
                cfg.admission_floor,
                min(1.0, (float(achieved) * cfg.drain_window_s) / maxq),
            )
            # act only on a meaningful (≥10%) move: the achieved rate
            # jitters tick to tick and the budget must not chatter
            if want < factor * 0.9:
                return [{
                    "actuator": "admission",
                    "from": round(factor, 4), "to": round(want, 4),
                    "reason": (
                        f"admit no faster than {stage} drains "
                        f"({cfg.drain_window_s:.0f}s window)"
                    ),
                }]
    elif factor < 1.0 and (
        tick - int(state.get("adm_confirmed_tick", 0)) > cfg.cooldown_ticks
    ):
        to = min(1.0, factor * 2.0)
        return [{
            "actuator": "admission",
            "from": round(factor, 4), "to": round(to, 4),
            "reason": "bottleneck no longer confirmed: recover the admission budget",
        }]
    return []


# determinism-scope
def _backend_decisions(inputs, state, cfg, stage, streak, confirmed) -> list[dict]:
    """Backend-steering actions with the trial protocol: switch to the
    alternative on a confirmed launch-limited verdict, evaluate one
    cooldown later against the pre-switch PER-LANE achieved launch
    rate, revert unless it improved, and pin the lane either way — no
    oscillation. Only runs with actuation armed: the trial is stateful
    (it interprets the next interval as the new backend's performance),
    so an observe-only controller must not record phantom trials."""
    actions: list[dict] = []
    tick = state["tick"]
    lanes = inputs.get("lanes") or {}
    for name in sorted(lanes):
        lane = lanes[name]
        launch_bps = lane.get("launch_bps")
        ls = state["lanes"].setdefault(name, {})
        trial = ls.get("backend_trial")
        if trial is not None:
            if tick - int(trial["since"]) <= cfg.cooldown_ticks:
                continue  # let the new backend accumulate data
            if launch_bps is None:
                # zero-traffic interval: the new backend was never
                # actually measured — extend the trial rather than
                # issuing a phantom revert-and-pin verdict
                continue
            base = trial.get("baseline_bps")
            improved = bool(
                base and float(launch_bps) >= float(base) * cfg.backend_improve
            )
            if not improved:
                actions.append({
                    "actuator": "backend", "lane": name,
                    "from": lane["backend"], "to": trial["from"],
                    "reason": "backend trial did not improve; reverting",
                })
            ls["backend_trial"] = None
            ls["backend_pinned"] = True  # one trial per lane per run
            continue
        if ls.get("backend_pinned"):
            continue
        if not (confirmed and stage == "launch" and lane["launches"] > 0):
            continue
        alt = ALT_BACKEND.get(lane["backend"])
        if alt is None:
            continue
        actions.append({
            "actuator": "backend", "lane": name,
            "from": lane["backend"], "to": alt,
            "reason": f"launch limiting x{streak}: trialing {alt}",
        })
        ls["backend_trial"] = {
            "from": lane["backend"],
            "baseline_bps": launch_bps,
            "since": tick,
        }
    return actions


# determinism-scope
def decide(inputs: dict, state: dict, cfg: ControlConfig) -> tuple[dict, dict]:
    """One controller decision: pure function of (inputs, state, cfg).

    Returns ``(decision, new_state)``; the caller applies
    ``decision["actions"]`` through the scheduler's actuator setters
    (or doesn't, when the controller is disabled). Feeding the same
    snapshot sequence always yields the same decision sequence."""
    st = {
        "tick": int(state.get("tick", 0)) + 1,
        "bn_stage": state.get("bn_stage"),
        "bn_streak": int(state.get("bn_streak", 0)),
        "adm_confirmed_tick": int(state.get("adm_confirmed_tick", 0)),
        "lanes": {name: dict(state.get("lanes", {})[name])
                  for name in sorted(state.get("lanes", {}))},
    }
    stage, streak, confirmed = _confirmed_stage(inputs, state, cfg)
    st["bn_stage"], st["bn_streak"] = stage, streak
    actions: list[dict] = []
    if cfg.adapt_batch:
        actions += _lane_decisions(inputs, st, cfg, stage, streak, confirmed)
    if cfg.adapt_admission:
        actions += _admission_decision(inputs, st, cfg, stage, confirmed)
    if cfg.adapt_backend and cfg.enabled:
        # the trial protocol is stateful (the next interval is read as
        # the NEW backend's performance), so it only runs when the steer
        # is actually applied — observe-only mode reports batch and
        # admission intents but never phantom backend experiments
        actions += _backend_decisions(inputs, st, cfg, stage, streak, confirmed)
    bn = (inputs.get("attribution") or {}).get("bottleneck")
    decision = {
        "tick": st["tick"],
        "bottleneck": (
            {**bn, "streak": streak, "confirmed": confirmed}
            if stage is not None and bn
            else None
        ),
        "actions": actions,
    }
    return decision, st


# determinism-scope
def decision_summary(status: dict) -> str:
    """One human line for top/doctor: the verdict and what moved."""
    if not status:
        return "autopilot: no decision yet"
    parts = ["autopilot:" if status.get("enabled") else "autopilot (observe-only):"]
    decision = status.get("decision") or {}
    bn = decision.get("bottleneck")
    if bn:
        parts.append(
            f"{bn.get('stage')} limiting x{bn.get('streak', 0)}"
            + (" [confirmed]" if bn.get("confirmed") else "")
        )
    else:
        parts.append("no confirmed bottleneck")
    applied = status.get("applied") or []
    if applied:
        parts.append(
            "— "
            + ", ".join(
                f"{a['actuator']}"
                + (f"[{a['lane']}]" if a.get("lane") else "")
                + f" {a.get('from')}→{a.get('applied', a.get('to'))}"
                for a in applied[:4]
            )
        )
    actuators = status.get("actuators") or {}
    factor = actuators.get("admission_factor")
    if factor is not None and factor < 1.0:
        parts.append(f"(admission ×{factor:.2f})")
    return " ".join(parts)


# ------------------------------------------------------------ autopilot


class SchedulerAutopilot:
    """The observe→act loop around one :class:`HashPlaneScheduler`.

    ``tick()`` is synchronous and cheap (snapshots + dict math); the
    optional background loop (:meth:`start`) just calls it every
    ``interval_s``. All state lives on the event loop that owns the
    scheduler — the bridge's serving loop, or a test's — so no locks
    are needed (worker threads never touch the autopilot)."""

    def __init__(self, scheduler, config: ControlConfig | None = None):
        from torrent_tpu.obs.hist import histograms
        from torrent_tpu.obs.ledger import pipeline_ledger

        self.sched = scheduler
        self.config = config or ControlConfig()
        self._state = initial_state()
        self._last: dict | None = None
        self._task: asyncio.Task | None = None
        self._actions_total: dict[str, int] = {}
        self._backend_switches = 0
        # baseline snapshots seeded at ATTACH (same discipline as the
        # fabric executor's _obs_base): the ledger and histogram
        # registries are process-global, so without a base the first
        # tick's "delta" would span everything the process did before
        # the autopilot existed and contaminate its first verdict
        self._prev_led: dict | None = pipeline_ledger().snapshot()
        self._prev_surface: dict | None = scheduler.control_surface()
        self._prev_qw = histograms().family_snapshot(QUEUE_WAIT_FAMILY)

    # ------------------------------------------------------------- tick

    def tick(self) -> dict:
        """One observe→decide→act pass. Returns the stored status dict
        (decision + applied actions + inputs summary)."""
        from torrent_tpu.obs.hist import histograms
        from torrent_tpu.obs.ledger import pipeline_ledger

        led = pipeline_ledger().snapshot()
        surface = self.sched.control_surface()
        qw = histograms().family_snapshot(QUEUE_WAIT_FAMILY)
        inputs = build_inputs(
            led, self._prev_led, surface, self._prev_surface, qw, self._prev_qw
        )
        decision, self._state = decide(inputs, self._state, self.config)
        applied = self._apply(decision) if self.config.enabled else []
        self._prev_led, self._prev_surface, self._prev_qw = led, surface, qw
        rep = inputs["attribution"]
        self._last = {
            "decision": decision,
            "applied": applied,
            "inputs": {
                "wall_s": rep.get("wall_s"),
                "bottleneck": rep.get("bottleneck"),
                "queue_wait_mean_s": inputs.get("queue_wait_mean_s"),
                "lanes": {
                    name: {
                        "fill": lane["fill"],
                        "launches": lane["launches"],
                        "target": lane["target"],
                    }
                    for name, lane in sorted(inputs["lanes"].items())
                },
            },
        }
        return self._last

    def _apply(self, decision: dict) -> list[dict]:
        applied: list[dict] = []
        for action in decision.get("actions", []):
            kind = action.get("actuator")
            got = None
            if kind == "batch_target":
                got = self.sched.set_lane_target(action["lane"], action["to"])
            elif kind == "flush_deadline":
                got = self.sched.set_lane_deadline(action["lane"], action["to"])
            elif kind == "admission":
                got = self.sched.set_admission_factor(action["to"])
            elif kind == "backend":
                got = self.sched.steer_lane_backend(action["lane"], action["to"])
                if got is not None:
                    self._backend_switches += 1
            if got is not None and got != action.get("from"):
                self._actions_total[kind] = self._actions_total.get(kind, 0) + 1
                applied.append({**action, "applied": got})
                log.info(
                    "autopilot: %s%s %s -> %s (%s)",
                    kind,
                    f"[{action['lane']}]" if action.get("lane") else "",
                    action.get("from"), got, action.get("reason", ""),
                )
        return applied

    # ------------------------------------------------------------- loop

    def start(self) -> "SchedulerAutopilot":
        """Spawn the periodic tick task on the running loop."""
        if self._task is None:
            self._task = asyncio.ensure_future(self._run())
        return self

    async def _run(self) -> None:
        while True:
            await asyncio.sleep(self.config.interval_s)
            try:
                self.tick()
            except Exception as e:  # a bad tick must not kill the loop
                log.error("autopilot tick failed: %s", e)

    async def close(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except (asyncio.CancelledError, Exception):
                pass
            self._task = None

    # ---------------------------------------------------------- surface

    @staticmethod
    def _lane_actuators(surface: dict) -> dict:
        """Per-lane actuator values (one definition shared by /v1/control
        and the Prometheus rendering, so the two can never diverge)."""
        return {
            name: {
                "target": lane.get("target"),
                "deadline": lane.get("deadline"),
                "backend": lane.get("backend"),
            }
            for name, lane in sorted((surface.get("lanes") or {}).items())
        }

    def status(self) -> dict:
        """The ``GET /v1/control`` payload: last decision, what was
        applied, the inputs it saw, and every actuator's current value."""
        surface = self.sched.control_surface()
        last = self._last or {}
        return {
            "enabled": bool(self.config.enabled),
            "tick": int(self._state.get("tick", 0)),
            "decision": last.get("decision"),
            "applied": last.get("applied"),
            "inputs": last.get("inputs"),
            "actuators": {
                "admission_factor": (surface.get("admission") or {}).get(
                    "factor", 1.0
                ),
                "lanes": self._lane_actuators(surface),
            },
            "actions_total": dict(sorted(self._actions_total.items())),
            "backend_switches": self._backend_switches,
        }

    def metrics_snapshot(self) -> dict:
        """Scalar counters for ``render_control_metrics``."""
        surface = self.sched.control_surface()
        last = self._last or {}
        decision = last.get("decision") or {}
        bn = decision.get("bottleneck") or {}
        return {
            "enabled": bool(self.config.enabled),
            "ticks": int(self._state.get("tick", 0)),
            "actions": dict(sorted(self._actions_total.items())),
            "backend_switches": self._backend_switches,
            "admission_factor": (surface.get("admission") or {}).get("factor", 1.0),
            "bottleneck": bn.get("stage"),
            "lanes": self._lane_actuators(surface),
        }
