"""Continuous-batching scheduler for the hash plane — the multi-tenant
verify queue that turns a fast single-caller plane into a servable one.

Every entry point used to dispatch its own device batches in isolation
(bridge routes, parallel/verify.py, parallel/bulk.py, session
rechecks), so concurrent small callers each paid the fixed ~55 ms
dispatch cost on mostly-empty launches (BASELINE.md: batch fill is the
dominant throughput knob — 4096-piece dispatches cap at ~67k p/s,
8192 reaches 169k). This subsystem owns all dispatch instead:

    submit ──► admission control ──► per-tenant queues ──► DRR
               (bounded bytes,        (one deque per       assembler
                shed = typed 429)      tenant per lane)       │
                                                              ▼
    awaiting callers ◄── per-launch demux ◄── device launch (full batch
                         (futures resolve      OR deadline flush, so a
                          per submission)      lone 4-piece request is
                                               never stranded)

Work items are grouped into **lanes** keyed ``(algo, piece-length
bucket)`` — the same pow-2 bucketing the bridge used, so a handful of
compiled executables serve any geometry and the compile cache survives
across callers. Each lane runs one assembler task: it flushes a launch
when the batch fills to the lane target **or** when the oldest queued
item's deadline expires (flush reasons: full / deadline / shutdown).

Fairness is deficit round-robin over queued *bytes*: each tenant's
deficit grows by ``drr_quantum × weight`` per assembly pass, so a greedy
bulk tenant cannot starve a trickle CLI verify, and low-priority tenants
(session self-heal rechecks, ``weight < 1``) yield to foreground
traffic without ever being starved.

Admission control bounds queue memory globally and per tenant. A
non-blocking submit over the bound sheds with :class:`SchedRejected`
(the bridge maps it to HTTP 429); a blocking submit waits for space —
that wait is the backpressure a streaming ingest propagates to its TCP
socket. Queue depth, batch-fill ratio, flush reasons, per-tenant served
bytes, and shed counts are exported via ``utils/metrics.py``
(``render_sched_metrics``). The obs plane (``torrent_tpu/obs``) rides
the same lifecycle: always-on log2 latency histograms (queue wait,
launch, per-tenant end-to-end) feed ``/metrics`` as real Prometheus
histograms, traced submissions get per-stage spans (enqueue →
admission/shed → lane wait → launch/retry/bisect → digest → verdict),
the flight recorder dumps a black box on breaker-open and
retry-exhausted failures, and device launches are annotated in the
deep-dive profiler timeline via ``obs/profiler.py``. The pipeline
ledger (``obs/ledger.py``) additionally accounts byte/time/occupancy
at every stage boundary — staging-slot copies, device puts (h2d),
launches, D2H fetches, and the verdict demux — feeding the bottleneck
attributor behind ``GET /v1/pipeline`` and ``doctor --bottleneck``.

Failure domains. A launch exception must not fail every co-batched
ticket across all tenants, so dispatch is fault-isolated in two layers:

* **Retry + bisection** (:meth:`HashPlaneScheduler._dispatch`): a
  failed launch is retried once if the error classifies as *transient*
  (device/XLA hiccups — retrying a *deterministic* payload error is
  pointless and skipped), then split in half and each half relaunched,
  recursively to ``bisect_depth``. A single poisoned ticket therefore
  fails alone — its submitter's future gets a classified
  :class:`SchedLaunchError` — while every innocent co-batched ticket
  still receives its digest.
* **Per-lane circuit breaker** (:class:`_LaneBreaker`): consecutive
  transient failures of a lane's primary plane trip the lane to the
  hashlib :class:`_CpuPlane` (the parity fallback the BASELINE contract
  keeps), so the verify plane degrades to correct-but-slower instead of
  erroring. After ``breaker_cooldown`` a half-open probe sends one
  launch back to the primary plane; success re-closes the breaker.
  Breaker state and transitions are exported in ``metrics_snapshot()``.

Both layers are driven deterministically in tests by
``torrent_tpu.sched.faults`` (a :class:`FaultPlan` wired through the
``plane_factory`` seam), so every behavior above has a CPU-only test.

Zero-copy ingest. Scheduler-fed read loops check a :class:`StagedSlab`
out of the per-(algo, bucket) ingest pools (:meth:`checkout_staging`),
land disk reads directly in its row-strided view, and submit it with
:meth:`enqueue_staged`: tickets carry :class:`SlotRow` views (no
per-piece ``bytes``), single-slab launches hit the planes'
``run_staged`` form (the slab IS the launch buffer — the ledger's
``stage`` copy stage records zero bytes), and device planes H2D the
slab outside ``_device_lock`` with donated input buffers so batch
N+1's transfer overlaps batch N's kernel. Slabs are reference counted
(one ref per ticket, released at demux on every path) and the pools'
``outstanding`` gauge must return to 0 — see ARCHITECTURE.md
"Zero-copy ingest" for ownership rules and the fallback matrix.

The scheduler autopilot (``sched/control.py``) closes the observe→act
loop over these sensors: a periodic controller turns ledger/attribution
snapshot deltas into bounded actuator moves through the seams below —
``set_lane_target`` / ``set_lane_deadline`` (adaptive batching, snapped
via the planes' ``launch_geometry`` hooks), ``set_admission_factor``
(admit no faster than the limiting stage drains), and
``steer_lane_backend`` (hysteresis-guarded backend trials). With no
autopilot attached every seam stays at its static default and behavior
is bit-identical to the config.

The v2 (sha256) lanes default to the hand-tiled pallas kernel
(:class:`_Sha256PallasPlane`; ``TORRENT_TPU_SHA256_BACKEND`` /
``SchedulerConfig.sha256_backend`` select, lax.scan is the fallback).
Lane batching is plane-aware: pallas lane flush targets snap to tile
multiples (full launches waste zero pad rows), sub-tile partial flushes
round up to the 1024-row granule with ``nblocks=0`` sentinels, and
admission control charges the padded staging footprint per queued piece
rather than raw payload bytes. See ARCHITECTURE.md "The v2 hash plane".
"""

from __future__ import annotations

import asyncio
import hashlib
import time
from collections import deque
from dataclasses import dataclass
from typing import Callable

from torrent_tpu.analysis.sanitizer import guard_attrs, named_lock
from torrent_tpu.obs.hist import histograms
from torrent_tpu.obs.ledger import pipeline_ledger
from torrent_tpu.obs.recorder import flight_recorder
from torrent_tpu.obs.tracer import tracer
from torrent_tpu.utils.log import get_logger

log = get_logger("sched")

DIGEST_LEN = {"sha1": 20, "sha256": 32}

# latency-histogram families (torrent_tpu/obs): always-on per-stage
# distributions rendered as Prometheus histograms on every scrape
_H_QUEUE_WAIT = (
    "torrent_tpu_sched_queue_wait_seconds",
    "Seconds tickets waited in lane queues before launch assembly",
)
_H_LAUNCH = (
    "torrent_tpu_sched_launch_seconds",
    "Hash-plane launch duration per attempt (staging + device run)",
)
_H_E2E = (
    "torrent_tpu_sched_e2e_seconds",
    "Ticket enqueue-to-verdict seconds, labeled by tenant",
)


class SchedRejected(Exception):
    """Typed admission-control rejection (load shed).

    Carries enough structure for callers to surface a useful 429: the
    reason, the tenant, and the observed/limit byte figures.
    """

    def __init__(self, reason: str, tenant: str, queued_bytes: int = 0, limit_bytes: int = 0):
        super().__init__(
            f"{reason} (tenant={tenant} queued={queued_bytes}B limit={limit_bytes}B)"
        )
        self.reason = reason
        self.tenant = tenant
        self.queued_bytes = queued_bytes
        self.limit_bytes = limit_bytes


class SchedLaunchError(Exception):
    """A submission's pieces could not be hashed after retry/bisection.

    ``kind`` classifies the root cause: ``"transient"`` (device/XLA
    error that outlived the retry budget — the caller may retry later;
    the bridge maps this to 503 + Retry-After) or ``"deterministic"``
    (the payload itself makes the plane fail — retrying cannot help).
    """

    def __init__(self, message: str, kind: str, cause: Exception | None = None):
        super().__init__(message)
        self.kind = kind
        self.cause = cause
        self.__cause__ = cause


def classify_error(e: BaseException) -> str:
    """``'deterministic'`` (payload-caused, retry is pointless) or
    ``'transient'`` (device-plane hiccup, worth one retry).

    Fault-injection errors self-classify via ``sched_error_class``;
    otherwise value/shape errors are deterministic and everything else
    (XLA runtime errors, OSError, …) is assumed transient.
    """
    kind = getattr(e, "sched_error_class", None)
    if kind in ("deterministic", "transient"):
        return kind
    if isinstance(e, (ValueError, TypeError, KeyError, IndexError, AssertionError)):
        return "deterministic"
    return "transient"


@dataclass
class SchedulerConfig:
    # pieces per device launch the assembler aims to fill (per-lane
    # targets shrink for big-piece buckets so staging stays bounded)
    batch_target: int = 256
    # seconds the oldest queued item may wait before a partial flush
    flush_deadline: float = 0.02
    # global admission bound: queued + in-flight payload bytes
    max_queue_bytes: int = 256 << 20
    # per-tenant admission bound (a single tenant can't fill the queue)
    max_tenant_bytes: int = 128 << 20
    # DRR byte quantum added to each tenant's deficit per assembly pass
    drr_quantum: int = 1 << 20
    # per-lane staging budget: device batch ≈ budget / padded_len, like
    # the bridge's old staging rule, so a 16 MiB bucket can't OOM
    staging_budget: int = 128 << 20
    # launches allowed in flight per lane: 2 = double-buffer (the next
    # batch assembles and stages while the previous one runs on device,
    # matching the old stream gate's pending depth); 1 = strictly serial
    pipeline_depth: int = 2
    # auto-registered tenants beyond this bound are evicted once idle
    # (explicitly registered tenants are pinned) — bounds the state an
    # attacker can create with fresh X-Tenant values per request
    max_idle_tenants: int = 1024
    # test/extension hook: (algo, bucket, batch) -> plane with
    # .run(payloads) -> list[digest]; None = built-in planes
    plane_factory: Callable | None = None
    # relaunches of a failed batch before bisection, transient errors
    # only (a deterministic payload error skips straight to bisection)
    launch_retries: int = 1
    # max split-and-relaunch recursion isolating a poisoned ticket: a
    # depth of 12 isolates one piece out of a 4096-piece launch; past
    # the bound the surviving group fails together
    bisect_depth: int = 12
    # consecutive transient failures of a lane's primary plane before
    # the lane trips to the CPU (hashlib) fallback plane
    breaker_threshold: int = 3
    # seconds an open breaker waits before a half-open probe re-admits
    # the primary plane
    breaker_cooldown: float = 30.0
    # sha256 device backend: 'pallas' | 'scan' | 'auto' (None = the
    # TORRENT_TPU_SHA256_BACKEND env knob, defaulting to auto: pallas on
    # TPU-kind devices, scan elsewhere). A lane whose tile floor would
    # blow the staging budget falls back to scan regardless.
    sha256_backend: str | None = None


def resolve_sha256_backend(override: str | None = None) -> str:
    """``'pallas'`` or ``'scan'`` for the sha256 device plane.

    Precedence: explicit ``override`` (SchedulerConfig / bridge CLI) >
    ``TORRENT_TPU_SHA256_BACKEND`` env > ``auto``. Auto picks pallas on
    TPU-kind devices and scan everywhere else — choosing pallas
    explicitly on a CPU host runs the kernel in interpret mode (the
    deterministic parity path tests and ``doctor --v2`` use).
    """
    import os

    choice = (override or os.environ.get("TORRENT_TPU_SHA256_BACKEND") or "auto")
    choice = choice.strip().lower()
    if choice not in ("auto", "pallas", "scan"):
        raise ValueError(
            f"sha256 backend must be auto|pallas|scan, got {choice!r}"
        )
    if choice != "auto":
        return choice
    try:
        from torrent_tpu.ops.sha1_pallas import _auto_interpret

        return "scan" if _auto_interpret() else "pallas"
    except ImportError:  # pragma: no cover - jax without pallas
        return "scan"


class _Tenant:
    __slots__ = (
        "name", "weight", "max_bytes", "queued_bytes", "served_bytes",
        "served_pieces", "shed", "deficit", "pinned",
    )

    def __init__(self, name: str, weight: float = 1.0, max_bytes: int | None = None):
        self.name = name
        self.weight = weight
        self.max_bytes = max_bytes
        self.queued_bytes = 0
        self.served_bytes = 0
        self.served_pieces = 0
        self.shed = 0
        self.deficit = 0
        self.pinned = False  # register_tenant pins; auto-registered may be evicted


class _Submission:
    """One caller request of N pieces; resolves when all N demuxed.

    ``trace`` is the obs span context — ``(trace_id, parent_span_id)``
    captured at enqueue when the caller ran inside a span (bridge
    requests always do) — carried explicitly because lane assembler
    tasks and worker threads never inherit a request's contextvars.
    """

    __slots__ = ("mode", "results", "remaining", "future", "trace", "traced_done")

    def __init__(self, n: int, mode: str, loop: asyncio.AbstractEventLoop):
        self.mode = mode  # 'digest' | 'verify'
        self.results: list = [None] * n
        self.remaining = n
        self.future: asyncio.Future = loop.create_future()
        self.trace: tuple[str, str] | None = None
        # terminal digest/verdict spans recorded (a submission split
        # across launches whose halves fail separately must not get one
        # span per failing demux)
        self.traced_done = False

    def deliver(self, idx: int, value) -> None:
        self.results[idx] = value
        self.remaining -= 1
        if self.remaining == 0 and not self.future.done():
            if self.mode == "verify":
                self.future.set_result(bytes(self.results))
            else:
                self.future.set_result(self.results)


class _Ticket:
    """One piece in the queue: (submission, index, payload, expected).

    ``nbytes`` is the true payload size (DRR fairness, served-bytes
    accounting); ``charged`` is what admission control holds for this
    row — the padded staging footprint on device lanes, so the queue
    bound tracks what the launch actually stages, not the raw bytes.
    """

    __slots__ = ("sub", "idx", "payload", "expected", "tenant", "nbytes",
                 "charged", "ts")

    def __init__(self, sub, idx, payload, expected, tenant, ts, charged=None):
        self.sub = sub
        self.idx = idx
        self.payload = payload
        self.expected = expected
        self.tenant = tenant
        self.nbytes = len(payload)
        self.charged = self.nbytes if charged is None else charged
        self.ts = ts


class _Lane:
    """Assembler state for one (algo, piece-length bucket) geometry."""

    __slots__ = (
        "algo", "bucket", "target", "queues", "rotation", "pending_pieces",
        "event", "task", "plane", "build_lock", "sem", "inflight",
        "breaker", "cpu_plane", "backend", "deadline",
        "launches", "fill_sum", "pad_rows_total",
    )

    def __init__(
        self,
        algo: str,
        bucket: int,
        target: int,
        pipeline_depth: int,
        breaker: "_LaneBreaker",
        backend: str = "device",
    ):
        self.algo = algo
        self.bucket = bucket
        self.target = target
        self.queues: dict[str, deque] = {}
        self.rotation: list[str] = []
        self.pending_pieces = 0
        self.event = asyncio.Event()
        self.task: asyncio.Task | None = None
        self.plane = None  # built lazily off the event loop
        # pipelined launches run _run_plane in concurrent worker threads,
        # so first-use plane construction needs a real lock
        self.build_lock = named_lock("sched.lane.build_lock")
        self.sem = asyncio.Semaphore(max(1, pipeline_depth))
        self.inflight: set[asyncio.Task] = set()
        self.breaker = breaker
        self.cpu_plane = None  # hashlib degradation plane, built lazily
        self.backend = backend  # 'cpu' | 'device' | 'scan' | 'pallas'
        # per-lane flush-deadline override (the autopilot's actuator);
        # None = the SchedulerConfig value, so controller-off behavior
        # is bit-identical to the static config
        self.deadline: float | None = None
        # per-lane observability: launch-fill and pad-row waste gauges
        self.launches = 0
        self.fill_sum = 0.0
        self.pad_rows_total = 0

    def oldest_ts(self) -> float:
        return min(q[0].ts for q in self.queues.values() if q)


class _LaneBreaker:
    """Per-lane circuit breaker over the primary (device) plane.

    closed → open after ``threshold`` consecutive transient failures;
    open → half_open after ``cooldown`` seconds; half_open admits ONE
    probe launch — success closes the breaker, failure re-opens it.
    Launches run in concurrent worker threads (pipeline_depth ≥ 2), so
    every state read/transition holds the lock. Deterministic payload
    failures are not device faults: they release a probe slot but never
    move the state or the failure count.
    """

    __slots__ = (
        "threshold", "cooldown", "state", "failures", "opened_at",
        "probing", "transitions", "lock", "_cells",
    )

    def __init__(self, threshold: int, cooldown: float):
        self.threshold = max(1, threshold)
        self.cooldown = cooldown
        self.state = "closed"
        self.failures = 0
        self.opened_at = 0.0
        self.probing = False  # one half-open probe in flight at a time
        self.transitions: dict[str, int] = {}
        self.lock = named_lock("sched.breaker.lock")
        # dynamic lockset checking (tsan-lite Eraser): the whole
        # state/failures/probing blob is one cell guarded by self.lock
        self._cells = guard_attrs("sched.breaker", "state")

    def _to(self, state: str) -> None:
        key = f"{self.state}->{state}"
        self.transitions[key] = self.transitions.get(key, 0) + 1
        self.state = state

    def acquire_primary(self) -> bool:
        """Whether the next launch may use the primary plane (False =
        degrade to the CPU plane for this launch)."""
        with self.lock:
            self._cells.write("state")  # probing may flip below
            if self.state == "closed":
                return True
            if (
                self.state == "open"
                and time.monotonic() - self.opened_at >= self.cooldown
            ):
                self._to("half_open")
                self.probing = False
            if self.state == "half_open" and not self.probing:
                self.probing = True
                return True
            return False

    def record_success(self) -> None:
        with self.lock:
            self._cells.write("state")
            self.probing = False
            self.failures = 0
            if self.state != "closed":
                self._to("closed")

    def record_failure(self) -> bool:
        """A transient primary-plane failure (deterministic payload
        errors go through :meth:`release_probe` instead). Returns True
        when THIS failure transitioned the breaker to open — the
        caller's flight-recorder trigger point, kept outside the lock
        (dumping under it would nest the obs locks below breaker
        state)."""
        with self.lock:
            self._cells.write("state")
            if self.state == "half_open":
                self.probing = False
                self._to("open")
                self.opened_at = time.monotonic()
                return True
            self.failures += 1
            if self.state == "closed" and self.failures >= self.threshold:
                self._to("open")
                self.opened_at = time.monotonic()
                return True
            return False

    def release_probe(self) -> None:
        with self.lock:
            self._cells.write("state")
            self.probing = False

    def snapshot(self) -> dict:
        with self.lock:
            self._cells.read("state")
            out = {
                "state": self.state,
                "consecutive_failures": self.failures,
                "transitions": dict(self.transitions),
                # readiness semantics (obs/slo.build_health): an open
                # breaker within its cooldown is a transient degradation;
                # one stuck open well past it means the half-open probe
                # path is wedged and the process should leave rotation
                "cooldown": self.cooldown,
            }
            if self.state == "open":
                out["open_age_s"] = max(0.0, time.monotonic() - self.opened_at)
            return out


# --------------------------------------------------------------- planes


def build_builtin_plane(
    hasher: str, algo: str, bucket: int, batch: int, sha256_backend: str | None = None
):
    """The plane the scheduler builds when no ``plane_factory`` is set.

    Module-level so fault injection (``sched/faults.py``) can wrap the
    real planes through the ``plane_factory`` seam without duplicating
    the construction rules. ``sha256_backend`` pins the v2 backend
    ('pallas'/'scan'); None resolves env/auto via
    :func:`resolve_sha256_backend`.
    """
    if hasher == "cpu":
        return _CpuPlane(algo)
    if algo == "sha256":
        if resolve_sha256_backend(sha256_backend) == "pallas":
            return _Sha256PallasPlane(bucket, batch)
        return _Sha256DevicePlane(bucket, batch)
    return _Sha1DevicePlane(bucket, batch)


def accepts_sha256_backend(fn) -> bool:
    """Whether a plane-factory callable takes the optional
    ``sha256_backend`` kwarg — the seam stays backward compatible with
    3-arg factories, but a factory that can take the lane's resolved
    backend must get it (a 'pallas' pin must not override a
    budget-forced scan fallback; see :meth:`_build_plane`)."""
    import inspect

    try:
        params = inspect.signature(fn).parameters
    except (TypeError, ValueError):  # builtins/partials w/o signature
        return False
    return "sha256_backend" in params or any(
        p.kind is inspect.Parameter.VAR_KEYWORD for p in params.values()
    )


class _StagingSlots:
    """Reusable tail-zeroed staging slots shared by the device planes.

    ``hash_pieces``-style staging allocates + zeroes a fresh
    ``batch × padded_len`` buffer every launch — tens of MiB of memset on
    the hot path. Slots are checked out of a locked free list instead
    (pipelined launches run in concurrent worker threads) and remember
    each row's content extent from the previous launch, so ``stage``
    zeroes only the stale tail ``pad_in_place`` requires.
    """

    def __init__(self, rows: int, piece_len: int):
        self.rows = rows
        self.piece_len = piece_len
        self._slots: list[tuple] = []  # (padded, view, ends) free list
        self._lock = named_lock("sched.staging._lock")
        self._cells = guard_attrs("sched.staging", "free_list")
        # leak accounting: every checkout must be balanced by a checkin
        # (asserted by tests and exported via metrics_snapshot)
        self.outstanding = 0
        self.checkouts = 0

    def checkout(self) -> tuple:
        """Raw ``(padded, view, ends)`` slot checkout — the zero-copy
        ingest path fills the slot itself (disk reads land directly in
        ``view``); ``stage`` uses the same checkout for its copy path.
        The caller MUST ``checkin(slot)`` exactly once."""
        import numpy as np

        from torrent_tpu.ops.padding import alloc_padded

        with self._lock:
            self._cells.write("free_list")
            slot = self._slots.pop() if self._slots else None
            self.outstanding += 1
            self.checkouts += 1
        if slot is None:
            padded, view = alloc_padded(self.rows, self.piece_len)
            slot = (padded, view, np.zeros(self.rows, dtype=np.int64))
        return slot

    def stage(self, chunk: list[bytes], rows: int | None = None):
        """Checkout a slot and stage ``chunk`` into its first ``rows``
        rows (default: the whole slot).

        Returns ``(slot, padded, nblocks)`` with ``nblocks`` of length
        ``rows``; rows past ``len(chunk)`` are ``nblocks=0`` sentinels.
        Bounding ``rows`` to the launch (the pallas plane's tile bucket)
        skips the staging work for slot rows the launch never reads —
        untouched rows keep their recorded extents, so later reuse still
        tail-zeroes them correctly. The caller runs its launch, then
        MUST ``checkin(slot)`` (a finally block) to recycle the buffer.
        """
        import numpy as np

        from torrent_tpu.ops.padding import alloc_padded, pad_in_place

        rows = self.rows if rows is None else rows
        # pipeline-ledger "stage" boundary: the host copy into the
        # staging slot (the tracker's lock is leaf-scoped at entry/exit;
        # the copy itself runs outside any obs lock)
        with pipeline_ledger().track(
            "stage", sum(len(c) for c in chunk)
        ):
            slot = self.checkout()
            padded, view, ends = slot
            try:
                lengths = np.zeros(rows, dtype=np.int64)
                for i in range(rows):
                    n = len(chunk[i]) if i < len(chunk) else 0
                    stale = int(ends[i])
                    if stale > n:
                        padded[i, n:stale] = 0
                    if n:
                        view[i, :n] = _payload_ndarray(chunk[i])
                        lengths[i] = n
                nblocks = pad_in_place(padded[:rows], lengths)
                # content extent (message + padding) per row, for the next
                # reuse's tail zeroing — recorded before sentinels clear
                ends[:rows] = nblocks.astype(np.int64) * 64
            except Exception:
                # return the slot instead of leaking it; rows may hold
                # half-staged content past their recorded extents, so mark
                # them full-width — the next reuse tail-zeroes everything
                ends[:rows] = padded.shape[1]
                self.checkin(slot)
                raise
            nblocks[len(chunk) :] = 0  # sentinel rows: skip entirely
            return slot, padded, nblocks

    def checkin(self, slot) -> None:
        with self._lock:
            self._cells.write("free_list")
            self._slots.append(slot)
            self.outstanding -= 1

    def stats(self) -> tuple[int, int]:
        """(outstanding, checkouts) under the free-list lock — snapshot
        readers run on other threads than the checking-out workers."""
        with self._lock:
            self._cells.read("free_list")
            return self.outstanding, self.checkouts


def _payload_ndarray(p):
    """uint8 ndarray view of a ticket payload — SlotRow rows come back
    as views into their slab (no copy), bytes-likes via frombuffer."""
    import numpy as np

    if type(p) is SlotRow:
        return p.ndview()
    return np.frombuffer(p, dtype=np.uint8)


class SlotRow:
    """One staged row of a :class:`StagedSlab`, used as a ticket payload.

    Quacks enough like ``bytes`` for the scheduler's bookkeeping
    (``len``, ``startswith`` for the fault plane's poisoned-prefix
    probe) while never materializing a bytes object: CPU hashing and
    mixed-batch staging consume the numpy row view directly.
    """

    __slots__ = ("slab", "row")

    def __init__(self, slab: "StagedSlab", row: int):
        self.slab = slab
        self.row = row

    def __len__(self) -> int:
        return int(self.slab.lengths[self.row])

    def ndview(self):
        """uint8[len] view into the slab row (zero-copy)."""
        return self.slab.view[self.row, : len(self)]

    def startswith(self, prefix) -> bool:
        n = len(prefix)
        if n > len(self):
            return False
        return bytes(self.slab.view[self.row, :n]) == bytes(prefix)

    def tobytes(self) -> bytes:
        return self.ndview().tobytes()


class StagedSlab:
    """A checked-out staging slot pre-filled by the zero-copy read path.

    Owns one ``(padded, view, ends)`` slot of a scheduler ingest pool
    plus the per-row ``lengths``/``nblocks`` the read path derived —
    disk reads land directly in ``view``'s row-strided memory, rows
    that failed to read carry ``nblocks=0`` sentinels, and the whole
    slab is handed to :meth:`HashPlaneScheduler.enqueue_staged` without
    ever materializing per-piece ``bytes``.

    Lifecycle is reference counted: the creator (the reader) holds one
    reference from checkout; ``enqueue_staged`` retains one per ticket
    and the scheduler's demux releases them as verdicts resolve. The
    slot returns to its pool exactly when the count hits zero — on
    every path (success, launch failure, shed, reader abort), which is
    what the leak-counter test asserts.
    """

    __slots__ = (
        "pool", "slot", "padded", "view", "ends", "nblocks", "lengths",
        "algo", "bucket", "piece_length", "n_used", "_refs", "_lock",
        "_cells",
    )

    def __init__(self, pool: _StagingSlots, slot: tuple, algo: str,
                 bucket: int, piece_length: int):
        import numpy as np

        self.pool = pool
        self.slot = slot
        self.padded, self.view, self.ends = slot
        self.nblocks = np.zeros(pool.rows, dtype=np.int32)
        self.lengths = np.zeros(pool.rows, dtype=np.int64)
        self.algo = algo
        self.bucket = bucket
        self.piece_length = piece_length
        self.n_used = 0
        self._refs = 1  # the creator's hold
        self._lock = named_lock("sched.slab._lock")
        self._cells = guard_attrs("sched.slab", "refs")

    @property
    def rows_total(self) -> int:
        return self.pool.rows

    def prepare(self, planned_lengths) -> None:
        """Zero each row's stale tail beyond its incoming content extent
        (the reads themselves overwrite ``[0, length)``), so a reused
        slot needs no full-width memset before ``pad_in_place``."""
        import numpy as np

        n = len(planned_lengths)
        self.n_used = n
        self.lengths[:n] = np.asarray(planned_lengths, dtype=np.int64)
        self.lengths[n:] = 0
        for i in range(n):
            stale = int(self.ends[i])
            ln = int(self.lengths[i])
            if stale > ln:
                self.padded[i, ln:stale] = 0

    def finalize(self, ok) -> None:
        """Pad the first ``n_used`` rows in place and sentinel the failed
        ones (``ok[i] is False`` → ``nblocks=0``; mark-and-continue)."""
        import numpy as np

        from torrent_tpu.ops.padding import pad_in_place

        n = self.n_used
        nb = pad_in_place(self.padded[:n], self.lengths[:n])
        # dirty extent per row for the NEXT reuse's tail zeroing: padding
        # extent for hashed rows, the attempted read extent for failed
        # ones (their partial bytes are garbage the sentinel masks)
        self.ends[:n] = np.maximum(nb.astype(np.int64) * 64, self.lengths[:n])
        nb[~np.asarray(ok, dtype=bool)] = 0
        self.nblocks[:n] = nb
        self.nblocks[n:] = 0

    def row(self, i: int):
        return self.view[i, : int(self.lengths[i])]

    def retain(self, n: int = 1) -> None:
        with self._lock:
            self._cells.write("refs")
            self._refs += n

    def release(self, n: int = 1) -> None:
        with self._lock:
            self._cells.write("refs")
            self._refs -= n
            done = self._refs == 0
        if done:
            self.pool.checkin(self.slot)


def _staged_batch(payloads):
    """``(slab, rows)`` when every payload is a SlotRow of ONE slab —
    the zero-copy launch form (the plane reads the pre-staged buffer
    directly); ``None`` for mixed batches, which take the copying
    ``plane.run`` path."""
    first = payloads[0] if payloads else None
    if type(first) is not SlotRow:
        return None
    slab = first.slab
    rows = []
    for p in payloads:
        if type(p) is not SlotRow or p.slab is not slab:
            return None
        rows.append(p.row)
    return slab, rows


def _masked_nblocks(slab: StagedSlab, rows: list[int]):
    """Full-slab nblocks with every row OUTSIDE ``rows`` sentineled —
    launches always present the slab's static shape to the compiled
    plane (one executable per lane regardless of fill or bisection
    half) and the masked rows' chains never run."""
    import numpy as np

    nb = np.zeros(slab.rows_total, dtype=np.int32)
    idx = np.asarray(rows, dtype=np.int64)
    nb[idx] = slab.nblocks[idx]
    return nb


def _donating_wrapper(fn):
    """Jit-wrap ``fn(data, nblocks)`` donating the data buffer on real
    accelerators (H2D of batch N+1 then overlaps the kernel of batch N
    without doubling device-resident input memory). On the CPU backend
    donation is refused by XLA and would only warn, so the fn is
    returned unwrapped."""
    import jax

    if jax.default_backend() == "cpu":
        return fn
    return jax.jit(fn, donate_argnums=(0,))


class _CpuPlane:
    """hashlib fallback plane — the CPU-path parity backend."""

    def __init__(self, algo: str):
        self._h = hashlib.sha256 if algo == "sha256" else hashlib.sha1

    @staticmethod
    def launch_geometry(n_rows: int, bucket: int) -> tuple[int, int]:
        """hashlib stages nothing: no padding, no staging footprint."""
        return n_rows, 0

    def run(self, payloads: list[bytes]) -> list[bytes]:
        h = self._h
        with pipeline_ledger().track("launch", sum(len(p) for p in payloads)):
            # SlotRow payloads hash their numpy row views directly —
            # hashlib takes any contiguous buffer, no bytes materialized
            return [h(_payload_ndarray(p) if type(p) is SlotRow else p).digest()
                    for p in payloads]

    def run_staged(self, slab: StagedSlab, rows: list[int]) -> list[bytes]:
        """Zero-copy form: hash the pre-staged rows in place."""
        h = self._h
        nb = int(slab.lengths[list(rows)].sum())
        with pipeline_ledger().track("launch", nb):
            return [h(slab.row(r)).digest() for r in rows]


class _Sha1DevicePlane:
    """SHA-1 device plane: one compiled TPUVerifier per bucket (the
    geometry-grouped compile cache the bulk/verify loops relied on).

    Stages into reusable per-plane :class:`_StagingSlots` instead of
    ``hash_pieces`` (which allocates + zeroes a fresh buffer every
    launch).

    The jitted execution itself is serialized per plane
    (``_device_lock``): two worker threads entering the same compiled
    executable concurrently can deadlock inside the XLA runtime
    (observed as an intermittent pipelined-launch hang on XLA-CPU).
    Host staging — the copy + pad, the expensive host-side part — still
    overlaps across pipelined launches; only the device call is single-
    file, and the device serializes launches anyway."""

    def __init__(self, bucket: int, batch: int):
        from torrent_tpu.models.verifier import TPUVerifier

        self._verifier = TPUVerifier(piece_length=bucket, batch_size=batch)
        self._slots = _StagingSlots(self._verifier.batch_size, bucket)
        self._device_lock = named_lock("sched.sha1_plane._device_lock")

    @staticmethod
    def launch_geometry(n_rows: int, bucket: int) -> tuple[int, int]:
        """Row-exact launches; staging charges the padded row width."""
        from torrent_tpu.ops.padding import padded_len_for

        return n_rows, n_rows * padded_len_for(bucket)

    def _launch_padded(self, padded, nblocks, nb: int):
        """One device launch with the real stage split: explicit upload
        (h2d, outside the device lock so batch N+1's transfer overlaps
        batch N's kernel), jitted dispatch under the lock (async — with
        a donated input buffer on real devices), blocking fetch (digest)
        back outside it. Falls back to the fused ``digest_batch`` when
        the flat upload path can't take this shape (multi-process mesh,
        odd geometry)."""
        import numpy as np

        led = pipeline_ledger()
        v = self._verifier
        if not v.upload_supported(padded):
            # fused fallback (multi-process mesh, odd geometry): the
            # transfer runs inside digest_batch, so the bytes stay
            # under `launch` — never charged to a zero-length h2d span
            with self._device_lock:
                with led.track("launch", nb):
                    return v.digest_batch(padded, nblocks)
        with led.track("h2d", nb):
            handle = v.upload_batch(padded)
        with self._device_lock:
            with led.track("launch", nb):
                words_dev = v.digest_uploaded(handle, nblocks)
        with led.track("digest", nb):
            return np.asarray(words_dev)

    def run(self, payloads: list[bytes]) -> list[bytes]:
        from torrent_tpu.ops.padding import words_to_digests

        v = self._verifier
        b = v.batch_size
        if any(len(p) > v.piece_length for p in payloads):
            # same guard as the sha256 planes: a too-long piece would
            # fail mid-stage with the slot checked out
            raise ValueError("piece longer than plane piece_length")
        out: list[bytes] = []
        for start in range(0, len(payloads), b):
            chunk = payloads[start : start + b]
            nb = sum(len(p) for p in chunk)
            slot, padded, nblocks = self._slots.stage(chunk)
            try:
                words = self._launch_padded(padded, nblocks, nb)
                out.extend(words_to_digests(words[: len(chunk)]))
            finally:
                self._slots.checkin(slot)
        return out

    def run_staged(self, slab: StagedSlab, rows: list[int]) -> list[bytes]:
        """Zero-copy launch: the pre-staged slab IS the launch buffer —
        no ``_StagingSlots.stage`` copy, rows outside the ticket set are
        masked to ``nblocks=0`` so one static shape serves every fill
        level and bisection half."""
        from torrent_tpu.ops.padding import words_to_digests

        v = self._verifier
        if (
            slab.padded.shape[1] != v.padded_len
            or slab.rows_total > v.batch_size
            or slab.rows_total % max(1, v.mesh.size)
        ):
            # row width / mesh-divisibility mismatch: copy path. A row
            # count merely SMALLER than the verifier's (tile/mesh)
            # rounded batch is fine — upload_batch's sharded form takes
            # any mesh-divisible shape, so zero-copy launches survive
            # the batch rounding real accelerators apply.
            return self.run([SlotRow(slab, r) for r in rows])
        nb = int(slab.lengths[list(rows)].sum())
        words = self._launch_padded(
            slab.padded, _masked_nblocks(slab, rows), nb
        )
        return words_to_digests(words[rows])


class _Sha256DevicePlane:
    """SHA-256 (BEP 52) scan-backend plane — the fallback when the
    pallas kernel is unavailable (non-TPU device, ``scan`` selected, or
    a bucket whose tile floor would blow the lane staging budget)."""

    def __init__(self, bucket: int, batch: int):
        from torrent_tpu.ops.sha256_jax import make_sha256_fn

        self._fn = make_sha256_fn("jax")
        # donated variant for the launch: frees the device input buffer
        # as the kernel consumes it, so the next batch's H2D can reuse
        # that memory while this kernel runs (identity on CPU)
        self._fn_launch = _donating_wrapper(self._fn)
        self._bucket = bucket
        self._batch = batch
        self._slots = _StagingSlots(batch, bucket)
        # serialize the jitted call: concurrent entry from pipelined
        # worker threads can deadlock the XLA runtime (see sha1 plane)
        self._device_lock = named_lock("sched.sha256_scan_plane._device_lock")

    @staticmethod
    def launch_geometry(n_rows: int, bucket: int) -> tuple[int, int]:
        from torrent_tpu.ops.padding import padded_len_for

        return n_rows, n_rows * padded_len_for(bucket)

    def run(self, payloads: list[bytes]) -> list[bytes]:
        import jax.numpy as jnp
        import numpy as np

        from torrent_tpu.models.merkle import words32_to_digests

        if any(len(p) > self._bucket for p in payloads):
            raise ValueError("piece longer than plane piece_length")
        out: list[bytes] = []
        b = self._batch
        led = pipeline_ledger()
        for start in range(0, len(payloads), b):
            chunk = payloads[start : start + b]
            nb = sum(len(p) for p in chunk)
            slot, padded, nblocks = self._slots.stage(chunk)
            try:
                # ledger stage boundaries: the explicit device put (h2d,
                # outside the device lock so transfers overlap kernels),
                # the jitted dispatch (launch — async, donated input),
                # D2H fetch (digest). Bytes are payload bytes throughout
                # so cross-stage rates compare (the physical transfer
                # moves the padded footprint).
                with led.track("h2d", nb):
                    dev_p = jnp.asarray(padded)
                    dev_n = jnp.asarray(nblocks)
                with self._device_lock:
                    with led.track("launch", nb):
                        words_dev = self._fn_launch(dev_p, dev_n)
                with led.track("digest", nb):
                    words = np.asarray(words_dev)
                out.extend(words32_to_digests(words[: len(chunk)]))
            finally:
                self._slots.checkin(slot)
        return out

    def run_staged(self, slab: StagedSlab, rows: list[int]) -> list[bytes]:
        """Zero-copy launch from a pre-staged slab (no ``stage`` copy;
        non-ticket rows masked to sentinels, static full-slab shape)."""
        import jax.numpy as jnp
        import numpy as np

        from torrent_tpu.models.merkle import words32_to_digests

        led = pipeline_ledger()
        nb = int(slab.lengths[list(rows)].sum())
        with led.track("h2d", nb):
            dev_p = jnp.asarray(slab.padded)
            dev_n = jnp.asarray(_masked_nblocks(slab, rows))
        with self._device_lock:
            with led.track("launch", nb):
                words_dev = self._fn_launch(dev_p, dev_n)
        with led.track("digest", nb):
            words = np.asarray(words_dev)
        return words32_to_digests(words[rows])


class _Sha256PallasPlane:
    """SHA-256 (BEP 52) pallas plane — the v2 fast path.

    The hand-tiled kernel (``ops/sha256_pallas.py``) wants tile-shaped
    batches; the old scan-only scheduler avoided it because every launch
    padded to the configured tile (default 32×128 = 4096 rows). This
    plane makes sub-tile launches cheap instead:

    * **Row-bucketed padding**: a live batch rounds up to the nearest
      ``SUB_TILE_ROWS`` (8×128 = 1024) multiple, and ``tile_sub_for_rows``
      picks the largest legal sublane count that tiles the bucketed row
      count — full-target launches keep the sweep-tuned TILE_SUB,
      partial flushes drop to smaller tiles instead of padding 4×.
    * **Sentinel rows** carry ``nblocks=0``; their chains never run and
      their stale staging contents are masked off (same contract as the
      scan plane).
    * **Reusable staging slots** (:class:`_StagingSlots`) sized to the
      lane target, with per-row stale-tail zeroing — no per-launch
      memset. The u32 view of the slot feeds the kernel's fast path
      (a u8→u32 bitcast on device lowers through a 4×-widened fusion).
    * **Per-plane launch-plan cache**: the (padded_rows → tile_sub,
      interleave2) decision is memoized per geometry; jax.jit then keys
      the compiled executable on the same statics, so a lane serves any
      fill level from a handful of executables.

    interleave2 needs ≥16 sublanes with whole-vreg halves, so 1024-row
    sub-tile launches silently run the straight kernel even when the
    knob is on (correctness is identical; the knob is a scheduling hint).
    """

    def __init__(self, bucket: int, batch: int, interpret: bool | None = None):
        from torrent_tpu.ops import sha256_pallas as sp

        self._sp = sp
        self._bucket = bucket
        # slots (and the max launch) are sized to the tile-bucketed
        # target, so a lane target that is already a tile multiple
        # wastes zero pad rows at full fill
        self._batch = sp.pad_rows_for(batch)
        self._interpret = interpret
        self._slots = _StagingSlots(self._batch, bucket)
        self._plans: dict[int, tuple[int, int, bool]] = {}  # n -> (rows, ts, il2)
        # donated launch callables per (tile_sub, interleave2) — built on
        # first use from the worker thread (jax backend probe included)
        self._launch_fns: dict[tuple[int, bool], Callable] = {}
        self._device_lock = named_lock("sched.sha256_pallas_plane._device_lock")

    @staticmethod
    def launch_geometry(n_rows: int, bucket: int) -> tuple[int, int]:
        """Tile-bucketed rows; staging charges the padded footprint
        including sentinel rows."""
        from torrent_tpu.ops.padding import padded_len_for
        from torrent_tpu.ops.sha256_pallas import pad_rows_for

        rows = pad_rows_for(n_rows)
        return rows, rows * padded_len_for(bucket)

    def _plan(self, n: int) -> tuple[int, int, bool]:
        plan = self._plans.get(n)
        if plan is None:
            sp = self._sp
            rows = min(sp.pad_rows_for(n), self._batch)
            ts = sp.tile_sub_for_rows(rows)
            il2 = sp.INTERLEAVE2 and ts >= 16 and not (ts // 2) % 8
            plan = self._plans[n] = (rows, ts, il2)
        return plan

    def _launch_fn(self, ts: int, il2: bool):
        """Kernel callable for a tiling, input-donated off-CPU (the
        double-buffer memory contract; see :func:`_donating_wrapper`)."""
        fn = self._launch_fns.get((ts, il2))
        if fn is None:
            sp, interp = self._sp, self._interpret

            def base(data32, nblocks, _ts=ts, _il2=il2):
                return sp.sha256_pieces_pallas(
                    data32, nblocks, interpret=interp, tile_sub=_ts,
                    interleave2=_il2,
                )

            fn = self._launch_fns[(ts, il2)] = _donating_wrapper(base)
        return fn

    def run(self, payloads: list[bytes]) -> list[bytes]:
        import jax.numpy as jnp
        import numpy as np

        from torrent_tpu.models.merkle import words32_to_digests

        if any(len(p) > self._bucket for p in payloads):
            raise ValueError("piece longer than plane piece_length")
        out: list[bytes] = []
        b = self._batch
        led = pipeline_ledger()
        for start in range(0, len(payloads), b):
            chunk = payloads[start : start + b]
            nb = sum(len(p) for p in chunk)
            rows, ts, il2 = self._plan(len(chunk))
            slot, padded, nblocks = self._slots.stage(chunk, rows)
            try:
                # slice to the bucketed row count, reinterpret as the
                # kernel's u32 fast path (rows are 128-byte aligned so
                # the view is free and the slab contiguous)
                data32 = padded[:rows].view(np.uint32)
                # same ledger boundaries as the scan plane: explicit put
                # = h2d (outside the device lock so transfers overlap
                # kernels), jitted dispatch = launch (async, donated
                # input), fetch = digest
                with led.track("h2d", nb):
                    dev_d = jnp.asarray(data32)
                    dev_n = jnp.asarray(nblocks)
                with self._device_lock:
                    with led.track("launch", nb):
                        words_dev = self._launch_fn(ts, il2)(dev_d, dev_n)
                with led.track("digest", nb):
                    words = np.asarray(words_dev)
                out.extend(words32_to_digests(words[: len(chunk)]))
            finally:
                self._slots.checkin(slot)
        return out

    def run_staged(self, slab: StagedSlab, rows: list[int]) -> list[bytes]:
        """Zero-copy launch from a pre-staged slab: tile-bucket the full
        slab row count, mask non-ticket rows to sentinels, feed the u32
        view of the slab directly — no ``stage`` copy."""
        import jax.numpy as jnp
        import numpy as np

        from torrent_tpu.models.merkle import words32_to_digests

        led = pipeline_ledger()
        launch_rows, ts, il2 = self._plan(slab.rows_total)
        if launch_rows > slab.rows_total or any(r >= launch_rows for r in rows):
            # pool slab smaller than the tile granule (or bigger than
            # the plane's max launch): copy path
            return self.run([SlotRow(slab, r) for r in rows])
        nb = int(slab.lengths[list(rows)].sum())
        nblocks = _masked_nblocks(slab, rows)[:launch_rows]
        data32 = slab.padded[:launch_rows].view(np.uint32)
        with led.track("h2d", nb):
            dev_d = jnp.asarray(data32)
            dev_n = jnp.asarray(nblocks)
        with self._device_lock:
            with led.track("launch", nb):
                words_dev = self._launch_fn(ts, il2)(dev_d, dev_n)
        with led.track("digest", nb):
            words = np.asarray(words_dev)
        return words32_to_digests(words[rows])


# ------------------------------------------------------------ scheduler


class HashPlaneScheduler:
    """The shared verify queue. One instance serves every consumer of a
    process's hash plane; see the module docstring for the data flow."""

    def __init__(self, config: SchedulerConfig | None = None, hasher: str = "tpu"):
        self.config = config or SchedulerConfig()
        self.hasher = hasher
        self._tenants: dict[str, _Tenant] = {}
        self._lanes: dict[tuple[str, int], _Lane] = {}
        self._queued_bytes = 0  # queued + in-flight payload bytes
        self._closing = False
        self._space = asyncio.Event()  # pulsed on every byte release
        # metrics
        self._launches = 0
        self._fill_sum = 0.0
        self._flush_reasons = {"full": 0, "deadline": 0, "shutdown": 0}
        self._shed_total = 0
        # fault-tolerance counters (satellite observability: exported
        # via metrics_snapshot -> render_sched_metrics -> /metrics)
        self._launch_failures = 0
        self._retries = 0
        self._bisections = 0
        self._cpu_fallback_launches = 0
        # the only fault counter touched off the event loop (worker
        # threads, possibly in different lanes) — needs its own lock
        self._counter_lock = named_lock("sched._counter_lock")
        self._counter_cells = guard_attrs("sched.scheduler", "fault_counters")
        self._failed_pieces = 0  # tickets that exhausted retry+bisection
        # rollup of evicted auto-registered tenants so served/shed totals
        # stay monotonic after their per-tenant series disappear
        self._evicted = {"tenants": 0, "served_bytes": 0, "served_pieces": 0, "shed": 0}
        # zero-copy ingest: reader-side staging pools per (algo, bucket)
        # — disk reads land directly in these slots and slot-carrying
        # submissions hand them to the planes without a stage copy.
        # Checked out from worker threads (read paths run off-loop).
        self._ingest_pools: dict[tuple[str, int], _StagingSlots] = {}
        self._ingest_lock = named_lock("sched._ingest_lock")
        # resolved-once sha256 backend ('pallas'/'scan'); auto-resolution
        # touches jax.devices(), which must stay off the event loop
        self._sha256_backend_resolved: str | None = None
        # autopilot actuator (sched/control.py): fraction of the
        # configured global admission budget currently admitted. 1.0 =
        # the static config exactly (the comparison short-circuits, so
        # controller-off behavior is bit-identical)
        self._admission_factor = 1.0

    # ------------------------------------------------------------ admin

    async def start(self) -> "HashPlaneScheduler":
        """Bind to the running loop (lanes spawn lazily on first use).

        Pre-resolves the sha256 backend in a worker thread: 'auto'
        probes ``jax.devices()``, which can block for minutes behind a
        wedged device tunnel — that wait must never land on the serving
        loop (``chunk_for`` / enqueue call :meth:`_lane_plan` inline).
        """
        if self.hasher != "cpu" and self._sha256_backend_resolved is None:
            self._sha256_backend_resolved = await asyncio.to_thread(
                resolve_sha256_backend, self.config.sha256_backend
            )
        return self

    async def close(self) -> None:
        """Flush every pending item (reason 'shutdown') and stop lanes."""
        self._closing = True
        for lane in self._lanes.values():
            lane.event.set()
        self._space.set()
        for lane in list(self._lanes.values()):
            if lane.task is not None:
                await lane.task
            if lane.inflight:
                await asyncio.gather(*lane.inflight, return_exceptions=True)

    def register_tenant(
        self, name: str, weight: float = 1.0, max_bytes: int | None = None
    ) -> None:
        """Declare a tenant's scheduling weight / byte bound (idempotent;
        unseen tenants are auto-registered at weight 1.0 on first use)."""
        if weight <= 0:
            raise ValueError("tenant weight must be positive")
        t = self._tenants.get(name)
        if t is None:
            t = self._tenants[name] = _Tenant(name, weight, max_bytes)
        else:
            t.weight = weight
            if max_bytes is not None:
                t.max_bytes = max_bytes
        t.pinned = True

    # ---------------------------------------------------------- helpers

    @staticmethod
    def bucket_for(piece_length: int) -> int:
        """Pow-2 piece-length bucket (shared executable per bucket)."""
        return 1 << (piece_length - 1).bit_length() if piece_length > 1 else 1

    def sha256_backend(self) -> str:
        """The resolved v2 backend ('pallas'/'scan'), memoized. start()
        pre-warms this in a worker thread — 'auto' probes
        ``jax.devices()``, which can block behind a wedged device tunnel
        and must not do so on the serving loop. An unstarted scheduler
        (tests, direct use) resolves inline on first need."""
        backend = self._sha256_backend_resolved
        if backend is None:
            backend = self._sha256_backend_resolved = resolve_sha256_backend(
                self.config.sha256_backend
            )
        return backend

    def _lane_plan(self, algo: str, bucket: int) -> tuple[str, int]:
        """(backend, flush target) for a lane — plane-aware batching.

        The base target is ``min(batch_target, staging_budget /
        padded_len)`` — big-piece buckets shrink the launch so staging
        stays bounded (the bridge's old private-buffer rule). Pallas
        sha256 lanes then snap the target to a tile multiple: UP to the
        next ``SUB_TILE_ROWS`` granule (a full launch wastes zero pad
        rows) but never past what the staging budget affords; a bucket
        whose single-tile floor already exceeds the budget falls back to
        the scan backend instead of overrunning it.
        """
        from torrent_tpu.ops.padding import padded_len_for

        cfg = self.config
        afford = max(1, cfg.staging_budget // padded_len_for(bucket))
        base = max(1, min(cfg.batch_target, afford))
        if algo != "sha256" or self.hasher == "cpu":
            return ("cpu" if self.hasher == "cpu" else "device"), base
        backend = self.sha256_backend()
        if backend == "pallas":
            from torrent_tpu.ops.sha256_pallas import (
                SUB_TILE_ROWS,
                TILE_LANE,
                TILE_SUB,
                pad_rows_for,
                tile_sub_for_rows,
            )

            if afford >= SUB_TILE_ROWS:
                target = min(
                    pad_rows_for(base), afford // SUB_TILE_ROWS * SUB_TILE_ROWS
                )
                # prefer the sweep-tuned tiling: a row count whose ONLY
                # legal tiling is the minimal tile_sub=8 (e.g. 5120 rows)
                # rounds down to a full configured-tile multiple (4096 →
                # tile_sub 32) — a slightly smaller launch on the fast
                # tiling beats a bigger one on the slow tiling. Targets
                # that tile at 16/24 sublanes stand: a user-configured
                # batch_target must not silently shrink over a mild
                # tiling preference.
                full_tile = TILE_SUB * TILE_LANE
                alt = target // full_tile * full_tile
                if alt and tile_sub_for_rows(target) == 8 < TILE_SUB:
                    target = alt
                return "pallas", target
            backend = "scan"  # tile floor would blow the staging budget
        return backend, base

    def checkout_staging(
        self, piece_length: int, n_rows: int, algo: str = "sha1"
    ) -> StagedSlab | None:
        """Check a staging slab out for the zero-copy ingest path.

        The read path (``parallel/verify.read_pieces_into``) fills the
        slab's row-strided view directly from disk, pads it in place,
        and submits it via :meth:`enqueue_staged` — no per-piece
        ``bytes``, no ``_StagingSlots.stage`` copy. Returns ``None``
        when this geometry can't take pre-staged submissions (chunk
        bigger than the lane's slab, scheduler closing) — callers then
        fall back to the ``read_pieces_chunk`` byte path. Safe to call
        from worker threads (read loops run off the event loop).

        The caller owns one reference; every path must end in
        ``slab.release()`` (directly, or via ``enqueue_staged``'s
        per-ticket refs resolving through demux).
        """
        if self._closing or algo not in DIGEST_LEN:
            return None
        bucket = self.bucket_for(piece_length)
        key = (algo, bucket)
        with self._ingest_lock:
            pool = self._ingest_pools.get(key)
        if pool is None:
            _, target = self._lane_plan(algo, bucket)
            with self._ingest_lock:
                pool = self._ingest_pools.setdefault(
                    key, _StagingSlots(target, bucket)
                )
        if n_rows > pool.rows:
            return None
        return StagedSlab(pool, pool.checkout(), algo, bucket, piece_length)

    def chunk_for(self, piece_length: int, algo: str = "sha1") -> int:
        """Effective batch target for this geometry — the lane flush
        size (plane-aware: pallas sha256 lanes snap to tile multiples).
        Stream ingests use it as their submission chunk so one
        submission maps to roughly one launch."""
        return self._lane_plan(algo, self.bucket_for(piece_length))[1]

    # -------------------------------------------- autopilot actuators
    # (sched/control.py — every setter is a no-op-able, bounded seam;
    # with no autopilot attached none of these ever runs and behavior
    # is bit-identical to the static config)

    def _lane_by_key(self, lane_key: str) -> _Lane | None:
        algo, _, bucket = lane_key.rpartition("/")
        try:
            return self._lanes.get((algo, int(bucket)))
        except ValueError:
            return None

    def set_lane_target(self, lane_key: str, target: int) -> int | None:
        """Set a lane's flush target (autopilot batch actuator).

        The applied value is clamped to the staging budget and snapped
        to what the built plane actually stages via its
        ``launch_geometry`` hook — a pallas lane's adapted target is
        always a tile multiple. Returns the applied target (None for an
        unknown lane)."""
        lane = self._lane_by_key(lane_key)
        if lane is None:
            return None
        target = max(1, int(target))
        afford = None
        if self.hasher != "cpu":
            from torrent_tpu.ops.padding import padded_len_for

            afford = max(1, self.config.staging_budget // padded_len_for(lane.bucket))
            target = min(target, afford)
        hook = (
            getattr(lane.plane, "launch_geometry", None)
            if lane.plane is not None
            else None
        )
        if hook is not None:
            rows = int(hook(target, lane.bucket)[0])
            if afford is not None and rows > afford:
                # the hook snaps UP (pallas tile granule); a snap past
                # the staging afford must round DOWN to the largest
                # granule multiple instead — same discipline as the
                # lane plan's `afford // SUB_TILE_ROWS * SUB_TILE_ROWS`.
                # When even one granule doesn't fit, the budget beats
                # the tiling and the raw afford stands.
                granule = max(1, int(hook(1, lane.bucket)[0]))
                rows = afford // granule * granule
                if rows < 1:
                    rows = afford
            if rows >= 1:
                target = rows
        elif lane.backend == "pallas":
            from torrent_tpu.ops.sha256_pallas import pad_rows_for

            rows = max(1, pad_rows_for(target))
            if afford is None or rows <= afford:
                target = rows
        lane.target = target
        lane.event.set()  # re-evaluate the flush condition now
        return lane.target

    def set_lane_deadline(self, lane_key: str, seconds: float) -> float | None:
        """Per-lane flush-deadline override (autopilot). Returns the
        applied value (None for an unknown lane)."""
        lane = self._lane_by_key(lane_key)
        if lane is None:
            return None
        lane.deadline = max(0.001, float(seconds))
        lane.event.set()
        return lane.deadline

    def set_admission_factor(self, factor: float) -> float:
        """Scale the global admission budget (autopilot). 1.0 restores
        the static config exactly; raising the factor wakes blocked
        submitters."""
        factor = min(1.0, max(0.01, float(factor)))
        raised = factor > self._admission_factor
        self._admission_factor = factor
        if raised:
            self._space.set()
        return factor

    def steer_lane_backend(self, lane_key: str, backend: str) -> str | None:
        """Steer a lane to another backend (autopilot). The plane is
        rebuilt lazily on the next launch; an in-flight launch finishes
        on the old plane (planes are stateless). Returns the new
        backend, or None when unknown lane / already there."""
        if backend not in ("cpu", "device", "scan", "pallas"):
            raise ValueError(f"unknown backend {backend!r}")
        lane = self._lane_by_key(lane_key)
        if lane is None or lane.backend == backend:
            return None
        log.info(
            "steering lane %s backend %s -> %s", lane_key, lane.backend, backend
        )
        lane.backend = backend
        lane.plane = None  # next _run_plane rebuilds under build_lock
        return backend

    def control_surface(self) -> dict:
        """Per-lane + admission view the autopilot decides over (pure
        reads; the controller deltas launches/fill_sum itself)."""
        from torrent_tpu.ops.padding import padded_len_for

        cfg = self.config
        lanes: dict[str, dict] = {}
        for (algo, bucket) in sorted(self._lanes):
            lane = self._lanes[(algo, bucket)]
            if self.hasher == "cpu":
                # hashlib stages nothing: growth is bounded only by the
                # controller's own target_max_factor law
                afford = max(lane.target, cfg.batch_target) * 64
            else:
                afford = max(1, cfg.staging_budget // padded_len_for(bucket))
            # launch granule (1 = row-exact): the controller snaps its
            # grow cap to this so it never proposes a target the
            # set_lane_target snap would round back down forever
            hook = (
                getattr(lane.plane, "launch_geometry", None)
                if lane.plane is not None
                else None
            )
            if hook is not None:
                granule = max(1, int(hook(1, bucket)[0]))
            elif lane.backend == "pallas":
                from torrent_tpu.ops.sha256_pallas import SUB_TILE_ROWS

                granule = SUB_TILE_ROWS
            else:
                granule = 1
            lanes[f"{algo}/{bucket}"] = {
                "algo": algo,
                "bucket": bucket,
                "granule": granule,
                "target": lane.target,
                "base_target": self._lane_plan(algo, bucket)[1],
                "afford": afford,
                "deadline": (
                    lane.deadline if lane.deadline is not None else cfg.flush_deadline
                ),
                "base_deadline": cfg.flush_deadline,
                "backend": lane.backend,
                "launches": lane.launches,
                "fill_sum": lane.fill_sum,
                "pending": lane.pending_pieces,
            }
        return {
            "lanes": lanes,
            "admission": {
                "factor": self._admission_factor,
                "max_queue_bytes": cfg.max_queue_bytes,
                "queue_bytes": self._queued_bytes,
            },
        }

    def _lane(self, algo: str, piece_length: int) -> _Lane:
        bucket = self.bucket_for(piece_length)
        key = (algo, bucket)
        lane = self._lanes.get(key)
        if lane is None:
            backend, target = self._lane_plan(algo, bucket)
            lane = _Lane(
                algo,
                bucket,
                target,
                self.config.pipeline_depth,
                _LaneBreaker(
                    self.config.breaker_threshold, self.config.breaker_cooldown
                ),
                backend=backend,
            )
            self._lanes[key] = lane
            lane.task = asyncio.ensure_future(self._lane_loop(lane))
        return lane

    def _tenant(self, name: str) -> _Tenant:
        t = self._tenants.get(name)
        if t is None:
            t = _Tenant(name)
            self._tenants[name] = t
            if len(self._tenants) > self.config.max_idle_tenants:
                self._prune_tenants()
        return t

    def _prune_tenants(self) -> None:
        """Evict idle auto-registered tenants once past the cardinality
        bound — an attacker sending a fresh X-Tenant per request must not
        grow per-tenant state, /metrics series, or the DRR rotation
        without limit. Pinned (register_tenant) tenants are kept."""
        excess = len(self._tenants) - self.config.max_idle_tenants
        for name, t in list(self._tenants.items()):
            if excess <= 0:
                return
            if t.pinned or t.queued_bytes:
                continue
            # queued_bytes misses zero-length payloads, so check queues too
            if any(lane.queues.get(name) for lane in self._lanes.values()):
                continue
            del self._tenants[name]
            for lane in self._lanes.values():
                if lane.queues.pop(name, None) is not None:
                    lane.rotation.remove(name)
            self._evicted["tenants"] += 1
            self._evicted["served_bytes"] += t.served_bytes
            self._evicted["served_pieces"] += t.served_pieces
            self._evicted["shed"] += t.shed
            excess -= 1

    # ------------------------------------------------------------ submit

    async def enqueue(
        self,
        tenant: str,
        pieces: list[bytes],
        expected: list[bytes] | None = None,
        algo: str = "sha1",
        piece_length: int | None = None,
        wait: bool = False,
    ) -> asyncio.Future:
        """Queue one submission; returns a future resolving to its
        results (digest list, or ok-bytes when ``expected`` is given).

        ``wait=False`` sheds with :class:`SchedRejected` when admission
        control is over budget (the bridge's 429); ``wait=True`` blocks
        until space frees — the backpressure path for streaming ingest.
        """
        if algo not in DIGEST_LEN:
            raise ValueError(f"unknown algo {algo!r}")
        mode = "digest" if expected is None else "verify"
        if expected is not None and len(expected) != len(pieces):
            raise ValueError("expected list must match pieces")
        loop = asyncio.get_running_loop()
        sub = _Submission(len(pieces), mode, loop)
        if not pieces:
            sub.future.set_result(b"" if mode == "verify" else [])
            return sub.future
        # span context captured HERE (the caller's task still holds it);
        # everything downstream runs in lane tasks / worker threads
        ctx = tracer().current_context()
        t_enq = time.monotonic()
        ts = self._tenant(tenant)
        plen = piece_length if piece_length else max(len(p) for p in pieces)
        bucket = self.bucket_for(plen)
        if any(len(p) > bucket for p in pieces):
            raise ValueError("piece exceeds submission piece_length")
        # Admission charges what a device launch actually stages — the
        # padded row footprint (lane-aligned padded_len per piece), not
        # the raw payload bytes; a 1-byte piece in a 16 MiB bucket still
        # pins a 16 MiB staging row. The CPU plane stages nothing, so it
        # keeps raw-byte accounting.
        if self.hasher == "cpu":
            row_cost = 0
            charged = sum(len(p) for p in pieces)
        else:
            from torrent_tpu.ops.padding import padded_len_for

            row_cost = padded_len_for(bucket)
            charged = len(pieces) * row_cost
        try:
            await self._admit(ts, charged, wait)
        except SchedRejected as e:
            if ctx is not None:
                tracer().add_span(
                    ctx[0], "sched.shed", parent_id=ctx[1], t0=t_enq,
                    status="error", tenant=tenant, reason=e.reason,
                    queued_bytes=e.queued_bytes, limit_bytes=e.limit_bytes,
                )
            raise
        t_admitted = time.monotonic()
        lane = self._lane(algo, plen)
        q = lane.queues.get(tenant)
        if q is None:
            q = lane.queues[tenant] = deque()
            lane.rotation.append(tenant)
        now = time.monotonic()
        for i, p in enumerate(pieces):
            q.append(
                _Ticket(
                    sub, i, p, expected[i] if expected else None, tenant, now,
                    charged=row_cost or len(p),
                )
            )
        lane.pending_pieces += len(pieces)
        ts.queued_bytes += charged
        self._queued_bytes += charged
        lane.event.set()
        if ctx is not None:
            t_queued = time.monotonic()
            enq_id = tracer().add_span(
                ctx[0], "sched.enqueue", parent_id=ctx[1], t0=t_enq,
                t1=t_queued, tenant=tenant, algo=algo, mode=mode,
                pieces=len(pieces), charged_bytes=charged,
                lane=f"{algo}/{bucket}",
            )
            tracer().add_span(
                ctx[0], "sched.admission", parent_id=enq_id, t0=t_enq,
                t1=t_admitted, tenant=tenant, wait=wait,
            )
            # later stages (lane wait, launch, digest) hang off the
            # enqueue span — carried by the submission, not contextvars
            sub.trace = (ctx[0], enq_id)
        return sub.future

    async def enqueue_staged(
        self,
        tenant: str,
        slab: StagedSlab,
        rows: list[int],
        expected: list[bytes] | None = None,
        wait: bool = False,
    ) -> asyncio.Future:
        """Slot-carrying submission: queue the pre-staged ``rows`` of a
        :class:`StagedSlab` (from :meth:`checkout_staging`).

        Tickets carry :class:`SlotRow` payloads — zero-copy views into
        the slab — and each holds one slab reference that the demux
        releases on verdict or failure, so the slot returns to its pool
        exactly when the last co-batched ticket resolves. Admission
        charging, DRR fairness, shed, retry/bisection and the breaker's
        CPU fallback all behave exactly as for byte submissions (the
        CPU plane hashes the slab rows in place). On shed/validation
        failure the retained ticket refs are released here; the
        CALLER's own reference is untouched either way.
        """
        payloads = [SlotRow(slab, r) for r in rows]
        slab.retain(len(payloads))  # one ref per ticket, released at demux
        try:
            return await self.enqueue(
                tenant,
                payloads,
                expected=expected,
                algo=slab.algo,
                piece_length=slab.piece_length,
                wait=wait,
            )
        except BaseException:
            slab.release(len(payloads))
            raise

    async def submit(self, tenant: str, pieces, expected=None, algo="sha1",
                     piece_length=None, wait: bool = False):
        """``enqueue`` + await: returns digests (or ok-bytes) directly."""
        fut = await self.enqueue(tenant, pieces, expected, algo, piece_length, wait)
        return await fut

    async def _admit(self, ts: _Tenant, nbytes: int, wait: bool) -> None:
        cfg = self.config
        tenant_limit = ts.max_bytes if ts.max_bytes is not None else cfg.max_tenant_bytes

        def max_queue() -> int:
            # the autopilot's admission actuator scales the GLOBAL budget
            # only (per-tenant limits are policy, not control); at the 1.0
            # default this is exactly the static config. Re-read on every
            # evaluation: a submitter blocked under a shrunken budget must
            # observe the recovered factor when set_admission_factor wakes
            # it, not a bound baked in at entry.
            factor = self._admission_factor
            if factor < 1.0:
                return max(1, int(cfg.max_queue_bytes * factor))
            return cfg.max_queue_bytes

        def over() -> tuple[bool, int, int]:
            # The empty-queue escape exists ONLY for the blocking path: an
            # oversize submission that can never fit must be admitted once
            # the queue drains or wait=True livelocks forever. On the shed
            # path it would let one giant submission blow past both bounds
            # into an idle queue and then 429 everyone else while it drains.
            limit = max_queue()
            if self._queued_bytes + nbytes > limit and not (
                wait and self._queued_bytes == 0
            ):
                return True, self._queued_bytes, limit
            if ts.queued_bytes + nbytes > tenant_limit and not (
                wait and ts.queued_bytes == 0
            ):
                return True, ts.queued_bytes, tenant_limit
            return False, 0, 0

        while True:
            if self._closing:
                ts.shed += 1
                self._shed_total += 1
                raise SchedRejected("scheduler shutting down", ts.name)
            is_over, got, limit = over()
            if not is_over:
                return
            if not wait:
                ts.shed += 1
                self._shed_total += 1
                raise SchedRejected("queue full", ts.name, got, limit)
            # blocking backpressure: wait for the next byte release.
            # clear-then-recheck so a release between over() and wait()
            # can't be lost.
            self._space.clear()
            is_over, _, _ = over()
            if not is_over:
                return
            await self._space.wait()

    # --------------------------------------------------------- assembler

    async def _lane_loop(self, lane: _Lane) -> None:
        cfg = self.config
        while True:
            if lane.pending_pieces == 0:
                if self._closing:
                    return
                lane.event.clear()
                if lane.pending_pieces == 0 and not self._closing:
                    await lane.event.wait()
                continue
            # oldest queued item bounds the wait: flush at target fill
            # or when its deadline expires, whichever comes first (the
            # autopilot may have set a per-lane deadline override)
            flush_after = (
                lane.deadline if lane.deadline is not None else cfg.flush_deadline
            )
            deadline = lane.oldest_ts() + flush_after
            while lane.pending_pieces < lane.target and not self._closing:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                lane.event.clear()
                if lane.pending_pieces >= lane.target or self._closing:
                    break
                try:
                    await asyncio.wait_for(lane.event.wait(), remaining)
                except asyncio.TimeoutError:
                    break
            tickets = self._drr_take(lane)
            if not tickets:
                continue
            reason = (
                "full"
                if len(tickets) >= lane.target
                else ("shutdown" if self._closing else "deadline")
            )
            # pipelined launch: the semaphore bounds in-flight launches
            # (depth 2 = double-buffer) while this loop keeps assembling
            # the next batch during the device run — the host/device
            # overlap the old stream gate had
            await lane.sem.acquire()
            task = asyncio.ensure_future(self._launch(lane, tickets, reason))
            lane.inflight.add(task)
            task.add_done_callback(lambda t, lane=lane: self._launch_done(lane, t))

    def _launch_done(self, lane: _Lane, task: asyncio.Task) -> None:
        lane.inflight.discard(task)
        lane.sem.release()
        if not task.cancelled() and task.exception() is not None:
            # _launch resolves caller futures on every path, so an escape
            # here is a bug — log it rather than dropping it silently
            log.error("sched launch task error: %r", task.exception())

    def _drr_take(self, lane: _Lane) -> list[_Ticket]:
        """Deficit round-robin over queued bytes, up to the lane target."""
        cfg = self.config
        taken: list[_Ticket] = []
        target = lane.target
        while len(taken) < target:
            active = [n for n in lane.rotation if lane.queues.get(n)]
            if not active:
                break
            for name in active:
                q = lane.queues[name]
                t = self._tenants[name]
                t.deficit += max(1, int(cfg.drr_quantum * t.weight))
                while q and len(taken) < target and t.deficit >= q[0].nbytes:
                    tkt = q.popleft()
                    t.deficit -= tkt.nbytes
                    lane.pending_pieces -= 1
                    taken.append(tkt)
                if not q:
                    t.deficit = 0  # classic DRR: no credit hoarding
                if len(taken) >= target:
                    break
        # rotate so the same tenant doesn't always lead the next pass
        if lane.rotation:
            lane.rotation.append(lane.rotation.pop(0))
        return taken

    # ------------------------------------------------------------ launch

    def _build_plane(self, lane: _Lane):
        cfg = self.config
        if lane.backend == "cpu" and self.hasher != "cpu":
            # controller-steered degradation (steer_lane_backend): like
            # the breaker's CPU fallback, this bypasses plane_factory —
            # hashlib is the parity floor, not a wrappable device plane
            return _CpuPlane(lane.algo)
        # the lane's planned backend is authoritative (it already folded
        # in the staging-budget fallback), so pass it explicitly rather
        # than re-resolving env/auto at build time — a factory holding
        # its own 'pallas' pin (bridge --fault-plan + --sha256-backend)
        # must not override a budget-forced scan fallback, or the tile
        # floor blows the staging budget the fallback exists to enforce
        sha256_backend = lane.backend if lane.backend in ("pallas", "scan") else None
        if cfg.plane_factory is not None:
            if accepts_sha256_backend(cfg.plane_factory):
                return cfg.plane_factory(
                    lane.algo, lane.bucket, lane.target,
                    sha256_backend=sha256_backend,
                )
            return cfg.plane_factory(lane.algo, lane.bucket, lane.target)
        return build_builtin_plane(
            self.hasher, lane.algo, lane.bucket, lane.target,
            sha256_backend=sha256_backend,
        )

    def _run_plane(
        self, lane: _Lane, payloads: list[bytes], obs_note: dict | None = None
    ) -> list[bytes]:
        """Worker-thread body: build the plane on first use (JAX init and
        compiles run off the event loop) and execute the launch under a
        trace annotation so batches are attributable in the timeline.

        The lane breaker gates the primary plane: while it is open,
        launches degrade to the hashlib CPU plane (correct, slower) and
        only a half-open probe touches the primary again. Transient
        primary failures feed the breaker; deterministic payload errors
        do not (the device is answering — the payload is the problem).

        ``obs_note`` carries per-launch observability facts back to the
        dispatching coroutine (plane used, breaker-open transition) —
        the flight-recorder trigger and launch-span attrs live THERE so
        no obs lock is ever taken under breaker or counter locks.
        """
        if obs_note is None:
            obs_note = {}
        if not lane.breaker.acquire_primary():
            if lane.cpu_plane is None:  # benign to race: planes are stateless
                lane.cpu_plane = _CpuPlane(lane.algo)
            with self._counter_lock:  # worker threads across lanes race this
                self._counter_cells.write("fault_counters")
                self._cpu_fallback_launches += 1
            obs_note["plane"] = "cpu_fallback"
            return lane.cpu_plane.run(payloads)
        if lane.plane is None:
            # pipelined launches reach here from concurrent worker
            # threads; double-checked lock so the plane compiles once
            with lane.build_lock:
                if lane.plane is None:
                    try:
                        lane.plane = self._build_plane(lane)
                    except Exception as e:
                        # same classification as the launch path: a
                        # deterministic build error (factory misconfig)
                        # must not masquerade as device flakiness
                        if classify_error(e) == "transient":
                            if lane.breaker.record_failure():
                                obs_note["breaker_opened"] = True
                        else:
                            lane.breaker.release_probe()
                        raise
        # pad-row waste: rows this launch stages beyond the live batch
        # (tile bucketing on the pallas plane; zero on row-exact planes
        # and the hashlib degradation path, which stages nothing). The
        # built plane's own launch_geometry hook is authoritative — a
        # plane_factory plane (faults seam) may stage differently than
        # the lane plan assumed; one exposing no hook is taken as
        # row-exact (FaultyPlane's hook-less default agrees). Charged
        # per actual attempt (retries and bisection halves each
        # re-stage), under the counter lock: worker threads run this.
        hook = getattr(lane.plane, "launch_geometry", None)
        if hook is not None:
            pad = hook(len(payloads), lane.bucket)[0] - len(payloads)
            if pad:
                with self._counter_lock:
                    self._counter_cells.write("fault_counters")
                    lane.pad_rows_total += pad
        # zero-copy launch form: when every ticket is a SlotRow of ONE
        # pre-staged slab and the plane can consume it in place, skip
        # the stage copy entirely (mixed batches — several slabs, or
        # slab rows interleaved with byte payloads — take the copying
        # run path, which stages SlotRow views like any other payload)
        staged = _staged_batch(payloads)
        run_staged = (
            getattr(lane.plane, "run_staged", None) if staged else None
        )
        if run_staged is not None:
            obs_note["staged"] = True
        try:
            if self.hasher == "cpu":
                if run_staged is not None:
                    digests = run_staged(*staged)
                else:
                    digests = lane.plane.run(payloads)
            else:
                from torrent_tpu.obs.profiler import maybe_profile_batch

                with maybe_profile_batch(f"sched_{lane.algo}_launch_b{lane.bucket}"):
                    if run_staged is not None:
                        digests = run_staged(*staged)
                    else:
                        digests = lane.plane.run(payloads)
            # contract check BEFORE record_success: a plane persistently
            # returning the wrong count must feed the breaker (and trip
            # to the CPU plane) instead of resetting it every launch
            if len(digests) != len(payloads):
                raise RuntimeError(
                    f"plane returned {len(digests)} digests for {len(payloads)} pieces"
                )
        except Exception as e:
            if classify_error(e) == "transient":
                if lane.breaker.record_failure():
                    obs_note["breaker_opened"] = True
            else:
                lane.breaker.release_probe()
            raise
        lane.breaker.record_success()
        return digests

    @staticmethod
    def _traced_subs(tickets: list[_Ticket]) -> dict[int, tuple[_Submission, float]]:
        """Distinct traced submissions in a batch with their oldest
        ticket timestamp (one obs span per submission, not per ticket)."""
        out: dict[int, tuple[_Submission, float]] = {}
        for t in tickets:
            if t.sub.trace is None:
                continue
            prev = out.get(id(t.sub))
            if prev is None or t.ts < prev[1]:
                out[id(t.sub)] = (t.sub, t.ts)
        return out

    async def _launch(self, lane: _Lane, tickets: list[_Ticket], reason: str) -> None:
        n = len(tickets)
        fill = n / lane.target
        self._launches += 1
        self._fill_sum += fill
        self._flush_reasons[reason] += 1
        lane.launches += 1
        lane.fill_sum += fill
        lane_name = f"{lane.algo}/{lane.bucket}"
        t_take = time.monotonic()
        # one lock acquisition for the whole launch's queue waits
        histograms().get(*_H_QUEUE_WAIT, lane=lane_name).observe_batch(
            [t_take - t.ts for t in tickets]
        )
        for sub, ts0 in self._traced_subs(tickets).values():
            tracer().add_span(
                sub.trace[0], "sched.lane_wait", parent_id=sub.trace[1],
                t0=ts0, t1=t_take, lane=lane_name, flush=reason, rows=n,
            )
        await self._dispatch(lane, tickets, depth=0)

    async def _dispatch(self, lane: _Lane, tickets: list[_Ticket], depth: int) -> None:
        """Run one (sub-)batch with failure-domain isolation: retry a
        transient failure once, then bisect so a poisoned ticket fails
        alone while innocent co-batched tenants still get digests. Every
        relaunch re-selects the plane, so a breaker that trips mid-
        bisection routes the surviving halves through the CPU plane."""
        cfg = self.config
        payloads = [t.payload for t in tickets]
        lane_name = f"{lane.algo}/{lane.bucket}"
        attempts = 0
        while True:
            obs_note: dict = {}
            t0 = time.monotonic()
            try:
                # digest-count contract is checked inside _run_plane, so
                # a persistent violation feeds the breaker there
                digests = await asyncio.to_thread(
                    self._run_plane, lane, payloads, obs_note
                )
            except Exception as e:  # a poisoned launch must not wedge the lane
                t1 = time.monotonic()
                histograms().get(*_H_LAUNCH, lane=lane_name).observe(t1 - t0)
                self._launch_failures += 1
                kind = classify_error(e)
                log.warning(
                    "sched launch failed (%s/%d, %d pieces, depth %d, %s): %s",
                    lane.algo, lane.bucket, len(tickets), depth, kind, e,
                )
                self._obs_launch_spans(
                    tickets, lane_name, t0, t1, depth, attempts, obs_note,
                    status="error", error=e,
                )
                if obs_note.get("breaker_opened"):
                    # black box BEFORE the state evaporates: the dump
                    # carries the breaker snapshot plus the failing
                    # tickets' span trees
                    flight_recorder().trigger(
                        "breaker_open",
                        detail={"lane": lane_name, "kind": kind,
                                "error": str(e)},
                        trace_ids=self._trace_ids(tickets),
                        snapshots={"sched": self.metrics_snapshot()},
                    )
                if kind == "transient" and attempts < cfg.launch_retries:
                    attempts += 1
                    self._retries += 1
                    continue
                if len(tickets) > 1 and depth < cfg.bisect_depth:
                    self._bisections += 1
                    mid = len(tickets) // 2
                    await self._dispatch(lane, tickets[:mid], depth + 1)
                    await self._dispatch(lane, tickets[mid:], depth + 1)
                    return
                self._failed_pieces += len(tickets)
                err = SchedLaunchError(
                    f"hash launch failed ({kind}, {len(tickets)} pieces, "
                    f"{attempts} retries): {e}",
                    kind,
                    e,
                )
                self._demux(tickets, None, error=err)
                flight_recorder().trigger(
                    "retry_exhausted",
                    detail={"lane": lane_name, "kind": kind,
                            "pieces": len(tickets), "depth": depth,
                            "retries": attempts, "error": str(e)},
                    trace_ids=self._trace_ids(tickets),
                    snapshots={"sched": self.metrics_snapshot()},
                )
                return
            t1 = time.monotonic()
            histograms().get(*_H_LAUNCH, lane=lane_name).observe(t1 - t0)
            self._obs_launch_spans(
                tickets, lane_name, t0, t1, depth, attempts, obs_note,
                status="ok",
            )
            self._demux(tickets, digests)
            return

    @staticmethod
    def _trace_ids(tickets: list[_Ticket]) -> list[str]:
        out: list[str] = []
        for t in tickets:
            if t.sub.trace is not None and t.sub.trace[0] not in out:
                out.append(t.sub.trace[0])
        return out

    def _obs_launch_spans(
        self, tickets, lane_name, t0, t1, depth, attempt, note, status,
        error=None,
    ) -> None:
        """One sched.launch span per traced submission in the batch
        (retry attempts and bisection halves each record their own,
        distinguished by the attempt/depth attrs)."""
        subs = self._traced_subs(tickets)
        if not subs:
            return
        attrs = {"lane": lane_name, "rows": len(tickets), "depth": depth,
                 "attempt": attempt}
        if note.get("plane") == "cpu_fallback":
            attrs["plane"] = "cpu_fallback"
        if note.get("staged"):
            attrs["staged"] = True
        if note.get("breaker_opened"):
            attrs["breaker_opened"] = True
        if error is not None:
            attrs["error"] = str(error)
            attrs["kind"] = classify_error(error)
        for sub, _ts0 in subs.values():
            tracer().add_span(
                sub.trace[0], "sched.launch", parent_id=sub.trace[1],
                t0=t0, t1=t1, status=status, **attrs,
            )

    def _demux(self, tickets: list[_Ticket], digests, error=None) -> None:
        """Per-launch result demux back to the awaiting submissions,
        releasing queue bytes (and any blocked submitters) as it goes."""
        with pipeline_ledger().track(
            "verdict", sum(t.nbytes for t in tickets)
        ):
            self._demux_inner(tickets, digests, error)

    def _demux_inner(self, tickets: list[_Ticket], digests, error=None) -> None:
        t_now = time.monotonic()
        e2e_by_tenant: dict[str, list[float]] = {}
        done_subs: dict[int, _Submission] = {}
        # slot-carrying tickets: release one slab ref per ticket AFTER
        # delivery (batched per slab; the slot returns to its pool when
        # the last ref drops) — on the error path too, so a launch that
        # outlives retry/bisection can never leak a staging slot
        slab_refs: dict[int, tuple[StagedSlab, int]] = {}
        for i, tkt in enumerate(tickets):
            if type(tkt.payload) is SlotRow:
                slab = tkt.payload.slab
                prev = slab_refs.get(id(slab))
                slab_refs[id(slab)] = (slab, 1 if prev is None else prev[1] + 1)
        for i, tkt in enumerate(tickets):
            # the tenant may have been pruned while a zero-byte ticket was
            # in flight — global accounting and delivery must still happen
            t = self._tenants.get(tkt.tenant)
            if t is not None:
                t.queued_bytes -= tkt.charged
            self._queued_bytes -= tkt.charged
            e2e_by_tenant.setdefault(tkt.tenant, []).append(t_now - tkt.ts)
            if error is not None:
                if not tkt.sub.future.done():
                    tkt.sub.future.set_exception(error)
                if tkt.sub.trace is not None:
                    done_subs.setdefault(id(tkt.sub), tkt.sub)
                continue
            if t is not None:
                t.served_bytes += tkt.nbytes
                t.served_pieces += 1
            d = digests[i]
            if tkt.sub.mode == "verify":
                tkt.sub.deliver(tkt.idx, 1 if d == tkt.expected else 0)
            else:
                tkt.sub.deliver(tkt.idx, d)
            if tkt.sub.trace is not None and tkt.sub.remaining == 0:
                done_subs.setdefault(id(tkt.sub), tkt.sub)
        for slab, n in slab_refs.values():
            slab.release(n)
        for tenant, vals in e2e_by_tenant.items():
            histograms().get(*_H_E2E, tenant=tenant).observe_batch(vals)
        for sub in done_subs.values():
            if sub.traced_done:
                continue
            sub.traced_done = True
            status = "error" if error is not None else "ok"
            attrs: dict = {"mode": sub.mode, "pieces": len(sub.results)}
            if error is not None:
                attrs["error"] = str(error)
            did = tracer().add_span(
                sub.trace[0], "sched.digest", parent_id=sub.trace[1],
                t0=t_now, status=status, **attrs,
            )
            if sub.mode == "verify":
                valid = sum(1 for r in sub.results if r) if error is None else 0
                tracer().add_span(
                    sub.trace[0], "sched.verdict", parent_id=did, t0=t_now,
                    status=status, valid=valid, pieces=len(sub.results),
                )
        self._space.set()  # wake admission waiters

    # ----------------------------------------------------------- metrics

    def _staging_snapshot(self) -> dict:
        # worker threads create pools under _ingest_lock; snapshot the
        # dict under it too so iteration can't race an insert
        with self._ingest_lock:
            pools = list(self._ingest_pools.values())
        # per-pool counters move under each pool's own lock (worker
        # threads mid-checkout); stats() reads them there
        stats = [p.stats() for p in pools]
        return {
            "pools": len(pools),
            "outstanding": sum(s[0] for s in stats),
            "checkouts": sum(s[1] for s in stats),
        }

    def metrics_snapshot(self) -> dict:
        """Counters for utils/metrics.py's Prometheus rendering."""
        pending = sum(l.pending_pieces for l in self._lanes.values())
        # _cpu_fallback_launches and the per-lane pad counters are
        # bumped from worker threads under _counter_lock; snapshot them
        # under it too (the other fault counters are loop-confined but
        # ride along in the same brief leaf-lock scope)
        with self._counter_lock:
            self._counter_cells.read("fault_counters")
            cpu_fallback_launches = self._cpu_fallback_launches
            pad_rows = {
                key: lane.pad_rows_total for key, lane in self._lanes.items()
            }
        return {
            "queue_pieces": pending,
            "queue_bytes": self._queued_bytes,
            # autopilot admission actuator (1.0 = the static config)
            "admission_factor": self._admission_factor,
            "lanes": len(self._lanes),
            "launches": self._launches,
            "fill_sum": self._fill_sum,
            "mean_fill": (self._fill_sum / self._launches) if self._launches else 0.0,
            "flush_reasons": dict(self._flush_reasons),
            "shed_total": self._shed_total,
            "launch_failures": self._launch_failures,
            "retries": self._retries,
            "bisections": self._bisections,
            "cpu_fallback_launches": cpu_fallback_launches,
            "failed_pieces": self._failed_pieces,
            "breakers": {
                f"{algo}/{bucket}": lane.breaker.snapshot()
                for (algo, bucket), lane in self._lanes.items()
            },
            # per-lane launch-fill and pad-row waste (pallas tile
            # bucketing observability: a healthy tile-snapped lane shows
            # mean_fill near 1.0 and pad_rows_total near 0 under load)
            "lane_stats": {
                f"{algo}/{bucket}": {
                    "backend": lane.backend,
                    "target": lane.target,
                    "deadline": (
                        lane.deadline
                        if lane.deadline is not None
                        else self.config.flush_deadline
                    ),
                    "launches": lane.launches,
                    "mean_fill": (
                        lane.fill_sum / lane.launches if lane.launches else 0.0
                    ),
                    "pad_rows_total": pad_rows.get((algo, bucket), 0),
                }
                for (algo, bucket), lane in self._lanes.items()
            },
            # zero-copy ingest pools: outstanding must return to 0 when
            # no read/launch is in flight (slab-leak test + ops gauge)
            "staging": self._staging_snapshot(),
            "evicted": dict(self._evicted),
            "tenants": {
                name: {
                    "queued_bytes": t.queued_bytes,
                    "served_bytes": t.served_bytes,
                    "served_pieces": t.served_pieces,
                    "shed": t.shed,
                    "weight": t.weight,
                }
                for name, t in self._tenants.items()
            },
        }
