"""Deterministic fault injection for the hash plane.

The fault-tolerance layer in ``scheduler.py`` (launch retry, bisection,
per-lane circuit breaker, CPU degradation) is only trustworthy if every
behavior has a deterministic CPU-only test — accelerator faults can't
be provoked on demand, so they are *injected* instead. A
:class:`FaultPlan` describes what goes wrong and when:

* ``fail_first`` / ``fail_launches`` — the Nth plane launches raise a
  *transient* :class:`DeviceFaultError` (the XLA-hiccup model; feeds
  the breaker, worth a retry).
* ``payload_prefix`` — any launch whose batch contains a payload with
  this byte prefix raises a *deterministic*
  :class:`PoisonedPayloadError` (the poisoned-ticket model; skips
  retries, drives bisection until the ticket fails alone).
* ``latency_s`` — every launch sleeps first (latency-spike model; used
  to prove deadlines/backpressure survive a slow plane). The sleep is
  accounted to the pipeline ledger's ``h2d`` stage — it models a slow
  host→device interconnect, which makes bottleneck attribution
  (``obs/attrib.py``, ``doctor --bottleneck``) deterministically
  testable on CPU-only hosts.
* ``read_latency_s`` — same mechanism, accounted to the ledger's
  ``read`` stage: the slow-storage model. This is how controller tests
  (``sched/control.py``) deterministically make ``read`` the limiting
  stage — the regime PR 8 predicted once H2D overlaps.
* ``dead_after`` — every launch past the Nth raises (permanent device
  loss; the breaker must pin the lane on the CPU plane).

Plans wrap whatever plane the scheduler would otherwise build, through
the existing ``SchedulerConfig.plane_factory`` seam::

    plan = FaultPlan.parse("fail_first=3;latency_ms=5")
    cfg = SchedulerConfig(plane_factory=plan.plane_factory(hasher="cpu"))

``bridge --fault-plan SPEC`` (dev/test mode only) and ``doctor
--faults`` wire the same specs up for manual chaos runs.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field

from torrent_tpu.analysis.sanitizer import named_lock

__all__ = [
    "DeviceFaultError",
    "FaultPlan",
    "FaultyPlane",
    "PoisonedPayloadError",
]


class DeviceFaultError(Exception):
    """Injected transient device failure (XLA/launch hiccup model)."""

    sched_error_class = "transient"


class PoisonedPayloadError(Exception):
    """Injected deterministic failure tied to a payload (poisoned
    ticket model) — retrying the same batch can never succeed."""

    sched_error_class = "deterministic"


@dataclass(frozen=True)
class FaultPlan:
    """Declarative description of injected hash-plane faults.

    Launch ordinals are 1-based and counted per wrapped plane (= per
    scheduler lane), under a lock — pipelined launches run in worker
    threads, and the count must stay deterministic.
    """

    # transient: launches 1..fail_first raise DeviceFaultError
    fail_first: int = 0
    # transient: these exact launch ordinals raise DeviceFaultError
    fail_launches: frozenset[int] = field(default_factory=frozenset)
    # deterministic: a batch containing a payload with this prefix
    # raises PoisonedPayloadError
    payload_prefix: bytes | None = None
    # every launch sleeps this long before running (latency spike,
    # charged to the ledger's h2d stage — slow interconnect model)
    latency_s: float = 0.0
    # every launch sleeps this long charged to the ledger's read stage
    # (slow-storage model; makes `read` the limiting stage on demand)
    read_latency_s: float = 0.0
    # permanent device loss: every launch past this ordinal raises
    dead_after: int | None = None
    # fabric-level lying worker (doctor --byzantine): the process
    # publishes forged verify receipts — every piece claimed ok with a
    # consistent Merkle root. Consumed by the CLI's fabric-verify path
    # (FabricConfig.forge_receipts), NOT by FaultyPlane: the lie
    # happens at the verdict layer, above the hash plane
    forge_receipts: bool = False

    @classmethod
    def parse(cls, spec: str) -> "FaultPlan":
        """Build a plan from the CLI spec grammar: ``;``-separated
        ``key=value`` pairs, e.g. ``"fail_first=3;latency_ms=5"`` or
        ``"payload=deadbeef;fail_launches=2,5"``."""
        kw: dict = {}
        for part in spec.split(";"):
            part = part.strip()
            if not part:
                continue
            if "=" not in part:
                raise ValueError(f"fault-plan term {part!r} is not key=value")
            key, _, value = part.partition("=")
            key, value = key.strip(), value.strip()
            if key not in (
                "fail_first", "fail_launches", "payload", "latency_ms",
                "read_latency_ms", "dead_after", "forge_receipts",
            ):
                raise ValueError(f"unknown fault-plan key {key!r}")
            try:
                if key == "fail_first":
                    kw["fail_first"] = int(value)
                elif key == "fail_launches":
                    kw["fail_launches"] = frozenset(
                        int(v) for v in value.split(",") if v
                    )
                elif key == "payload":
                    kw["payload_prefix"] = bytes.fromhex(value)
                elif key == "latency_ms":
                    kw["latency_s"] = float(value) / 1e3
                elif key == "read_latency_ms":
                    kw["read_latency_s"] = float(value) / 1e3
                elif key == "dead_after":
                    kw["dead_after"] = int(value)
                elif key == "forge_receipts":
                    kw["forge_receipts"] = bool(int(value))
            except Exception as e:  # int()/fromhex() failures with context
                raise ValueError(f"bad fault-plan value {part!r}: {e}") from e
        plan = cls(**kw)
        if plan.fail_first < 0 or (plan.dead_after is not None and plan.dead_after < 0):
            raise ValueError("fault-plan launch ordinals must be >= 0")
        if plan.latency_s < 0 or plan.read_latency_s < 0:
            raise ValueError("fault-plan latency must be >= 0")
        if plan.payload_prefix is not None and not plan.payload_prefix:
            # b"" startswith-matches every payload: a typo'd "payload="
            # must not silently become fail-every-launch
            raise ValueError("fault-plan payload prefix must be non-empty")
        return plan

    def plane_factory(
        self, hasher: str = "tpu", base_factory=None, sha256_backend: str | None = None
    ):
        """A ``SchedulerConfig.plane_factory`` injecting this plan
        around the planes the scheduler would otherwise build (or
        around ``base_factory``'s planes when given). ``sha256_backend``
        pins the v2 plane ('pallas'/'scan') the same way the scheduler's
        own builder does — but the lane's resolved backend, when the
        scheduler passes one at build time, wins over the pin: the lane
        plan folds in the staging-budget scan fallback, and a pinned
        'pallas' must not resurrect a tile floor the budget can't hold."""

        pin = sha256_backend

        def factory(
            algo: str, bucket: int, batch: int, sha256_backend: str | None = None
        ):
            backend = sha256_backend if sha256_backend is not None else pin
            from torrent_tpu.sched.scheduler import (
                accepts_sha256_backend,
                build_builtin_plane,
            )

            if base_factory is not None:
                # forward the resolved backend when the base factory can
                # take it — a nested builder pinning 'pallas' on its own
                # would bypass the budget fallback just like we would
                if accepts_sha256_backend(base_factory):
                    inner = base_factory(algo, bucket, batch, sha256_backend=backend)
                else:
                    inner = base_factory(algo, bucket, batch)
            else:
                inner = build_builtin_plane(
                    hasher, algo, bucket, batch, sha256_backend=backend
                )
            return FaultyPlane(self, inner)

        return factory


class FaultyPlane:
    """Plane wrapper applying a :class:`FaultPlan` to each launch."""

    def __init__(self, plan: FaultPlan, inner):
        self.plan = plan
        self.inner = inner
        self.launches = 0
        self._lock = named_lock("sched.faulty_plane._lock")

    def launch_geometry(self, n_rows: int, bucket: int) -> tuple[int, int]:
        """Faults change nothing about staging: delegate to the wrapped
        plane's geometry (row-exact if it exposes none)."""
        hook = getattr(self.inner, "launch_geometry", None)
        if hook is None:
            return n_rows, 0
        return hook(n_rows, bucket)

    def _apply_faults(self, payloads) -> None:
        """Count the launch and raise per the plan. ``payloads`` may be
        bytes or the scheduler's zero-copy ``SlotRow`` views — both
        support ``len`` and the ``startswith`` prefix probe, so fault
        semantics are identical for byte and slot-carrying submissions."""
        plan = self.plan
        with self._lock:
            self.launches += 1
            n = self.launches
        if plan.read_latency_s:
            from torrent_tpu.obs.ledger import pipeline_ledger

            # slow-storage model: the sleep is charged to the ledger's
            # read stage, so `read` becomes the limiting stage on demand
            # (controller tests; the sleep runs outside every obs lock)
            with pipeline_ledger().track(
                "read", sum(len(p) for p in payloads)
            ):
                time.sleep(plan.read_latency_s)
        if plan.latency_s:
            from torrent_tpu.obs.ledger import pipeline_ledger

            # the injected latency models a slow host→device transfer:
            # account it to the ledger's h2d stage so the bottleneck
            # attributor can be exercised deterministically without a
            # device (the sleep runs outside every obs lock)
            with pipeline_ledger().track(
                "h2d", sum(len(p) for p in payloads)
            ):
                time.sleep(plan.latency_s)
        if plan.payload_prefix is not None and any(
            p.startswith(plan.payload_prefix) for p in payloads
        ):
            raise PoisonedPayloadError(
                f"injected poisoned payload (prefix {plan.payload_prefix.hex()}, "
                f"launch {n})"
            )
        if (
            n <= plan.fail_first
            or n in plan.fail_launches
            or (plan.dead_after is not None and n > plan.dead_after)
        ):
            raise DeviceFaultError(f"injected device fault (launch {n})")

    def run(self, payloads: list[bytes]) -> list[bytes]:
        self._apply_faults(payloads)
        return self.inner.run(payloads)

    def run_staged(self, slab, rows: list[int]) -> list[bytes]:
        """Zero-copy launch form: same fault plan, applied to the slab's
        ticket rows, then delegated to the wrapped plane's staged path
        (or its copy path when it has none)."""
        from torrent_tpu.sched.scheduler import SlotRow

        slot_rows = [SlotRow(slab, r) for r in rows]
        self._apply_faults(slot_rows)
        inner_staged = getattr(self.inner, "run_staged", None)
        if inner_staged is not None:
            return inner_staged(slab, rows)
        return self.inner.run(slot_rows)
