"""torrent_tpu.sched — the continuous-batching hash-plane scheduler.

All hash-plane dispatch flows through one :class:`HashPlaneScheduler`
per process: the bridge's unary and streaming routes, the
``parallel/verify.py`` and ``parallel/bulk.py`` scheduler sessions, and
session self-heal rechecks submit into a shared multi-tenant queue with
admission control, deadline-aware batch assembly, deficit-round-robin
fairness, and per-launch result demux. See scheduler.py for the design.
"""

from torrent_tpu.sched.control import ControlConfig, SchedulerAutopilot
from torrent_tpu.sched.faults import (
    DeviceFaultError,
    FaultPlan,
    PoisonedPayloadError,
)
from torrent_tpu.sched.scheduler import (
    HashPlaneScheduler,
    SchedLaunchError,
    SchedRejected,
    SchedulerConfig,
    classify_error,
    resolve_sha256_backend,
)

__all__ = [
    "ControlConfig",
    "DeviceFaultError",
    "FaultPlan",
    "HashPlaneScheduler",
    "PoisonedPayloadError",
    "SchedLaunchError",
    "SchedRejected",
    "SchedulerConfig",
    "SchedulerAutopilot",
    "classify_error",
    "resolve_sha256_backend",
]
