"""Swarm wire-plane observability: bounded per-peer telemetry.

The obs plane can name the limiting stage, process, and fleet-wide
budget burn for the verify pipeline — but the live swarm it was all
built to serve was a black box: ``session/torrent.py`` runs a
rarest-first picker, choke rounds, endgame, and per-peer pipelining,
yet not one byte of wire traffic reached the ledger, tracer, timeline,
or SLO engine. This module is the missing tier:

* :class:`SwarmTelemetry` — a bounded per-peer registry fed by the
  session layer: per-message-type byte/count accounting
  (``Torrent._handle_message``), choke/interest state transitions WITH
  cumulative durations (the choke timeline), request-pipeline depth,
  block round-trip log2 histograms (the ``obs/hist`` bucket bounds,
  mergeable like every other family), snub / endgame-cancel / reject
  counters, and connection lifecycle spans through the tracer. One
  leaf :func:`named_lock`; per-peer records are bounded at
  :data:`MAX_TRACKED_PEERS` live entries (excess peers share one
  ``overflow`` record) and process totals stay cumulative forever, so
  the SLO window deltas never see a counter drop when a peer leaves.
* :func:`build_swarm_snapshot` — the PURE rollup (analysis determinism
  pass scope, like the digest builders): top-:data:`TOP_PEERS` peers by
  transferred bytes with an ``overflow`` fold of the rest, per-peer
  RTT p50/p99 from the bucket counts, choke-timeline seconds, and the
  process totals. Served as ``GET /v1/swarm`` (bridge AND session
  MetricsServer), rendered as ``torrent_tpu_swarm_*`` / bounded
  ``torrent_tpu_peer_*`` Prometheus families, and drawn by
  ``torrent-tpu top --swarm``.
* **Flight-recorder triggers**, exactly once per transition (the
  breaker-open discipline): ``snub_storm`` (half the swarm — at least
  :data:`SNUB_STORM_MIN` peers — simultaneously snubbed),
  ``all_peers_choked`` (every connected peer choking us while we're
  interested), and ``announce_failure_streak``
  (:data:`ANNOUNCE_STREAK` consecutive announce failures). Each
  re-arms only after the condition clears.

The registry is lock-leaf disciplined: the tracer, the histogram
registry, and the flight recorder are only ever called AFTER the
telemetry lock is released. Block RTTs additionally feed the shared
log2 family ``torrent_tpu_swarm_block_rtt_seconds`` so SLO latency
objectives (``p99_ms=…:block_rtt``) cover the swarm tier.
"""

from __future__ import annotations

import time
from bisect import bisect_left

from torrent_tpu.analysis.sanitizer import guard_attrs, named_lock
from torrent_tpu.obs.hist import BUCKET_BOUNDS

__all__ = [
    "ANNOUNCE_STREAK",
    "MAX_TRACKED_PEERS",
    "MSG_KINDS",
    "SNUB_STORM_MIN",
    "TOP_PEERS",
    "SwarmTelemetry",
    "build_swarm_snapshot",
    "swarm_telemetry",
]

SWARM_VERSION = 1

# live per-peer records; further peers share one "overflow" record so
# a 10k-peer swarm can't grow the registry (process totals still count
# every byte)
MAX_TRACKED_PEERS = 64
# peers named individually in a snapshot / /metrics scrape; the rest
# fold into the snapshot's own "overflow" aggregate
TOP_PEERS = 8
# snub-storm floor: the trigger needs at least this many peers snubbed
# at once (AND at least half the connected swarm) — a lone flaky peer
# is normal BitTorrent weather, not a storm
SNUB_STORM_MIN = 2
# consecutive announce failures before the flight recorder fires (the
# swarm is coasting on cached peers; operators should know now, not
# when the peer list drains). Streaks are per announcing torrent
# (origin), bounded at MAX_ANNOUNCE_ORIGINS tracked origins.
ANNOUNCE_STREAK = 3
MAX_ANNOUNCE_ORIGINS = 256

# the shared log2 family block RTTs observe into (SLO family key:
# "block_rtt" — see obs/timeline.SAMPLE_HIST_FAMILIES)
BLOCK_RTT_FAMILY = "torrent_tpu_swarm_block_rtt_seconds"

# bounded wire-message kinds (protocol.py class names); anything else —
# a future message, a subclass — folds into "other" so per-kind series
# cardinality is fixed
MSG_KINDS = frozenset(
    {
        "KeepAlive", "Choke", "Unchoke", "Interested", "NotInterested",
        "Have", "BitfieldMsg", "Request", "Piece", "Cancel", "SuggestPiece",
        "HaveAll", "HaveNone", "RejectRequest", "AllowedFast", "HashRequest",
        "Hashes", "HashReject", "Extended",
    }
)

_OVERFLOW_KEY = "overflow"

# the four wire-state flags whose transitions the choke timeline tracks
_FLAGS = ("am_choking", "am_interested", "peer_choking", "peer_interested")
# spec-default positions (BEP 3): both sides start choked, uninterested
_FLAG_DEFAULTS = {
    "am_choking": True,
    "am_interested": False,
    "peer_choking": True,
    "peer_interested": False,
}


class _PeerTel:
    """One live peer's counters. Mutated only under the registry lock."""

    __slots__ = (
        "key", "inbound", "connected_t", "trace_id", "bytes_down", "bytes_up",
        "blocks", "msgs", "flags", "flag_since", "flag_true_s", "transitions",
        "depth", "depth_max", "rtt_counts", "rtt_count", "rtt_sum", "snubs",
        "snubbed", "rejects", "endgame_cancels", "corrupt",
    )

    def __init__(self, key: str, inbound: bool, now: float, trace_id: str | None):
        self.key = key
        self.inbound = inbound
        self.connected_t = now
        self.trace_id = trace_id
        self.bytes_down = 0
        self.bytes_up = 0
        self.blocks = 0
        # kind -> [count, bytes]
        self.msgs: dict[str, list] = {}
        self.flags = dict(_FLAG_DEFAULTS)
        # per-flag: when the CURRENT value was entered / cumulative
        # seconds spent with the flag True (closed intervals only; the
        # snapshot extends the open interval to its own instant)
        self.flag_since = {f: now for f in _FLAGS}
        self.flag_true_s = {f: 0.0 for f in _FLAGS}
        self.transitions = 0
        self.depth = 0
        self.depth_max = 0
        self.rtt_counts = [0] * (len(BUCKET_BOUNDS) + 1)
        self.rtt_count = 0
        self.rtt_sum = 0.0
        self.snubs = 0
        self.snubbed = False
        self.rejects = 0
        self.endgame_cancels = 0
        self.corrupt = 0

    def raw(self, now: float) -> dict:
        """Scalar-only copy for the pure snapshot builder (durations
        finalized to ``now`` so the builder itself never reads a clock)."""
        true_s = {}
        for f in _FLAGS:
            open_s = max(0.0, now - self.flag_since[f]) if self.flags[f] else 0.0
            true_s[f] = self.flag_true_s[f] + open_s
        return {
            "key": self.key,
            "inbound": self.inbound,
            "connected_s": max(0.0, now - self.connected_t),
            "bytes_down": self.bytes_down,
            "bytes_up": self.bytes_up,
            "blocks": self.blocks,
            "msgs": {k: [v[0], v[1]] for k, v in self.msgs.items()},
            "state": dict(self.flags),
            "flag_true_s": true_s,
            "transitions": self.transitions,
            "depth": self.depth,
            "depth_max": self.depth_max,
            "rtt_counts": list(self.rtt_counts),
            "rtt_count": self.rtt_count,
            "rtt_sum": self.rtt_sum,
            "snubs": self.snubs,
            "snubbed": self.snubbed,
            "rejects": self.rejects,
            "endgame_cancels": self.endgame_cancels,
            "corrupt": self.corrupt,
        }


# --------------------------------------------------------------- builders
# (analysis determinism pass scope, like the fleet digest builders: no
# wall clock, no randomness, sorted iteration — every instant below was
# resolved by the registry before the builder runs)


def _as_float(value, default: float = 0.0) -> float:
    """Defensive finite float: hostile raw fields (None, strings, NaN,
    ±Inf — NaN is truthy, so ``value or 0`` does NOT save you) read as
    ``default``. The snapshot must json-serialize with allow_nan=False."""
    try:
        f = float(value)
    except (TypeError, ValueError):
        return default
    return f if f == f and abs(f) != float("inf") else default


def _as_int(value, default: int = 0) -> int:
    return int(_as_float(value, float(default)))


# determinism-scope
def _rtt_summary(counts: list, count, total) -> dict:
    """p50/p99 upper-bound estimates from log2 bucket counts (pure).
    The overflow bucket has no finite upper bound: a quantile landing
    there reports ``None`` plus an ``overflow`` flag — same contract as
    the SLO evaluator's p99 (json must never carry Infinity)."""
    count = _as_int(count)
    total = _as_float(total)
    out: dict = {"count": count, "mean_s": round(total / count, 6) if count > 0 else None}
    counts = [_as_int(c) for c in counts] if isinstance(counts, list) else []
    for name, q in (("p50_s", 0.50), ("p99_s", 0.99)):
        est = None
        overflow = False
        if count > 0:
            want = q * count
            cum = 0
            for idx, c in enumerate(counts):
                cum += c
                if cum >= want:
                    if idx < len(BUCKET_BOUNDS):
                        est = round(BUCKET_BOUNDS[idx], 6)
                    else:
                        overflow = True
                    break
        out[name] = est
        if name == "p99_s":
            out["p99_overflow"] = overflow
    return out


# determinism-scope
def _peer_entry(raw: dict) -> dict:
    """One snapshot peer entry from a finalized raw record (pure,
    total: every field goes through the defensive scalar parsers)."""
    msgs = raw.get("msgs")
    msgs = msgs if isinstance(msgs, dict) else {}
    true_s = raw.get("flag_true_s")
    true_s = true_s if isinstance(true_s, dict) else {}
    state = raw.get("state")
    state = state if isinstance(state, dict) else {}
    return {
        "inbound": bool(raw.get("inbound")),
        "connected_s": round(_as_float(raw.get("connected_s")), 3),
        "bytes_down": _as_int(raw.get("bytes_down")),
        "bytes_up": _as_int(raw.get("bytes_up")),
        "blocks": _as_int(raw.get("blocks")),
        "msgs": {
            str(k): {
                "count": _as_int(msgs[k][0]),
                "bytes": _as_int(msgs[k][1]),
            }
            for k in sorted(msgs, key=str)
            if isinstance(msgs[k], (list, tuple)) and len(msgs[k]) >= 2
        },
        "state": {f: bool(state.get(f)) for f in _FLAGS},
        # the choke timeline: cumulative seconds each flag spent True
        # plus the transition count — "choked 41 of 42 connected
        # seconds" is the line a stalled download needs
        "choke_timeline": {
            "transitions": _as_int(raw.get("transitions")),
            **{f: round(_as_float(true_s.get(f)), 3) for f in _FLAGS},
        },
        "pipeline": {
            "depth": _as_int(raw.get("depth")),
            "depth_max": _as_int(raw.get("depth_max")),
        },
        "block_rtt": _rtt_summary(
            raw.get("rtt_counts"), raw.get("rtt_count"), raw.get("rtt_sum")
        ),
        "snubs": _as_int(raw.get("snubs")),
        "snubbed": bool(raw.get("snubbed")),
        "rejects": _as_int(raw.get("rejects")),
        "endgame_cancels": _as_int(raw.get("endgame_cancels")),
        "corrupt": _as_int(raw.get("corrupt")),
    }


# determinism-scope
def _fold_entries(raws: list) -> dict:
    """Aggregate raw peer records into one overflow entry (pure):
    counters sum, RTT buckets merge elementwise. A raw carrying its own
    ``peers`` count (the registry's shared overflow record speaks for
    many connections) contributes that count; ordinary records count 1."""
    folded = {
        "peers": sum(
            _as_int(raw.get("peers", 1), 1) if isinstance(raw, dict) else 1
            for raw in raws
        ),
        "bytes_down": 0,
        "bytes_up": 0,
        "blocks": 0,
        "snubs": 0,
        "snubbed": 0,
        "rejects": 0,
        "endgame_cancels": 0,
        "transitions": 0,
        "depth": 0,
    }
    counts = [0] * (len(BUCKET_BOUNDS) + 1)
    count = 0
    total = 0.0
    for raw in raws:
        folded["bytes_down"] += _as_int(raw.get("bytes_down"))
        folded["bytes_up"] += _as_int(raw.get("bytes_up"))
        folded["blocks"] += _as_int(raw.get("blocks"))
        folded["snubs"] += _as_int(raw.get("snubs"))
        folded["snubbed"] += 1 if raw.get("snubbed") else 0
        folded["rejects"] += _as_int(raw.get("rejects"))
        folded["endgame_cancels"] += _as_int(raw.get("endgame_cancels"))
        folded["transitions"] += _as_int(raw.get("transitions"))
        folded["depth"] += _as_int(raw.get("depth"))
        rc = raw.get("rtt_counts")
        rc = rc if isinstance(rc, list) else []
        for i in range(min(len(counts), len(rc))):
            counts[i] += _as_int(rc[i])
        count += _as_int(raw.get("rtt_count"))
        total += _as_float(raw.get("rtt_sum"))
    folded["block_rtt"] = _rtt_summary(counts, count, total)
    return folded


# determinism-scope
def build_swarm_snapshot(peer_raws: dict, totals: dict, top_k: int = TOP_PEERS) -> dict:
    """The pure swarm rollup over finalized raw records.

    ``peer_raws``: key -> :meth:`_PeerTel.raw` dict (durations already
    finalized). ``totals``: the registry's cumulative process counters.
    Top-``top_k`` peers by transferred bytes (total order: bytes desc,
    then key) are named; the rest fold into ``overflow``. Total and
    defensive: hostile/partial raw dicts produce a well-formed snapshot,
    never a crash — the hypothesis property in tests/test_fuzz.py."""
    src = peer_raws if isinstance(peer_raws, dict) else {}
    raws = {
        str(k): src[k]
        for k in sorted(src, key=str)
        if isinstance(src[k], dict)
    }
    # the registry's shared overflow record is NEVER a named peer — it
    # aggregates many connections, so ranking it into the top-K would
    # emit the peer="overflow" series twice on /metrics (an invalid
    # exposition); it always joins the snapshot's own fold instead
    shared_overflow = raws.pop(_OVERFLOW_KEY, None)
    order = sorted(
        raws,
        key=lambda k: (
            -(_as_int(raws[k].get("bytes_down")) + _as_int(raws[k].get("bytes_up"))),
            k,
        ),
    )
    top_k = max(0, _as_int(top_k))
    named = order[:top_k]
    folded = order[top_k:]
    fold_raws = [raws[k] for k in folded]
    if shared_overflow is not None:
        fold_raws.append(shared_overflow)
    totals = totals if isinstance(totals, dict) else {}
    def _state(k) -> dict:
        s = raws[k].get("state")
        return s if isinstance(s, dict) else {}

    counts = {
        # the shared overflow record contributes its own live-peer count
        # (per-peer flags over an aggregate are meaningless, so the
        # flag-derived counts cover individually-tracked peers only)
        "connected": len(raws) + (
            _as_int(shared_overflow.get("peers"))
            if shared_overflow is not None
            else 0
        ),
        "snubbed": sum(1 for k in order if raws[k].get("snubbed")),
        "choking_us": sum(1 for k in order if _state(k).get("peer_choking")),
        "interested_in": sum(1 for k in order if _state(k).get("am_interested")),
        "unchoked_by_us": sum(
            1 for k in order if not _state(k).get("am_choking", True)
        ),
    }
    return {
        "v": SWARM_VERSION,
        "counts": counts,
        "peers": {k: _peer_entry(raws[k]) for k in named},
        "overflow": _fold_entries(fold_raws) if fold_raws else None,
        # totals are registry-owned int counters in practice, but the
        # builder is total over hostile dicts: every value normalizes
        # through the defensive int parser (the snapshot must
        # json-serialize with allow_nan=False)
        "totals": {str(k): _as_int(totals[k]) for k in sorted(totals, key=str)},
    }


# --------------------------------------------------------------- registry


class SwarmTelemetry:
    """Bounded per-peer wire telemetry. One global instance
    (:func:`swarm_telemetry`) serves every torrent of the process;
    tests may construct private ones."""

    def __init__(self, max_peers: int = MAX_TRACKED_PEERS):
        self._lock = named_lock("obs.swarm._lock")
        # dynamic lockset checking: the peer table + totals are one cell
        # guarded by _lock (the session loop writes, metrics scrapers
        # and the timeline sampler thread read)
        self._cells = guard_attrs("obs.swarm", "peers")
        self._max_peers = max(1, int(max_peers))
        self._peers: dict[str, _PeerTel] = {}
        self._totals: dict[str, int] = {
            "connections": 0,
            "bytes_down": 0,
            "bytes_up": 0,
            "blocks": 0,
            "snubs": 0,
            "rejects": 0,
            "endgame_cancels": 0,
            "corrupt": 0,
            "announce_ok": 0,
            "announce_failed": 0,
        }
        self._msg_totals: dict[str, list] = {}  # kind -> [count, bytes]
        # live connections sharing the overflow record (its per-peer
        # record speaks for this many peers; when the last one leaves
        # the record is removed so the connected gauge never inflates)
        self._overflow_live = 0
        # exactly-once trigger latches (re-arm when the condition clears)
        self._storm_active = False
        self._all_choked_active = False
        # announce failure streaks are PER ORIGIN (one per torrent's
        # announce loop): a healthy torrent's successes must not mask a
        # dead tracker on another torrent. Bounded: past the cap, new
        # origins share one fold key — the trigger still fires, only
        # per-origin precision degrades.
        self._announce_streaks: dict[str, int] = {}
        self._trigger_counts: dict[str, int] = {}

    # ---------------------------------------------------------- lifecycle

    def peer_connected(
        self, key: str, inbound: bool = False, trace_id: str | None = None
    ) -> None:
        now = time.monotonic()
        with self._lock:
            self._cells.write("peers")
            self._totals["connections"] += 1
            if key not in self._peers and len(self._peers) >= self._max_peers:
                self._overflow_live += 1
                if _OVERFLOW_KEY not in self._peers:
                    self._peers[_OVERFLOW_KEY] = _PeerTel(
                        _OVERFLOW_KEY, inbound, now, None
                    )
                return  # folded: no per-peer record, no lifecycle span
            self._peers[key] = _PeerTel(key, inbound, now, trace_id)
        if trace_id is not None:
            from torrent_tpu.obs.tracer import tracer

            # outside the telemetry lock: the tracer takes its own leaf
            tracer().add_span(
                trace_id, "swarm.peer.connect", t0=now, t1=now,
                peer=key, inbound=inbound,
            )

    def peer_dropped(self, key: str) -> None:
        now = time.monotonic()
        span = None
        with self._lock:
            self._cells.write("peers")
            tel = self._peers.pop(key, None)
            if tel is None:
                # an untracked (folded) peer leaving: its connection is
                # one of the overflow record's; at zero the record goes
                # too — the connected gauge must not inflate forever
                # (the cumulative _totals already counted its bytes)
                if self._overflow_live > 0:
                    self._overflow_live -= 1
                    if self._overflow_live == 0:
                        self._peers.pop(_OVERFLOW_KEY, None)
                return
            if tel.trace_id is not None:
                span = (
                    tel.trace_id, tel.connected_t,
                    {
                        "peer": tel.key, "inbound": tel.inbound,
                        "bytes_down": tel.bytes_down, "bytes_up": tel.bytes_up,
                        "blocks": tel.blocks, "snubs": tel.snubs,
                    },
                )
            fire = self._recheck_latches_locked()
        if span is not None:
            from torrent_tpu.obs.tracer import tracer

            trace_id, t0, attrs = span
            tracer().add_span(trace_id, "swarm.peer", t0=t0, t1=now, **attrs)
        self._fire(fire)

    # ------------------------------------------------------------- events

    def _tel(self, key: str) -> _PeerTel | None:
        # caller holds self._lock; a late event for a dropped/unknown
        # peer lands on the overflow record when one exists
        return self._peers.get(key) or self._peers.get(_OVERFLOW_KEY)

    def on_message(self, key: str, kind: str, nbytes: int = 0) -> None:
        kind = kind if kind in MSG_KINDS else "other"
        with self._lock:
            self._cells.write("peers")
            slot = self._msg_totals.setdefault(kind, [0, 0])
            slot[0] += 1
            slot[1] += nbytes
            tel = self._tel(key)
            if tel is not None:
                pslot = tel.msgs.setdefault(kind, [0, 0])
                pslot[0] += 1
                pslot[1] += nbytes

    def on_state(self, key: str, **flags) -> None:
        """Record wire-state flag transitions (``am_choking=False`` …).
        No-op values (already current) don't count as transitions."""
        now = time.monotonic()
        fire = None
        with self._lock:
            self._cells.write("peers")
            tel = self._tel(key)
            if tel is None:
                return
            changed = False
            for name, value in sorted(flags.items()):
                if name not in _FLAGS or bool(value) == tel.flags[name]:
                    continue
                if tel.flags[name]:  # closing a True interval
                    tel.flag_true_s[name] += max(0.0, now - tel.flag_since[name])
                tel.flags[name] = bool(value)
                tel.flag_since[name] = now
                tel.transitions += 1
                changed = True
            # the latch scan is bounded O(live peers) but still only
            # worth paying when a flag actually transitioned
            if changed:
                fire = self._recheck_latches_locked()
        self._fire(fire)

    def on_block(self, key: str, nbytes: int, rtt_s: float | None = None) -> None:
        """A payload block arrived: bytes, RTT, and snub redemption.
        (``rejects`` stays CUMULATIVE like its sibling counters — the
        session tracks its own since-last-block reject burst for the
        snub gate.) The hot path stays O(1): the bounded latch scan
        runs only when this delivery redeems a snubbed peer, the one
        state change a block can cause."""
        fire = None
        with self._lock:
            self._cells.write("peers")
            self._totals["bytes_down"] += nbytes
            self._totals["blocks"] += 1
            tel = self._tel(key)
            if tel is not None:
                tel.bytes_down += nbytes
                tel.blocks += 1
                redeemed = tel.snubbed
                tel.snubbed = False  # delivering redeems (session mirror)
                if rtt_s is not None and rtt_s >= 0:
                    tel.rtt_counts[bisect_left(BUCKET_BOUNDS, rtt_s)] += 1
                    tel.rtt_count += 1
                    tel.rtt_sum += rtt_s
                if redeemed:
                    fire = self._recheck_latches_locked()
        if rtt_s is not None and rtt_s >= 0:
            from torrent_tpu.obs.hist import histograms

            # outside the telemetry lock (hist locks are their own leaves)
            histograms().get(
                BLOCK_RTT_FAMILY,
                help="Block round-trip time: request written to payload received",
            ).observe(rtt_s)
        self._fire(fire)

    def on_upload(self, key: str, nbytes: int) -> None:
        with self._lock:
            self._cells.write("peers")
            self._totals["bytes_up"] += nbytes
            tel = self._tel(key)
            if tel is not None:
                tel.bytes_up += nbytes

    def on_depth(self, key: str, depth: int) -> None:
        with self._lock:
            self._cells.write("peers")
            tel = self._tel(key)
            if tel is not None:
                tel.depth = depth
                if depth > tel.depth_max:
                    tel.depth_max = depth

    def on_snub(self, key: str) -> None:
        fire = None
        with self._lock:
            self._cells.write("peers")
            self._totals["snubs"] += 1
            tel = self._tel(key)
            if tel is not None:
                tel.snubs += 1
                tel.snubbed = True
            fire = self._recheck_latches_locked()
        self._fire(fire)

    def on_reject(self, key: str) -> None:
        with self._lock:
            self._cells.write("peers")
            self._totals["rejects"] += 1
            tel = self._tel(key)
            if tel is not None:
                tel.rejects += 1

    def on_endgame_cancel(self, key: str) -> None:
        with self._lock:
            self._cells.write("peers")
            self._totals["endgame_cancels"] += 1
            tel = self._tel(key)
            if tel is not None:
                tel.endgame_cancels += 1

    def on_corrupt(self, key: str) -> None:
        with self._lock:
            self._cells.write("peers")
            self._totals["corrupt"] += 1
            tel = self._tel(key)
            if tel is not None:
                tel.corrupt += 1

    def on_announce(self, ok: bool, origin: str = "") -> None:
        """Tracker announce outcome. ``origin`` names the announcing
        torrent (its swarm trace id): streaks are tracked per origin so
        one torrent's healthy tracker can never mask another's dead one.
        The flight recorder fires exactly once when an origin's streak
        crosses :data:`ANNOUNCE_STREAK`, re-arming on its next success."""
        fire = None
        origin = str(origin)
        with self._lock:
            self._cells.write("peers")
            if origin not in self._announce_streaks and (
                len(self._announce_streaks) >= MAX_ANNOUNCE_ORIGINS
            ):
                origin = _OVERFLOW_KEY
            if ok:
                self._totals["announce_ok"] += 1
                self._announce_streaks.pop(origin, None)
            else:
                self._totals["announce_failed"] += 1
                streak = self._announce_streaks.get(origin, 0) + 1
                self._announce_streaks[origin] = streak
                if streak == ANNOUNCE_STREAK:
                    fire = [(
                        "announce_failure_streak",
                        {"streak": streak, "origin": origin},
                    )]
                    self._trigger_counts["announce_failure_streak"] = (
                        self._trigger_counts.get("announce_failure_streak", 0) + 1
                    )
        self._fire(fire)

    # ----------------------------------------------------------- triggers

    def _recheck_latches_locked(self):
        """Evaluate the latched swarm-state triggers. Caller holds the
        lock; returns the list of (reason, detail) pairs to fire
        OUTSIDE it — each latch contributes at most one entry per
        False→True transition and re-arms only when it clears."""
        live = [t for k, t in self._peers.items() if k != _OVERFLOW_KEY]
        n = len(live)
        snubbed = sum(1 for t in live if t.snubbed)
        storm = n >= SNUB_STORM_MIN and snubbed >= max(SNUB_STORM_MIN, (n + 1) // 2)
        fires = []
        if storm and not self._storm_active:
            self._storm_active = True
            self._trigger_counts["snub_storm"] = (
                self._trigger_counts.get("snub_storm", 0) + 1
            )
            fires.append(("snub_storm", {"snubbed": snubbed, "connected": n}))
        elif not storm:
            self._storm_active = False
        all_choked = (
            n >= 2
            and all(t.flags["peer_choking"] for t in live)
            and any(t.flags["am_interested"] for t in live)
        )
        if all_choked and not self._all_choked_active:
            self._all_choked_active = True
            # fire only when a transfer was underway among the LIVE
            # peers: every BitTorrent connection STARTS choked (spec
            # defaults), so the condition is trivially true at swarm
            # startup — and a process-cumulative gate would still fire
            # spuriously when a SECOND torrent is added after the first
            # ever moved a block. The alarming transition is these
            # peers choking us after they had been delivering.
            if any(t.blocks > 0 for t in live):
                self._trigger_counts["all_peers_choked"] = (
                    self._trigger_counts.get("all_peers_choked", 0) + 1
                )
                fires.append(("all_peers_choked", {"connected": n}))
        elif not all_choked:
            self._all_choked_active = False
        return fires

    def _fire(self, fires) -> None:
        if not fires:
            return
        from torrent_tpu.obs.recorder import flight_recorder

        for reason, detail in fires:
            # outside the telemetry lock; the snapshot the dump carries
            # is taken fresh (the recorder redacts it)
            flight_recorder().trigger(
                reason, detail=detail, snapshots={"swarm": self.snapshot()}
            )

    # ----------------------------------------------------------- snapshot

    def snapshot(self, top_k: int = TOP_PEERS) -> dict:
        """The ``/v1/swarm`` payload: raw records finalized under the
        lock, then rolled up by the pure builder outside it."""
        now = time.monotonic()
        with self._lock:
            self._cells.read("peers")
            raws = {k: t.raw(now) for k, t in self._peers.items()}
            if _OVERFLOW_KEY in raws:
                # the shared record speaks for this many live folded
                # connections (build_swarm_snapshot folds it, never
                # names it)
                raws[_OVERFLOW_KEY]["peers"] = self._overflow_live
            totals = dict(self._totals)
            # the worst current per-origin failure streak (0 = healthy)
            totals["announce_streak"] = max(
                self._announce_streaks.values(), default=0
            )
            msgs = {k: [v[0], v[1]] for k, v in self._msg_totals.items()}
            triggers = dict(self._trigger_counts)
        snap = build_swarm_snapshot(raws, totals, top_k=top_k)
        snap["msgs"] = {
            k: {"count": msgs[k][0], "bytes": msgs[k][1]} for k in sorted(msgs)
        }
        snap["triggers"] = {k: triggers[k] for k in sorted(triggers)}
        return snap

    def sample_summary(self) -> dict | None:
        """The compact cumulative form a timeline sample carries (the
        SLO swarm objectives delta it). ``None`` while the swarm plane
        has never seen a connection — idle processes stay byte-identical
        to a swarm-less build."""
        with self._lock:
            self._cells.read("peers")
            if not self._totals["connections"]:
                return None
            live = [t for k, t in self._peers.items() if k != _OVERFLOW_KEY]
            return {
                "peers": len(live) + self._overflow_live,
                "snubbed": sum(1 for t in live if t.snubbed),
                "bytes_down": self._totals["bytes_down"],
                "bytes_up": self._totals["bytes_up"],
                "blocks": self._totals["blocks"],
                "snubs": self._totals["snubs"],
                "announce_failed": self._totals["announce_failed"],
                "all_choked": 1 if self._all_choked_active else 0,
            }

    def active(self) -> bool:
        with self._lock:
            self._cells.read("peers")
            return bool(self._totals["connections"])

    def clear(self) -> None:
        with self._lock:
            self._cells.write("peers")
            self._peers.clear()
            for k in self._totals:
                self._totals[k] = 0
            self._msg_totals.clear()
            self._overflow_live = 0
            self._storm_active = False
            self._all_choked_active = False
            self._announce_streaks.clear()
            self._trigger_counts.clear()


_telemetry = None
# construction guard, same rationale as the pipeline ledger's: first use
# can race between the session loop and a metrics scrape thread
_telemetry_guard = named_lock("obs.swarm._guard")


def swarm_telemetry() -> SwarmTelemetry:
    """The process-wide swarm telemetry registry (constructed on first
    use, so TSAN enabling in conftest instruments its lock)."""
    global _telemetry
    if _telemetry is None:
        with _telemetry_guard:
            if _telemetry is None:
                _telemetry = SwarmTelemetry()
    return _telemetry
