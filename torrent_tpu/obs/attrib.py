"""Bottleneck attribution over pipeline-ledger snapshots.

Turns the raw per-stage counters of ``obs/ledger.py`` into the verdict
an operator actually wants: *which stage limits the pipeline, and by
how much*. The model is the classic pipelined-stage one, the same
treat-storage-to-accelerator-as-one-pipeline lens as "GPUs as Storage
System Accelerators" (PAPERS.md):

* ``utilization``  — a stage's busy-seconds per second of pipeline wall
  time. Overlapped work (depth-2 launch pipelining, concurrent reader
  threads) can push this above 1.0; that is honest occupancy, not an
  error.
* ``achieved_bps`` — the stage's throughput *while busy*
  (``bytes / busy_s``): what the stage can do.
* ``demanded_bps`` — the fastest achieved rate among the OTHER stages:
  what the rest of the pipeline could feed (or drain) if this stage
  were free. For a true bottleneck ``achieved ≪ demanded``; the ratio
  is the headroom unlocked by fixing it.

The **limiting stage** is the one with the highest utilization (ties
broken toward more bytes — the stage doing real pipeline volume).
Attribution works on a single since-start snapshot or on the delta
between two (``prev=``) — ``doctor --bottleneck`` and the bench
harness use deltas so one process can attribute several runs.

Pure functions over plain dicts: no locks, no globals, trivially
testable, and safe to call from the bridge's serving loop.
"""

from __future__ import annotations

__all__ = ["attribute", "format_rate", "format_report"]

_EPS = 1e-9


def _delta(cur: dict, prev: dict | None) -> tuple[dict, float]:
    """Per-stage counter deltas and the wall interval they span."""
    pstages = (prev or {}).get("stages", {})
    stages = {}
    for name, s in cur.get("stages", {}).items():
        p = pstages.get(name, {})
        stages[name] = {
            "busy_s": max(0.0, s.get("busy_s", 0.0) - p.get("busy_s", 0.0)),
            "bytes": max(0, s.get("bytes", 0) - p.get("bytes", 0)),
            "ops": max(0, s.get("ops", 0) - p.get("ops", 0)),
            "active": s.get("active", 0),
            "max_active": s.get("max_active", 0),
        }
    t0 = cur.get("t_first")
    t1 = cur.get("t_last")
    if prev is not None:
        # anchor the interval at the moment `prev` was TAKEN (t_snap),
        # not at the previous activity's end (t_last): idle time between
        # a prior run and the snapshot — doctor's setup work, a quiet
        # bridge — must not count into this interval's wall and dilute
        # utilization. Older prev dicts without t_snap fall back.
        anchor = prev.get("t_snap") or prev.get("t_last")
        if anchor is not None:
            t0 = anchor
    wall = 0.0
    if t0 is not None and t1 is not None:
        wall = max(0.0, t1 - t0)
    return stages, wall


def attribute(snapshot: dict, prev: dict | None = None) -> dict:
    """Attribution report for one ledger snapshot (or the delta between
    two). Always returns a complete dict; ``bottleneck`` is ``None``
    when the interval recorded no activity (fresh ledger, idle plane).
    """
    stages, wall = _delta(snapshot, prev)
    active = {n: s for n, s in stages.items() if s["ops"] > 0}
    report_stages: dict[str, dict] = {}
    for name, s in stages.items():
        report_stages[name] = {
            "busy_s": round(s["busy_s"], 6),
            "bytes": s["bytes"],
            "ops": s["ops"],
            "active": s["active"],
            "max_active": s["max_active"],
            "utilization": round(s["busy_s"] / wall, 6) if wall > _EPS else 0.0,
            "achieved_bps": (
                round(s["bytes"] / s["busy_s"], 3) if s["busy_s"] > _EPS else None
            ),
        }
    # cross-stage occupancy overlap (the double-buffering visibility
    # series): delta the overlap seconds like any counter; the
    # max-concurrent high-water is since-start (snapshots may predate
    # the field — missing dicts read as zeros)
    ov = snapshot.get("overlap") or {}
    pov = (prev or {}).get("overlap") or {}
    overlap_s = max(0.0, ov.get("busy_s", 0.0) - pov.get("busy_s", 0.0))
    out: dict = {
        "wall_s": round(wall, 6),
        "stages": report_stages,
        "bottleneck": None,
        "pipeline_bytes": stages.get("verdict", {}).get("bytes", 0),
        "pipeline_bps": None,
        "overlap": {
            "busy_s": round(overlap_s, 6),
            "share": round(overlap_s / wall, 6) if wall > _EPS else 0.0,
            "concurrent_stages": ov.get("concurrent_stages", 0),
            "max_concurrent_stages": ov.get("max_concurrent_stages", 0),
        },
    }
    if wall > _EPS and out["pipeline_bytes"]:
        out["pipeline_bps"] = round(out["pipeline_bytes"] / wall, 3)
    if not active or wall <= _EPS:
        return out
    # limiting stage: highest busy share of the wall, ties toward bytes
    limit = max(active, key=lambda n: (active[n]["busy_s"], active[n]["bytes"]))
    achieved = report_stages[limit]["achieved_bps"]
    others = [
        report_stages[n]["achieved_bps"]
        for n in active
        if n != limit and report_stages[n]["achieved_bps"]
    ]
    demanded = max(others) if others else None
    out["bottleneck"] = {
        "stage": limit,
        "utilization": report_stages[limit]["utilization"],
        "achieved_bps": achieved,
        "demanded_bps": demanded,
        # headroom if this stage were as fast as the best other stage
        "headroom": (
            round(demanded / achieved, 2)
            if achieved and demanded and achieved > _EPS
            else None
        ),
    }
    return out


def format_rate(bps: float | None) -> str:
    """Human-readable byte rate (shared by format_report and `top`)."""
    if not bps:
        return "—"
    for unit, div in (("GiB/s", 1 << 30), ("MiB/s", 1 << 20), ("KiB/s", 1 << 10)):
        if bps >= div:
            return f"{bps / div:.1f} {unit}"
    return f"{bps:.0f} B/s"


def format_report(report: dict) -> str:
    """One-paragraph human rendering (doctor --bottleneck, bench logs)."""
    bn = report.get("bottleneck")
    if bn is None:
        return "pipeline idle: no stage activity recorded"
    parts = [
        f"{bn['stage']} limits the pipeline: {bn['utilization'] * 100:.0f}% of "
        f"{report['wall_s']:.2f}s wall, {format_rate(bn['achieved_bps'])} achieved"
    ]
    if bn.get("demanded_bps"):
        parts.append(f"vs {format_rate(bn['demanded_bps'])} demanded")
    if bn.get("headroom"):
        parts.append(f"({bn['headroom']}x headroom)")
    shares = ", ".join(
        f"{name} {st['utilization'] * 100:.0f}%"
        for name, st in sorted(
            report["stages"].items(), key=lambda kv: -kv[1]["busy_s"]
        )
        if st["ops"]
    )
    return " ".join(parts) + (f"; stage shares: {shares}" if shares else "")
