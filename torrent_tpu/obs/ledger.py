"""Always-on pipeline ledger: per-stage byte/time/occupancy accounting.

The hash plane banks 60.18 GiB/s while the end-to-end recheck in the
SAME record measured 3.1 p/s — and the only way anyone knew the gap was
host→device transfer was a human reconstructing it from bench logs
(BENCH_r05). The ledger makes that attribution continuous and
machine-readable: every stage boundary of the verify pipeline

    recv → read → stage → h2d → launch → digest → verdict

records monotonic busy-seconds, payload bytes, and occupancy into a
bounded process-global table, and ``obs/attrib.py`` turns any two
snapshots into a bottleneck verdict ("h2d is 96% of pipeline wall
time, 24.9 MiB/s achieved vs 2.1 GiB/s demanded"). Surfaced as
``GET /v1/pipeline``, ``torrent_tpu_pipeline_*`` Prometheus series on
both ``/metrics`` endpoints, ``doctor --bottleneck``, ``torrent-tpu
top``, and embedded in every ``torrent-tpu bench`` record.

Stage boundaries (instrumentation sites):

* ``recv``    — the live-swarm wire stage AHEAD of ``read``: seconds a
  peer loop spent blocked on the socket while requests were in flight
  (plus download-cap pacing waits) and the payload bytes of downloaded
  blocks as they land in the piece-assembly buffers
  (``session/torrent.py``). When the network is the limiting resource,
  this stage owns the wall and ``doctor --bottleneck`` / ``torrent-tpu
  replay`` can finally say so instead of blaming disk.
* ``read``    — storage reads: ``parallel/verify.read_pieces_chunk``
  (byte-path chunks + the fabric sentinel re-hash), the native
  ``io_engine.read_into`` batch path, and the pure-Python
  ``Storage.read_batch`` fallback walk (exactly one runs per row).
* ``stage``   — the staging-slot copy (``sched._StagingSlots.stage``).
  ZERO bytes on the zero-copy ingest path: ``read_pieces_into`` lands
  reads directly in the launch slab, so this stage only records for
  byte-path and mixed-slab launches.
* ``h2d``     — host→device transfer: the explicit device put on every
  device plane (sha1 included — the zero-copy refactor split its
  previously fused ``digest_batch`` span); ``sched/faults.py``'s
  ``latency_ms`` hook also accounts here (it models a slow
  interconnect), which is what makes bottleneck attribution
  deterministically testable on CPU.
* ``launch``  — the device (or hashlib) hash execution.
* ``digest``  — D2H fetch + digest-word conversion.
* ``verdict`` — the scheduler's per-launch demux back to submitters.

The ledger also integrates cross-stage occupancy overlap — wall
seconds with ≥2 distinct stages simultaneously busy and the
max-concurrent-stages high-water mark — the series that makes
double-buffered ingest (read while h2d while launch) visible.

Design constraints, same as ``obs/hist.py``: scalar-only counters,
bounded cardinality (the six pipeline stages plus a capped overflow of
unknown names folded into ``other``), one :func:`named_lock` that is a
leaf of the lock-order graph and is NEVER held across the timed body —
``track()`` acquires it briefly at stage entry and exit only, so no
device call ever runs under an obs lock.
"""

from __future__ import annotations

import time

from torrent_tpu.analysis.sanitizer import guard_attrs, named_lock
from torrent_tpu.utils.metrics import _esc

__all__ = [
    "PIPELINE_STAGES",
    "PipelineLedger",
    "pipeline_ledger",
    "render_pipeline_metrics",
]

# the canonical stage order (pipeline position, used by renderers).
# "egress" is the serving direction — blocks leaving through the seeder
# plane — appended after the verify chain so download attribution
# reports keep their familiar shape.
PIPELINE_STAGES = ("recv", "read", "stage", "h2d", "launch", "digest", "verdict", "egress")

# unknown stage names fold into "other" past this bound — the ledger's
# cardinality must stay fixed no matter what a plane_factory plane does
MAX_STAGES = 16


class _Tracked:
    """One in-flight stage entry: ``with ledger.track("read") as t:``.

    Bytes may be declared up front (``nbytes=``) or accumulated as the
    stage discovers them (``t.add(n)`` — the read loop knows its byte
    count only piece by piece). The ledger lock is taken briefly at
    enter and exit; the tracked body runs entirely outside it.
    """

    __slots__ = ("_ledger", "stage", "nbytes", "_t0")

    def __init__(self, ledger: "PipelineLedger", stage: str, nbytes: int):
        self._ledger = ledger
        self.stage = stage
        self.nbytes = nbytes
        self._t0 = 0.0

    def add(self, nbytes: int) -> None:
        self.nbytes += nbytes

    def __enter__(self) -> "_Tracked":
        self._t0 = time.monotonic()
        self._ledger._enter(self.stage, self._t0)
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        t1 = time.monotonic()
        self._ledger._exit(self.stage, self.nbytes, t1 - self._t0, t1)


class _Stage:
    __slots__ = ("busy_s", "bytes", "ops", "active", "max_active")

    def __init__(self):
        self.busy_s = 0.0
        self.bytes = 0
        self.ops = 0
        self.active = 0
        self.max_active = 0


class PipelineLedger:
    """Bounded per-process stage table. One global instance
    (:func:`pipeline_ledger`) serves the scheduler, planes, read paths,
    and fabric; tests may construct private ones."""

    def __init__(self):
        self._lock = named_lock("obs.ledger._lock")
        # dynamic lockset checking: the stage table + overlap integrator
        # is one cell guarded by _lock (stage entries arrive from worker
        # threads, the loop, and metrics scrapers concurrently)
        self._cells = guard_attrs("obs.ledger", "stages")
        self._stages: dict[str, _Stage] = {}
        # monotonic extent of recorded activity — the attribution wall
        self._t_first: float | None = None
        self._t_last: float | None = None
        # cross-stage overlap: how many DISTINCT stages are occupied at
        # once. Double-buffered ingest is only proven when read, h2d and
        # launch are simultaneously busy — per-stage max_active can't
        # show that, so the ledger integrates it here: seconds with ≥2
        # stages concurrently active, plus the high-water stage count.
        self._stages_active = 0  # stages with active > 0 right now
        self._overlap_t0: float | None = None  # when ≥2 became true
        self._overlap_s = 0.0
        self._max_concurrent_stages = 0

    # ------------------------------------------------------------ record

    def track(self, stage: str, nbytes: int = 0) -> _Tracked:
        """Context manager timing one stage entry (occupancy-aware)."""
        return _Tracked(self, stage, nbytes)

    def record(self, stage: str, nbytes: int, seconds: float) -> None:
        """Post-hoc accounting for a stage whose duration was measured
        by the caller (no occupancy window)."""
        now = time.monotonic()
        with self._lock:
            self._cells.write("stages")
            s = self._stage_locked(stage)
            s.busy_s += max(0.0, seconds)
            s.bytes += nbytes
            s.ops += 1
            self._touch_locked(now - max(0.0, seconds))
            self._touch_locked(now)

    def _stage_locked(self, stage: str) -> _Stage:
        s = self._stages.get(stage)
        if s is None:
            if stage not in PIPELINE_STAGES and len(self._stages) >= MAX_STAGES:
                return self._stages.setdefault("other", _Stage())
            s = self._stages[stage] = _Stage()
        return s

    def _touch_locked(self, t: float) -> None:
        if self._t_first is None or t < self._t_first:
            self._t_first = t
        if self._t_last is None or t > self._t_last:
            self._t_last = t

    def _enter(self, stage: str, t0: float) -> None:
        with self._lock:
            self._cells.write("stages")
            s = self._stage_locked(stage)
            s.active += 1
            if s.active > s.max_active:
                s.max_active = s.active
            if s.active == 1:
                self._stages_active += 1
                if self._stages_active > self._max_concurrent_stages:
                    self._max_concurrent_stages = self._stages_active
                if self._stages_active == 2:
                    self._overlap_t0 = t0
            self._touch_locked(t0)

    def _exit(self, stage: str, nbytes: int, dt: float, t1: float) -> None:
        with self._lock:
            self._cells.write("stages")
            s = self._stage_locked(stage)
            s.active -= 1
            s.busy_s += max(0.0, dt)
            s.bytes += nbytes
            s.ops += 1
            if s.active == 0:
                self._stages_active -= 1
                if self._stages_active == 1 and self._overlap_t0 is not None:
                    self._overlap_s += max(0.0, t1 - self._overlap_t0)
                    self._overlap_t0 = None
            self._touch_locked(t1)

    # ---------------------------------------------------------- snapshot

    def snapshot(self) -> dict:
        """Scalar-only copy for attribution, ``/v1/pipeline``, and the
        Prometheus renderer. ``t_first``/``t_last`` are monotonic (never
        wall clock): meaningful only as a difference. ``t_snap`` is the
        snapshot's own monotonic timestamp — delta attribution anchors
        its wall interval there, so idle time BEFORE the snapshot (a
        previous run's tail, setup work) never dilutes the next
        interval's utilization."""
        with self._lock:
            self._cells.read("stages")
            now = time.monotonic()
            overlap_s = self._overlap_s
            if self._overlap_t0 is not None:  # an overlap window is open
                overlap_s += max(0.0, now - self._overlap_t0)
            return {
                "t_first": self._t_first,
                "t_last": self._t_last,
                "t_snap": now,
                "overlap": {
                    "busy_s": overlap_s,
                    "concurrent_stages": self._stages_active,
                    "max_concurrent_stages": self._max_concurrent_stages,
                },
                "stages": {
                    name: {
                        "busy_s": s.busy_s,
                        "bytes": s.bytes,
                        "ops": s.ops,
                        "active": s.active,
                        "max_active": s.max_active,
                    }
                    for name, s in self._stages.items()
                },
            }

    def clear(self) -> None:
        with self._lock:
            self._cells.write("stages")
            self._stages.clear()
            self._t_first = None
            self._t_last = None
            self._stages_active = 0
            self._overlap_t0 = None
            self._overlap_s = 0.0
            self._max_concurrent_stages = 0


def _stage_order(names) -> list[str]:
    """Canonical pipeline order first, unknown stages after (sorted)."""
    known = [s for s in PIPELINE_STAGES if s in names]
    return known + sorted(n for n in names if n not in PIPELINE_STAGES)


def render_pipeline_metrics(ledger: PipelineLedger | None = None) -> str:
    """Prometheus text for the ledger: raw per-stage counters plus the
    attributor's utilization/bottleneck verdict. Appended to both
    ``/metrics`` endpoints via ``obs.render_obs_metrics``. Defensive:
    a fresh (empty) ledger renders headers with no samples."""
    from torrent_tpu.obs.attrib import attribute

    snap = (ledger or pipeline_ledger()).snapshot()
    rep = attribute(snap)
    stages = _stage_order(snap["stages"])
    lines = [
        "# HELP torrent_tpu_pipeline_stage_busy_seconds_total Seconds this pipeline stage was occupied",
        "# TYPE torrent_tpu_pipeline_stage_busy_seconds_total counter",
    ]
    for name in stages:
        lines.append(
            f'torrent_tpu_pipeline_stage_busy_seconds_total{{stage="{_esc(name)}"}} '
            f"{snap['stages'][name]['busy_s']:.6f}"
        )
    lines.append(
        "# HELP torrent_tpu_pipeline_stage_bytes_total Payload bytes that flowed through this stage"
    )
    lines.append("# TYPE torrent_tpu_pipeline_stage_bytes_total counter")
    for name in stages:
        lines.append(
            f'torrent_tpu_pipeline_stage_bytes_total{{stage="{_esc(name)}"}} '
            f"{snap['stages'][name]['bytes']}"
        )
    lines.append(
        "# HELP torrent_tpu_pipeline_stage_ops_total Stage entries (launches, reads, demuxes)"
    )
    lines.append("# TYPE torrent_tpu_pipeline_stage_ops_total counter")
    for name in stages:
        lines.append(
            f'torrent_tpu_pipeline_stage_ops_total{{stage="{_esc(name)}"}} '
            f"{snap['stages'][name]['ops']}"
        )
    lines.append(
        "# HELP torrent_tpu_pipeline_stage_active Concurrent entries currently inside this stage"
    )
    lines.append("# TYPE torrent_tpu_pipeline_stage_active gauge")
    for name in stages:
        lines.append(
            f'torrent_tpu_pipeline_stage_active{{stage="{_esc(name)}"}} '
            f"{snap['stages'][name]['active']}"
        )
    lines.append(
        "# HELP torrent_tpu_pipeline_stage_max_active High-water concurrent entries observed inside this stage"
    )
    lines.append("# TYPE torrent_tpu_pipeline_stage_max_active gauge")
    for name in stages:
        lines.append(
            f'torrent_tpu_pipeline_stage_max_active{{stage="{_esc(name)}"}} '
            f"{snap['stages'][name]['max_active']}"
        )
    lines.append(
        "# HELP torrent_tpu_pipeline_stage_utilization Stage busy-seconds per pipeline wall second "
        "(can exceed 1 with overlapped launches)"
    )
    lines.append("# TYPE torrent_tpu_pipeline_stage_utilization gauge")
    for name in stages:
        st = rep["stages"].get(name, {})
        lines.append(
            f'torrent_tpu_pipeline_stage_utilization{{stage="{_esc(name)}"}} '
            f"{st.get('utilization', 0.0):.6f}"
        )
    # the bottleneck verdict as a labeled 0/1 enum family (alert on the
    # stage whose series is 1)
    bn = (rep.get("bottleneck") or {}).get("stage")
    lines.append(
        "# HELP torrent_tpu_pipeline_bottleneck Limiting stage per the attributor (1 = current bottleneck)"
    )
    lines.append("# TYPE torrent_tpu_pipeline_bottleneck gauge")
    for name in stages:
        lines.append(
            f'torrent_tpu_pipeline_bottleneck{{stage="{_esc(name)}"}} '
            f"{1 if name == bn else 0}"
        )
    # cross-stage occupancy overlap: the double-buffering proof series
    # (read while h2d while launch shows up as overlap seconds plus a
    # max-concurrent-stages high-water mark)
    ov = snap.get("overlap") or {}
    lines += [
        "# HELP torrent_tpu_pipeline_overlap_seconds_total Seconds with two or more pipeline stages concurrently occupied",
        "# TYPE torrent_tpu_pipeline_overlap_seconds_total counter",
        f"torrent_tpu_pipeline_overlap_seconds_total {ov.get('busy_s', 0.0):.6f}",
        "# HELP torrent_tpu_pipeline_concurrent_stages Distinct pipeline stages currently occupied",
        "# TYPE torrent_tpu_pipeline_concurrent_stages gauge",
        f"torrent_tpu_pipeline_concurrent_stages {ov.get('concurrent_stages', 0)}",
        "# HELP torrent_tpu_pipeline_concurrent_stages_max High-water distinct pipeline stages concurrently occupied",
        "# TYPE torrent_tpu_pipeline_concurrent_stages_max gauge",
        f"torrent_tpu_pipeline_concurrent_stages_max {ov.get('max_concurrent_stages', 0)}",
        "# HELP torrent_tpu_pipeline_wall_seconds Monotonic extent of recorded pipeline activity",
        "# TYPE torrent_tpu_pipeline_wall_seconds gauge",
        f"torrent_tpu_pipeline_wall_seconds {rep.get('wall_s', 0.0):.6f}",
    ]
    return "\n".join(lines) + "\n"


_ledger = None
# construction guard: unlike the request-driven tracer/histogram
# singletons, first ledger use can race between a scheduler worker
# thread and the serving loop — a lost construction would silently drop
# one side's stage records
_ledger_guard = named_lock("obs.ledger._guard")


def pipeline_ledger() -> PipelineLedger:
    """The process-wide pipeline ledger (constructed on first use, so
    TSAN enabling in conftest instruments its lock)."""
    global _ledger
    if _ledger is None:
        with _ledger_guard:
            if _ledger is None:
                _ledger = PipelineLedger()
    return _ledger
