"""Fleet observability: heartbeat-carried obs digests and the swarm
rollup.

PR 6/7 gave every process deep self-observability (ledger, histograms,
attribution) — but ``/v1/fabric/status`` is local-process only, so
diagnosing a 4-process run meant tailing N ``/metrics`` endpoints by
hand. This module lifts the per-process plane one level up:

* :func:`obs_digest` — a compact, bounded, deterministic summary of ONE
  process's observability state: pipeline-ledger stage deltas since the
  sweep started, mergeable latency-histogram summaries (fixed log2
  buckets, so peers sum them bucket-for-bucket), a scheduler summary
  (breaker states, shed/fault counters, lane fill), and fabric unit
  progress. Carried as the ``"obs"`` field of every fabric heartbeat —
  both transports — and budgeted into ``plan_payload_bytes`` via
  :data:`DIGEST_MAX_BYTES`. Built only from monotonic/counter state:
  the builders sit in the analysis plane's determinism pass exactly
  like ``heartbeat_span_context``.
* :func:`aggregate_fleet` — merges peer digests into a swarm-wide
  rollup with **two-level bottleneck attribution**: the limiting
  process (the one whose recorded activity spans the longest wall —
  the straggler that defines when the fleet finishes), then THAT
  process's limiting stage via ``obs/attrib.attribute`` — "process 0
  limits the fleet, and h2d limits process 0". Plus the **straggler
  scoreboard**: per-pid achieved B/s vs the fleet median, lapse/
  degraded/distrusted status, and adoption debt (units a survivor must
  pick up).
* :class:`FleetObsServer` — a tiny HTTP surface (``GET /v1/fleet`` +
  ``GET /metrics``) any fabric worker can expose
  (``fabric-verify --obs-port``), so ``torrent-tpu top --fleet`` and
  ``doctor --fleet`` can watch a peer's view of the swarm live. The
  bridge serves the same ``/v1/fleet`` route itself.

Size/cardinality budget: a digest is clamped to
:data:`DIGEST_MAX_BYTES` (drop order: histogram summaries, scheduler
summary, stage table — unit progress survives longest), breaker lanes
are capped at :data:`MAX_DIGEST_BREAKER_LANES`, and the Prometheus
rendering (``utils/metrics.render_fleet_metrics``) caps per-pid series.
Everything here is pure functions over plain dicts — no locks, safe on
any serving loop; the only state is what callers pass in.
"""

from __future__ import annotations

import json

from torrent_tpu.obs.attrib import _delta, attribute
from torrent_tpu.obs.hist import histograms
from torrent_tpu.obs.ledger import pipeline_ledger

__all__ = [
    "DIGEST_MAX_BYTES",
    "DIGEST_VERSION",
    "FleetObsServer",
    "aggregate_fleet",
    "build_obs_digest",
    "clamp_digest",
    "digest_bytes",
    "local_fleet_snapshot",
    "obs_digest",
]

DIGEST_VERSION = 1
# worst-case wire size of one digest (json, default separators) — the
# term plan_payload_bytes budgets into the allgather buffer, and the
# bound clamp_digest enforces
DIGEST_MAX_BYTES = 2048
# breaker lanes named individually in a digest; the rest fold into a
# single open-lane count so a lane-happy plane can't grow the payload
MAX_DIGEST_BREAKER_LANES = 6
# histogram families a digest summarizes: the two that attribute queue
# pressure vs device time (short key -> registry family name)
DIGEST_HIST_FAMILIES = (
    ("queue_wait", "torrent_tpu_sched_queue_wait_seconds"),
    ("launch", "torrent_tpu_sched_launch_seconds"),
)
# a reporting process under this fraction of the fleet median achieved
# rate is flagged a straggler on the scoreboard
STRAGGLER_RATIO = 0.5


# --------------------------------------------------------------- builders
# (in the analysis determinism pass's scope: no wall clock, no
# randomness, every dict iteration sorted — digest bytes ride the
# heartbeat exchange and must be bit-stable across re-runs)


# determinism-scope
def digest_bytes(digest: dict) -> int:
    """Wire size of a digest under the heartbeat's JSON encoding."""
    return len(json.dumps(digest, sort_keys=True).encode())


# determinism-scope
def _digest_stages(stages: dict) -> dict:
    out = {}
    for name in sorted(stages):
        s = stages[name]
        if not s.get("ops"):
            continue
        out[name] = {
            "busy_s": round(s.get("busy_s", 0.0), 6),
            "bytes": int(s.get("bytes", 0)),
            "ops": int(s.get("ops", 0)),
        }
    return out


# determinism-scope
def _digest_hist(hist_snaps: dict) -> dict:
    out = {}
    for short in sorted(hist_snaps):
        snap = hist_snaps[short]
        if snap is None:
            continue
        counts, count, total = snap
        if not count:
            continue
        out[short] = {
            "count": int(count),
            "sum": round(float(total), 6),
            # sparse buckets: index -> count, zeros omitted (string keys
            # so the JSON round-trip is exact)
            "buckets": {
                str(i): int(c) for i, c in enumerate(counts) if c
            },
        }
    return out


# determinism-scope
def _digest_sched(sched_snap: dict) -> dict:
    breakers = sched_snap.get("breakers") or {}
    named = {}
    extra_open = 0
    for i, lane in enumerate(sorted(breakers)):
        state = breakers[lane].get("state", "closed")
        if i < MAX_DIGEST_BREAKER_LANES:
            named[lane] = state
        elif state != "closed":
            extra_open += 1
    out = {
        "launches": int(sched_snap.get("launches", 0)),
        "mean_fill": round(float(sched_snap.get("mean_fill", 0.0)), 4),
        "queue_bytes": int(sched_snap.get("queue_bytes", 0)),
        "shed": int(sched_snap.get("shed_total", 0)),
        "launch_failures": int(sched_snap.get("launch_failures", 0)),
        "retries": int(sched_snap.get("retries", 0)),
        "cpu_fallback": int(sched_snap.get("cpu_fallback_launches", 0)),
        "failed_pieces": int(sched_snap.get("failed_pieces", 0)),
        "breakers": named,
    }
    if extra_open:
        out["breakers_open_unnamed"] = extra_open
    return out


# determinism-scope
def build_obs_digest(
    ledger_snap: dict,
    base_snap: dict | None,
    hist_snaps: dict,
    sched_snap: dict,
    unit: dict | None = None,
    slo: dict | None = None,
) -> dict:
    """Assemble one process's obs digest from already-taken snapshots.

    ``ledger_snap``/``base_snap``: ``PipelineLedger.snapshot()`` dicts —
    the digest carries the DELTA (stage busy/bytes/ops and the wall it
    spans), so a long-lived process's earlier traffic never dilutes this
    sweep's attribution. ``hist_snaps``: short-key ->
    ``family_snapshot()`` tuple. ``sched_snap``: the scheduler's
    ``metrics_snapshot()``. ``unit``: fabric unit-progress counters.
    Clamped to :data:`DIGEST_MAX_BYTES` on the way out."""
    stages, wall = _delta(ledger_snap, base_snap)
    ov = ledger_snap.get("overlap") or {}
    bov = (base_snap or {}).get("overlap") or {}
    digest = {
        "v": DIGEST_VERSION,
        "wall_s": round(wall, 6),
        "stages": _digest_stages(stages),
        "overlap": {
            "busy_s": round(
                max(0.0, ov.get("busy_s", 0.0) - bov.get("busy_s", 0.0)), 6
            ),
            "max_concurrent_stages": int(ov.get("max_concurrent_stages", 0)),
        },
        "hist": _digest_hist(hist_snaps),
        "sched": _digest_sched(sched_snap),
        "unit": dict(sorted((unit or {}).items())),
    }
    if slo:
        # the SLO engine's compact budget-health summary (obs/slo
        # digest_summary): tiny and scalar-only, so it survives the
        # clamp alongside unit progress
        digest["slo"] = dict(sorted(slo.items()))
    return clamp_digest(digest)


# determinism-scope
def clamp_digest(digest: dict, max_bytes: int = DIGEST_MAX_BYTES) -> dict:
    """Enforce the digest size bound. Drop order is fixed — histogram
    summaries first (recoverable from /metrics), then the scheduler
    summary, then the stage table — so unit progress and the wall
    survive longest; the floor is the bare envelope."""
    d = dict(digest)
    for field in ("hist", "sched", "stages"):
        if digest_bytes(d) <= max_bytes:
            return d
        d.pop(field, None)
    if digest_bytes(d) <= max_bytes:
        return d
    return {
        "v": d.get("v", DIGEST_VERSION),
        "wall_s": d.get("wall_s", 0.0),
        "unit": d.get("unit") or {},
    }


# determinism-scope
def obs_digest(
    scheduler=None, base: dict | None = None, unit: dict | None = None
) -> dict:
    """This process's obs digest, gathered from the process-global
    ledger and histogram registry (plus ``scheduler`` when given).
    ``base``: a ledger snapshot taken when the sweep started — stage
    counters are reported as deltas against it."""
    reg = histograms()
    hist_snaps = {}
    for short, family in DIGEST_HIST_FAMILIES:
        hist_snaps[short] = reg.family_snapshot(family)
    sched_snap = scheduler.metrics_snapshot() if scheduler is not None else {}
    # worst burn-rate / breach flag from the process's armed SLO engine
    # (obs/slo): None unless objectives were explicitly configured, so
    # an unarmed run's digest bytes are byte-identical to before
    from torrent_tpu.obs import slo as _slo

    engine = _slo.armed()
    return build_obs_digest(
        pipeline_ledger().snapshot(), base, hist_snaps, sched_snap, unit,
        slo=engine.summary() if engine is not None else None,
    )


# -------------------------------------------------------------- aggregate


def digest_to_snapshot(digest: dict) -> dict:
    """Reconstruct a ledger-shaped snapshot from a digest so
    ``obs/attrib.attribute`` runs unchanged on a PEER's counters: the
    digest's wall becomes the snapshot's monotonic extent."""
    wall = float(digest.get("wall_s") or 0.0)
    stages = {}
    for name, s in sorted((digest.get("stages") or {}).items()):
        stages[name] = {
            "busy_s": float(s.get("busy_s", 0.0)),
            "bytes": int(s.get("bytes", 0)),
            "ops": int(s.get("ops", 0)),
            "active": 0,
            "max_active": 0,
        }
    ov = digest.get("overlap") or {}
    return {
        "t_first": 0.0,
        "t_last": wall,
        "t_snap": wall,
        "overlap": {
            "busy_s": float(ov.get("busy_s", 0.0)),
            "concurrent_stages": 0,
            "max_concurrent_stages": int(ov.get("max_concurrent_stages", 0)),
        },
        "stages": stages,
    }


def _median(values: list[float]) -> float | None:
    if not values:
        return None
    vs = sorted(values)
    n = len(vs)
    mid = n // 2
    return vs[mid] if n % 2 else (vs[mid - 1] + vs[mid]) / 2.0


def aggregate_fleet(
    digests: dict[int, dict],
    statuses: dict[int, str] | None = None,
    planned_units: dict[int, int] | None = None,
    nproc: int | None = None,
    digest_drops: int = 0,
) -> dict:
    """Merge per-process obs digests into the swarm-wide rollup.

    Two-level bottleneck attribution: the **limiting process** is the
    one whose recorded pipeline activity spans the longest wall — the
    fleet finishes when its slowest member does, so the longest-running
    shard IS the fleet's critical path (ties break toward higher
    limiting-stage utilization, then lower pid — every key is a total
    order, so the verdict is deterministic). Its **limiting stage**
    comes from running the PR 7 attributor over that process's digest.

    The **straggler scoreboard** ranks every pid: achieved B/s vs the
    fleet median, lapse/degraded/distrusted status (from ``statuses``,
    typically the executor's heartbeat view), and adoption debt — the
    planned-but-undone units of an unavailable process that survivors
    must absorb. Pure function: trivially testable with synthetic
    digests, safe on any serving loop."""
    statuses = statuses or {}
    planned_units = planned_units or {}
    pids = sorted(set(digests) | set(statuses) | set(planned_units))
    if nproc is None:
        nproc = (max(pids) + 1) if pids else 0
    reports: dict[int, dict] = {}
    for pid in pids:
        d = digests.get(pid)
        if isinstance(d, dict):
            reports[pid] = attribute(digest_to_snapshot(d))
    rates = [
        reports[p]["pipeline_bps"]
        for p in sorted(reports)
        if reports[p]["pipeline_bps"]
    ]
    median = _median(rates)
    scoreboard = []
    totals = {
        "pieces_verified": 0,
        "units_done": 0,
        "bytes": 0,
        # Byzantine receipt plane (digest keys exist only at f > 0,
        # so these stay 0 on a trusted fabric)
        "audit_checks": 0,
        "audit_mismatches": 0,
        "convictions": 0,
    }
    for pid in sorted(set(range(nproc)) | set(pids)):
        digest = digests.get(pid) if isinstance(digests.get(pid), dict) else {}
        unit = digest.get("unit") or {}
        rep = reports.get(pid)
        status = statuses.get(pid) or ("ok" if rep is not None else "unreported")
        bps = rep["pipeline_bps"] if rep else None
        vs_median = (
            round(bps / median, 3) if bps and median else None
        )
        planned = planned_units.get(pid, int(unit.get("planned", 0)))
        done = int(unit.get("done", 0))
        row = {
            "pid": pid,
            "status": status,
            "achieved_bps": bps,
            "vs_median": vs_median,
            "straggler": bool(
                vs_median is not None and vs_median < STRAGGLER_RATIO
            ),
            "limiting_stage": (
                (rep.get("bottleneck") or {}).get("stage") if rep else None
            ),
            "wall_s": rep["wall_s"] if rep else 0.0,
            "units_done": done,
            "units_planned": planned,
            "units_adopted": int(unit.get("adopted", 0)),
            "pieces_verified": int(unit.get("pieces", 0)),
            "stragglers": int(unit.get("stragglers", 0)),
            "audit_checks": int(unit.get("audits", 0)),
            "audit_mismatches": int(unit.get("audit_miss", 0)),
            "convictions": int(unit.get("convict", 0)),
            "degraded": bool(unit.get("degraded"))
            or status == "degraded",
            # units a survivor must absorb when this process is out
            "adoption_debt": (
                max(0, planned - done)
                if status in ("lapsed", "degraded", "distrusted")
                else 0
            ),
        }
        scoreboard.append(row)
        totals["pieces_verified"] += row["pieces_verified"]
        totals["units_done"] += row["units_done"]
        totals["bytes"] += rep["pipeline_bytes"] if rep else 0
        totals["audit_checks"] += row["audit_checks"]
        totals["audit_mismatches"] += row["audit_mismatches"]
        totals["convictions"] += row["convictions"]
    # fleet bottleneck: longest activity wall wins (the straggler IS the
    # fleet's critical path); ties toward hotter limiting stage, then
    # lower pid (max keeps the first — lowest — pid on full ties)
    active = {
        p: rep for p, rep in reports.items() if rep.get("bottleneck")
    }
    bottleneck = None
    if active:
        limit = max(
            sorted(active),
            key=lambda p: (
                active[p]["wall_s"],
                active[p]["bottleneck"]["utilization"],
            ),
        )
        bn = active[limit]["bottleneck"]
        proc_bps = active[limit]["pipeline_bps"]
        bottleneck = {
            "pid": limit,
            "stage": bn["stage"],
            "utilization": bn["utilization"],
            "achieved_bps": bn["achieved_bps"],
            "process_bps": proc_bps,
            "wall_s": active[limit]["wall_s"],
            "fleet_median_bps": median,
            # headroom if the limiting process ran at the fleet median
            "headroom": (
                round(median / proc_bps, 2)
                if median and proc_bps
                else None
            ),
        }
    fleet_bps = round(sum(rates), 3) if rates else None
    # fleet-wide SLO budget health: the worst heartbeat-carried burn
    # rate across reporting processes (digests only carry an "slo"
    # field when that process armed an engine — obs/slo)
    slo_rows = {
        p: digests[p]["slo"]
        for p in sorted(digests)
        if isinstance(digests.get(p), dict)
        and isinstance(digests[p].get("slo"), dict)
    }
    slo = None
    if slo_rows:
        worst_pid = max(
            sorted(slo_rows), key=lambda p: slo_rows[p].get("burn") or 0.0
        )
        slo = {
            "pid": worst_pid,
            "objective": slo_rows[worst_pid].get("objective"),
            "worst_burn": slo_rows[worst_pid].get("burn"),
            "breaching": sum(
                1 for p in sorted(slo_rows) if slo_rows[p].get("breach")
            ),
        }
    return {
        "v": DIGEST_VERSION,
        "nproc": nproc,
        "reporting": len(reports),
        "bottleneck": bottleneck,
        "scoreboard": scoreboard,
        "slo": slo,
        "processes": {str(p): reports[p] for p in sorted(reports)},
        "totals": {**totals, "fleet_bps": fleet_bps},
        "digest_drops": int(digest_drops),
    }


def local_fleet_snapshot(scheduler=None, pid: int = 0) -> dict:
    """A fleet-of-one rollup from this process's own obs state — what
    the bridge's ``GET /v1/fleet`` serves when no fabric job is running,
    so the route (and ``top --fleet``) always answers."""
    roll = aggregate_fleet({pid: obs_digest(scheduler=scheduler)})
    roll["pid"] = pid
    roll["state"] = "local"
    return roll


# ----------------------------------------------------------------- server


class FleetObsServer:
    """``GET /v1/fleet`` (JSON rollup) + ``GET /metrics`` (Prometheus,
    fleet series included) for one fabric worker process.

    The bridge already serves both routes; this is the same surface for
    CLI workers (``fabric-verify --obs-port``), so ``doctor --fleet``
    can ask worker B which peer limits the fleet while the sweep runs.
    ``executor_ref`` is a zero-arg callable returning the live
    :class:`~torrent_tpu.fabric.FabricExecutor` (or ``None`` before the
    sweep starts — the route then serves the local fleet-of-one).
    Loopback-only by default, same trust model as the bridge."""

    def __init__(self, executor_ref, scheduler=None, host: str = "127.0.0.1"):
        self._executor_ref = executor_ref
        self.scheduler = scheduler
        self.host = host
        self.port: int | None = None
        self._server = None
        self._handlers: set = set()

    def snapshot(self) -> dict:
        ex = self._executor_ref() if callable(self._executor_ref) else None
        if ex is not None:
            return ex.fleet_snapshot()
        return local_fleet_snapshot(self.scheduler)

    async def start(self, port: int = 0) -> "FleetObsServer":
        import asyncio

        self._server = await asyncio.start_server(
            self._accept, self.host, port
        )
        self.port = self._server.sockets[0].getsockname()[1]
        return self

    def _accept(self, reader, writer):
        import asyncio

        task = asyncio.ensure_future(self._handle(reader, writer))
        self._handlers.add(task)
        task.add_done_callback(self._handlers.discard)

    def close(self) -> None:
        if self._server is not None:
            self._server.close()
        for task in list(self._handlers):
            task.cancel()

    def _metrics_text(self) -> str:
        from torrent_tpu.obs import render_obs_metrics
        from torrent_tpu.utils.metrics import (
            render_fabric_metrics,
            render_fleet_metrics,
            render_sched_metrics,
        )

        text = ""
        if self.scheduler is not None:
            text += render_sched_metrics(self.scheduler)
        ex = self._executor_ref() if callable(self._executor_ref) else None
        if ex is not None:
            text += render_fabric_metrics(ex.metrics_snapshot())
        text += render_fleet_metrics(self.snapshot())
        text += render_obs_metrics()
        return text

    async def _handle(self, reader, writer):
        import asyncio

        try:
            request = await asyncio.wait_for(reader.readline(), timeout=10)
            while True:
                line = await asyncio.wait_for(reader.readline(), timeout=10)
                if line in (b"\r\n", b"\n", b""):
                    break
            parts = request.split()
            path = parts[1].split(b"?")[0] if len(parts) >= 2 else b""
            if parts and parts[0] == b"GET" and path == b"/v1/fleet":
                body = json.dumps(self.snapshot(), sort_keys=True).encode()
                status, ctype = "200 OK", "application/json"
            elif parts and parts[0] == b"GET" and path == b"/metrics":
                body = self._metrics_text().encode()
                status = "200 OK"
                ctype = "text/plain; version=0.0.4; charset=utf-8"
            else:
                body, status, ctype = b"not found\n", "404 Not Found", "text/plain"
            writer.write(
                (
                    f"HTTP/1.1 {status}\r\nContent-Type: {ctype}\r\n"
                    f"Content-Length: {len(body)}\r\nConnection: close\r\n\r\n"
                ).encode("latin-1")
                + body
            )
            await writer.drain()
        except (ConnectionError, asyncio.TimeoutError, ValueError, OSError):
            pass
        finally:
            writer.close()
