"""Always-on span/event tracer for the ticket lifecycle.

The stack's five planes (scheduler, faults/breaker, pallas fast path,
fabric, sanitizer) interact per *ticket*, but until now the only way to
attribute an end-to-end latency to a stage was bench archaeology
(BENCH_r05: the 60 GiB/s plane collapsing to 3.1 p/s end-to-end had to
be diagnosed by hand). The tracer records one bounded span tree per
trace:

* **Trace IDs are minted at the bridge** — an ``X-Trace-Id`` request
  header is honored (and echoed back), otherwise the bridge mints one —
  and threaded through the scheduler ticket lifecycle (enqueue →
  admission/shed → lane wait → launch/retry/bisect → digest/verdict)
  via the submission, not contextvars: lane assembler tasks and worker
  threads are long-lived and never inherit a request's context.
* **Fabric trace IDs are deterministic** (plan fingerprint + pid, see
  :func:`fabric_trace_id`) so every process in a pod names the same
  sweep the same way without exchanging random bytes — the heartbeat
  span context (:func:`heartbeat_span_context`) stays inside the
  analysis plane's determinism pass.
* **Monotonic-only timestamps.** Spans carry ``time.monotonic()``
  start/end; serialization emits offsets relative to the trace's first
  span, so durations are non-negative by construction and no wall-clock
  ever reaches exchanged or dumped bytes.

Bounded everywhere: traces are LRU-evicted past ``max_traces``, spans
per trace are capped (a drop counter replaces the tail), and a small
global ring of recently finished spans feeds the flight recorder.
All mutation is behind a :func:`~torrent_tpu.analysis.sanitizer.
named_lock`; no other named lock is ever acquired while holding it.
"""

from __future__ import annotations

import contextlib
import contextvars
import os
import time
from collections import OrderedDict, deque

from torrent_tpu.analysis.sanitizer import named_lock

__all__ = [
    "Span",
    "Tracer",
    "fabric_trace_id",
    "heartbeat_span_context",
    "tracer",
]

# current (trace_id, span_id) for the running task/thread; to_thread and
# task creation copy the context, so bridge request handlers propagate
# it naturally into their own awaits — but NOT into the scheduler's
# long-lived lane tasks, which is why submissions carry context explicitly
_current: contextvars.ContextVar[tuple[str, str] | None] = contextvars.ContextVar(
    "torrent_tpu_obs_span", default=None
)

MAX_TRACES = 256
MAX_SPANS_PER_TRACE = 256
RECENT_SPANS = 256
MAX_ATTR_STR = 200

_ID_OK = frozenset(
    "abcdefghijklmnopqrstuvwxyzABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789._-"
)


def valid_trace_id(raw: str) -> bool:
    """Client-supplied trace ids are tokens, not free text: 1..64 chars
    of ``[A-Za-z0-9._-]`` (anything else would leak header bytes into
    logs, JSON dumps, and Prometheus exemplars)."""
    return 0 < len(raw) <= 64 and all(c in _ID_OK for c in raw)


def _clean_attr(value):
    """Span attrs are scalars only — payload bytes must never enter the
    trace store (the flight recorder dumps it verbatim)."""
    if isinstance(value, bool) or value is None:
        return value
    if isinstance(value, (int, float)):
        return value
    if isinstance(value, (bytes, bytearray, memoryview)):
        return f"<{len(value)} bytes>"
    s = str(value)
    return s if len(s) <= MAX_ATTR_STR else s[: MAX_ATTR_STR - 1] + "…"


class Span:
    """One stage of one trace: monotonic [t0, t1] plus scalar attrs."""

    __slots__ = (
        "trace_id", "span_id", "parent_id", "name", "t0", "t1", "status",
        "attrs",
    )

    def __init__(self, trace_id, span_id, parent_id, name, t0, t1, status, attrs):
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.name = name
        self.t0 = t0
        self.t1 = t1
        self.status = status
        self.attrs = attrs

    def to_dict(self, epoch: float | None = None) -> dict:
        """JSON-ready form. ``epoch`` (the trace's first span start)
        turns raw monotonic stamps into relative offsets — the only
        time representation that is meaningful across a dump."""
        base = self.t0 if epoch is None else epoch
        return {
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "name": self.name,
            "start_ms": round((self.t0 - base) * 1e3, 3),
            "duration_ms": round(max(0.0, self.t1 - self.t0) * 1e3, 3),
            "status": self.status,
            "attrs": dict(sorted(self.attrs.items())),
        }


class Tracer:
    """Bounded per-process trace store. One global instance
    (:func:`tracer`) serves the bridge, scheduler, and fabric; tests may
    construct private ones."""

    def __init__(
        self,
        max_traces: int = MAX_TRACES,
        max_spans_per_trace: int = MAX_SPANS_PER_TRACE,
    ):
        self._lock = named_lock("obs.tracer._lock")
        self._max_traces = max_traces
        self._max_spans = max_spans_per_trace
        # trace_id -> list[Span], LRU order (most recently touched last)
        self._traces: OrderedDict[str, list[Span]] = OrderedDict()
        self._dropped: dict[str, int] = {}
        self._recent: deque[Span] = deque(maxlen=RECENT_SPANS)
        self._minted = 0
        self._next_span = 0
        self.spans_total = 0

    # ------------------------------------------------------------- ids

    def mint(self) -> str:
        """A fresh trace id (bridge-side; fabric ids come from
        :func:`fabric_trace_id` so they stay deterministic)."""
        with self._lock:
            self._minted += 1
            n = self._minted
        return f"t{n:x}-{os.urandom(4).hex()}"

    def _span_id(self) -> str:
        # caller holds self._lock
        self._next_span += 1
        return f"s{self._next_span:x}"

    # --------------------------------------------------------- context

    @staticmethod
    def current_context() -> tuple[str, str] | None:
        """(trace_id, span_id) of the active span in this task, or None."""
        return _current.get()

    @contextlib.contextmanager
    def span(self, name: str, trace_id: str | None = None, **attrs):
        """Run a stage under a span. With ``trace_id`` this starts (or
        continues) that trace as a root-or-current child; without one it
        nests under the current context, or no-ops when there is none —
        the zero-cost path for untraced callers."""
        ctx = _current.get()
        parent_id = None
        if trace_id is None:
            if ctx is None:
                yield None
                return
            trace_id, parent_id = ctx
        elif ctx is not None and ctx[0] == trace_id:
            parent_id = ctx[1]
        t0 = time.monotonic()
        with self._lock:
            span_id = self._span_id()
        token = _current.set((trace_id, span_id))
        status = "ok"
        clean = {k: _clean_attr(v) for k, v in attrs.items()}
        try:
            yield span_id
        except BaseException as e:
            status = "error"
            clean["error"] = _clean_attr(repr(e))
            raise
        finally:
            _current.reset(token)
            self._store(
                Span(trace_id, span_id, parent_id, name, t0, time.monotonic(),
                     status, clean)
            )

    def add_span(
        self,
        trace_id: str,
        name: str,
        parent_id: str | None = None,
        t0: float | None = None,
        t1: float | None = None,
        status: str = "ok",
        **attrs,
    ) -> str:
        """Record a finished span explicitly (the scheduler/fabric path:
        stage boundaries are known timestamps, not ``with`` scopes).
        Returns the new span id, usable as a later stage's parent."""
        now = time.monotonic()
        t0 = now if t0 is None else t0
        t1 = max(t0, now if t1 is None else t1)
        clean = {k: _clean_attr(v) for k, v in attrs.items()}
        with self._lock:
            span_id = self._span_id()
        self._store(Span(trace_id, span_id, parent_id, name, t0, t1, status, clean))
        return span_id

    # ----------------------------------------------------------- store

    def _store(self, span: Span) -> None:
        with self._lock:
            self.spans_total += 1
            spans = self._traces.get(span.trace_id)
            if spans is None:
                spans = self._traces[span.trace_id] = []
                while len(self._traces) > self._max_traces:
                    evicted, _ = self._traces.popitem(last=False)
                    self._dropped.pop(evicted, None)
            else:
                self._traces.move_to_end(span.trace_id)
            if len(spans) >= self._max_spans:
                # keyed only by traces live in _traces and popped when
                # they evict — cardinality rides the trace ring's cap
                self._dropped[span.trace_id] = (  # bounded-by: _max_traces
                    self._dropped.get(span.trace_id, 0) + 1
                )
            else:
                spans.append(span)
            self._recent.append(span)

    # ---------------------------------------------------------- output

    def trace_ids(self) -> list[str]:
        with self._lock:
            return list(self._traces)

    def get_trace(self, trace_id: str) -> list[Span]:
        """The trace's finished spans, ordered by start time."""
        with self._lock:
            spans = list(self._traces.get(trace_id, ()))
        return sorted(spans, key=lambda s: (s.t0, s.span_id))

    def trace_tree(self, trace_id: str) -> dict | None:
        """Ordered span tree (JSON-ready): children nested under their
        parents, siblings ordered by start time, offsets relative to
        the trace's first span so durations read monotonically."""
        spans = self.get_trace(trace_id)
        if not spans:
            return None
        epoch = spans[0].t0
        nodes = {s.span_id: {**s.to_dict(epoch), "children": []} for s in spans}
        roots = []
        for s in spans:
            node = nodes[s.span_id]
            parent = nodes.get(s.parent_id) if s.parent_id else None
            (parent["children"] if parent else roots).append(node)
        with self._lock:
            dropped = self._dropped.get(trace_id, 0)
        return {
            "trace_id": trace_id,
            "span_count": len(spans),
            "dropped_spans": dropped,
            "spans": roots,
        }

    def recent_spans(self) -> list[dict]:
        """The global finished-span ring (the flight recorder's 'last N
        things that happened'), oldest first."""
        with self._lock:
            spans = list(self._recent)
        if not spans:
            return []
        epoch = min(s.t0 for s in spans)
        return [s.to_dict(epoch) for s in spans]

    def clear(self) -> None:
        with self._lock:
            self._traces.clear()
            self._dropped.clear()
            self._recent.clear()


# ------------------------------------------------------ fabric context


# determinism-scope
def fabric_trace_id(plan_fingerprint: str, pid: int) -> str:
    """Deterministic fabric trace id: every process derives it from the
    plan fingerprint it already agrees on, so no random bytes need to
    cross the heartbeat."""
    return f"fabric-{plan_fingerprint[:12]}-p{pid}"


# determinism-scope
def heartbeat_span_context(trace_id: str, seq: int) -> dict:
    """The span context a fabric heartbeat payload carries. In the
    analysis plane's determinism scope: literal keys, monotonic-free,
    random-free — exchanged bytes must be identical across re-runs."""
    return {"seq": seq, "trace": trace_id}


_tracer = None


def tracer() -> Tracer:
    """The process-wide tracer (constructed on first use, so TSAN
    enabling in conftest instruments its lock)."""
    global _tracer
    if _tracer is None:
        _tracer = Tracer()
    return _tracer
