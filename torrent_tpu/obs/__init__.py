"""torrent_tpu.obs — the observability plane.

Three tiers over the same ticket lifecycle, cheapest first:

1. **Latency histograms** (``obs/hist``) and the **pipeline ledger**
   (``obs/ledger`` + ``obs/attrib``): always-on fixed-log2-bucket
   per-stage distributions (queue wait, launch, end-to-end per tenant,
   bridge request) plus byte/time/occupancy accounting at every
   pipeline stage boundary (read → stage → h2d → launch → digest →
   verdict) feeding a bottleneck attributor — rendered as real
   Prometheus series on every ``/metrics`` scrape and served as
   ``GET /v1/pipeline`` / ``torrent-tpu top`` / ``doctor
   --bottleneck``.
2. **Span tracer** (``obs/tracer``): per-trace span trees — trace IDs
   minted at the bridge (``X-Trace-Id`` honored/emitted), threaded
   through the scheduler's ticket lifecycle and the fabric's units,
   served by ``GET /v1/trace?id=…``.
3. **Profiler** (``obs/profiler``): ``jax.profiler`` device-timeline
   capture of the first N batches (``TORRENT_TPU_PROFILE``), the
   deep-dive tier.

Plus the **fleet plane** (``obs/fleet``): a compact deterministic
per-process obs digest carried on every fabric heartbeat, merged into a
swarm-wide rollup with two-level bottleneck attribution (limiting
process → its limiting stage) and a straggler scoreboard — served as
``GET /v1/fleet``, ``torrent_tpu_fleet_*`` Prometheus series,
``torrent-tpu top --fleet``, and ``doctor --fleet``.

Plus the **flight recorder** (``obs/recorder``): a bounded ring of
recent spans + component snapshots, dumped as redacted black-box JSON
on breaker-open, retry-exhausted failure, fabric distrust, or an
observed lock-order cycle — ``GET /v1/trace``, ``torrent-tpu trace
dump``, ``doctor --trace``.

Everything here locks via ``analysis.sanitizer.named_lock`` (obs locks
are leaves of the lock-order graph) and keeps exchanged/dumped bytes
deterministic: monotonic-only timestamps, sorted keys.
"""

from torrent_tpu.obs.attrib import attribute, format_report
from torrent_tpu.obs.fleet import (
    DIGEST_MAX_BYTES,
    aggregate_fleet,
    local_fleet_snapshot,
    obs_digest,
)
from torrent_tpu.obs.hist import (
    HistogramRegistry,
    LogHistogram,
    histograms,
    merge_snapshots,
)
from torrent_tpu.obs.ledger import (
    PIPELINE_STAGES,
    PipelineLedger,
    pipeline_ledger,
    render_pipeline_metrics,
)
from torrent_tpu.obs.recorder import FlightRecorder, flight_recorder
from torrent_tpu.obs.swarm import (
    SwarmTelemetry,
    build_swarm_snapshot,
    swarm_telemetry,
)
from torrent_tpu.obs.slo import (
    SloEngine,
    SloObjective,
    build_health,
    evaluate_slo,
    parse_objectives,
)
from torrent_tpu.obs.timeline import (
    Timeline,
    TimelineSampler,
    build_sample,
    replay_report,
)
from torrent_tpu.obs.tracer import (
    Span,
    Tracer,
    fabric_trace_id,
    heartbeat_span_context,
    tracer,
    valid_trace_id,
)

__all__ = [
    "DIGEST_MAX_BYTES",
    "FlightRecorder",
    "HistogramRegistry",
    "LogHistogram",
    "PIPELINE_STAGES",
    "PipelineLedger",
    "SloEngine",
    "SloObjective",
    "Span",
    "SwarmTelemetry",
    "Timeline",
    "TimelineSampler",
    "Tracer",
    "aggregate_fleet",
    "build_swarm_snapshot",
    "attribute",
    "build_health",
    "build_sample",
    "evaluate_slo",
    "parse_objectives",
    "replay_report",
    "fabric_trace_id",
    "flight_recorder",
    "format_report",
    "heartbeat_span_context",
    "histograms",
    "local_fleet_snapshot",
    "merge_snapshots",
    "obs_digest",
    "pipeline_ledger",
    "render_obs_metrics",
    "render_pipeline_metrics",
    "swarm_telemetry",
    "tracer",
    "valid_trace_id",
]


def render_obs_metrics() -> str:
    """The obs plane's /metrics contribution: every latency-histogram
    family, the pipeline ledger's per-stage series + bottleneck verdict,
    the swarm wire-plane families (``torrent_tpu_swarm_*`` + bounded
    ``torrent_tpu_peer_*``), the seeder plane's ``torrent_tpu_serve_*``
    (only once this process has actually served — tracker-only scrapes
    stay lean), and the flight-recorder dump counters. Appended by both
    the bridge's ``/metrics`` and the session ``MetricsServer``."""
    from torrent_tpu.serve_plane.telemetry import serve_telemetry
    from torrent_tpu.utils.metrics import (
        render_serve_metrics,
        render_swarm_metrics,
    )

    serve_obs = serve_telemetry()
    return (
        histograms().render()
        + render_pipeline_metrics()
        + render_swarm_metrics(swarm_telemetry().snapshot())
        + (
            render_serve_metrics(serve_obs.snapshot())
            if serve_obs.active()
            else ""
        )
        + flight_recorder().render_metrics()
    )
