"""torrent_tpu.obs — the observability plane.

Three tiers over the same ticket lifecycle, cheapest first:

1. **Latency histograms** (``obs/hist``): always-on fixed-log2-bucket
   per-stage distributions (queue wait, launch, end-to-end per tenant,
   bridge request), rendered as real Prometheus histogram series on
   every ``/metrics`` scrape.
2. **Span tracer** (``obs/tracer``): per-trace span trees — trace IDs
   minted at the bridge (``X-Trace-Id`` honored/emitted), threaded
   through the scheduler's ticket lifecycle and the fabric's units,
   served by ``GET /v1/trace?id=…``.
3. **Profiler** (``obs/profiler``): ``jax.profiler`` device-timeline
   capture of the first N batches (``TORRENT_TPU_PROFILE``), the
   deep-dive tier.

Plus the **flight recorder** (``obs/recorder``): a bounded ring of
recent spans + component snapshots, dumped as redacted black-box JSON
on breaker-open, retry-exhausted failure, fabric distrust, or an
observed lock-order cycle — ``GET /v1/trace``, ``torrent-tpu trace
dump``, ``doctor --trace``.

Everything here locks via ``analysis.sanitizer.named_lock`` (obs locks
are leaves of the lock-order graph) and keeps exchanged/dumped bytes
deterministic: monotonic-only timestamps, sorted keys.
"""

from torrent_tpu.obs.hist import HistogramRegistry, LogHistogram, histograms
from torrent_tpu.obs.recorder import FlightRecorder, flight_recorder
from torrent_tpu.obs.tracer import (
    Span,
    Tracer,
    fabric_trace_id,
    heartbeat_span_context,
    tracer,
    valid_trace_id,
)

__all__ = [
    "FlightRecorder",
    "HistogramRegistry",
    "LogHistogram",
    "Span",
    "Tracer",
    "fabric_trace_id",
    "flight_recorder",
    "heartbeat_span_context",
    "histograms",
    "render_obs_metrics",
    "tracer",
    "valid_trace_id",
]


def render_obs_metrics() -> str:
    """The obs plane's /metrics contribution: every latency-histogram
    family plus the flight-recorder dump counters. Appended by both the
    bridge's ``/metrics`` and the session ``MetricsServer``."""
    return histograms().render() + flight_recorder().render_metrics()
