"""Deep-dive profiler tier above the always-on tracer.

The obs plane has three tiers: always-on latency histograms
(``obs/hist``), per-ticket span tracing (``obs/tracer``), and — this
module — full ``jax.profiler`` device-timeline capture of the first N
hash batches, the heavyweight tool for kernel-level work (XProf /
TensorBoard). Moved here from ``utils/trace.py`` (which remains as a
shim) when the obs plane landed.

Set ``TORRENT_TPU_PROFILE=/some/dir`` to capture;
``TORRENT_TPU_PROFILE_BATCHES`` (default 8) bounds how many batches the
trace spans. Both env knobs are resolved **lazily per call** — enabling
the profiler after the module was imported (a long-lived sidecar, a
test toggling it) works, where the old import-time read silently
ignored it.
"""

from __future__ import annotations

import contextlib
import os

from torrent_tpu.utils.log import get_logger

log = get_logger("obs.profiler")

_PROFILE_ENV = "TORRENT_TPU_PROFILE"
_BATCHES_ENV = "TORRENT_TPU_PROFILE_BATCHES"

_trace_started = False
_trace_done = False  # capture happens once; later batches run unprofiled
_batches_seen = 0


def profile_dir() -> str | None:
    """Where to write the capture, or None when profiling is off.
    Read from the environment on every call — never cached at import."""
    return os.environ.get(_PROFILE_ENV) or None


def profile_batches() -> int:
    """How many batches the capture spans (invalid values fall back
    to the default rather than raising on the hot path)."""
    raw = os.environ.get(_BATCHES_ENV, "")
    try:
        n = int(raw) if raw else 8
    except ValueError:
        return 8
    return n if n > 0 else 8


def _flush_trace() -> None:
    """Stop an open trace (idempotent); registered atexit once started."""
    global _trace_started, _trace_done
    if _trace_started:
        import jax

        try:
            jax.profiler.stop_trace()
        except Exception:
            pass
        _trace_started = False
        _trace_done = True
        log.info("profiler trace flushed at exit")


@contextlib.contextmanager
def annotate(name: str):
    """Named region in the device timeline (no-op off-device)."""
    import jax

    with jax.profiler.TraceAnnotation(name):
        yield


@contextlib.contextmanager
def maybe_profile_batch(name: str):
    """Profile the first N hash batches when TORRENT_TPU_PROFILE is set."""
    global _trace_started, _batches_seen, _trace_done
    import jax

    trace_dir = profile_dir()
    if trace_dir is None or _trace_done:
        with jax.profiler.TraceAnnotation(name):
            yield
        return
    if not _trace_started:
        jax.profiler.start_trace(trace_dir)
        _trace_started = True
        # Runs with fewer than N batches would otherwise exit with the
        # trace open and unflushed — close it at interpreter exit.
        import atexit

        atexit.register(_flush_trace)
        log.info("profiler trace started → %s", trace_dir)
    _batches_seen += 1
    try:
        with jax.profiler.TraceAnnotation(name):
            yield
    finally:
        if _batches_seen >= profile_batches() and _trace_started:
            jax.profiler.stop_trace()
            _trace_started = False
            _trace_done = True
            log.info("profiler trace stopped after %d batches", _batches_seen)
