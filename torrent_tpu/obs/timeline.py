"""Timeline ring: the observability plane's history tier.

Every surface built so far (tracer, ledger/attributor, fleet digests,
autopilot decisions) is *instantaneous*: the system can name its
bottleneck right now but cannot say whether it has been degrading for
the last ten minutes, or what was limiting five minutes before a crash.
This module adds the missing axis — time:

* :func:`build_sample` — one compact, bounded snapshot of the whole
  observability plane at a single monotonic instant: pipeline-ledger
  stage counters, a scheduler summary (shed/faults/breaker states/
  fill), latency-histogram family summaries, integrity counters
  (breaker-open transitions, lockset races, distrust events), plus
  optional control/fleet/tracker facts. Pure function of already-taken
  snapshots — it sits in the analysis plane's determinism pass like the
  fleet digest builders, so a sample's bytes are bit-stable given the
  same inputs. Counters are CUMULATIVE; consumers (the SLO engine, the
  replay attributor) delta consecutive samples.
* :class:`Timeline` — a fixed-depth ring of samples behind ONE leaf
  :func:`named_lock` (never held while a snapshot is taken), with a
  drop counter when the ring wraps — the same cardinality/bounding
  discipline as the fleet digest.
* :class:`TimelineSampler` — an off-loop periodic sampler (a daemon
  thread, so capture never stalls a serving loop), dumping the ring to
  ``TORRENT_TPU_TIMELINE_DIR`` for post-mortems. ``sample_once()`` is
  public so tests and doctor drive sampling deterministically.
* :func:`replay_report` — offline replay: the PR 7 attributor run over
  the HISTORICAL deltas between ring samples, so "what was limiting at
  T-5m" is answerable after the process is gone (``torrent-tpu replay
  <file>``).

Overhead when off is zero: nothing here is constructed unless a caller
arms it (``bridge --slo``, ``torrent-tpu serve --slo``, a test).
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque

from torrent_tpu.analysis.sanitizer import guard_attrs, named_lock
from torrent_tpu.obs.fleet import _digest_hist, _digest_sched, _digest_stages
from torrent_tpu.obs.hist import histograms
from torrent_tpu.obs.ledger import pipeline_ledger
from torrent_tpu.utils.log import get_logger

log = get_logger("obs.timeline")

__all__ = [
    "DEFAULT_DEPTH",
    "DEFAULT_INTERVAL_S",
    "TIMELINE_DIR_ENV",
    "TIMELINE_VERSION",
    "Timeline",
    "TimelineSampler",
    "build_sample",
    "replay_report",
    "sample_now",
]

TIMELINE_VERSION = 1
# ring depth: at the default 1 s cadence this is ~8.5 minutes of
# history in ~a few hundred KiB of dicts — bounded however long the
# process lives (older samples fall off; the drop counter says so)
DEFAULT_DEPTH = 512
DEFAULT_INTERVAL_S = 1.0
# dump the ring to disk every N appended samples (plus once at stop),
# so a crash loses at most one dump interval of history
DUMP_EVERY = 32

TIMELINE_DIR_ENV = "TORRENT_TPU_TIMELINE_DIR"

# histogram families a sample summarizes (short key -> family name):
# the SLO latency objectives evaluate p99 targets over these
SAMPLE_HIST_FAMILIES = (
    ("queue_wait", "torrent_tpu_sched_queue_wait_seconds"),
    ("launch", "torrent_tpu_sched_launch_seconds"),
    ("request", "torrent_tpu_bridge_request_seconds"),
    # the swarm wire tier (obs/swarm): block round-trip times, so
    # `p99_ms=…:block_rtt` objectives page on a slow swarm
    ("block_rtt", "torrent_tpu_swarm_block_rtt_seconds"),
)

# per-process run token in dump filenames, same rationale as the flight
# recorder's: a restarted process must not overwrite the previous run's
# post-mortem evidence. Wall clock is fine — filenames never enter
# deterministic or exchanged bytes.
_RUN_TOKEN = f"{int(time.time()):x}-{os.getpid():x}"


# --------------------------------------------------------------- builders
# (analysis determinism pass scope, like the fleet digest builders: no
# wall clock, no randomness, sorted iteration — the monotonic instant is
# PASSED IN by the sampler, never read here)


def _num(value, default: float = 0.0) -> float:
    """Defensive float: replay/fuzz feed arbitrary JSON back through
    these helpers, so a missing/NaN/str field reads as ``default``."""
    try:
        f = float(value)
    except (TypeError, ValueError):
        return default
    return f if f == f and abs(f) != float("inf") else default


# determinism-scope
def _integrity_counters(sched_snap: dict, tsan_snap: dict | None, distrust: int) -> dict:
    """Cumulative integrity-event counters: breaker open-transitions,
    currently-open lanes, lockset races, distrust events. Any of these
    burns the integrity SLO budget instantly (obs/slo)."""
    opens = 0
    open_lanes = 0
    breakers = (sched_snap or {}).get("breakers") or {}
    for lane in sorted(breakers):
        b = breakers[lane] or {}
        if b.get("state") in ("open", "half_open"):
            open_lanes += 1
        transitions = b.get("transitions") or {}
        for key in sorted(transitions):
            if key.endswith("->open"):
                opens += int(_num(transitions[key]))
    return {
        "breaker_opens": opens,
        "open_lanes": open_lanes,
        "races": int(_num((tsan_snap or {}).get("lockset_race_count"))),
        "distrust": int(_num(distrust)),
    }


# determinism-scope
def _sample_sched(sched_snap: dict) -> dict:
    """The fleet digest's scheduler summary plus the two extra counters
    the SLO availability objective needs: total served pieces (the
    denominator) and the admission actuator's current factor."""
    out = _digest_sched(sched_snap or {})
    tenants = (sched_snap or {}).get("tenants") or {}
    evicted = (sched_snap or {}).get("evicted") or {}
    evicted = evicted if isinstance(evicted, dict) else {}
    # the availability denominator must be CUMULATIVE: live tenants'
    # served pieces PLUS the pieces of idle tenants the scheduler has
    # since evicted — without the evicted share, an eviction makes the
    # counter drop and the window delta goes wrong in both directions
    # (a real burst reads as zero events, a benign eviction reads as a
    # false fast burn)
    out["pieces"] = sum(
        int(_num(tenants[name].get("served_pieces")))
        for name in sorted(tenants)
        if isinstance(tenants[name], dict)
    ) + int(_num(evicted.get("served_pieces")))
    out["admission_factor"] = round(
        _num((sched_snap or {}).get("admission_factor"), 1.0), 4
    )
    return out


# determinism-scope
def build_sample(
    t_mono: float,
    ledger_snap: dict,
    sched_snap: dict | None = None,
    hist_snaps: dict | None = None,
    tsan_snap: dict | None = None,
    control: dict | None = None,
    fleet: dict | None = None,
    tracker: dict | None = None,
    swarm: dict | None = None,
    distrust: int = 0,
) -> dict:
    """Assemble one timeline sample from already-taken snapshots.

    All counters are cumulative (consumers delta consecutive samples);
    ``t_mono`` is the capture instant on the local monotonic clock —
    meaningful only as a difference between samples, never wall time.
    """
    ledger_snap = ledger_snap or {}
    overlap = ledger_snap.get("overlap") or {}
    sample = {
        "v": TIMELINE_VERSION,
        "t": round(_num(t_mono), 6),
        "stages": _digest_stages(ledger_snap.get("stages") or {}),
        "overlap_s": round(_num(overlap.get("busy_s")), 6),
        "sched": _sample_sched(sched_snap or {}),
        "hist": _digest_hist(hist_snaps or {}),
        "integrity": _integrity_counters(sched_snap or {}, tsan_snap, distrust),
    }
    if control:
        sample["control"] = {
            "stage": control.get("stage"),
            "confirmed": bool(control.get("confirmed")),
        }
    if fleet:
        sample["fleet"] = {
            "pid": fleet.get("pid"),
            "stage": fleet.get("stage"),
        }
    if tracker:
        sample["tracker"] = {
            "announces": int(_num(tracker.get("announces"))),
            "peers": int(_num(tracker.get("peers"))),
            "swarms": int(_num(tracker.get("swarms"))),
        }
    if swarm:
        # the swarm wire tier (obs/swarm.sample_summary): cumulative
        # counters the swarm SLO objectives delta — bytes/blocks for the
        # download-rate floor, snubs/blocks for the snub-ratio budget
        sample["swarm"] = {
            "peers": int(_num(swarm.get("peers"))),
            "snubbed": int(_num(swarm.get("snubbed"))),
            "bytes_down": int(_num(swarm.get("bytes_down"))),
            "bytes_up": int(_num(swarm.get("bytes_up"))),
            "blocks": int(_num(swarm.get("blocks"))),
            "snubs": int(_num(swarm.get("snubs"))),
            "announce_failed": int(_num(swarm.get("announce_failed"))),
            "all_choked": int(_num(swarm.get("all_choked"))),
        }
    return sample


def sample_now(
    scheduler=None,
    control: dict | None = None,
    fleet: dict | None = None,
    tracker: dict | None = None,
    distrust: int = 0,
) -> dict:
    """Capture one sample from the process-global obs state (plus
    ``scheduler`` when given). Reads the monotonic clock and every leaf
    snapshot OUTSIDE any timeline lock."""
    from torrent_tpu.analysis import sanitizer

    reg = histograms()
    hist_snaps = {}
    for short, family in SAMPLE_HIST_FAMILIES:
        hist_snaps[short] = reg.family_snapshot(family)
    sched_snap = scheduler.metrics_snapshot() if scheduler is not None else {}
    tsan_snap = sanitizer.snapshot() if sanitizer.is_enabled() else None
    from torrent_tpu.obs.swarm import swarm_telemetry

    # None until the process ever saw a peer connection, so swarm-less
    # samples stay byte-identical to a pre-swarm-plane build
    swarm = swarm_telemetry().sample_summary()
    return build_sample(
        time.monotonic(),
        pipeline_ledger().snapshot(),
        sched_snap=sched_snap,
        hist_snaps=hist_snaps,
        tsan_snap=tsan_snap,
        control=control,
        fleet=fleet,
        tracker=tracker,
        swarm=swarm,
        distrust=distrust,
    )


# ------------------------------------------------------------------- ring


class Timeline:
    """Fixed-depth sample ring. One leaf lock taken only around the
    deque push/copy — never while a sample is being captured."""

    def __init__(self, depth: int = DEFAULT_DEPTH):
        self.depth = max(2, int(depth))
        self._lock = named_lock("obs.timeline._lock")
        # dynamic lockset checking: the ring + counters are one cell
        # guarded by _lock (the sampler thread appends, serving loops
        # snapshot)
        self._cells = guard_attrs("obs.timeline", "ring")
        self._ring: deque[dict] = deque(maxlen=self.depth)
        self._seq = 0
        self._drops = 0

    def push(self, sample: dict) -> int:
        with self._lock:
            self._cells.write("ring")
            self._seq += 1
            if len(self._ring) == self.depth:
                self._drops += 1
            self._ring.append({**sample, "seq": self._seq})
            return self._seq

    def snapshot(self) -> dict:
        """The ``GET /v1/timeline`` payload (and the dump file body)."""
        with self._lock:
            self._cells.read("ring")
            return {
                "v": TIMELINE_VERSION,
                "depth": self.depth,
                "seq": self._seq,
                "drops": self._drops,
                "samples": [dict(s) for s in self._ring],
            }

    def stats(self) -> dict:
        """Counters only — what the /metrics rendering needs. Unlike
        :meth:`snapshot` this never copies the sample dicts, so a hot
        Prometheus scrape path holds the leaf lock for O(1)."""
        with self._lock:
            self._cells.read("ring")
            return {
                "v": TIMELINE_VERSION,
                "depth": self.depth,
                "seq": self._seq,
                "drops": self._drops,
                "fill": len(self._ring),
            }

    def samples(self) -> list[dict]:
        with self._lock:
            self._cells.read("ring")
            return [dict(s) for s in self._ring]

    def tail_snapshot(self, n: int) -> dict:
        """Snapshot-shaped dict carrying only the newest ``n`` samples —
        what the SLO engine's windows actually read. Bounds the
        per-capture copy (and the leaf-lock hold) to the window size
        instead of the whole ring."""
        n = max(2, int(n))
        with self._lock:
            self._cells.read("ring")
            # refs only under the lock (O(depth) pointer copy); the
            # per-sample dict copies happen outside it, tail-bounded
            ring = list(self._ring)
            seq, drops = self._seq, self._drops
        tail = ring[-n:] if len(ring) > n else ring
        return {
            "v": TIMELINE_VERSION,
            "depth": self.depth,
            "seq": seq,
            "drops": drops,
            "samples": [dict(s) for s in tail],
        }

    def clear(self) -> None:
        with self._lock:
            self._cells.write("ring")
            self._ring.clear()
            self._seq = 0
            self._drops = 0


# ---------------------------------------------------------------- sampler


class TimelineSampler:
    """Off-loop periodic capture into a :class:`Timeline`.

    A daemon thread (not an asyncio task): snapshot capture takes the
    scheduler/ledger/histogram leaf locks and may contend briefly, and
    the serving loop must never pay for it. ``sources`` maps optional
    sample fields to zero-arg callables evaluated per capture (control
    status, fleet verdict, tracker facts, distrust count); a raising
    source is dropped from that sample, never kills the sampler.
    ``on_sample`` (the SLO engine's ``observe``) runs after each append
    with the fresh ring snapshot — tail-bounded to ``on_sample_tail``
    samples when set (pass the engine's long window: the evaluator
    never reads past it, so copying the whole ring per capture would be
    pure waste). When ``TORRENT_TPU_TIMELINE_DIR`` (or ``dump_dir``) is
    set, the ring is dumped atomically every :data:`DUMP_EVERY` samples
    and once at :meth:`stop` — the post-mortem file ``torrent-tpu
    replay`` reads."""

    def __init__(
        self,
        timeline: Timeline,
        interval_s: float = DEFAULT_INTERVAL_S,
        scheduler=None,
        sources: dict | None = None,
        on_sample=None,
        on_sample_tail: int | None = None,
        dump_dir: str | None = None,
    ):
        self.timeline = timeline
        self.interval_s = max(0.01, float(interval_s))
        self.scheduler = scheduler
        self.sources = dict(sources or {})
        self.on_sample = on_sample
        self.on_sample_tail = on_sample_tail
        self._dump_dir = dump_dir
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._since_dump = 0

    # --------------------------------------------------------- lifecycle

    def start(self) -> "TimelineSampler":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._run, name="tt-timeline-sampler", daemon=True
            )
            self._thread.start()
        return self

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout)
            self._thread = None
        self.dump()

    @property
    def alive(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    # ----------------------------------------------------------- capture

    def _source(self, name: str):
        fn = self.sources.get(name)
        if fn is None:
            return None
        try:
            return fn()
        except Exception as e:  # a broken source must not kill sampling
            log.warning("timeline source %s failed: %s", name, e)
            return None

    def sample_once(self) -> dict:
        """One capture → append → on_sample pass. Public so tests and
        ``doctor --slo`` drive the timeline deterministically instead of
        racing the thread's cadence."""
        distrust = self._source("distrust")
        sample = sample_now(
            scheduler=self.scheduler,
            control=self._source("control"),
            fleet=self._source("fleet"),
            tracker=self._source("tracker"),
            distrust=int(distrust) if distrust else 0,
        )
        self.timeline.push(sample)
        if self.on_sample is not None:
            try:
                self.on_sample(
                    self.timeline.tail_snapshot(self.on_sample_tail)
                    if self.on_sample_tail
                    else self.timeline.snapshot()
                )
            except Exception as e:  # the SLO hook must not kill sampling
                log.warning("timeline on_sample hook failed: %s", e)
        self._since_dump += 1
        if self._since_dump >= DUMP_EVERY:
            self.dump()
        return sample

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.sample_once()
            except Exception as e:  # a bad capture must not kill the loop
                log.warning("timeline capture failed: %s", e)

    # -------------------------------------------------------------- dump

    def dump(self) -> str | None:
        """Write the ring to the timeline dir (atomic replace). Returns
        the path, or None when no dir is configured / the write failed
        (best-effort: the in-memory ring still has everything)."""
        directory = self._dump_dir or os.environ.get(TIMELINE_DIR_ENV)
        self._since_dump = 0
        if not directory:
            return None
        snap = self.timeline.snapshot()
        try:
            os.makedirs(directory, exist_ok=True)
            path = os.path.join(directory, f"timeline_{_RUN_TOKEN}.json")
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(snap, f, sort_keys=True)
            os.replace(tmp, path)
            return path
        except OSError as e:
            log.warning("timeline dump to %s failed: %s", directory, e)
            return None


# ----------------------------------------------------------------- replay


# determinism-scope
def _sample_to_ledger(sample: dict) -> dict:
    """Reconstruct a ledger-shaped snapshot from one timeline sample so
    ``obs/attrib.attribute`` runs unchanged over HISTORICAL counters —
    the same trick the fleet rollup plays on peer digests."""
    stages = {}
    raw = sample.get("stages")
    raw = raw if isinstance(raw, dict) else {}
    for name in sorted(raw):
        s = raw[name] if isinstance(raw[name], dict) else {}
        stages[str(name)] = {
            "busy_s": _num(s.get("busy_s")),
            "bytes": int(_num(s.get("bytes"))),
            "ops": int(_num(s.get("ops"))),
            "active": 0,
            "max_active": 0,
        }
    t = _num(sample.get("t"))
    return {
        "t_first": None,
        "t_last": t,
        "t_snap": t,
        "overlap": {
            "busy_s": _num(sample.get("overlap_s")),
            "concurrent_stages": 0,
            "max_concurrent_stages": 0,
        },
        "stages": stages,
    }


# determinism-scope
def replay_report(timeline_snap: dict, objectives=None) -> dict:
    """Offline replay of a dumped (or fetched) timeline.

    Runs the PR 7 bottleneck attributor over the delta between every
    consecutive sample pair — so "what was limiting at T-5m" has the
    SAME answer the live attributor would have given — plus an overall
    first→last attribution and (optionally) the SLO evaluation over the
    ring. Pure function of the payload: usable long after the process
    that recorded it is gone."""
    from torrent_tpu.obs.attrib import attribute

    raw = timeline_snap.get("samples") if isinstance(timeline_snap, dict) else timeline_snap
    samples = [s for s in (raw or []) if isinstance(s, dict)]
    t_end = _num(samples[-1].get("t")) if samples else 0.0
    intervals = []
    for prev, cur in zip(samples, samples[1:]):
        rep = attribute(_sample_to_ledger(cur), prev=_sample_to_ledger(prev))
        bn = rep.get("bottleneck")
        intervals.append(
            {
                # age of this interval's END relative to the newest
                # sample: "T-300s" = five minutes before the dump
                "age_s": round(max(0.0, t_end - _num(cur.get("t"))), 3),
                "wall_s": rep.get("wall_s"),
                "limiting": bn.get("stage") if bn else None,
                "utilization": bn.get("utilization") if bn else None,
                "pipeline_bps": rep.get("pipeline_bps"),
                "sched": {
                    "shed": (cur.get("sched") or {}).get("shed", 0),
                    "failed_pieces": (cur.get("sched") or {}).get(
                        "failed_pieces", 0
                    ),
                },
            }
        )
    overall = None
    if len(samples) >= 2:
        overall = attribute(
            _sample_to_ledger(samples[-1]), prev=_sample_to_ledger(samples[0])
        )
    out = {
        "v": TIMELINE_VERSION,
        "samples": len(samples),
        "span_s": round(
            max(0.0, t_end - _num(samples[0].get("t"))), 3
        )
        if samples
        else 0.0,
        "drops": int(_num(timeline_snap.get("drops")))
        if isinstance(timeline_snap, dict)
        else 0,
        "intervals": intervals,
        "overall": overall,
    }
    if objectives is not None:
        from torrent_tpu.obs.slo import evaluate_slo

        out["slo"] = evaluate_slo(samples, objectives)
    return out
