"""Black-box flight recorder: bounded, always-armed, dump-on-fault.

When the plane misbehaves in production the evidence is usually gone by
the time anyone looks — the queue drained, the breaker re-closed, the
peer was distrusted an hour ago. The recorder keeps the last N finished
spans (the tracer's global ring) armed at all times and, on a trigger,
freezes a redacted JSON dump of them plus the component snapshots the
caller passes in:

* ``breaker_open``      — a lane breaker transitioned to open
* ``retry_exhausted``   — a launch failure outlived retry + bisection
* ``fabric_distrust``   — a sentinel cross-check rejected a peer's verdicts
* ``tsan_cycle``        — the runtime sanitizer observed a lock-order cycle

Each trigger produces exactly one dump (callers sit at the transition
point, not in a polling loop). Dumps are kept in a bounded ring,
served via ``GET /v1/trace`` and ``torrent-tpu trace dump``, surfaced
by ``doctor --trace``, and — when ``TORRENT_TPU_FLIGHT_DIR`` is set —
written to ``blackbox_<seq>.json`` off-thread so a crash right after
the fault still leaves the evidence on disk.

Redaction: span attrs are scalar-only by construction (tracer), and
:func:`_redact` walks every snapshot the caller passes — bytes become
length tags, long strings are truncated, depth is bounded — so piece
payloads or peer tokens can never reach a dump file.

The dump dict is assembled entirely OUTSIDE the recorder lock (and the
lock never wraps a tracer/sanitizer call), keeping the obs locks
leaves of the lock-order graph.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque

from torrent_tpu.analysis.sanitizer import named_lock
from torrent_tpu.utils.log import get_logger
from torrent_tpu.utils.metrics import _esc

log = get_logger("obs.recorder")

# per-process run token in dump filenames: a restarted process must not
# overwrite the PREVIOUS run's crash evidence (the post-mortem case the
# flight dir exists for). Wall clock is fine here — filenames never
# enter exchanged or deterministic bytes.
_RUN_TOKEN = f"{int(time.time()):x}-{os.getpid():x}"

__all__ = ["FlightRecorder", "flight_recorder"]

MAX_DUMPS = 16
MAX_REDACT_DEPTH = 6
MAX_REDACT_ITEMS = 128
MAX_REDACT_STR = 300

_FLIGHT_DIR_ENV = "TORRENT_TPU_FLIGHT_DIR"


def _redact(value, depth: int = 0):
    """JSON-safe, payload-free copy of an arbitrary snapshot dict."""
    if depth >= MAX_REDACT_DEPTH:
        return "<depth>"
    if isinstance(value, bool) or value is None:
        return value
    if isinstance(value, (int, float)):
        return value
    if isinstance(value, (bytes, bytearray, memoryview)):
        return f"<{len(value)} bytes>"
    if isinstance(value, str):
        return value if len(value) <= MAX_REDACT_STR else value[:MAX_REDACT_STR] + "…"
    if isinstance(value, dict):
        return {
            str(k): _redact(v, depth + 1)
            for k, v in list(value.items())[:MAX_REDACT_ITEMS]
        }
    if isinstance(value, (list, tuple, set, frozenset)):
        items = sorted(value, key=repr) if isinstance(value, (set, frozenset)) else value
        return [_redact(v, depth + 1) for v in list(items)[:MAX_REDACT_ITEMS]]
    return _redact(repr(value), depth + 1)


class FlightRecorder:
    """Bounded dump ring. One global instance (:func:`flight_recorder`)
    is shared by the scheduler, fabric, sanitizer, and bridge."""

    def __init__(self, max_dumps: int = MAX_DUMPS):
        self._lock = named_lock("obs.recorder._lock")
        self._dumps: deque[dict] = deque(maxlen=max_dumps)
        self._seq = 0
        self._counts: dict[str, int] = {}

    def trigger(
        self,
        reason: str,
        detail: dict | None = None,
        trace_ids=(),
        snapshots: dict | None = None,
    ) -> dict:
        """Freeze one black-box dump. ``trace_ids`` name the traces
        whose full span lists matter (e.g. the failing ticket's);
        ``snapshots`` carries component state (scheduler counters +
        breakers, fabric gauges) — redacted before storage."""
        from torrent_tpu.analysis import sanitizer
        from torrent_tpu.obs.tracer import tracer

        tr = tracer()
        dump = {
            "reason": reason,
            "t_mono": round(time.monotonic(), 6),
            "detail": _redact(detail or {}),
            "recent_spans": tr.recent_spans(),
            "traces": {
                tid: tr.trace_tree(tid)
                for tid in list(trace_ids)[:4]
                if tid is not None
            },
            "snapshots": _redact(snapshots or {}),
        }
        if sanitizer.is_enabled():
            dump["tsan"] = _redact(sanitizer.snapshot())
        with self._lock:
            self._seq += 1
            dump["seq"] = self._seq
            self._counts[reason] = self._counts.get(reason, 0) + 1
            self._dumps.append(dump)
        log.warning(
            "flight recorder dump #%d (%s): %d recent spans, %d traces",
            dump["seq"], reason, len(dump["recent_spans"]), len(dump["traces"]),
        )
        directory = os.environ.get(_FLIGHT_DIR_ENV)
        if directory:
            # off-thread: triggers fire from async contexts and worker
            # threads alike; neither may stall on disk
            threading.Thread(
                target=_write_dump, args=(directory, dump), daemon=True
            ).start()
        return dump

    def dumps(self) -> list[dict]:
        """Stored dumps, oldest first."""
        with self._lock:
            return list(self._dumps)

    def counts(self) -> dict[str, int]:
        with self._lock:
            return dict(self._counts)

    def render_metrics(self) -> str:
        """Prometheus series for dump counts (appended to /metrics)."""
        counts = self.counts()
        lines = [
            "# HELP torrent_tpu_flight_dumps_total Black-box flight-recorder dumps by trigger reason",
            "# TYPE torrent_tpu_flight_dumps_total counter",
        ]
        for reason, n in sorted(counts.items()):
            lines.append(
                f'torrent_tpu_flight_dumps_total{{reason="{_esc(reason)}"}} {n}'
            )
        return "\n".join(lines) + "\n"

    def clear(self) -> None:
        with self._lock:
            self._dumps.clear()
            self._counts.clear()


def _write_dump(directory: str, dump: dict) -> None:
    try:
        os.makedirs(directory, exist_ok=True)
        path = os.path.join(
            directory, f"blackbox_{_RUN_TOKEN}_{dump['seq']:04d}.json"
        )
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(dump, f, sort_keys=True, indent=1)
        os.replace(tmp, path)
    except OSError as e:  # best-effort: the in-memory ring still has it
        log.warning("flight recorder could not write %s: %s", directory, e)


_recorder = None


def flight_recorder() -> FlightRecorder:
    """The process-wide flight recorder."""
    global _recorder
    if _recorder is None:
        _recorder = FlightRecorder()
    return _recorder
