"""Always-on low-overhead latency histograms (fixed log2 buckets).

The continuous instrument the ingest-gap work needs: per-stage p50/p99
(queue wait, launch, end-to-end per tenant, bridge request) visible on
every scrape, not reconstructed from bench records. Design constraints:

* **Fixed log2 buckets** — ``2^-17 s`` (~7.6 µs) through ``2^6 s``
  (64 s), 24 boundaries plus +Inf. No per-series configuration, so a
  bucket index is one ``bisect`` on a shared tuple and every series is
  mergeable across processes.
* **Batch observation** — the scheduler observes a whole launch's
  queue waits under ONE lock acquisition (``observe_batch``), keeping
  the hot path at amortized nanoseconds per ticket.
* **Bounded label cardinality** — an attacker minting fresh ``X-Tenant``
  values per request must not grow ``/metrics`` without limit: past
  ``max_series`` per family, new label sets fold into a single
  ``overflow`` series.
* **Real Prometheus histograms** — rendered as cumulative ``_bucket``
  series with ``le`` labels, plus ``_sum``/``_count``, under one
  ``# HELP``/``# TYPE histogram`` header per family.

Locks come from :func:`~torrent_tpu.analysis.sanitizer.named_lock`;
the registry lock and the per-histogram lock are never nested with any
other named lock.
"""

from __future__ import annotations

from bisect import bisect_left

from torrent_tpu.analysis.sanitizer import named_lock
from torrent_tpu.utils.metrics import _esc

__all__ = [
    "BUCKET_BOUNDS",
    "HistogramRegistry",
    "LogHistogram",
    "histograms",
    "merge_snapshots",
]

# 2^-17 s .. 2^6 s: sub-10µs through 64 s, the full range a hash-plane
# stage can plausibly occupy (a CPU-plane 16 MiB piece is ~50 ms; a
# wedged launch hits the +Inf bucket)
BUCKET_BOUNDS: tuple[float, ...] = tuple(2.0**k for k in range(-17, 7))

MAX_SERIES_PER_FAMILY = 256


class LogHistogram:
    """One (family, label-set) series: per-bucket counts + sum/count."""

    __slots__ = ("counts", "count", "sum", "_lock")

    def __init__(self):
        self.counts = [0] * (len(BUCKET_BOUNDS) + 1)  # last = +Inf
        self.count = 0
        self.sum = 0.0
        self._lock = named_lock("obs.hist._lock")

    def observe(self, seconds: float) -> None:
        idx = bisect_left(BUCKET_BOUNDS, seconds)
        with self._lock:
            self.counts[idx] += 1
            self.count += 1
            self.sum += seconds

    def observe_batch(self, values) -> None:
        """All of ``values`` under one lock acquisition — the scheduler
        records a whole launch's ticket waits in one call."""
        if not values:
            return
        idxs = [bisect_left(BUCKET_BOUNDS, v) for v in values]
        total = sum(values)
        with self._lock:
            for idx in idxs:
                self.counts[idx] += 1
            self.count += len(idxs)
            self.sum += total

    def snapshot(self) -> tuple[list[int], int, float]:
        with self._lock:
            return list(self.counts), self.count, self.sum


def merge_snapshots(
    snaps,
) -> tuple[list[int], int, float]:
    """Bucket-aligned sum of :meth:`LogHistogram.snapshot` tuples.

    Because every histogram shares the fixed :data:`BUCKET_BOUNDS`,
    merging series — across label sets, or across PROCESSES (the fleet
    rollup merges digest-carried summaries from every fabric peer) — is
    an elementwise sum; the final +Inf overflow bucket merges like any
    other, so wedged-launch outliers survive aggregation. Rejects
    snapshots whose bucket count diverges (a peer running a different
    build must fail loudly, not mis-bin silently). An empty iterable
    merges to the all-zero snapshot."""
    counts: list[int] | None = None
    count = 0
    total = 0.0
    for c, k, s in snaps:
        if counts is None:
            counts = list(c)
        else:
            if len(c) != len(counts):
                raise ValueError(
                    f"bucket count mismatch: {len(c)} != {len(counts)} "
                    "(snapshots from different BUCKET_BOUNDS builds?)"
                )
            for i, v in enumerate(c):
                counts[i] += v
        count += int(k)
        total += float(s)
    if counts is None:
        counts = [0] * (len(BUCKET_BOUNDS) + 1)
    return counts, count, total


class HistogramRegistry:
    """(family name, labels) -> :class:`LogHistogram`, bounded per
    family, rendered as Prometheus exposition text."""

    def __init__(self, max_series: int = MAX_SERIES_PER_FAMILY):
        self._lock = named_lock("obs.hist._reg_lock")
        self._max_series = max_series
        # family -> {label_items_tuple -> LogHistogram}
        self._families: dict[str, dict[tuple, LogHistogram]] = {}
        self._help: dict[str, str] = {}

    def get(self, name: str, help: str = "", **labels) -> LogHistogram:
        key = tuple(sorted(labels.items()))
        with self._lock:
            fam = self._families.get(name)
            if fam is None:
                fam = self._families[name] = {}
                self._help[name] = help or name
            h = fam.get(key)
            if h is None:
                if len(fam) >= self._max_series:
                    # cardinality bound: unseen label sets beyond the cap
                    # share one overflow series instead of growing /metrics
                    key = (("overflow", "true"),)
                    h = fam.get(key)
                    if h is not None:
                        return h
                h = fam[key] = LogHistogram()
            return h

    def family_snapshot(self, name: str) -> tuple[list[int], int, float] | None:
        """One merged snapshot for a whole family (every label set summed
        via :func:`merge_snapshots`) — the compact per-process form the
        fleet obs digest carries. ``None`` when the family has never been
        observed, so digests stay minimal on idle planes."""
        with self._lock:
            fam = self._families.get(name)
            hists = [h for _, h in sorted(fam.items())] if fam else []
        # snapshot OUTSIDE the registry lock (same discipline as render:
        # the registry and per-histogram locks are both leaves and are
        # never nested)
        if not hists:
            return None
        return merge_snapshots(h.snapshot() for h in hists)

    def render(self) -> str:
        """Prometheus text exposition for every family: cumulative
        ``_bucket`` series (``le`` ascending, ending at +Inf), then
        ``_sum`` and ``_count`` per label set."""
        with self._lock:
            families = {
                name: (self._help[name], dict(fam))
                for name, fam in sorted(self._families.items())
            }
        lines: list[str] = []
        for name, (help_text, fam) in families.items():
            lines.append(f"# HELP {name} {help_text}")
            lines.append(f"# TYPE {name} histogram")
            for key, h in sorted(fam.items()):
                counts, count, total = h.snapshot()
                base = ",".join(f'{k}="{_esc(str(v))}"' for k, v in key)
                sep = "," if base else ""
                cum = 0
                for bound, c in zip(BUCKET_BOUNDS, counts):
                    cum += c
                    lines.append(
                        f'{name}_bucket{{{base}{sep}le="{bound:.10g}"}} {cum}'
                    )
                lines.append(f'{name}_bucket{{{base}{sep}le="+Inf"}} {count}')
                suffix = f"{{{base}}}" if base else ""
                lines.append(f"{name}_sum{suffix} {total:.9g}")
                lines.append(f"{name}_count{suffix} {count}")
        return "\n".join(lines) + "\n" if lines else ""

    def clear(self) -> None:
        with self._lock:
            self._families.clear()
            self._help.clear()


_registry = None


def histograms() -> HistogramRegistry:
    """The process-wide latency-histogram registry."""
    global _registry
    if _registry is None:
        _registry = HistogramRegistry()
    return _registry
