"""Declarative service-level objectives over the timeline ring.

"Persistent BitTorrent Trackers" motivates the tracker as a long-lived
service with availability expectations, and "GPUs as Storage System
Accelerators" frames the verify plane as a storage-tier service — both
need the standard service contract this module provides: declared
objectives, error-budget burn-rate alerting, and health/readiness
semantics a load balancer can act on.

Objectives (:class:`SloObjective`, parsed from a spec string):

* **availability** — the shed + retry-exhausted failure ratio over the
  pieces the scheduler was asked to process. ``target`` is the success
  ratio (e.g. 0.999 → a 0.1% error budget).
* **latency** — a p99 target (seconds) over one of the existing log2
  histogram families (queue_wait / launch / request). The error events
  are observations above the target bound; the budget is the 1% a p99
  objective tolerates by definition.
* **throughput** — an achieved-B/s floor over the pipeline ledger's
  verdict stage. Error events are ACTIVE intervals (verdict ops moved)
  that ran under the floor; idle intervals never burn.
* **integrity** — breaker-open transitions, lockset races, and
  distrust events burn the budget instantly (the budget fraction is
  effectively zero: one event is a fast burn).

Evaluation (:func:`evaluate_slo`) is a **pure function over timeline
samples** — in the analysis determinism pass's scope exactly like the
autopilot's ``decide()`` and the fleet digest builders. Windows are
counted in SAMPLES (deterministic over any ring, independent of
wall-clock jitter); at the sampler's cadence they map to time
(30 samples × 1 s ≈ 30 s short window).

Burn-rate model (the multi-window SRE idiom): over a window,
``burn = error_ratio / error_budget`` — burn 1.0 spends the budget
exactly at the window's length. Classification:

* ``fast_burn`` — short-window burn ≥ :data:`FAST_BURN` (page now);
* ``slow_burn`` — long-window burn ≥ :data:`SLOW_BURN` (ticket);
* ``ok`` otherwise.

``breach`` is the page-now condition: a fast burn, or an exhausted
budget (remaining 0) while the short window still shows errors. A
breach CLEARS when the short window runs clean — the property the
recovery leg of the acceptance scenario pins.

The stateful :class:`SloEngine` wraps evaluation with breach-transition
tracking: each observe() pass that newly breaches one or more
objectives fires exactly ONE ``slo_breach`` flight-recorder dump (the
dump lists every newly-breached objective), and nothing fires again
until the breach clears and re-occurs.

:func:`build_health` is the shared liveness/readiness verdict for
``GET /v1/health`` on the bridge AND the tracker listener: ready only
when the backend probe resolved, no lane breaker is stuck open past
its cooldown, the tracker pump is ticking, and the sampler is alive;
``degraded`` (still live, not ready) while any SLO objective breaches.
"""

from __future__ import annotations

from dataclasses import dataclass

from torrent_tpu.analysis.sanitizer import guard_attrs, named_lock
from torrent_tpu.obs.hist import BUCKET_BOUNDS
from torrent_tpu.utils.log import get_logger

log = get_logger("obs.slo")

__all__ = [
    "DEFAULT_LONG_SAMPLES",
    "DEFAULT_SHORT_SAMPLES",
    "DEFAULT_SLO_SPEC",
    "FAST_BURN",
    "SLOW_BURN",
    "SloEngine",
    "SloObjective",
    "arm",
    "armed",
    "build_health",
    "default_objectives",
    "digest_summary",
    "disarm",
    "evaluate_slo",
    "parse_objectives",
]

# multi-window burn-rate thresholds (the classic SRE workbook numbers:
# 14.4× spends a 30-day budget in ~2 days; 3× in ~10 days)
FAST_BURN = 14.4
SLOW_BURN = 3.0

# window lengths in SAMPLES (deterministic over any ring; at the
# default 1 s sampler cadence: 30 s / 5 min)
DEFAULT_SHORT_SAMPLES = 30
DEFAULT_LONG_SAMPLES = 300

# a p99 objective tolerates 1% above target by definition; that 1% IS
# its error budget
LATENCY_BUDGET = 0.01
# fraction of ACTIVE intervals a throughput floor may dip under
THROUGHPUT_BUDGET = 0.1
# the integrity budget is "effectively zero": one event is an instant
# fast burn (burn = ratio / budget explodes past FAST_BURN)
INTEGRITY_BUDGET = 1e-9

# an open breaker should have gone half-open after its cooldown; stuck
# open for this multiple of the cooldown means the probe path is wedged
BREAKER_STUCK_FACTOR = 2.0

DEFAULT_SLO_SPEC = "availability=0.999;integrity=on"

# hist short keys a latency objective may target — must match the
# sampler's SAMPLE_HIST_FAMILIES (obs/timeline) or the objective could
# never observe data. "block_rtt" is the swarm wire tier's family
# (obs/swarm): a p99 objective over it pages on a slow swarm.
LATENCY_FAMILIES = ("queue_wait", "launch", "request", "block_rtt")

# fraction of block arrivals the snub-ratio budget tolerates mapping is
# expressed by the objective's own target (a success ratio, like
# availability); the swarm download floor shares the throughput budget
SWARM_THROUGHPUT_BUDGET = 0.1

_KINDS = (
    "availability", "integrity", "latency", "throughput",
    "swarm_availability", "swarm_throughput",
)


@dataclass(frozen=True)
class SloObjective:
    """One declared objective. ``target`` means: success ratio
    (availability), p99 seconds (latency), floor B/s (throughput);
    integrity ignores it. ``family`` is the sample ``hist`` short key a
    latency objective reads (queue_wait / launch / request)."""

    name: str
    kind: str
    target: float = 0.0
    family: str = ""


def default_objectives(
    availability: float = 0.999, integrity: bool = True
) -> tuple[SloObjective, ...]:
    objs = [SloObjective("availability", "availability", availability)]
    if integrity:
        objs.append(SloObjective("integrity", "integrity"))
    return tuple(objs)


def parse_objectives(spec: str) -> tuple[SloObjective, ...]:
    """Parse a declarative objective spec, e.g.
    ``"availability=0.999;p99_ms=50:queue_wait;floor_mibps=10;integrity=on"``.

    Keys: ``availability=<ratio in (0,1)>``, ``p99_ms=<ms>[:family]``
    (family defaults to ``queue_wait``; ``block_rtt`` targets the swarm
    wire tier), ``floor_mibps=<MiB/s>``, ``integrity=on|off``, plus the
    swarm tier: ``swarm_floor_mibps=<MiB/s>`` (a download-rate floor
    over the samples' cumulative swarm bytes) and ``swarm_snub=<ratio
    in (0,1)>`` (snub-ratio availability: the success ratio of block
    arrivals vs snub events). Raises ValueError with the offending
    pair."""
    objs: list[SloObjective] = []
    for pair in (spec or "").split(";"):
        pair = pair.strip()
        if not pair:
            continue
        key, _, value = pair.partition("=")
        key = key.strip()
        value = value.strip()
        try:
            if key == "availability":
                target = float(value)
                if not 0.0 < target < 1.0:
                    raise ValueError("availability target must be in (0, 1)")
                objs.append(SloObjective("availability", "availability", target))
            elif key == "p99_ms":
                ms, _, family = value.partition(":")
                family = family or "queue_wait"
                if family not in LATENCY_FAMILIES:
                    # a typo'd family would arm an objective that can
                    # never observe data — green forever, unmonitored
                    raise ValueError(
                        f"unknown latency family {family!r} (one of "
                        f"{', '.join(LATENCY_FAMILIES)})"
                    )
                target = float(ms) / 1e3
                if target <= 0:
                    raise ValueError("p99_ms target must be positive")
                objs.append(
                    SloObjective(f"latency_{family}", "latency", target, family)
                )
            elif key == "floor_mibps":
                floor = float(value) * (1 << 20)
                if floor <= 0:
                    raise ValueError("floor_mibps must be positive")
                objs.append(SloObjective("throughput", "throughput", floor))
            elif key == "swarm_floor_mibps":
                floor = float(value) * (1 << 20)
                if floor <= 0:
                    raise ValueError("swarm_floor_mibps must be positive")
                objs.append(
                    SloObjective("swarm_throughput", "swarm_throughput", floor)
                )
            elif key == "swarm_snub":
                target = float(value)
                if not 0.0 < target < 1.0:
                    raise ValueError("swarm_snub target must be in (0, 1)")
                objs.append(
                    SloObjective(
                        "swarm_availability", "swarm_availability", target
                    )
                )
            elif key == "integrity":
                if value not in ("on", "off"):
                    raise ValueError("integrity must be on or off")
                if value == "on":
                    objs.append(SloObjective("integrity", "integrity"))
            else:
                raise ValueError(f"unknown objective key {key!r}")
        except ValueError as e:
            raise ValueError(f"bad SLO spec pair {pair!r}: {e}") from e
    if not objs:
        raise ValueError(f"SLO spec declares no objectives: {spec!r}")
    names = [o.name for o in objs]
    dupes = sorted({n for n in names if names.count(n) > 1})
    if dupes:
        # evaluate_slo keys its report by name, so a duplicate would
        # silently collapse last-wins — the earlier target declared but
        # never checked (green forever, unmonitored)
        raise ValueError(f"duplicate SLO objective(s): {', '.join(dupes)}")
    return tuple(objs)


# ------------------------------------------------------------- evaluation
# (analysis determinism pass scope: pure functions of the sample list —
# no wall clock, no randomness, sorted iteration)


# one defensive float parser for the whole sample layer: the evaluator
# and the replay attributor must agree on every hostile field
from torrent_tpu.obs.timeline import _num  # noqa: E402


# determinism-scope
def _tail(samples: list, n: int) -> list:
    n = max(2, int(n))
    return samples[-n:] if len(samples) > n else samples


def _sched_of(sample) -> dict:
    s = sample.get("sched") if isinstance(sample, dict) else None
    return s if isinstance(s, dict) else {}


def _integrity_of(sample) -> dict:
    s = sample.get("integrity") if isinstance(sample, dict) else {}
    return s if isinstance(s, dict) else {}


# determinism-scope
def _counter_objective(
    errors_short: float,
    events_short: float,
    errors_long: float,
    events_long: float,
    budget: float,
) -> dict:
    """The shared burn-rate machinery: ratio per window → burn per
    window → classification + budget remaining + breach. Monotone in
    the error count (for fixed totals) — the hypothesis property."""
    ratio_short = (errors_short / events_short) if events_short > 0 else 0.0
    ratio_long = (errors_long / events_long) if events_long > 0 else 0.0
    budget = budget if budget > 0 else 1e-9
    burn_short = ratio_short / budget
    burn_long = ratio_long / budget
    remaining = max(0.0, 1.0 - burn_long)
    if burn_short >= FAST_BURN:
        classification = "fast_burn"
    elif burn_long >= SLOW_BURN:
        classification = "slow_burn"
    else:
        classification = "ok"
    return {
        "errors": int(errors_long),
        "events": int(events_long),
        "error_ratio": round(ratio_long, 6),
        "burn_rate": round(burn_short, 3),
        "burn_rate_long": round(burn_long, 3),
        "budget_remaining": round(remaining, 6),
        "classification": classification,
        "breach": bool(
            classification == "fast_burn"
            or (remaining <= 0.0 and ratio_short > 0.0)
        ),
    }


# determinism-scope
def _avail_counters(sample) -> tuple[float, float]:
    """(errors, events) cumulative: shed + retry-exhausted failures over
    everything the scheduler was asked to process."""
    sched = _sched_of(sample)
    errors = _num(sched.get("shed")) + _num(sched.get("failed_pieces"))
    events = errors + _num(sched.get("pieces"))
    return errors, events


# determinism-scope
def _window_delta(samples: list, extract) -> tuple[float, float]:
    """Delta of ``extract(sample) -> (errors, events)`` across a window
    (first vs last sample), clamped at 0 for counter resets."""
    if len(samples) < 2:
        return 0.0, 0.0
    e0, n0 = extract(samples[0])
    e1, n1 = extract(samples[-1])
    return max(0.0, e1 - e0), max(0.0, n1 - n0)


# determinism-scope
def _eval_availability(short: list, long: list, obj: SloObjective) -> dict:
    es, ns = _window_delta(short, _avail_counters)
    el, nl = _window_delta(long, _avail_counters)
    out = _counter_objective(es, ns, el, nl, 1.0 - obj.target)
    out.update({"kind": obj.kind, "target": obj.target})
    return out


# determinism-scope
def _hist_window(samples: list, family: str) -> tuple[dict, float, float]:
    """(bucket-count deltas, total count delta) for one histogram
    family across a window; sparse string-keyed buckets like the
    digest encoding."""
    if len(samples) < 2:
        return {}, 0.0, 0.0

    def counters(sample):
        hist = sample.get("hist") if isinstance(sample, dict) else {}
        fam = (hist or {}).get(family) if isinstance(hist, dict) else {}
        return fam if isinstance(fam, dict) else {}

    first, last = counters(samples[0]), counters(samples[-1])
    b0 = first.get("buckets") if isinstance(first.get("buckets"), dict) else {}
    b1 = last.get("buckets") if isinstance(last.get("buckets"), dict) else {}
    deltas = {}
    for key in sorted(set(b0) | set(b1)):
        d = _num(b1.get(key)) - _num(b0.get(key))
        if d > 0:
            deltas[str(key)] = d
    count = max(0.0, _num(last.get("count")) - _num(first.get("count")))
    total = max(0.0, _num(last.get("sum")) - _num(first.get("sum")))
    return deltas, count, total


# determinism-scope
def _hist_errors(bucket_deltas: dict, target_s: float) -> float:
    """Observations whose bucket lies entirely above the target bound
    (conservative: a bucket straddling the target does not count)."""
    errors = 0.0
    for key in sorted(bucket_deltas):
        try:
            idx = int(key)
        except (TypeError, ValueError):
            continue
        if idx <= 0:
            continue  # the first bucket's lower edge is 0
        # bucket idx covers (BOUNDS[idx-1], BOUNDS[idx]]; the overflow
        # bucket (idx == len(BOUNDS)) has lower edge BOUNDS[-1]
        lower = BUCKET_BOUNDS[min(idx, len(BUCKET_BOUNDS)) - 1]
        if lower >= target_s:
            errors += _num(bucket_deltas[key])
    return errors


# determinism-scope
def _p99_estimate(bucket_deltas: dict, count: float) -> float | None:
    """Upper-bound p99 estimate from log2 bucket deltas."""
    if count <= 0:
        return None
    want = 0.99 * count
    # normalize keys BEFORE walking: a hostile/hand-edited dump may
    # carry '07'/' 7' keys whose int() form is not their dict key, and
    # negative indices must not wrap around BUCKET_BOUNDS
    by_idx: dict[int, float] = {}
    for key in sorted(bucket_deltas):
        try:
            idx = int(key)
        except (TypeError, ValueError):
            continue
        if idx < 0:
            continue
        by_idx[idx] = by_idx.get(idx, 0.0) + _num(bucket_deltas[key])
    cum = 0.0
    for idx in sorted(by_idx):
        cum += by_idx[idx]
        if cum >= want:
            if idx < len(BUCKET_BOUNDS):
                return BUCKET_BOUNDS[idx]
            return float("inf")
    return None


# determinism-scope
def _eval_latency(short: list, long: list, obj: SloObjective) -> dict:
    bs, cs, _ = _hist_window(short, obj.family)
    bl, cl, _ = _hist_window(long, obj.family)
    out = _counter_objective(
        _hist_errors(bs, obj.target), cs, _hist_errors(bl, obj.target), cl,
        LATENCY_BUDGET,
    )
    p99 = _p99_estimate(bl, cl)
    out.update({
        "kind": obj.kind,
        "target": obj.target,
        "family": obj.family,
        # the overflow bucket has no finite upper bound; report None +
        # a flag rather than float('inf'), which json.dumps would emit
        # as the non-RFC token `Infinity` and break every strict parser
        # of /v1/slo exactly when latency is pathological
        "p99_s": (
            round(p99, 6) if p99 is not None and p99 != float("inf") else None
        ),
        "p99_overflow": bool(p99 == float("inf")),
    })
    return out


# determinism-scope
def _throughput_intervals(samples: list, floor_bps: float) -> tuple[float, float, float]:
    """(slow_intervals, active_intervals, last_bps) over consecutive
    sample pairs: an interval is ACTIVE when verdict ops moved; a slow
    interval ran under the floor. Idle intervals never burn."""

    def verdict(sample):
        stages = sample.get("stages") if isinstance(sample, dict) else {}
        v = (stages or {}).get("verdict") if isinstance(stages, dict) else {}
        v = v if isinstance(v, dict) else {}
        return _num(v.get("bytes")), _num(v.get("ops"))

    slow = active = 0.0
    last_bps = 0.0
    for prev, cur in zip(samples, samples[1:]):
        b0, o0 = verdict(prev)
        b1, o1 = verdict(cur)
        if o1 - o0 <= 0:
            continue
        dt = _num(cur.get("t") if isinstance(cur, dict) else 0) - _num(
            prev.get("t") if isinstance(prev, dict) else 0
        )
        if dt <= 0:
            continue
        active += 1
        last_bps = max(0.0, b1 - b0) / dt
        if last_bps < floor_bps:
            slow += 1
    return slow, active, last_bps


# determinism-scope
def _eval_throughput(short: list, long: list, obj: SloObjective) -> dict:
    ss, ns, _ = _throughput_intervals(short, obj.target)
    sl, nl, last_bps = _throughput_intervals(long, obj.target)
    out = _counter_objective(ss, ns, sl, nl, THROUGHPUT_BUDGET)
    out.update({
        "kind": obj.kind,
        "target": obj.target,
        "achieved_bps": round(last_bps, 3),
    })
    return out


def _swarm_of(sample) -> dict:
    s = sample.get("swarm") if isinstance(sample, dict) else None
    return s if isinstance(s, dict) else {}


# determinism-scope
def _swarm_avail_counters(sample) -> tuple[float, float]:
    """(errors, events) cumulative for the snub-ratio budget: snub
    transitions over block deliveries + snubs — a swarm whose peers
    keep getting snubbed is failing its users even while bytes trickle."""
    swarm = _swarm_of(sample)
    errors = _num(swarm.get("snubs"))
    events = errors + _num(swarm.get("blocks"))
    return errors, events


# determinism-scope
def _eval_swarm_availability(short: list, long: list, obj: SloObjective) -> dict:
    es, ns = _window_delta(short, _swarm_avail_counters)
    el, nl = _window_delta(long, _swarm_avail_counters)
    out = _counter_objective(es, ns, el, nl, 1.0 - obj.target)
    out.update({"kind": obj.kind, "target": obj.target})
    return out


# determinism-scope
def _swarm_throughput_intervals(
    samples: list, floor_bps: float
) -> tuple[float, float, float]:
    """(slow_intervals, active_intervals, last_bps) over consecutive
    sample pairs of the swarm download counters: an interval is ACTIVE
    when blocks arrived; a slow interval downloaded under the floor.
    Idle intervals (seeding, no download) never burn."""

    def counters(sample):
        swarm = _swarm_of(sample)
        return _num(swarm.get("bytes_down")), _num(swarm.get("blocks"))

    slow = active = 0.0
    last_bps = 0.0
    for prev, cur in zip(samples, samples[1:]):
        b0, o0 = counters(prev)
        b1, o1 = counters(cur)
        if o1 - o0 <= 0:
            continue
        dt = _num(cur.get("t") if isinstance(cur, dict) else 0) - _num(
            prev.get("t") if isinstance(prev, dict) else 0
        )
        if dt <= 0:
            continue
        active += 1
        last_bps = max(0.0, b1 - b0) / dt
        if last_bps < floor_bps:
            slow += 1
    return slow, active, last_bps


# determinism-scope
def _eval_swarm_throughput(short: list, long: list, obj: SloObjective) -> dict:
    ss, ns, _ = _swarm_throughput_intervals(short, obj.target)
    sl, nl, last_bps = _swarm_throughput_intervals(long, obj.target)
    out = _counter_objective(ss, ns, sl, nl, SWARM_THROUGHPUT_BUDGET)
    out.update({
        "kind": obj.kind,
        "target": obj.target,
        "achieved_bps": round(last_bps, 3),
    })
    return out


# determinism-scope
def _integrity_counters_of(sample) -> tuple[float, float]:
    integ = _integrity_of(sample)
    errors = (
        _num(integ.get("breaker_opens"))
        + _num(integ.get("races"))
        + _num(integ.get("distrust"))
    )
    return errors, 0.0


# determinism-scope
def _eval_integrity(short: list, long: list, obj: SloObjective) -> dict:
    es, _ = _window_delta(short, _integrity_counters_of)
    el, _ = _window_delta(long, _integrity_counters_of)
    # events = the interval count: each window interval is one chance
    # for an integrity event; the budget is effectively zero, so ONE
    # event anywhere in the short window is an instant fast burn
    ns = max(0, len(short) - 1)
    nl = max(0, len(long) - 1)
    out = _counter_objective(es, ns, el, nl, INTEGRITY_BUDGET)
    out.update({"kind": obj.kind, "target": obj.target, "events_seen": int(el)})
    return out


# determinism-scope
def evaluate_slo(
    samples: list,
    objectives: tuple[SloObjective, ...],
    short_samples: int = DEFAULT_SHORT_SAMPLES,
    long_samples: int = DEFAULT_LONG_SAMPLES,
) -> dict:
    """Evaluate every objective over a sample ring. Pure and total:
    arbitrary (even hostile) sample dicts evaluate to a well-formed
    report — missing fields read as zero, never a crash."""
    samples = [s for s in (samples or []) if isinstance(s, dict)]
    long = _tail(samples, max(2, int(long_samples)))
    short = _tail(long, max(2, int(short_samples)))
    per: dict[str, dict] = {}
    for obj in sorted(objectives or (), key=lambda o: o.name):
        if obj.kind == "availability":
            per[obj.name] = _eval_availability(short, long, obj)
        elif obj.kind == "latency":
            per[obj.name] = _eval_latency(short, long, obj)
        elif obj.kind == "throughput":
            per[obj.name] = _eval_throughput(short, long, obj)
        elif obj.kind == "swarm_availability":
            per[obj.name] = _eval_swarm_availability(short, long, obj)
        elif obj.kind == "swarm_throughput":
            per[obj.name] = _eval_swarm_throughput(short, long, obj)
        elif obj.kind == "integrity":
            per[obj.name] = _eval_integrity(short, long, obj)
    worst = None
    for name in sorted(per):
        burn = per[name]["burn_rate"]
        if worst is None or burn > per[worst]["burn_rate"]:
            worst = name
    return {
        "objectives": per,
        "worst": (
            {
                "objective": worst,
                "burn_rate": per[worst]["burn_rate"],
                "classification": per[worst]["classification"],
            }
            if worst is not None
            else None
        ),
        "breach_any": any(per[name]["breach"] for name in sorted(per)),
        "window": {
            "samples": len(samples),
            "short_samples": len(short),
            "long_samples": len(long),
            "span_s": round(
                max(
                    0.0,
                    _num(samples[-1].get("t")) - _num(samples[0].get("t")),
                ),
                3,
            )
            if len(samples) >= 2
            else 0.0,
        },
    }


# determinism-scope
def digest_summary(report: dict | None) -> dict | None:
    """The compact form the fleet obs digest carries (worst burn rate +
    breach flag), so ``top --fleet`` shows fleet-wide budget health."""
    if not isinstance(report, dict):
        return None
    worst = report.get("worst")
    if not isinstance(worst, dict):
        return None
    return {
        "burn": round(_num(worst.get("burn_rate")), 3),
        "objective": str(worst.get("objective")),
        "breach": 1 if report.get("breach_any") else 0,
    }


# ----------------------------------------------------------------- health


# determinism-scope
def build_health(
    probe_ok: bool | None = None,
    breakers: dict | None = None,
    sampler_alive: bool | None = None,
    pump_age_s: float | None = None,
    pump_max_age_s: float | None = None,
    slo_report: dict | None = None,
) -> dict:
    """The shared liveness/readiness verdict (pure — every age is
    passed in). ``live`` is unconditionally True: answering at all IS
    the liveness probe. ``status``:

    * ``ready``    — serve traffic;
    * ``degraded`` — structurally healthy but an SLO objective is in
      breach (drain politely: the budget is burning);
    * ``unready``  — a structural reason (probe unresolved, breaker
      stuck open past cooldown, sampler dead, tracker pump stalled).

    A ``None`` input means "component not applicable here" and is
    skipped — the bridge has no pump, the tracker has no device probe.
    """
    reasons: list[str] = []
    if probe_ok is False:
        reasons.append("backend probe unresolved")
    for lane in sorted(breakers or {}):
        b = (breakers or {})[lane]
        if not isinstance(b, dict) or b.get("state") != "open":
            continue
        age = b.get("open_age_s")
        cooldown = _num(b.get("cooldown"))
        if age is not None and cooldown > 0 and _num(age) > cooldown * BREAKER_STUCK_FACTOR:
            reasons.append(f"breaker stuck open past cooldown: {lane}")
    if sampler_alive is False:
        reasons.append("timeline sampler dead")
    if (
        pump_age_s is not None
        and pump_max_age_s is not None
        and _num(pump_age_s) > _num(pump_max_age_s)
    ):
        reasons.append(f"tracker pump stalled ({_num(pump_age_s):.1f}s)")
    breaches = sorted(
        name
        for name, obj in ((slo_report or {}).get("objectives") or {}).items()
        if isinstance(obj, dict) and obj.get("breach")
    )
    if reasons:
        status = "unready"
    elif breaches:
        status = "degraded"
    else:
        status = "ready"
    return {
        "live": True,
        "ready": status == "ready",
        "status": status,
        "reasons": reasons,
        "slo_breaches": breaches,
    }


# ----------------------------------------------------------------- engine


class SloEngine:
    """Stateful wrapper: evaluation + breach-transition tracking.

    ``observe(timeline_snapshot)`` (the sampler's ``on_sample`` hook,
    called from the sampler thread) re-evaluates and fires exactly one
    ``slo_breach`` flight-recorder dump per observe pass that NEWLY
    breaches one or more objectives; nothing fires again until the
    breach clears and re-occurs. ``report()`` is read from serving
    loops (``GET /v1/slo``, /metrics) — state sits behind one leaf
    :func:`named_lock`, and the recorder trigger runs OUTSIDE it."""

    def __init__(
        self,
        objectives: tuple[SloObjective, ...] | str = DEFAULT_SLO_SPEC,
        short_samples: int = DEFAULT_SHORT_SAMPLES,
        long_samples: int = DEFAULT_LONG_SAMPLES,
    ):
        if isinstance(objectives, str):
            objectives = parse_objectives(objectives)
        self.objectives = tuple(objectives)
        self.short_samples = short_samples
        self.long_samples = long_samples
        self._lock = named_lock("obs.slo._lock")
        # dynamic lockset checking: report + breach map are one cell
        # (sampler thread writes, serving loops read)
        self._cells = guard_attrs("obs.slo", "report")
        self._report: dict | None = None
        self._breached: dict[str, bool] = {}
        self._breach_dumps = 0

    def observe(self, timeline_snapshot: dict) -> dict:
        samples = (
            timeline_snapshot.get("samples")
            if isinstance(timeline_snapshot, dict)
            else timeline_snapshot
        )
        report = evaluate_slo(
            samples or [], self.objectives, self.short_samples, self.long_samples
        )
        newly: list[str] = []
        with self._lock:
            self._cells.write("report")
            for name in sorted(report["objectives"]):
                breach = report["objectives"][name]["breach"]
                if breach and not self._breached.get(name):
                    newly.append(name)
                self._breached[name] = breach
            self._report = report
            if newly:
                self._breach_dumps += 1
        if newly:
            # outside the engine lock: the recorder takes its own leaf
            # lock and snapshots the tracer ring
            from torrent_tpu.obs.recorder import flight_recorder

            flight_recorder().trigger(
                "slo_breach",
                detail={
                    "objectives": newly,
                    "report": {
                        name: report["objectives"][name] for name in newly
                    },
                    "window": report["window"],
                },
            )
            log.warning("SLO breach: %s", ", ".join(newly))
        return report

    def report(self) -> dict | None:
        with self._lock:
            self._cells.read("report")
            return self._report

    def summary(self) -> dict | None:
        """The fleet-digest form of the last report."""
        return digest_summary(self.report())

    def metrics_snapshot(self) -> dict:
        with self._lock:
            self._cells.read("report")
            return {
                "report": self._report,
                "breach_dumps": self._breach_dumps,
                "objectives": len(self.objectives),
            }


# a process may arm at most one engine (the bridge's, or serve's); the
# fleet obs digest reads it so heartbeats carry budget health. None
# unless explicitly armed — zero overhead, zero byte-difference when
# objectives are off.
_armed: SloEngine | None = None


def arm(engine: SloEngine) -> SloEngine:
    global _armed
    _armed = engine
    return engine


def armed() -> SloEngine | None:
    return _armed


def disarm(engine: SloEngine | None = None) -> None:
    """Clear the armed slot. Pass the engine you armed: if another
    server armed a NEWER engine since, its slot must survive your
    shutdown (disarm(None) force-clears — tests only)."""
    global _armed
    if engine is None or _armed is engine:
        _armed = None
