"""`torrent-tpu doctor` — one-command environment triage.

Checks, in dependency order, each with a PASS/WARN/FAIL line and a
one-line remedy on failure:

1. python deps (numpy, jax) and versions
2. JAX platform + device visibility (never hangs: the device probe runs
   in a subprocess with a bounded wait, abandoned — not killed — on
   timeout, because killing a mid-grant process wedges shared tunnels)
3. hash kernels: SHA-1/SHA-256 planes vs hashlib on this host's default
   backend (interpret/scan on CPU)
4. native io_engine availability (falls back to Python preads)
5. loopback swarm smoke: author → seed → download 256 KiB through a
   real tracker + two Clients
6. verify-scheduler smoke: four tenants coalesce into one shared
   hash-plane launch with correct digests (torrent_tpu/sched)
7. bridge smoke: /v1/digests round-trip on an ephemeral port

Exit codes (stable — CI consumes every mode, not just ``--lint``):

* **0** — every check PASS or WARN (WARN = degraded-but-working, e.g.
  no accelerator visible; it never fails the run)
* **1** — at least one check FAILed (including the core-deps short
  circuit)
* **2** — usage error (argparse: unknown flag/bad value)

With ``--json``, stdout carries exactly one JSON object (``doctor
--json | jq .`` works) with ``ok``/``fails``/``warns``/``exit_code``
and the per-check ``{status, name, detail}`` list covering whichever
modes ran; human check lines and the watchdog move to stderr. The
reference ships no equivalent; this exists because a TPU-backed stack
has strictly more environment to go wrong (plugins, tunnels, kernels,
native engine).

Un-wedgeable by construction (round-4 verdict next #3): the triage tool
must not depend on the component it triages. On images whose
``sitecustomize`` force-registers a device plugin when
``PALLAS_AXON_POOL_IPS`` is set, that registration can block a *parent*
interpreter at startup while the relay is contended — the exact
pathology doctor exists to diagnose. So the CLI entrypoint (`run_cli`)
prints a watchdog line first, then re-execs itself with the pool var
stripped (saved aside) and ``JAX_PLATFORMS=cpu``, keeping ALL device
contact in the bounded, abandoned-not-killed subprocess probe, which
gets the saved vars back. For the worst case — the first interpreter
never reaching Python code at all — strip the env before any
interpreter starts: use the shell wrapper ``bin/torrent-tpu-doctor``
(source checkouts; not installed by pip), or equivalently::

    env -u PALLAS_AXON_POOL_IPS \
        TORRENT_TPU_DOCTOR_AXON_IPS="$PALLAS_AXON_POOL_IPS" \
        TORRENT_TPU_DOCTOR_AXON_PLATFORMS="$JAX_PLATFORMS" \
        JAX_PLATFORMS=cpu python -m torrent_tpu.tools.doctor --json

In-process callers (tests, cli embedding) use `main()`, which never
re-execs.
"""

from __future__ import annotations

import asyncio
import hashlib
import os
import subprocess
import sys
import tempfile
import time

_RESULTS: list[tuple[str, str, str]] = []  # (status, name, detail)

# With --json, stdout must carry exactly one JSON object so
# `doctor --json | jq .` works; all human/watchdog lines move to stderr
# (still line-buffered and flushed, so the wedge-location property the
# watchdog exists for is preserved on either stream).
_JSON_MODE = False


def _say(line: str) -> None:
    print(line, flush=True, file=sys.stderr if _JSON_MODE else sys.stdout)

# Env vars the CLI re-exec moves the axon pool config into, so the
# parent interpreter can never trigger plugin registration while the
# device probe subprocess still can.
_AXON_VAR = "PALLAS_AXON_POOL_IPS"
_SAVED_AXON_VAR = "TORRENT_TPU_DOCTOR_AXON_IPS"
_SAVED_PLATFORMS_VAR = "TORRENT_TPU_DOCTOR_AXON_PLATFORMS"


def _isolated_env(argv_env: dict[str, str]) -> dict[str, str]:
    """Return a copy of `argv_env` with the axon registration disarmed:
    the pool var moved aside (the probe restores it) and jax pinned to
    CPU for everything that runs in-process."""
    env = dict(argv_env)
    env[_SAVED_AXON_VAR] = env.pop(_AXON_VAR, "")
    env[_SAVED_PLATFORMS_VAR] = env.get("JAX_PLATFORMS", "")
    env["JAX_PLATFORMS"] = "cpu"
    # the re-exec runs `-m torrent_tpu.tools.doctor`; make sure the
    # package root stays importable however the first process was started
    root = os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    env["PYTHONPATH"] = (
        root + os.pathsep + env["PYTHONPATH"]
        if env.get("PYTHONPATH")
        else root
    )
    return env


def _probe_env() -> dict[str, str]:
    """Env for the device-probe subprocess: the ONE place the axon path
    is allowed — restore the saved pool/platform vars if the re-exec
    (or the shell wrapper) moved them aside."""
    env = dict(os.environ)
    saved_ips = env.pop(_SAVED_AXON_VAR, None)
    saved_platforms = env.pop(_SAVED_PLATFORMS_VAR, None)
    if saved_ips:
        env[_AXON_VAR] = saved_ips
    # restore platforms independently of the pool var: a host configured
    # via JAX_PLATFORMS alone must still get its real platform probed
    if saved_platforms is not None:
        if saved_platforms:
            env["JAX_PLATFORMS"] = saved_platforms
        elif saved_ips is not None:
            # isolation ran but the original env had no JAX_PLATFORMS
            env.pop("JAX_PLATFORMS", None)
    return env


def run_cli(argv=None) -> int:
    """CLI entrypoint: never lets the parent touch the axon registration
    path. Prints a watchdog line before anything that could block, then
    re-execs into an interpreter whose startup skips plugin
    registration entirely (`sitecustomize` only registers when the pool
    var is set). Device contact stays in `_check_device`'s bounded
    subprocess, which gets the original env back via `_probe_env`."""
    args = list(sys.argv[1:] if argv is None else argv)
    global _JSON_MODE
    _JSON_MODE = "--json" in args  # pre-argparse: keep stdout clean NOW
    # the watchdog line: if nothing else ever prints, this names the
    # wedge location (interpreter started, re-exec about to happen)
    _say(f"doctor alive pid={os.getpid()} — checking environment")
    if os.environ.get(_AXON_VAR):
        _say(
            f"doctor: re-exec with {_AXON_VAR} stripped so the parent "
            "skips device-plugin registration (device probe keeps it)"
        )
        os.execve(
            sys.executable,
            [sys.executable, "-m", "torrent_tpu.tools.doctor", *args],
            _isolated_env(dict(os.environ)),
        )
    return main(args)


def _report(status: str, name: str, detail: str = "") -> None:
    _RESULTS.append((status, name, detail))
    pad = {"PASS": "  ", "WARN": "  ", "FAIL": "  "}[status]
    line = f"[{status}]{pad}{name}"
    if detail:
        line += f" — {detail}"
    _say(line)


def _check_deps() -> bool:
    try:
        import numpy

        _report("PASS", "numpy", numpy.__version__)
    except Exception as e:  # pragma: no cover - image always has numpy
        _report("FAIL", "numpy", f"{e!r}; install numpy")
        return False
    try:
        import jax

        _report("PASS", "jax", jax.__version__)
    except Exception as e:
        _report("FAIL", "jax", f"{e!r}; install jax (CPU wheels suffice)")
        return False
    return True


def _check_device(wait_s: float) -> None:
    """Probe device visibility WITHOUT risking a hang: subprocess with a
    bounded wait, abandoned on timeout (never killed — a killed
    mid-grant process can wedge a shared device tunnel for later
    processes, the same discipline bench.py follows)."""
    probe = (
        "import jax\n"
        "d = jax.devices()[0]\n"
        "print(d.platform, len(jax.devices()))\n"
    )
    proc = subprocess.Popen(
        [sys.executable, "-c", probe],
        stdout=subprocess.PIPE,
        stderr=subprocess.DEVNULL,
        stdin=subprocess.DEVNULL,
        text=True,
        start_new_session=True,
        env=_probe_env(),
    )
    try:
        out, _ = proc.communicate(timeout=wait_s)
    except subprocess.TimeoutExpired:
        # communicate() on timeout leaves the child RUNNING — exactly the
        # abandon-don't-kill semantics the tunnel discipline requires
        _report(
            "WARN",
            "device probe",
            f"no answer in {wait_s:.0f}s (wedged tunnel?); probe left "
            f"running, continuing on the host platform",
        )
        return
    out = (out or "").strip()
    if proc.returncode == 0 and out:
        try:
            # last line: import-time banners may precede the answer
            platform, n = out.splitlines()[-1].split()
        except ValueError:
            _report("WARN", "device probe", f"unparseable probe output {out!r}")
            return
        status = "PASS" if platform != "cpu" else "WARN"
        detail = f"platform={platform} devices={n}"
        if platform == "cpu":
            detail += " (no accelerator; kernels run in interpret/scan mode)"
        _report(status, "device probe", detail)
    else:
        _report(
            "WARN",
            "device probe",
            "device init failed; CPU fallback works but is not the point",
        )


def _device_backend_unavailable(e: Exception) -> bool:
    return "Unable to initialize backend" in str(e)


def _swap_to_cpu_platform() -> bool:
    """When the image pins jax to a device plugin whose tunnel is down,
    in-process jax raises at first use. Swap the CPU platform in so the
    remaining checks still verify the kernels (reported as WARN)."""
    try:
        import jax

        jax.config.update("jax_platforms", "cpu")
        jax.devices()
        return True
    except Exception:
        return False


def _check_kernels() -> bool:
    # under re-exec/wrapper isolation a device IS configured but the
    # kernels deliberately run on CPU (device contact is probe-only);
    # say so and downgrade to WARN exactly like the fallback path, so
    # "kernels verified on the device" can never be misread from a PASS
    note = (
        " (device configured but isolated; kernels verified on CPU — "
        "device contact is probe-only)"
        if os.environ.get(_SAVED_AXON_VAR)
        else ""
    )

    def run_sha1():
        from torrent_tpu.models.verifier import TPUVerifier

        v = TPUVerifier(piece_length=16384, batch_size=4)
        pieces = [bytes([i]) * 16384 for i in range(4)]
        got = list(v.hash_pieces(pieces))
        want = [hashlib.sha1(p).digest() for p in pieces]
        return got == want, f"backend={v.backend}{note}"

    def run_sha256():
        from torrent_tpu.models.merkle import words32_to_digests
        from torrent_tpu.models.v2 import _leaf_words_device

        data = b"\xa5" * 16384
        got = words32_to_digests(_leaf_words_device(data, "auto"))[0]
        return got == hashlib.sha256(data).digest(), note.strip()

    ok = True
    for name, fn in (("sha1 plane", run_sha1), ("sha256 plane", run_sha256)):
        for attempt in (0, 1):
            try:
                good, detail = fn()
            except Exception as e:
                if (
                    attempt == 0
                    and _device_backend_unavailable(e)
                    and _swap_to_cpu_platform()
                ):
                    note = " (device backend unavailable; verified on CPU)"
                    continue
                _report("FAIL", name, repr(e))
                ok = False
                break
            if good:
                _report("WARN" if note else "PASS", name, detail)
            else:
                _report("FAIL", name, "digests diverge from hashlib")
                ok = False
            break
    return ok


def _check_native_io() -> None:
    try:
        from torrent_tpu.native.io_engine import native_available

        if native_available():
            _report("PASS", "native io_engine", "C++ pread pool loaded")
        else:
            _report(
                "WARN",
                "native io_engine",
                "not built; Python pread fallback active "
                "(python -m torrent_tpu.native.build to build)",
            )
    except Exception:
        _report("WARN", "native io_engine", "module unavailable; Python fallback")


class _LoopbackSwarm:
    """Shared two-client loopback scaffold for the swarm smokes: tmp
    payload file → in-memory tracker → seed + leech clients → download
    to completion. One copy of the port-0/teardown plumbing serves both
    doctor smokes (the bench swarm rung keeps its own rep-scoped
    variant — it times each leg and recreates the tracker per rep)."""

    def __init__(self, tmp: str, payload: bytes, name: str,
                 piece_length: int = 16384, seed_bps: int = 0):
        self.tmp = tmp
        self.payload = payload
        self.name = name
        self.piece_length = piece_length
        self.seed_bps = seed_bps  # client-global seed upload cap (0 = off)
        self.seed = self.leech = self.server = None
        self.seed_dir = self.leech_dir = None
        self.torrent = None  # the leech's Torrent once downloaded

    async def __aenter__(self) -> "_LoopbackSwarm":
        from torrent_tpu.codec.metainfo import parse_metainfo
        from torrent_tpu.server.in_memory import run_tracker
        from torrent_tpu.server.tracker import ServeOptions
        from torrent_tpu.session.client import Client, ClientConfig
        from torrent_tpu.tools.make_torrent import make_torrent

        self.seed_dir = os.path.join(self.tmp, "seed")
        os.makedirs(self.seed_dir)
        with open(os.path.join(self.seed_dir, self.name), "wb") as f:
            f.write(self.payload)
        self.server, _ = await run_tracker(
            ServeOptions(http_port=0, udp_port=None, interval=1)
        )
        ann = f"http://127.0.0.1:{self.server.http_port}/announce"
        self.meta = parse_metainfo(
            make_torrent(
                os.path.join(self.seed_dir, self.name), ann,
                piece_length=self.piece_length,
            )
        )
        self.leech_dir = os.path.join(self.tmp, "leech")
        os.makedirs(self.leech_dir)
        self.seed = Client(ClientConfig(
            port=0, enable_upnp=False, resume=False,
            max_upload_bps=self.seed_bps,
        ))
        self.leech = Client(ClientConfig(port=0, enable_upnp=False, resume=False))
        await self.seed.start()
        await self.leech.start()
        return self

    async def download(self, deadline_polls: int = 1200) -> None:
        t1 = await self.seed.add(self.meta, self.seed_dir)
        assert t1.bitfield.complete, "seed recheck failed"
        self.torrent = await self.leech.add(self.meta, self.leech_dir)
        for _ in range(deadline_polls):
            if self.torrent.bitfield.complete:
                return
            await asyncio.sleep(0.05)
        assert self.torrent.bitfield.complete, "download did not complete"

    async def __aexit__(self, *exc) -> None:
        if self.seed is not None:
            await self.seed.close()
        if self.leech is not None:
            await self.leech.close()
        if self.server is not None:
            self.server.close()


async def _swarm_smoke(tmp: str) -> None:
    import numpy as np

    payload = np.random.default_rng(1).integers(
        0, 256, 256 * 1024, dtype=np.uint8
    ).tobytes()
    async with _LoopbackSwarm(tmp, payload, "smoke.bin") as swarm:
        await swarm.download(deadline_polls=600)
        with open(os.path.join(swarm.leech_dir, "smoke.bin"), "rb") as f:
            assert f.read() == payload, "payload mismatch"


async def _swarm_wire_smoke(tmp: str) -> str:
    """Swarm wire-plane smoke (``--swarm``): a two-peer loopback
    seed→leech download over a THROTTLED link (the seed's client-global
    upload token bucket models a slow network), checked against the
    whole observe→attribute→alert stack one layer down:

    - the ledger's ``recv`` stage charged the downloaded bytes, and the
      bridge's ``/v1/pipeline`` attribution names ``recv`` as the
      limiting stage — the network, not disk;
    - ``/v1/swarm`` reports bounded per-peer telemetry: per-peer
      byte/block accounting, a choke timeline with durations, a
      block-RTT p99, pipeline depth, and the top-K + overflow contract;
    - ``/metrics`` carries the ``torrent_tpu_swarm_*`` and
      ``torrent_tpu_peer_*`` families;
    - a snub storm driven through the SAME registry API the session
      uses fires exactly ONE ``snub_storm`` flight dump per transition
      (further snubs while the storm holds must not re-fire).
    """
    import json as _json

    import numpy as np

    from torrent_tpu.bridge.service import BridgeServer
    from torrent_tpu.obs.ledger import pipeline_ledger
    from torrent_tpu.obs.recorder import flight_recorder
    from torrent_tpu.obs.swarm import swarm_telemetry

    # 384 KiB at a 128 KiB/s seed cap: the token bucket's one-second
    # burst passes the first 128 KiB, the remaining 256 KiB pace at the
    # cap — ~2 s of wall that only the wire (recv) can own
    payload = np.random.default_rng(3).integers(
        0, 256, 384 * 1024, dtype=np.uint8
    ).tobytes()
    prev = pipeline_ledger().snapshot()
    svc = await BridgeServer("127.0.0.1", port=0, hasher="cpu").start()
    _http = _http_request
    try:
        async with _LoopbackSwarm(
            tmp, payload, "wire.bin", seed_bps=128 * 1024
        ) as loop_swarm:
            await loop_swarm.download()

            # (a) recv owns the delta: the download was wire-limited,
            # so the recv stage must have charged the payload's bytes
            # and more busy time than any other stage of this interval
            snap = pipeline_ledger().snapshot()
            recv = snap["stages"].get("recv") or {}
            prev_recv = (prev.get("stages") or {}).get("recv") or {}
            recv_bytes = recv.get("bytes", 0) - prev_recv.get("bytes", 0)
            assert recv_bytes >= len(payload), (
                f"recv charged {recv_bytes} B, payload was {len(payload)} B"
            )
            status, body = await _http(svc.port, "GET", "/v1/pipeline")
            assert status == 200, status
            pipe = _json.loads(body)
            # attribute the ROUTE's served snapshot against this
            # smoke's start (the ledger is process-global and
            # cumulative: another doctor flag's scheduler traffic must
            # not make a healthy system fail this check — the same
            # delta discipline bench uses)
            from torrent_tpu.obs.attrib import attribute

            bn = (attribute(pipe["snapshot"], prev=prev) or {}).get(
                "bottleneck"
            ) or {}
            assert bn.get("stage") == "recv", (
                f"attribution blamed {bn.get('stage')!r}, expected recv"
            )
            assert (pipe.get("attribution") or {}).get("bottleneck"), (
                "route served no attribution"
            )

            # (b) /v1/swarm: bounded per-peer telemetry (both ends of
            # the loopback pair live in this process's registry)
            status, body = await _http(svc.port, "GET", "/v1/swarm")
            assert status == 200, status
            swarm_json = _json.loads(body)
            assert swarm_json["counts"]["connected"] >= 2, swarm_json["counts"]
            assert "overflow" in swarm_json and "peers" in swarm_json
            downloaded = [
                p for p in swarm_json["peers"].values()
                if p.get("bytes_down", 0) >= len(payload)
            ]
            assert downloaded, "no peer shows the downloaded bytes"
            p = downloaded[0]
            assert p["block_rtt"]["count"] > 0
            assert p["block_rtt"]["p99_s"] is not None
            assert "choke_timeline" in p and "peer_choking" in p["choke_timeline"]
            assert p["pipeline"]["depth_max"] > 0

            # (c) the Prometheus families ride both /metrics endpoints
            status, body = await _http(svc.port, "GET", "/metrics")
            text = body.decode()
            assert "torrent_tpu_swarm_peers " in text
            assert 'torrent_tpu_peer_bytes_down_total{peer="' in text

            # (d) snub-storm trigger: drive the registry with the same
            # API the session uses — exactly one dump per False→True
            # transition
            reg = swarm_telemetry()
            base = flight_recorder().counts().get("snub_storm", 0)
            for i in range(2):
                reg.peer_connected(f"doc{i}@127.0.0.1:{7000 + i}")
            reg.on_snub("doc0@127.0.0.1:7000")
            reg.on_snub("doc1@127.0.0.1:7001")
            storm1 = flight_recorder().counts().get("snub_storm", 0) - base
            reg.on_snub("doc0@127.0.0.1:7000")  # storm already active
            storm2 = flight_recorder().counts().get("snub_storm", 0) - base
            for i in range(2):
                reg.peer_dropped(f"doc{i}@127.0.0.1:{7000 + i}")  # clears
            assert storm1 == 1 and storm2 == 1, (
                f"expected exactly one snub_storm dump, got {storm1}/{storm2}"
            )
            rtt_ms = (p["block_rtt"]["p99_s"] or 0.0) * 1e3
    finally:
        svc.close()
        await svc.wait_closed()
    return (
        f"recv limiting ({recv_bytes >> 10} KiB wire-charged), "
        f"block-RTT p99 {rtt_ms:.1f} ms, one snub_storm dump"
    )


async def _sched_smoke() -> str:
    """Verify-scheduler smoke: four tenants submit small piece lists
    concurrently and must come back with correct digests out of a
    COALESCED launch (cross-request batch fill is the scheduler's whole
    point). Returns the observed mean batch-fill ratio for the check
    detail line."""
    from torrent_tpu.sched import HashPlaneScheduler, SchedulerConfig

    sched = HashPlaneScheduler(
        SchedulerConfig(batch_target=32, flush_deadline=0.25), hasher="cpu"
    )
    await sched.start()
    try:
        pieces = [bytes([i]) * 1024 for i in range(8)]
        want = [hashlib.sha1(p).digest() for p in pieces]
        outs = await asyncio.gather(
            *(sched.submit(f"smoke{j}", pieces, algo="sha1") for j in range(4))
        )
        assert all(o == want for o in outs), "scheduler digests diverge from hashlib"
        snap = sched.metrics_snapshot()
        assert snap["launches"] >= 1, "no launch recorded"
        return f"4 tenants coalesced, mean fill {snap['mean_fill']:.2f}"
    finally:
        await sched.close()


async def _faults_smoke() -> str:
    """Fault-tolerance smoke (``--faults``): an in-process scheduler with
    an injected fail-then-recover plan must (a) bisect a poisoned batch
    so only the poisoned piece fails while co-batched pieces get correct
    digests, and (b) trip the lane breaker to the CPU plane under
    consecutive device faults, then restore the device plane with a
    half-open probe. Deterministic and CPU-only: the faults come from
    sched/faults.py through the plane_factory seam."""
    from torrent_tpu.sched import (
        FaultPlan,
        HashPlaneScheduler,
        SchedLaunchError,
        SchedulerConfig,
    )

    # (a) poisoned-payload isolation via bisection
    poison = b"\xbd" * 64
    plan = FaultPlan(payload_prefix=b"\xbd\xbd\xbd\xbd")
    sched = HashPlaneScheduler(
        SchedulerConfig(
            batch_target=16,
            flush_deadline=0.2,
            plane_factory=plan.plane_factory(hasher="cpu"),
        ),
        hasher="cpu",
    )
    await sched.start()
    try:
        good = [bytes([i + 1]) * 64 for i in range(15)]
        # enqueue both before awaiting (no intervening yield), so the 16
        # pieces deterministically ride ONE coalesced poisoned launch
        fut_ok = await sched.enqueue("ok", good)
        fut_bad = await sched.enqueue("poisoned", [poison])
        results = await asyncio.gather(fut_ok, fut_bad, return_exceptions=True)
        assert results[0] == [hashlib.sha1(p).digest() for p in good], (
            "co-batched pieces lost to a poisoned ticket"
        )
        assert isinstance(results[1], SchedLaunchError), results[1]
        snap = sched.metrics_snapshot()
        assert snap["bisections"] > 0, "poisoned batch was not bisected"
        bisections = snap["bisections"]
    finally:
        await sched.close()

    # (b) breaker trip -> CPU degradation -> half-open recovery: the
    # first two plane launches fail (launch + its retry -> threshold 2
    # trips the breaker, bisected halves ride the CPU plane), and the
    # third — the half-open probe after the cooldown — succeeds
    plan = FaultPlan(fail_first=2)
    sched = HashPlaneScheduler(
        SchedulerConfig(
            batch_target=4,
            flush_deadline=0.05,
            breaker_threshold=2,
            breaker_cooldown=300.0,
            plane_factory=plan.plane_factory(hasher="cpu"),
        ),
        hasher="cpu",
    )
    await sched.start()
    try:
        pieces = [bytes([i]) * 128 for i in range(4)]
        want = [hashlib.sha1(p).digest() for p in pieces]
        assert await sched.submit("t", pieces) == want, "CPU degradation wrong"
        snap = sched.metrics_snapshot()
        lane = next(iter(snap["breakers"].values()))
        assert lane["state"] == "open", f"breaker did not trip: {lane}"
        assert snap["cpu_fallback_launches"] > 0
        # expire the cooldown without sleeping (wall-clock-stall-proof):
        # the next launch becomes the half-open probe
        for ln in sched._lanes.values():
            with ln.breaker.lock:
                ln.breaker.opened_at -= 1e6
        assert await sched.submit("t", pieces) == want
        lane = next(iter(sched.metrics_snapshot()["breakers"].values()))
        assert lane["state"] == "closed", f"probe did not recover: {lane}"
        assert lane["transitions"].get("half_open->closed", 0) >= 1
    finally:
        await sched.close()
    return f"bisected poisoned piece ({bisections} splits), breaker tripped+recovered"


async def _v2_smoke() -> str:
    """BEP 52 plane smoke (``--v2``): 16 KiB leaf digests AND 64-byte
    merkle-pair digests vs hashlib, through the scheduler's pallas
    sha256 lane. Interpret-safe: on a CPU host the backend pin runs the
    kernel in interpret mode, so this validates the exact dispatch path
    the v2 fast path uses without needing a device. Also asserts the
    tile-snapped lane wastes zero pad rows at full fill."""
    from torrent_tpu.sched import HashPlaneScheduler, SchedulerConfig

    sched = HashPlaneScheduler(
        SchedulerConfig(
            batch_target=1024, flush_deadline=0.2, sha256_backend="pallas"
        ),
        hasher="tpu",
    )
    await sched.start()
    try:
        # leaf leg: a couple of 16 KiB BEP 52 leaf blocks (ragged tail)
        leaves = [bytes([i + 1]) * 16384 for i in range(2)] + [b"\x42" * 5000]
        got = await sched.submit("doctor", leaves, algo="sha256", piece_length=16384)
        assert got == [hashlib.sha256(p).digest() for p in leaves], (
            "leaf digests diverge from hashlib"
        )
        # merkle-pair leg: 64-byte child concatenations (the interior-
        # node message shape), a full 1024-piece launch — the snapped
        # lane target — which must waste zero pad rows
        pairs = [bytes([i % 251]) * 64 for i in range(1024)]
        got = await sched.submit("doctor", pairs, algo="sha256", piece_length=64)
        assert got == [hashlib.sha256(p).digest() for p in pairs], (
            "merkle-pair digests diverge from hashlib"
        )
        snap = sched.metrics_snapshot()
        pair_lane = snap["lane_stats"]["sha256/64"]
        assert pair_lane["backend"] == "pallas", pair_lane
        assert pair_lane["pad_rows_total"] == 0, (
            f"full-tile launch wasted pad rows: {pair_lane}"
        )
        assert snap["cpu_fallback_launches"] == 0, "pallas lane fell back to CPU"
        leaf_lane = snap["lane_stats"]["sha256/16384"]
        return (
            f"leaf+pair parity ok (pallas, pair fill "
            f"{pair_lane['mean_fill']:.2f}, leaf pad rows "
            f"{leaf_lane['pad_rows_total']})"
        )
    finally:
        await sched.close()


def _fabric_smoke(tmp: str) -> str:
    """Verify-fabric self-test (``--fabric``): a tiny two-torrent
    library, TWO real fabric-verify worker subprocesses over the
    shared-directory heartbeat transport (explicit process ids — no
    jax.distributed), worker 1 fault-injected to die after its first
    unit. Worker 0 must watch the heartbeat lapse, adopt the orphaned
    units, sentinel-cross-check the dead worker's published verdicts,
    and finish with every piece verified — plan → execute → heartbeat →
    adopt, end to end. Returns the per-process shard stats line."""
    import json

    import numpy as np

    from torrent_tpu.tools.make_torrent import make_torrent

    plen = 16384
    rng = np.random.default_rng(3)
    tdir = os.path.join(tmp, "torrents")
    ddir = os.path.join(tmp, "data")
    os.makedirs(tdir)
    # 96 + 160 pieces at 16 KiB = 5 one-MiB work units across 2 workers
    for t, npieces in enumerate((96, 160)):
        root = os.path.join(ddir, f"fab{t}")
        os.makedirs(root)
        payload = os.path.join(root, "payload.bin")
        with open(payload, "wb") as f:
            f.write(
                rng.integers(
                    0, 256, (npieces - 1) * plen + plen // 3, dtype=np.uint8
                ).tobytes()
            )
        with open(os.path.join(tdir, f"fab{t}.torrent"), "wb") as f:
            f.write(
                make_torrent(payload, "http://t.invalid/announce", piece_length=plen)
            )
    hb = os.path.join(tmp, "hb")
    env = dict(os.environ)
    env.pop(_AXON_VAR, None)  # workers must never register a device plugin
    env["JAX_PLATFORMS"] = "cpu"
    root = os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    env["PYTHONPATH"] = root + os.pathsep + env.get("PYTHONPATH", "")
    workers = []
    for p in range(2):
        cmd = [
            sys.executable, "-m", "torrent_tpu", "fabric-verify", tdir, ddir,
            "--hasher", "cpu", "--num-processes", "2", "--process-id", str(p),
            "--heartbeat-dir", hb, "--heartbeat-interval", "0.1",
            "--lapse-after", "1.0", "--unit-mb", "1", "--batch-target", "64",
            "--result-file", os.path.join(tmp, f"result_{p}.json"),
        ]
        if p == 1:
            cmd += ["--die-after-units", "1"]
        workers.append(
            subprocess.Popen(
                cmd, env=env, stdout=subprocess.PIPE,
                stderr=subprocess.PIPE, text=True,
            )
        )
    try:
        for p, w in enumerate(workers):
            _, err = w.communicate(timeout=180)
            if p == 0:
                assert w.returncode == 0, f"worker 0 failed:\n{err[-2000:]}"
            else:
                from torrent_tpu.fabric import FAULT_EXIT_CODE

                assert w.returncode == FAULT_EXIT_CODE, (
                    f"worker 1 should die with the fault code, got "
                    f"{w.returncode}:\n{err[-2000:]}"
                )
    finally:
        for w in workers:
            if w.poll() is None:
                w.kill()
                w.communicate()
    with open(os.path.join(tmp, "result_0.json")) as f:
        rec = json.load(f)
    assert rec["n_valid"] == rec["n_pieces"], (
        f"survivor left pieces unverified: {rec['n_valid']}/{rec['n_pieces']}"
    )
    assert rec["units_adopted"] >= 1, f"no units adopted: {rec}"
    assert rec["sentinel_checks"] >= 1, f"no sentinel cross-check ran: {rec}"
    assert rec["sentinel_mismatches"] == 0, rec
    return (
        f"worker1 died after 1 unit; survivor shard {rec['shard_units']}u/"
        f"{rec['shard_bytes'] >> 20}MiB + {rec['units_adopted']} adopted, "
        f"{rec['sentinel_checks']} sentinel checks, "
        f"{rec['n_valid']}/{rec['n_pieces']} pieces valid (plan {rec['plan']})"
    )


def _byzantine_smoke(tmp: str) -> str:
    """Byzantine-fabric self-test (``--byzantine``): one 96-piece
    torrent with ONE genuinely corrupt piece, TWO real fabric-verify
    workers at ``byzantine_f=1`` / ``audit_rate=1.0``, worker 1 lying
    via ``--fault-plan forge_receipts=1`` (every piece claimed ok under
    a consistent Merkle root, so only audit re-hashing can catch it).
    Worker 0's audit must convict the liar with portable evidence;
    worker 1 must re-verify that evidence against its own storage and
    convict ITSELF — symmetric termination: identical exit codes,
    bit-identical global bitfields rejecting exactly the corrupt piece,
    the liar in both distrusted sets, and exactly one
    ``fabric_distrust`` flight dump per process."""
    import json

    import numpy as np

    from torrent_tpu.tools.make_torrent import make_torrent

    plen = 16384
    npieces = 96
    bad_piece = 70
    rng = np.random.default_rng(3)
    tdir = os.path.join(tmp, "torrents")
    ddir = os.path.join(tmp, "data")
    os.makedirs(tdir)
    root_dir = os.path.join(ddir, "byz0")
    os.makedirs(root_dir)
    payload = os.path.join(root_dir, "payload.bin")
    with open(payload, "wb") as f:
        f.write(
            rng.integers(
                0, 256, (npieces - 1) * plen + plen // 3, dtype=np.uint8
            ).tobytes()
        )
    with open(os.path.join(tdir, "byz0.torrent"), "wb") as f:
        f.write(
            make_torrent(payload, "http://t.invalid/announce", piece_length=plen)
        )
    # corrupt one piece AFTER hashing: every honest verdict must reject
    # exactly this piece, and the forger's all-ok claim about it is the
    # lie the audit plane has to catch
    with open(payload, "r+b") as f:
        f.seek(bad_piece * plen)
        chunk = f.read(64)
        f.seek(bad_piece * plen)
        f.write(bytes(b ^ 0xFF for b in chunk))
    hb = os.path.join(tmp, "hb")
    env = dict(os.environ)
    env.pop(_AXON_VAR, None)  # workers must never register a device plugin
    env["JAX_PLATFORMS"] = "cpu"
    root = os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    env["PYTHONPATH"] = root + os.pathsep + env.get("PYTHONPATH", "")
    workers = []
    for p in range(2):
        flight = os.path.join(tmp, f"flight_{p}")
        os.makedirs(flight)
        cmd = [
            sys.executable, "-m", "torrent_tpu", "fabric-verify", tdir, ddir,
            "--hasher", "cpu", "--num-processes", "2", "--process-id", str(p),
            "--heartbeat-dir", hb, "--heartbeat-interval", "0.1",
            "--lapse-after", "2.0", "--unit-mb", "1", "--batch-target", "64",
            "--byzantine-f", "1", "--audit-rate", "1.0",
            "--result-file", os.path.join(tmp, f"result_{p}.json"),
        ]
        if p == 1:
            cmd += ["--fault-plan", "forge_receipts=1"]
        wenv = dict(env)
        wenv["TORRENT_TPU_FLIGHT_DIR"] = flight
        workers.append(
            subprocess.Popen(
                cmd, env=wenv, stdout=subprocess.PIPE,
                stderr=subprocess.PIPE, text=True,
            )
        )
    codes = []
    try:
        for p, w in enumerate(workers):
            _, err = w.communicate(timeout=180)
            # one genuinely corrupt piece -> n_valid != n_pieces -> rc 2
            assert w.returncode == 2, (
                f"worker {p} should exit 2 (one corrupt piece), got "
                f"{w.returncode}:\n{err[-2000:]}"
            )
            codes.append(w.returncode)
    finally:
        for w in workers:
            if w.poll() is None:
                w.kill()
                w.communicate()
    assert codes[0] == codes[1], f"exit-code parity broken: {codes}"
    recs = []
    for p in range(2):
        with open(os.path.join(tmp, f"result_{p}.json")) as f:
            recs.append(json.load(f))
    assert recs[0]["bitfields"] == recs[1]["bitfields"], (
        "global bitfields diverge between the honest worker and the liar"
    )
    bits = recs[0]["bitfields"][0]  # "0"/"1" chars, one per piece
    assert recs[0]["n_valid"] == npieces - 1 and bits[bad_piece] == "0", (
        f"corrupt piece survived the quorum: {recs[0]['n_valid']}/{npieces}, "
        f"bit {bits[bad_piece]!r}"
    )
    for p, rec in enumerate(recs):
        assert rec["byzantine_f"] == 1 and rec["quorum_need"] == 2, rec
        assert 1 in rec["distrusted"], (
            f"worker {p} never convicted the liar: {rec['distrusted']}"
        )
        assert rec["convictions"] >= 1, f"worker {p}: no conviction recorded"
        dumps = [
            n for n in os.listdir(os.path.join(tmp, f"flight_{p}"))
            if n.startswith("blackbox_")
        ]
        assert len(dumps) == 1, (
            f"worker {p}: expected exactly one fabric_distrust flight "
            f"dump, found {dumps}"
        )
        with open(os.path.join(tmp, f"flight_{p}", dumps[0])) as f:
            dump = json.load(f)
        assert dump.get("reason") == "fabric_distrust", dump.get("reason")
    assert recs[0]["audit_checks"] >= 1, "honest worker ran no audits"
    assert recs[0]["audit_mismatches"] >= 1, (
        "honest worker audits never caught the forged claim"
    )
    return (
        f"liar convicted on both processes ({recs[0]['audit_checks']}+"
        f"{recs[1]['audit_checks']} audits, "
        f"{recs[0]['audit_mismatches']} mismatch); bitfields identical, "
        f"{recs[0]['n_valid']}/{npieces} pieces valid, 1 flight dump each"
    )


def _fleet_smoke(tmp: str) -> str:
    """Fleet-observability self-test (``--fleet``): two real
    fabric-verify worker subprocesses over the shared-directory
    heartbeat, worker 0 fault-throttled with a ``latency_ms`` plan (the
    slow-interconnect model, accounted to its h2d ledger stage) and
    worker 1 serving its live obs surface (``--obs-port``). Worker 1's
    ``/v1/fleet`` — the heartbeat-carried digests merged by
    obs/fleet — must name worker 0 as the fleet's limiting process and
    ``h2d`` as its limiting stage: cross-process bottleneck
    attribution proven deterministically on CPU, from the PEER's point
    of view. Also exercises the ``top --fleet`` renderer on the live
    payload."""
    import json
    import urllib.request

    import numpy as np

    from torrent_tpu.tools.make_torrent import make_torrent
    from torrent_tpu.tools.top import render_fleet

    plen = 16384
    rng = np.random.default_rng(17)
    tdir = os.path.join(tmp, "torrents")
    ddir = os.path.join(tmp, "data")
    os.makedirs(tdir)
    # 96 + 160 pieces at 16 KiB = 5 one-MiB work units across 2 workers
    for t, npieces in enumerate((96, 160)):
        root = os.path.join(ddir, f"fleet{t}")
        os.makedirs(root)
        payload = os.path.join(root, "payload.bin")
        with open(payload, "wb") as f:
            f.write(
                rng.integers(
                    0, 256, (npieces - 1) * plen + plen // 3, dtype=np.uint8
                ).tobytes()
            )
        with open(os.path.join(tdir, f"fleet{t}.torrent"), "wb") as f:
            f.write(
                make_torrent(payload, "http://t.invalid/announce", piece_length=plen)
            )
    hb = os.path.join(tmp, "hb")
    port_file = os.path.join(tmp, "obs_port")
    env = dict(os.environ)
    env.pop(_AXON_VAR, None)  # workers must never register a device plugin
    env["JAX_PLATFORMS"] = "cpu"
    root = os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    env["PYTHONPATH"] = root + os.pathsep + env.get("PYTHONPATH", "")
    workers = []
    for p in range(2):
        cmd = [
            sys.executable, "-m", "torrent_tpu", "fabric-verify", tdir, ddir,
            "--hasher", "cpu", "--num-processes", "2", "--process-id", str(p),
            "--heartbeat-dir", hb, "--heartbeat-interval", "0.1",
            "--lapse-after", "30", "--unit-mb", "1", "--batch-target", "16",
            "--result-file", os.path.join(tmp, f"result_{p}.json"),
        ]
        if p == 0:
            # worker 0 is the designated straggler: every launch's h2d
            # sleeps 250 ms, so its shard dominates the sweep's wall
            cmd += ["--fault-plan", "latency_ms=250"]
        else:
            cmd += ["--obs-port", "0", "--obs-port-file", port_file]
        workers.append(
            subprocess.Popen(
                cmd, env=env, stdout=subprocess.PIPE,
                stderr=subprocess.PIPE, text=True,
            )
        )
    live_fleet = None
    live_frames = 0
    try:
        deadline = time.monotonic() + 180
        port = None
        while time.monotonic() < deadline:
            if all(w.poll() is not None for w in workers):
                break
            if port is None:
                try:
                    with open(port_file) as f:
                        port = int(f.read().strip())
                except (OSError, ValueError):
                    time.sleep(0.1)
                    continue
            try:
                with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/v1/fleet", timeout=5
                ) as r:
                    live_fleet = json.loads(r.read().decode())
                    live_frames += 1
            except (OSError, ValueError):
                pass
            time.sleep(0.1)
        for p, w in enumerate(workers):
            _, err = w.communicate(timeout=60)
            assert w.returncode == 0, f"worker {p} failed:\n{err[-2000:]}"
    finally:
        for w in workers:
            if w.poll() is None:
                w.kill()
                w.communicate()
    # the deterministic check: worker 1's FINAL fleet view (the result
    # record embeds it) must name worker 0 / h2d — the two-level verdict
    with open(os.path.join(tmp, "result_1.json")) as f:
        rec = json.load(f)
    assert rec["n_valid"] == rec["n_pieces"], (
        f"sweep left pieces unverified: {rec['n_valid']}/{rec['n_pieces']}"
    )
    fleet = rec.get("fleet") or {}
    bn = fleet.get("bottleneck") or {}
    assert bn.get("pid") == 0, (
        f"peer view did not name the throttled worker 0 as limiting: {bn}"
    )
    assert bn.get("stage") == "h2d", (
        f"peer view did not name h2d as worker 0's limiting stage: {bn}"
    )
    assert fleet.get("reporting", 0) == 2, f"peer digest missing: {fleet}"
    assert fleet.get("digest_drops", 0) == 0, fleet
    row0 = next(r for r in fleet["scoreboard"] if r["pid"] == 0)
    assert row0.get("limiting_stage") == "h2d", row0
    # the live surface answered while the sweep ran, and the top --fleet
    # renderer names the same verdict from the same payload
    assert live_frames > 0, "worker 1's /v1/fleet never answered"
    frame = render_fleet(live_fleet)
    assert "fleet bottleneck: process 0 (h2d)" in render_fleet(fleet), (
        f"top --fleet rendering lost the verdict:\n{render_fleet(fleet)}"
    )
    return (
        f"worker0 h2d-throttled; peer's /v1/fleet named pid 0/h2d "
        f"({bn.get('utilization', 0) * 100:.0f}% util, "
        f"{fleet['reporting']}/2 digests, {live_frames} live frames, "
        f"{len(frame.splitlines())}-line top frame)"
    )


async def _trace_smoke() -> str:
    """Observability smoke (``--trace``): a traced, fault-injected run
    must produce (a) an ordered span tree covering the ticket lifecycle
    (enqueue → admission → lane wait → launch → digest), (b) latency-
    histogram series for the queue-wait and launch stages, and (c)
    exactly one flight-recorder dump for a retry-exhausted launch and
    one for a breaker-open transition. Deterministic and CPU-only —
    the same machinery ``GET /v1/trace`` and ``torrent-tpu trace
    dump`` expose on a live bridge."""
    from torrent_tpu.obs import flight_recorder, histograms, tracer
    from torrent_tpu.sched import (
        FaultPlan,
        HashPlaneScheduler,
        SchedLaunchError,
        SchedulerConfig,
    )

    t = tracer()
    base = flight_recorder().counts()

    # (a)+(b): a healthy traced submission
    sched = HashPlaneScheduler(
        SchedulerConfig(batch_target=8, flush_deadline=0.05), hasher="cpu"
    )
    await sched.start()
    try:
        pieces = [bytes([i]) * 256 for i in range(4)]
        want = [hashlib.sha1(p).digest() for p in pieces]
        tid = t.mint()
        with t.span("doctor.trace", trace_id=tid):
            assert await sched.submit("doctor", pieces) == want
    finally:
        await sched.close()
    tree = t.trace_tree(tid)
    assert tree is not None, "trace not recorded"

    def names(node):
        yield node["name"]
        for c in node["children"]:
            yield from names(c)

    got = [n for root in tree["spans"] for n in names(root)]
    for stage in ("sched.enqueue", "sched.admission", "sched.lane_wait",
                  "sched.launch", "sched.digest"):
        assert stage in got, f"span tree missing {stage}: {got}"
    rendered = histograms().render()
    for family in ("torrent_tpu_sched_queue_wait_seconds",
                   "torrent_tpu_sched_launch_seconds"):
        assert f"{family}_bucket" in rendered, f"no {family} histogram"

    # (c) retry-exhausted: a poisoned single-piece launch fails alone
    plan = FaultPlan(payload_prefix=b"\xbd\xbd")
    sched = HashPlaneScheduler(
        SchedulerConfig(
            batch_target=4, flush_deadline=0.05,
            plane_factory=plan.plane_factory(hasher="cpu"),
        ),
        hasher="cpu",
    )
    await sched.start()
    try:
        try:
            await sched.submit("doctor", [b"\xbd\xbd" + b"x" * 64])
            raise AssertionError("poisoned launch unexpectedly succeeded")
        except SchedLaunchError:
            pass
    finally:
        await sched.close()

    # (c) breaker-open: enough consecutive transient faults to trip the
    # breaker; the CPU fallback still answers, so the ticket succeeds
    plan = FaultPlan(fail_first=2)
    sched = HashPlaneScheduler(
        SchedulerConfig(
            batch_target=4, flush_deadline=0.05, breaker_threshold=2,
            launch_retries=2, breaker_cooldown=300.0,
            plane_factory=plan.plane_factory(hasher="cpu"),
        ),
        hasher="cpu",
    )
    await sched.start()
    try:
        pieces = [bytes([i]) * 128 for i in range(2)]
        want = [hashlib.sha1(p).digest() for p in pieces]
        assert await sched.submit("doctor", pieces) == want
    finally:
        await sched.close()

    counts = flight_recorder().counts()
    retry = counts.get("retry_exhausted", 0) - base.get("retry_exhausted", 0)
    brk = counts.get("breaker_open", 0) - base.get("breaker_open", 0)
    assert retry == 1, f"expected exactly 1 retry_exhausted dump, got {retry}"
    assert brk == 1, f"expected exactly 1 breaker_open dump, got {brk}"
    return (
        f"{tree['span_count']}-span tree, queue-wait/launch histograms, "
        f"1 retry-exhausted + 1 breaker-open dump"
    )


async def _bottleneck_smoke(throttled: bool, tmp: str) -> str:
    """Pipeline-ledger smoke (``--bottleneck``): a scheduler-fed library
    recheck with the ledger attributing every stage boundary
    (read → stage → h2d → launch → digest → verdict). Plain mode
    reports the attribution; with ``--faults`` the H2D stage is
    latency-throttled through ``sched/faults.py``'s ``latency_ms`` hook
    (the slow-interconnect model) and the attributor MUST name ``h2d``
    as the limiting stage with the majority of pipeline wall time —
    the deterministic, CPU-only proof that bottleneck attribution
    works. The same verdict is served by ``GET /v1/pipeline`` and
    rendered by ``torrent-tpu top``."""
    import numpy as np

    from torrent_tpu.codec.metainfo import parse_metainfo
    from torrent_tpu.obs.attrib import attribute, format_report
    from torrent_tpu.obs.ledger import pipeline_ledger
    from torrent_tpu.parallel.bulk import verify_library_sched
    from torrent_tpu.sched import FaultPlan, HashPlaneScheduler, SchedulerConfig
    from torrent_tpu.storage.storage import FsStorage, Storage
    from torrent_tpu.tools.make_torrent import make_torrent

    payload = os.path.join(tmp, "bottleneck.bin")
    with open(payload, "wb") as f:
        f.write(
            np.random.default_rng(5)
            .integers(0, 256, 64 * 16384, dtype=np.uint8)
            .tobytes()
        )
    meta = parse_metainfo(
        make_torrent(payload, "http://t.invalid/announce", piece_length=16384)
    )
    storage = Storage(FsStorage(tmp), meta.info)

    factory = None
    if throttled:
        factory = FaultPlan(latency_s=0.03).plane_factory(hasher="cpu")
    led = pipeline_ledger()
    prev = led.snapshot()
    sched = HashPlaneScheduler(
        SchedulerConfig(
            batch_target=16, flush_deadline=0.02, plane_factory=factory
        ),
        hasher="cpu",
    )
    await sched.start()
    try:
        res = await verify_library_sched(
            [(storage, meta.info)], sched, tenant="doctor"
        )
    finally:
        await sched.close()
    assert int(res.bitfields[0].sum()) == meta.info.num_pieces, (
        "recheck left pieces unverified"
    )
    rep = attribute(led.snapshot(), prev=prev)
    assert rep["bottleneck"] is not None, "ledger recorded no activity"
    # zero-copy ingest proof: the scheduler-fed recheck reads straight
    # into staging slabs, so the `stage` copy stage must account ~zero
    # bytes — and every slab must have come back to its pool
    stage_bytes = rep["stages"].get("stage", {}).get("bytes", 0)
    assert stage_bytes == 0, (
        f"zero-copy path still staged {stage_bytes} bytes"
    )
    staging = sched.metrics_snapshot().get("staging", {})
    assert staging.get("outstanding", 0) == 0, (
        f"staging slabs leaked: {staging}"
    )
    if throttled:
        bn = rep["bottleneck"]
        assert bn["stage"] == "h2d", (
            f"throttled H2D not named as limiting stage: {bn}"
        )
        assert bn["utilization"] > 0.5, (
            f"throttled H2D should own the majority of wall time: {bn}"
        )
    return format_report(rep) + "; zero-copy: stage 0 B, slabs all returned"


async def _control_smoke() -> str:
    """Scheduler-autopilot smoke (``--control``): an in-process
    scheduler whose plane is h2d-throttled through ``sched/faults.py``
    (``latency_ms`` — the slow-interconnect model) runs waves of
    submissions while the autopilot ticks between them. The controller
    must (a) name ``h2d`` as the confirmed bottleneck, (b) move the
    batch actuator TOWARD it — grow the lane's flush target so fewer,
    bigger launches amortize the fixed per-launch transfer cost — and
    (c) pull the admission budget down to what the limiting stage
    drains. A disabled controller ticking over the same scheduler must
    move nothing (controller-off = bit-identical static config).
    Deterministic and CPU-only; the decisions are pure functions of
    ledger/lane snapshot deltas."""
    import hashlib as _hashlib

    from torrent_tpu.sched import (
        ControlConfig,
        FaultPlan,
        HashPlaneScheduler,
        SchedulerAutopilot,
        SchedulerConfig,
    )

    base_target = 8
    plan = FaultPlan.parse("latency_ms=40")
    sched = HashPlaneScheduler(
        SchedulerConfig(
            batch_target=base_target,
            flush_deadline=0.02,
            plane_factory=plan.plane_factory(hasher="cpu"),
        ),
        hasher="cpu",
    )
    await sched.start()
    pilot = SchedulerAutopilot(
        sched, ControlConfig(enabled=True, hysteresis_ticks=1, cooldown_ticks=0)
    )
    try:
        pieces = [bytes([i % 251]) * 1024 for i in range(64)]
        want = [_hashlib.sha1(p).digest() for p in pieces]
        pilot.tick()  # baseline snapshots
        last = None
        for _ in range(3):
            assert await sched.submit("doctor", pieces) == want, (
                "digests diverged under autopilot control"
            )
            last = pilot.tick()
        decision = last["decision"]
        bn = decision.get("bottleneck") or {}
        assert bn.get("stage") == "h2d", (
            f"controller did not name the throttled h2d stage: {decision}"
        )
        snap = sched.metrics_snapshot()
        lane = next(iter(snap["lane_stats"].values()))
        assert lane["target"] > base_target, (
            f"batch actuator did not move toward the bottleneck: {lane}"
        )
        assert snap["admission_factor"] < 1.0, (
            f"admission budget did not follow the limiting stage: "
            f"{snap['admission_factor']}"
        )
        grown = lane["target"]
        factor = snap["admission_factor"]

        # controller-off parity: a DISABLED pilot over a fresh scheduler
        # must leave every actuator at its static value
        plan2 = FaultPlan.parse("latency_ms=40")
        sched2 = HashPlaneScheduler(
            SchedulerConfig(
                batch_target=base_target,
                flush_deadline=0.02,
                plane_factory=plan2.plane_factory(hasher="cpu"),
            ),
            hasher="cpu",
        )
        await sched2.start()
        try:
            pilot2 = SchedulerAutopilot(sched2, ControlConfig(enabled=False))
            pilot2.tick()
            assert await sched2.submit("doctor", pieces) == want
            off = pilot2.tick()
            assert not off.get("applied"), f"disabled pilot applied {off}"
            snap2 = sched2.metrics_snapshot()
            lane2 = next(iter(snap2["lane_stats"].values()))
            assert lane2["target"] == base_target, lane2
            assert snap2["admission_factor"] == 1.0, snap2
        finally:
            await sched2.close()
    finally:
        await sched.close()
    return (
        f"h2d confirmed limiting; lane target {base_target}→{grown}, "
        f"admission ×{factor:.2f}; disabled controller moved nothing"
    )


async def _slo_smoke() -> str:
    """SLO-engine smoke (``--slo``): a ``--slo``-armed bridge with a
    deterministic ``FaultPlan`` payload-poison plan. Healthy traffic
    keeps ``/v1/health`` ready; a burst of poisoned pieces (every piece
    fails deterministically → ``failed_pieces`` burns the availability
    budget) must drive ``/v1/slo`` into a fast-burn breach, flip
    ``/v1/health`` ready→degraded (503), and fire exactly ONE
    ``slo_breach`` flight-recorder dump; healthy traffic afterwards must
    clear the breach and restore readiness. Timeline samples are driven
    manually (``sampler.sample_once()``) so the whole scenario is
    deterministic on CPU — no cadence races."""
    import json as _json

    from torrent_tpu.bridge.service import BridgeServer
    from torrent_tpu.codec.bencode import bencode
    from torrent_tpu.obs.recorder import flight_recorder
    from torrent_tpu.sched import FaultPlan

    poison = b"DOCTORPOISON"
    _http = _http_request

    svc = await BridgeServer(
        "127.0.0.1", port=0, hasher="cpu",
        fault_plan=FaultPlan.parse(f"payload={poison.hex()}"),
        slo="availability=0.99", timeline_interval_s=3600.0,
        slo_short_samples=4, slo_long_samples=64,
    ).start()
    try:
        await svc._probe_task  # readiness gates on the resolved probe
        base_dumps = flight_recorder().counts().get("slo_breach", 0)
        good = bencode({b"pieces": [b"healthy-piece-%d" % i for i in range(8)]})
        svc.sampler.sample_once()
        status, _ = await _http(svc.port, "POST", "/v1/digests", good)
        assert status == 200, f"healthy wave failed: {status}"
        svc.sampler.sample_once()
        status, body = await _http(svc.port, "GET", "/v1/health")
        health = _json.loads(body)
        assert status == 200 and health["status"] == "ready", health

        # the burst: every piece carries the poison prefix → the whole
        # launch fails deterministically → failed_pieces burns budget
        bad = bencode({b"pieces": [poison + b"-%d" % i for i in range(8)]})
        status, _ = await _http(svc.port, "POST", "/v1/digests", bad)
        assert status == 500, f"poisoned wave should 500: {status}"
        svc.sampler.sample_once()
        status, body = await _http(svc.port, "GET", "/v1/slo")
        slo = _json.loads(body)
        avail = slo["report"]["objectives"]["availability"]
        assert avail["breach"] and avail["classification"] == "fast_burn", avail
        assert avail["budget_remaining"] < 1.0, avail
        status, body = await _http(svc.port, "GET", "/v1/health")
        health = _json.loads(body)
        assert status == 503 and health["status"] == "degraded", health
        dumps = flight_recorder().counts().get("slo_breach", 0) - base_dumps
        assert dumps == 1, f"expected exactly one slo_breach dump, got {dumps}"

        # recovery: healthy waves push the errors out of the short
        # window; the breach clears and readiness returns
        for _ in range(5):
            status, _ = await _http(svc.port, "POST", "/v1/digests", good)
            assert status == 200
            svc.sampler.sample_once()
        status, body = await _http(svc.port, "GET", "/v1/health")
        health = _json.loads(body)
        assert status == 200 and health["status"] == "ready", health
        dumps = flight_recorder().counts().get("slo_breach", 0) - base_dumps
        assert dumps == 1, f"recovery must not re-dump: {dumps}"
        burned = avail["budget_remaining"]
    finally:
        svc.close()
        await svc.wait_closed()
    return (
        f"availability fast-burn breach (budget {burned * 100:.0f}% left), "
        "health ready→degraded→ready, exactly one slo_breach dump"
    )


async def _announce_smoke() -> str:
    """Announce-plane smoke (``--announce``): concurrent announce storms
    from multiple simulated swarms against the sharded store, then
    three contracts checked:

    - sampled replies are well-formed (≤ numwant peers, valid ports,
      never the requester itself);
    - shard counts reconcile (per-shard peer sums == store totals ==
      scrape sums — no peer lost or double-counted across shard locks);
    - the batch path (the UDP drain's shape) returns one outcome per
      announce in order.
    """
    import hashlib

    from torrent_tpu.net.types import AnnounceEvent
    from torrent_tpu.server.shard import ShardedSwarmStore

    n_workers, per_worker = 4, 50
    store = ShardedSwarmStore(n_shards=4)
    swarm_hashes = [
        hashlib.sha1(b"doctor-swarm-%d" % i).digest() for i in range(4)
    ]

    def worker(wi: int) -> None:
        for k in range(per_worker):
            ih = swarm_hashes[(wi + k) % len(swarm_hashes)]
            pid = (b"W%dK%03d" % (wi, k)).ljust(20, b"w")
            store.announce(
                ih, pid, f"10.1.{wi}.{k}", 7000 + wi,
                left=k % 2, event=AnnounceEvent.EMPTY, numwant=20,
            )

    await asyncio.gather(
        *(asyncio.to_thread(worker, wi) for wi in range(n_workers))
    )

    probe_id = b"probe".ljust(20, b"q")
    out = store.announce(
        swarm_hashes[0], probe_id, "10.9.9.9", 9999, left=1, numwant=10
    )
    assert len(out.peers) <= 10, f"reply overflows numwant: {len(out.peers)}"
    assert all(0 < p.port < 65536 for p in out.peers), "invalid sampled port"
    assert all(p.peer_id != probe_id for p in out.peers), "sampled self"
    assert out.complete + out.incomplete >= len(out.peers)

    snap = store.metrics_snapshot()
    expected = n_workers * per_worker + 1  # unique announcers + the probe
    assert snap["peers"] == expected, (snap["peers"], expected)
    assert snap["peers"] == sum(s["peers"] for s in snap["shards"])
    sc = store.scrape(swarm_hashes)
    assert sum(c + i for _, c, _, i in sc) == expected, "scrape diverges"
    shards_hit = sum(1 for s in snap["shards"] if s["peers"])
    assert shards_hit >= 2, f"swarms all landed on one shard: {snap}"

    batch = [
        (swarm_hashes[i % 4], (b"B%02d" % i).ljust(20, b"b"),
         "10.2.0.1", 8000 + i, 1, AnnounceEvent.EMPTY, 5)
        for i in range(8)
    ]
    outs = store.announce_batch(batch)
    assert len(outs) == len(batch) and all(o.interval > 0 for o in outs)
    return (
        f"{snap['peers']} peers / {snap['swarms']} swarms reconcile across "
        f"{shards_hit}/4 shards; sampled replies ≤ numwant, batch path ok"
    )


def _lint_smoke() -> str:
    """Analysis-plane smoke (``--lint``): run all eight static passes
    over the installed package and require a clean gate — zero findings
    beyond the committed baseline (= what `torrent-tpu lint` enforces)."""
    from torrent_tpu.analysis.findings import diff_baseline, load_baseline
    from torrent_tpu.analysis.lint import default_baseline, default_root
    from torrent_tpu.analysis.passes import ALL_PASS_NAMES, run_passes

    root = default_root()
    findings, _index = run_passes(root)
    baseline = load_baseline(default_baseline(root))
    diff = diff_baseline(findings, baseline)
    if diff.new:
        lines = "; ".join(f.format() for f in diff.new[:5])
        raise AssertionError(
            f"{len(diff.new)} finding(s) beyond baseline: {lines}"
        )
    return (
        f"{len(ALL_PASS_NAMES)} passes, {len(findings)} findings, "
        f"all baselined ({len(baseline)} baseline entries)"
    )


def _scenario_smoke(name: str) -> str:
    """Scenario-plane smoke (``--scenario``): run one bundled
    hostile-internet scenario TWICE against the real serve stack. The
    verdict must pass (all behavior invariants held, no SLO objective
    breached), the wall-plane announce latency must hold its budget,
    and the two same-seed runs must produce bit-identical canonical
    verdict + timeline bytes — the determinism contract the replay
    surface depends on."""
    from torrent_tpu.scenario import canonical_bytes, run_scenario
    from torrent_tpu.scenario.library import get

    spec = get(name)
    first = run_scenario(spec)
    second = run_scenario(spec)
    b1 = canonical_bytes(first["verdict"], first["timeline"])
    b2 = canonical_bytes(second["verdict"], second["timeline"])
    if b1 != b2:
        raise AssertionError(
            "same-seed replay diverged: canonical verdict/timeline "
            f"bytes differ ({len(b1)} vs {len(b2)} bytes)"
        )
    verdict = first["verdict"]
    if not verdict["pass"]:
        raise AssertionError(
            "scenario failed: " + "; ".join(verdict["reasons"][:4])
        )
    wall = verdict["wall"]
    if not wall["ok"]:
        raise AssertionError(
            f"wall plane over budget: announce p99 {wall['p99_us']}us "
            f"vs {wall['budget_ms']}ms budget"
        )
    return (
        f"{verdict['population']} actors x {spec.ticks} ticks; "
        f"{verdict['budget']}; announce p99 {wall['p99_us']}us "
        f"({wall['announces_per_s']}/s) within {wall['budget_ms']}ms; "
        "replay bit-identical"
    )


async def _seed_smoke(tmp: str) -> str:
    """Seeder-plane smoke (``--seed``): ONE seeding client against a
    small crowd of raw-wire leechers dialing the listen port directly
    (no tracker — the serve side is the exam, not discovery):

    - every leecher downloads one full piece and the bytes must match
      the authored payload (the reactor + egress path serves correct
      frames under concurrency);
    - the serve telemetry's egress fallback matrix must show zero-copy
      traffic (``sendfile`` where the platform allows, ``preadv``
      staging otherwise) — a single-file FsStorage layout maps every
      block contiguously, so a smoke that served only via the ``copy``
      path means the zero-copy plane silently disengaged;
    - the choke economics must have run rounds AND rotated the
      optimistic slot (more interested leechers than slots);
    - ``/v1/swarm`` on the session MetricsServer must carry the
      serving-side ``serve`` entries, and ``/metrics`` the
      ``torrent_tpu_serve_*`` families.
    """
    import json as _json

    import numpy as np

    from torrent_tpu.codec.metainfo import parse_metainfo
    from torrent_tpu.net import protocol as proto
    from torrent_tpu.serve_plane.telemetry import serve_telemetry
    from torrent_tpu.session.client import Client, ClientConfig
    from torrent_tpu.session.torrent import TorrentConfig
    from torrent_tpu.tools.make_torrent import make_torrent
    from torrent_tpu.utils.metrics import MetricsServer

    piece_len = 65536
    block = 16384
    n_leechers = 6
    payload = np.random.default_rng(23).integers(
        0, 256, 8 * piece_len, dtype=np.uint8
    ).tobytes()
    seed_dir = os.path.join(tmp, "seedplane")
    os.makedirs(seed_dir)
    with open(os.path.join(seed_dir, "seed.bin"), "wb") as f:
        f.write(payload)
    meta = parse_metainfo(
        make_torrent(
            os.path.join(seed_dir, "seed.bin"),
            "http://127.0.0.1:1/announce",
            piece_length=piece_len,
        )
    )
    n_pieces = len(payload) // piece_len
    # fast rounds + fewer slots than leechers: rotations must happen in
    # smoke time, and the crowd must contend for the unchoke slots
    seed = Client(ClientConfig(
        port=0, enable_upnp=False, resume=False,
        torrent=TorrentConfig(choke_interval=0.1, unchoke_slots=2),
    ))
    base = serve_telemetry().snapshot()
    base_paths = {
        k: v.get("blocks", 0) for k, v in (base.get("paths") or {}).items()
    }
    await seed.start()
    metrics = await MetricsServer(seed).start()
    writers: list = []
    try:
        t = await seed.add(meta, seed_dir)
        assert t.bitfield.complete, "seed recheck failed"

        async def leech(i: int) -> None:
            reader, writer = await asyncio.open_connection(
                "127.0.0.1", seed.port
            )
            writers.append(writer)
            pid = (b"-DC0001-" + f"{i:012d}".encode())[:20]
            await proto.send_handshake(writer, meta.info_hash, pid)
            await proto.read_handshake_head(reader)
            await proto.read_handshake_peer_id(reader)
            await proto.send_message(writer, proto.Interested())
            piece = i % n_pieces
            offsets = list(range(0, piece_len, block))
            got: dict[int, bytes] = {}
            while len(got) < len(offsets):
                msg = await proto.read_message(reader)
                if isinstance(msg, proto.Unchoke):
                    # (re-)request everything still missing — a choke
                    # tick may have silently dropped queued requests
                    for off in offsets:
                        if off not in got:
                            await proto.send_message(
                                writer, proto.Request(piece, off, block)
                            )
                elif isinstance(msg, proto.Piece) and msg.index == piece:
                    got[msg.begin] = msg.block
            data = b"".join(got[off] for off in offsets)
            want = payload[piece * piece_len:(piece + 1) * piece_len]
            assert data == want, f"leecher {i}: piece {piece} bytes diverge"

        await asyncio.wait_for(
            asyncio.gather(*(leech(i) for i in range(n_leechers))), 60
        )

        # the serving-side entries ride /v1/swarm while peers are live
        status, body = await _http_request(metrics.port, "GET", "/v1/swarm")
        assert status == 200, status
        swarm_json = _json.loads(body)
        serve_view = swarm_json.get("serve")
        assert serve_view, "/v1/swarm carries no serve entries"
        assert serve_view["counts"]["serving"] >= 1, serve_view["counts"]
        assert serve_view["totals"]["blocks"] >= n_leechers * (
            piece_len // block
        ), serve_view["totals"]

        status, body = await _http_request(metrics.port, "GET", "/metrics")
        assert status == 200, status
        text = body.decode()
        assert 'torrent_tpu_serve_bytes_total{path="sendfile"}' in text
        assert "torrent_tpu_serve_choke_rounds_total" in text

        snap = serve_telemetry().snapshot()
        paths = {
            k: v.get("blocks", 0) - base_paths.get(k, 0)
            for k, v in (snap.get("paths") or {}).items()
        }
        zero_copy = paths.get("sendfile", 0) + paths.get("preadv", 0)
        assert zero_copy > 0, (
            f"no zero-copy egress on a contiguous single-file layout "
            f"(fallback matrix: {paths})"
        )
        econ = t._serve_econ
        assert econ.rounds > 0, "choke economics never ran a round"
        assert econ.rotations > 0, "optimistic slot never rotated"
        served = dict(t._egress.served)
    finally:
        for w in writers:
            w.close()
        metrics.close()
        await seed.close()
    return (
        f"{n_leechers} leechers fed ({n_leechers} pieces bit-exact); "
        f"egress sendfile/preadv/copy = {served.get('sendfile', 0)}/"
        f"{served.get('preadv', 0)}/{served.get('copy', 0)} blocks; "
        f"{econ.rounds} choke rounds, {econ.rotations} optimistic rotations"
    )


async def _http_request(port: int, method: str, path: str, body: bytes = b""):
    """Minimal loopback HTTP round-trip (status, payload) — the bridge
    and SLO smokes share it; doctor must not depend on a client lib."""
    reader, writer = await asyncio.open_connection("127.0.0.1", port)
    writer.write(
        f"{method} {path} HTTP/1.1\r\nHost: x\r\n"
        f"Content-Length: {len(body)}\r\n\r\n".encode() + body
    )
    await writer.drain()
    status_line = await reader.readline()
    clen = 0
    while True:
        line = await reader.readline()
        if line in (b"\r\n", b""):
            break
        if line.lower().startswith(b"content-length:"):
            clen = int(line.split(b":", 1)[1])
    payload = await reader.readexactly(clen)
    writer.close()
    return int(status_line.split()[1]), payload


async def _bridge_smoke() -> None:
    from torrent_tpu.bridge.service import BridgeServer
    from torrent_tpu.codec.bencode import bdecode, bencode

    svc = await BridgeServer("127.0.0.1", port=0, hasher="cpu").start()
    try:
        status, resp = await _http_request(
            svc.port, "POST", "/v1/digests",
            bencode({b"pieces": [b"doctor"]}),
        )
        assert status == 200, status
        got = bdecode(resp)[b"digests"][0]
        assert got == hashlib.sha1(b"doctor").digest(), "bridge digest wrong"
    finally:
        svc.close()
        await svc.wait_closed()


def main(argv=None) -> int:
    import argparse

    # allow_abbrev=False keeps argparse in agreement with run_cli's
    # pre-argparse exact `"--json" in args` scan (an abbreviated `--js`
    # would otherwise enable JSON output without the stdout/stderr split)
    ap = argparse.ArgumentParser(
        prog="torrent-tpu doctor", description=__doc__, allow_abbrev=False
    )
    ap.add_argument(
        "--device-wait",
        type=float,
        default=20.0,
        help="seconds to wait for the device probe before moving on",
    )
    ap.add_argument(
        "--skip-swarm", action="store_true", help="skip the loopback swarm smoke"
    )
    ap.add_argument(
        "--faults",
        action="store_true",
        help="also run the fault-tolerance smoke: injected fail-then-recover "
        "plan proving bisection isolation and breaker trip/recovery",
    )
    ap.add_argument(
        "--v2",
        action="store_true",
        help="also run the BEP 52 plane smoke: leaf + merkle-pair digests vs "
        "hashlib through the scheduler's pallas sha256 lane (interpret-safe)",
    )
    ap.add_argument(
        "--fabric",
        action="store_true",
        help="also run the verify-fabric self-test: two local worker "
        "processes plan/execute/heartbeat over a shared directory, one "
        "dies mid-run, the survivor adopts and sentinel-checks its shard",
    )
    ap.add_argument(
        "--byzantine",
        action="store_true",
        help="also run the Byzantine-fabric self-test: two worker "
        "processes at byzantine_f=1, one publishing forged Merkle "
        "receipts over a genuinely corrupt piece; the audit plane must "
        "convict the liar with portable evidence on BOTH processes, "
        "bitfields must stay identical, and each process must dump "
        "exactly one fabric_distrust flight recording",
    )
    ap.add_argument(
        "--fleet",
        action="store_true",
        help="also run the fleet-observability smoke: two worker "
        "processes, one h2d-throttled via latency_ms faults; the healthy "
        "peer's /v1/fleet must name the throttled process (and its h2d "
        "stage) as the fleet bottleneck",
    )
    ap.add_argument(
        "--lint",
        action="store_true",
        help="also run the analysis-plane smoke: all eight static passes "
        "over the installed package, clean against the committed baseline",
    )
    ap.add_argument(
        "--trace",
        action="store_true",
        help="also run the observability smoke: traced fault-injected run "
        "producing a span tree, latency histograms, and flight-recorder "
        "dumps (retry-exhausted + breaker-open)",
    )
    ap.add_argument(
        "--bottleneck",
        action="store_true",
        help="also run the pipeline-ledger smoke: a scheduler-fed recheck "
        "attributed stage by stage (read/stage/h2d/launch/digest/verdict); "
        "combined with --faults the H2D stage is latency-throttled and the "
        "attributor must name it as the limiting stage",
    )
    ap.add_argument(
        "--control",
        action="store_true",
        help="also run the scheduler-autopilot smoke: an h2d-throttled "
        "scheduler under the controller must get its lane target grown "
        "and its admission budget pulled toward the limiting stage, while "
        "a disabled controller moves nothing",
    )
    ap.add_argument(
        "--slo",
        action="store_true",
        help="also run the SLO-engine smoke: a FaultPlan fail burst "
        "through a --slo bridge burns the availability budget, flips "
        "/v1/health ready→degraded, fires exactly one slo_breach "
        "flight dump, and recovers",
    )
    ap.add_argument(
        "--announce",
        action="store_true",
        help="also run the announce-plane smoke: concurrent announces "
        "from multiple simulated swarms against the sharded store; "
        "sampled replies must be well-formed and shard counts must "
        "reconcile with the store totals and scrape sums",
    )
    ap.add_argument(
        "--scenario",
        metavar="NAMES",
        help="run bundled hostile-internet scenarios (comma-separated "
        "names from scenario/library, e.g. sybil-stampede,churn-storm): "
        "each runs TWICE against the real serve stack on a virtual "
        "timeline — the SLO verdict must pass, the wall-plane announce "
        "latency must hold its budget, and the same-seed replay must be "
        "bit-identical",
    )
    ap.add_argument(
        "--swarm",
        action="store_true",
        help="also run the swarm wire-plane smoke: a throttled two-peer "
        "loopback download whose /v1/pipeline attribution must name the "
        "new recv stage limiting, /v1/swarm must report bounded "
        "per-peer telemetry (choke timeline, block-RTT p99, top-K + "
        "overflow), and a driven snub storm must fire exactly one "
        "flight dump",
    )
    ap.add_argument(
        "--seed",
        action="store_true",
        help="also run the seeder-plane smoke: one seeding client vs a "
        "crowd of raw-wire leechers dialing the port directly — every "
        "piece served bit-exact, the zero-copy egress counters "
        "(sendfile/preadv) non-zero on a contiguous layout, choke "
        "rounds rotating the optimistic slot, and /v1/swarm carrying "
        "the serving-side entries",
    )
    ap.add_argument(
        "--json",
        action="store_true",
        help="emit one JSON object after the checks (machine-readable)",
    )
    args = ap.parse_args(argv)
    global _JSON_MODE
    _JSON_MODE = args.json  # direct main() callers (tests, embedding)

    def emit_json() -> None:
        if not args.json:
            return
        import json

        fails = sum(1 for s, _, _ in _RESULTS if s == "FAIL")
        warns = sum(1 for s, _, _ in _RESULTS if s == "WARN")
        print(
            json.dumps(
                {
                    "ok": fails == 0,
                    "fails": fails,
                    "warns": warns,
                    # the documented contract (module docstring): 0 all
                    # PASS/WARN, 1 any FAIL — mirrored here so CI can
                    # read one field instead of re-deriving it
                    "exit_code": 1 if fails else 0,
                    "checks": [
                        {"status": s, "name": n, "detail": d}
                        for s, n, d in _RESULTS
                    ],
                }
            )
        )

    _RESULTS.clear()  # main() may run more than once per process (tests)
    # watchdog before the first import that could block: numpy/jax
    # imports are where a mis-wired plugin environment can stall
    _say("doctor: checking deps…")
    if not _check_deps():
        _say("\n1 FAIL — core dependencies missing")
        emit_json()  # the broken-environment case is where JSON matters most
        return 1
    _check_device(args.device_wait)
    _check_kernels()
    _check_native_io()
    if not args.skip_swarm:
        with tempfile.TemporaryDirectory(prefix="doctor_") as tmp:
            try:
                asyncio.run(asyncio.wait_for(_swarm_smoke(tmp), 90))
                _report("PASS", "loopback swarm", "256 KiB author→seed→download")
            except Exception as e:
                _report("FAIL", "loopback swarm", repr(e))
    try:
        detail = asyncio.run(asyncio.wait_for(_sched_smoke(), 30))
        _report("PASS", "verify scheduler", detail)
    except Exception as e:
        _report("FAIL", "verify scheduler", repr(e))
    if args.faults:
        try:
            detail = asyncio.run(asyncio.wait_for(_faults_smoke(), 30))
            _report("PASS", "fault tolerance", detail)
        except Exception as e:
            _report("FAIL", "fault tolerance", repr(e))
    if args.v2:
        try:
            # generous bound: interpret-mode compiles of two lane
            # geometries dominate (the kernel itself is milliseconds)
            detail = asyncio.run(asyncio.wait_for(_v2_smoke(), 120))
            _report("PASS", "v2 hash plane", detail)
        except Exception as e:
            _report("FAIL", "v2 hash plane", repr(e))
    if args.lint:
        try:
            detail = _lint_smoke()
            _report("PASS", "analysis plane", detail)
        except Exception as e:
            _report("FAIL", "analysis plane", repr(e))
    if args.trace:
        try:
            detail = asyncio.run(asyncio.wait_for(_trace_smoke(), 30))
            _report("PASS", "observability plane", detail)
        except Exception as e:
            _report("FAIL", "observability plane", repr(e))
    if args.bottleneck:
        with tempfile.TemporaryDirectory(prefix="doctor_bn_") as tmp:
            try:
                detail = asyncio.run(
                    asyncio.wait_for(_bottleneck_smoke(args.faults, tmp), 60)
                )
                _report("PASS", "pipeline ledger", detail)
            except Exception as e:
                _report("FAIL", "pipeline ledger", repr(e))
    if args.control:
        try:
            detail = asyncio.run(asyncio.wait_for(_control_smoke(), 60))
            _report("PASS", "scheduler autopilot", detail)
        except Exception as e:
            _report("FAIL", "scheduler autopilot", repr(e))
    if args.announce:
        try:
            detail = asyncio.run(asyncio.wait_for(_announce_smoke(), 30))
            _report("PASS", "announce plane", detail)
        except Exception as e:
            _report("FAIL", "announce plane", repr(e))
    if args.scenario:
        for scenario_name in [
            n.strip() for n in args.scenario.split(",") if n.strip()
        ]:
            try:
                detail = _scenario_smoke(scenario_name)
                _report("PASS", f"scenario {scenario_name}", detail)
            except Exception as e:
                _report("FAIL", f"scenario {scenario_name}", repr(e))
    if args.swarm:
        with tempfile.TemporaryDirectory(prefix="doctor_wire_") as tmp:
            try:
                detail = asyncio.run(asyncio.wait_for(_swarm_wire_smoke(tmp), 90))
                _report("PASS", "swarm wire plane", detail)
            except Exception as e:
                _report("FAIL", "swarm wire plane", repr(e))
    if args.seed:
        with tempfile.TemporaryDirectory(prefix="doctor_seed_") as tmp:
            try:
                detail = asyncio.run(asyncio.wait_for(_seed_smoke(tmp), 90))
                _report("PASS", "seeder plane", detail)
            except Exception as e:
                _report("FAIL", "seeder plane", repr(e))
    if args.slo:
        try:
            detail = asyncio.run(asyncio.wait_for(_slo_smoke(), 60))
            _report("PASS", "slo engine", detail)
        except Exception as e:
            _report("FAIL", "slo engine", repr(e))
    if args.fabric:
        with tempfile.TemporaryDirectory(prefix="doctor_fabric_") as tmp:
            try:
                # bounded by the workers' communicate(timeout) inside
                detail = _fabric_smoke(tmp)
                _report("PASS", "verify fabric", detail)
            except Exception as e:
                _report("FAIL", "verify fabric", repr(e))
    if args.byzantine:
        with tempfile.TemporaryDirectory(prefix="doctor_byz_") as tmp:
            try:
                # bounded by the workers' communicate(timeout) inside
                detail = _byzantine_smoke(tmp)
                _report("PASS", "byzantine fabric", detail)
            except Exception as e:
                _report("FAIL", "byzantine fabric", repr(e))
    if args.fleet:
        with tempfile.TemporaryDirectory(prefix="doctor_fleet_") as tmp:
            try:
                # bounded by the poll deadline + communicate(timeout)
                detail = _fleet_smoke(tmp)
                _report("PASS", "fleet observability", detail)
            except Exception as e:
                _report("FAIL", "fleet observability", repr(e))
    try:
        asyncio.run(asyncio.wait_for(_bridge_smoke(), 30))
        _report("PASS", "bridge", "/v1/digests round-trip")
    except Exception as e:
        _report("FAIL", "bridge", repr(e))

    fails = sum(1 for s, _, _ in _RESULTS if s == "FAIL")
    warns = sum(1 for s, _, _ in _RESULTS if s == "WARN")
    _say(f"\n{len(_RESULTS)} checks: {fails} FAIL, {warns} WARN")
    emit_json()
    return 1 if fails else 0


if __name__ == "__main__":  # pragma: no cover - manual entrypoint
    raise SystemExit(run_cli())
