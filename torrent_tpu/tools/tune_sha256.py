"""On-device Pallas SHA-256 knob sweep (tile_sub x unroll) — leaf plane.

The v2 (BEP 52) hash plane hashes 16 KiB leaf blocks, a much shorter
chain (256 compression blocks) than the SHA-1 plane's 256 KiB pieces —
its best tiling need not match. Same measurement discipline as
tools/tune_sha1 (see BASELINE.md "Measured environment characteristics"):

- data generated ON device (TPU PRNG); only golden rows cross the tunnel
- every timed dispatch distinct (``rand ^ salt``, fresh salt each time)
- completion forced by fetching an on-device reduction of the LAST
  dispatch (plain block_until_ready returns early on relay backends)
- u32 fast-path input, the form the leaf plane uploads

Apply the winner via ``TORRENT_TPU_SHA256_TILE_SUB`` /
``TORRENT_TPU_SHA256_UNROLL`` (ops/sha256_pallas.py reads them at
import).

Usage::

    python -m torrent_tpu.tools.tune_sha256 [--block-kb 16] [--batch 32768]
        [--grid 8x16,16x16,32x8,32x16,32x32] [--iters 8]

Prints one ranked JSON line per config plus a ``best`` summary line.
"""

from __future__ import annotations

import argparse
import functools
import hashlib
import json
import os
import sys
import time

import numpy as np


def _parse_grid(spec: str) -> list[tuple[int, int]]:
    out = []
    for part in spec.split(","):
        ts, un = part.lower().split("x")
        out.append((int(ts), int(un)))
    return out


def _pad_tail(mlen: int) -> np.ndarray:
    """The 64-byte SHA-2 padding block for a message of exactly ``mlen``
    bytes (mlen % 64 == 0, so the pad is a standalone final block —
    identical framing to SHA-1: 0x80, zeros, 64-bit big-endian bitlen)."""
    assert mlen % 64 == 0
    tail = np.zeros(64, dtype=np.uint8)
    tail[0] = 0x80
    tail[-8:] = np.frombuffer((mlen * 8).to_bytes(8, "big"), dtype=np.uint8)
    return tail


def run_sweep(
    block_kb: int,
    batch: int,
    grid: list[tuple[int, int]],
    iters: int,
    interpret: bool = False,
):
    import jax

    if interpret:
        jax.config.update("jax_platforms", "cpu")
    else:
        # persist sweep compiles across processes (see tune_sha1.py)
        try:
            cache = os.path.join(
                os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
                ".bench",
                "xla_cache",
            )
            os.makedirs(cache, exist_ok=True)
            jax.config.update("jax_compilation_cache_dir", cache)
            jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
        except Exception:
            pass
    import jax.numpy as jnp

    from torrent_tpu.ops import sha256_pallas as sp
    from torrent_tpu.ops.padding import num_blocks_for, padded_len_for

    mlen = block_kb * 1024
    padded = padded_len_for(mlen)
    nblk = int(num_blocks_for(mlen))
    tail = np.zeros(padded - mlen, dtype=np.uint8)
    tail[:64] = _pad_tail(mlen)[: min(64, padded - mlen)]

    key = jax.random.key(20260730)

    @functools.partial(jax.jit, static_argnames="rows")
    def _gen(k, rows):
        return jax.random.bits(k, (rows, mlen // 4), jnp.uint32)

    rows_per = max(1, min(batch, (256 << 20) // mlen))
    parts = []
    for i, start in enumerate(range(0, batch, rows_per)):
        parts.append(_gen(jax.random.fold_in(key, i), min(rows_per, batch - start)))
    rand = jnp.concatenate(parts, axis=0) if len(parts) > 1 else parts[0]
    del parts
    rand_rows = {
        i: np.asarray(rand[i]).view(np.uint8).tobytes() for i in (0, batch - 1)
    }
    golden = {i: hashlib.sha256(rand_rows[i]).digest() for i in rand_rows}
    tail_dev = jax.device_put(tail.view(np.uint32))
    nblocks = jnp.full((batch,), nblk, dtype=jnp.int32)

    results = []
    # the straight-line 64-round body (full_unroll) can only compile on
    # real Mosaic — interpret mode would hang the XLA CPU simplifier —
    # and has no off-chip validation, so it is swept as an EXTRA
    # candidate with golden mismatches recorded, never fatal
    # (full_unroll, interleave2) combos: straight-line only on Mosaic;
    # the loop-form interleave IS interpret-safe, so smoke covers it
    variants = (
        [(False, False), (False, True)]
        if interpret
        else [(False, False), (True, False), (False, True), (True, True)]
    )
    for tile_sub, unroll in grid:
        if batch % (tile_sub * 128):
            print(
                f"# skip {tile_sub}x{unroll}: batch {batch} not a multiple of "
                f"tile {tile_sub * 128}",
                file=sys.stderr,
            )
            continue
      # fall through to the per-variant loop below

        for full, il2 in variants:
            if il2 and (tile_sub < 16 or (tile_sub // 2) % 8):
                continue  # halves must be whole vregs

            @jax.jit
            def hash_salted(
                r, t, nb, salt, _ts=tile_sub, _un=unroll, _fu=full, _il2=il2
            ):
                data = jnp.concatenate(
                    [r ^ salt, jnp.broadcast_to(t, (batch, t.shape[0]))], axis=1
                )
                return sp.sha256_pieces_pallas(
                    data, nb, interpret=interpret, tile_sub=_ts, unroll=_un,
                    full_unroll=_fu, interleave2=_il2,
                )

            reduce_sum = jax.jit(lambda s: jnp.sum(s, dtype=jnp.uint32))
            tag = {
                "tile_sub": tile_sub,
                "unroll": unroll,
                "full_unroll": full,
                "interleave2": il2,
            }

            try:
                t0 = time.perf_counter()
                state0 = hash_salted(rand, tail_dev, nblocks, jnp.uint32(0))
                got = np.asarray(state0[np.array([0, batch - 1])])
                compile_s = time.perf_counter() - t0
            except Exception as e:  # Mosaic can reject a tiling outright
                print(json.dumps({**tag, "error": repr(e)[:200]}))
                continue
            bad = False
            for row, idx in ((0, 0), (1, batch - 1)):
                want = np.frombuffer(golden[idx], dtype=">u4").astype(np.uint32)
                if not np.array_equal(got[row], want):
                    if full or il2:
                        # an experimental on-chip body (straight-line or
                        # interleaved — both invisible to CPU-interpret
                        # smoke) failed its golden: record and move on —
                        # never poison the sweep
                        print(json.dumps({**tag, "error": "golden mismatch"}))
                        bad = True
                        break
                    raise SystemExit(
                        f"golden mismatch at {tile_sub}x{unroll} row {idx}: "
                        f"{got[row]} != {want}"
                    )
            if bad:
                continue
            _ = int(reduce_sum(state0))  # warm the completion-forcing reduction

            t0 = time.perf_counter()
            outs = [
                hash_salted(rand, tail_dev, nblocks, jnp.uint32(s))
                for s in range(1, iters + 1)
            ]
            _ = int(reduce_sum(outs[-1]))
            secs = time.perf_counter() - t0
            bps = iters * batch / secs
            line = {
                **tag,
                "blocks_per_sec": round(bps, 1),
                "gib_per_sec": round(bps * mlen / 2**30, 2),
                "compile_s": round(compile_s, 1),
            }
            results.append(line)
            print(json.dumps(line), flush=True)

    if results:
        best = max(results, key=lambda r: r["blocks_per_sec"])
        # the winner as ready-to-export env knobs: the scheduler's pallas
        # plane and models/v2's leaf fn read these at import, so a rung
        # script can `export $(jq ...)` the sweep result straight into
        # the bench run (see .bench/r6_sha256_rung.sh)
        env = {
            "TORRENT_TPU_SHA256_TILE_SUB": best["tile_sub"],
            "TORRENT_TPU_SHA256_UNROLL": best["unroll"],
            "TORRENT_TPU_SHA256_FULL_UNROLL": int(best["full_unroll"]),
            "TORRENT_TPU_SHA256_INTERLEAVE2": int(best["interleave2"]),
        }
        print(json.dumps({"best": best, "env": env, "block_kb": block_kb, "batch": batch}))
    return results


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--block-kb", type=int, default=16)
    ap.add_argument("--batch", type=int, default=32768)
    ap.add_argument("--grid", default="8x16,16x16,32x8,32x16,32x32")
    ap.add_argument("--iters", type=int, default=8)
    ap.add_argument(
        "--interpret",
        action="store_true",
        help="interpret-mode kernel (CPU smoke test of the sweep itself)",
    )
    args = ap.parse_args()
    run_sweep(
        args.block_kb, args.batch, _parse_grid(args.grid), args.iters, args.interpret
    )


if __name__ == "__main__":
    main()
