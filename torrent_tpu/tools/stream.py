"""HTTP streaming of a downloading torrent (watch-while-fetching).

Serves one file of a session torrent over HTTP/1.1 with Range support
(the request shape media players emit). The reader position drives the
scheduler: each served chunk re-points the torrent's stream window
(`Torrent.set_stream_window`), so the pieces a player needs next jump
the queue, a mid-file seek re-points instantly, and the rest of the
download proceeds normally behind the window. Reads park on
`Torrent.wait_piece` until the data is verified on disk — bytes that
leave this server have always passed the hash plane.

No reference counterpart (its roadmap stops at a CLI, README.md:24-40);
this composes the existing selection/priority scheduler with a small
asyncio HTTP server, the same pattern popular streaming clients ship.
"""

from __future__ import annotations

import asyncio

from torrent_tpu.storage.storage import StorageError
from torrent_tpu.utils.log import get_logger

log = get_logger("tools.stream")

CHUNK = 256 * 1024  # read/serve granularity; also the window advance step


class BoxStreamServer:
    """Whole-client HTTP streamer (the seeding-box media server):
    ``GET /`` lists torrents, ``GET /<infohash-hex>/`` lists a torrent's
    files, ``GET /<infohash-hex>/<index>`` streams one (Range-capable,
    verified bytes only). Reuses the one-torrent StreamServer per
    registered torrent, routed by infohash."""

    def __init__(self, client, host: str = "127.0.0.1"):
        self.client = client
        self.host = host
        self.port: int | None = None
        self._server: asyncio.AbstractServer | None = None
        self._handlers: set[asyncio.Task] = set()
        self._per_torrent: dict[bytes, StreamServer] = {}

    async def start(self, port: int = 0) -> "BoxStreamServer":
        self._server = await asyncio.start_server(self._accept, self.host, port)
        self.port = self._server.sockets[0].getsockname()[1]
        return self

    def _accept(self, reader, writer):
        task = asyncio.ensure_future(self._handle(reader, writer))
        self._handlers.add(task)
        task.add_done_callback(self._handlers.discard)

    def close(self) -> None:
        if self._server is not None:
            self._server.close()
        for task in list(self._handlers):
            task.cancel()
        for sub in self._per_torrent.values():
            sub.torrent.clear_stream_window()

    def _sub(self, torrent) -> "StreamServer":
        key = torrent.metainfo.info_hash
        sub = self._per_torrent.get(key)
        if sub is None or sub.torrent is not torrent:
            # identity check: a removed-and-re-added torrent is a NEW
            # object; serving the cached dead one would park forever
            sub = self._per_torrent[key] = StreamServer(torrent, host=self.host)
        return sub

    async def _handle(self, reader, writer):
        try:
            parsed = await _parse_http_head(reader)
            if parsed is None:
                await _plain_response(writer, 405, b"method not allowed")
                return
            method, path, rng = parsed
            segs = [s for s in path.split("/") if s]
            if not segs:
                import json

                out = [
                    {
                        "info_hash": ih.hex(),
                        "name": t.info.name,
                        "files": sum(1 for _ in content_files(t)),
                        "complete": t.bitfield.complete,
                    }
                    for ih, t in self.client.torrents.items()
                ]
                body = json.dumps({"torrents": out}).encode()
                writer.write(
                    (
                        "HTTP/1.1 200 OK\r\nContent-Type: application/json\r\n"
                        f"Content-Length: {len(body)}\r\nConnection: close\r\n\r\n"
                    ).encode("latin-1")
                    + (body if method != b"HEAD" else b"")
                )
                await writer.drain()
                return
            try:
                torrent = self.client.torrents.get(bytes.fromhex(segs[0]))
            except ValueError:
                torrent = None
            if torrent is None:
                await _plain_response(writer, 404, b"no such torrent")
                return
            # delegate to the per-torrent server with the subpath
            sub = self._sub(torrent)
            subpath = "/" + "/".join(segs[1:]) if len(segs) > 1 else "/"
            await sub.serve_parsed(writer, method, subpath, rng)
        except (
            ConnectionError,
            asyncio.TimeoutError,
            asyncio.LimitOverrunError,
            ValueError,
            OSError,
            RuntimeError,
            LookupError,
            StorageError,
        ):
            pass
        finally:
            writer.close()


def _http_date() -> str:
    from email.utils import formatdate

    return formatdate(usegmt=True)


async def _parse_http_head(reader):
    """→ (method, path-without-query, range-header | None), or None for
    a non-GET/HEAD request line. One parser for both stream servers."""
    request = await asyncio.wait_for(reader.readline(), timeout=30)
    parts = request.split()
    if len(parts) < 2 or parts[0] not in (b"GET", b"HEAD"):
        return None
    method = parts[0]
    path = parts[1].decode("latin-1", "replace").split("?", 1)[0]
    rng = None
    while True:
        line = await asyncio.wait_for(reader.readline(), timeout=30)
        if line in (b"\r\n", b"\n", b""):
            break
        if line.lower().startswith(b"range:"):
            rng = line.split(b":", 1)[1].strip().decode("latin-1", "replace")
    return method, path, rng


async def _plain_response(writer, status: int, body: bytes, extra: str = "") -> None:
    writer.write(
        (
            f"HTTP/1.1 {status} x\r\nContent-Length: {len(body)}\r\n"
            f"{extra}Connection: close\r\n\r\n"
        ).encode("latin-1")
        + body
    )
    await writer.drain()


def content_files(torrent):
    """(index, display_path, start, length) for every non-pad file —
    the single source for what the streamer and the CLI announce."""
    entries = torrent.info.files or ()
    for i, (start, length) in enumerate(torrent.file_ranges()):
        fe = entries[i] if i < len(entries) else None
        if fe is not None and getattr(fe, "pad", False):
            continue  # BEP 47 pads aren't content
        name = "/".join(fe.path) if fe is not None else torrent.info.name
        yield i, name, start, length


class StreamServer:
    """One-torrent HTTP streamer: ``GET /<file_index>`` with Range
    support, backed by the torrent's verified storage. ``GET /`` (or
    ``/index.json``) returns a JSON file index — players and scripts
    discover indices there rather than guessing."""

    def __init__(self, torrent, host: str = "127.0.0.1", window_pieces: int = 16):
        self.torrent = torrent
        self.host = host
        self.window_pieces = window_pieces
        self.port: int | None = None
        self._server: asyncio.AbstractServer | None = None
        self._handlers: set[asyncio.Task] = set()

    async def start(self, port: int = 0) -> "StreamServer":
        self._server = await asyncio.start_server(self._accept, self.host, port)
        self.port = self._server.sockets[0].getsockname()[1]
        return self

    def _accept(self, reader, writer):
        # tracked so close() can cancel in-flight streams — a parked
        # reader must not outlive the server
        task = asyncio.ensure_future(self._handle(reader, writer))
        self._handlers.add(task)
        task.add_done_callback(self._handlers.discard)

    def close(self) -> None:
        if self._server is not None:
            self._server.close()
        for task in list(self._handlers):
            task.cancel()
        self.torrent.clear_stream_window()

    # ------------------------------------------------------------ request

    async def _handle(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter):
        try:
            parsed = await _parse_http_head(reader)
            if parsed is None:
                await self._plain(writer, 405, b"method not allowed")
                return
            await self.serve_parsed(writer, *parsed)
        except (
            ConnectionError,
            asyncio.TimeoutError,
            asyncio.LimitOverrunError,  # oversized request/header line
            ValueError,  # readline on a line past the stream limit
            OSError,
            RuntimeError,  # torrent stopped mid-stream (wait_piece)
            LookupError,  # piece deselected mid-stream (wait_piece)
            StorageError,  # file vanished under a mid-stream read
        ):
            pass
        finally:
            writer.close()

    async def serve_parsed(self, writer, method: bytes, path: str, rng) -> None:
        """Serve one already-parsed request (also the BoxStreamServer's
        delegation point; caller owns closing the writer and catching
        stream-abort exceptions)."""
        if path in ("/", "/index.json"):
            # discovery: players/users can't guess file indices
            await self._index(writer, method)
            return
        try:
            file_index = int(path.lstrip("/") or "0")
            if file_index < 0:
                raise IndexError("negative index")  # no wrap-around files
            start, length = self._file_span(file_index)
        except (ValueError, IndexError):
            await self._plain(writer, 404, b"no such file")
            return
        if not self.torrent.span_servable(start, length):
            # a deselected file's pieces will never be scheduled —
            # parking the reader would hang the connection forever
            await self._plain(writer, 409, b"file not selected for download")
            return
        lo, hi = 0, length - 1
        status = 200
        if rng is not None:
            parsed = self._parse_range(rng, length)
            if parsed is None:
                await self._plain(
                    writer,
                    416,
                    b"bad range",
                    extra=f"Content-Range: bytes */{length}\r\n",
                )
                return
            lo, hi = parsed
            status = 206
        headers = [
            f"HTTP/1.1 {status} {'Partial Content' if status == 206 else 'OK'}",
            f"Date: {_http_date()}",
            "Accept-Ranges: bytes",
            "Content-Type: application/octet-stream",
            f"Content-Length: {hi - lo + 1}",
            "Connection: close",
        ]
        if status == 206:
            headers.append(f"Content-Range: bytes {lo}-{hi}/{length}")
        writer.write(("\r\n".join(headers) + "\r\n\r\n").encode("latin-1"))
        await writer.drain()
        if method == b"HEAD":
            return
        await self._serve_span(writer, start + lo, hi - lo + 1)

    async def _index(self, writer, method: bytes) -> None:
        """JSON file index: [{index, path, length, streamable}]."""
        import json

        t = self.torrent
        out = [
            {
                "index": i,
                "path": name,
                "length": length,
                "streamable": length > 0 and t.span_servable(start, length),
            }
            for i, name, start, length in content_files(t)
        ]
        body = json.dumps({"name": t.info.name, "files": out}).encode()
        writer.write(
            (
                "HTTP/1.1 200 OK\r\nContent-Type: application/json\r\n"
                f"Content-Length: {len(body)}\r\nConnection: close\r\n\r\n"
            ).encode("latin-1")
        )
        if method != b"HEAD":
            writer.write(body)
        await writer.drain()

    async def _plain(self, writer, status: int, body: bytes, extra: str = ""):
        await _plain_response(writer, status, body, extra)

    # ------------------------------------------------------------- plumbing

    def _file_span(self, file_index: int) -> tuple[int, int]:
        """(global start offset, length) of the served file."""
        ranges = self.torrent.file_ranges()
        start, length = ranges[file_index]
        if length == 0:
            raise IndexError("empty file")
        entries = self.torrent.info.files or ()
        if file_index < len(entries) and getattr(entries[file_index], "pad", False):
            # BEP 47 pad spans aren't content; the CLI hides them and a
            # GET must 404, not stream phantom zeros
            raise IndexError("pad file")
        return start, length


    @staticmethod
    def _parse_range(value: str, length: int):
        """``bytes=lo-hi`` / ``bytes=lo-`` / ``bytes=-suffix`` → (lo, hi),
        or None when unsatisfiable. Multi-range requests fall back to the
        first range (players only ever send one)."""
        if not value.startswith("bytes="):
            return None
        spec = value[len("bytes=") :].split(",")[0].strip()
        lo_s, dash, hi_s = spec.partition("-")
        if not dash:
            return None
        try:
            if not lo_s:  # suffix form: last N bytes
                n = int(hi_s)
                if n <= 0:
                    return None
                return max(0, length - n), length - 1
            lo = int(lo_s)
            hi = int(hi_s) if hi_s else length - 1
        except ValueError:
            return None
        if lo < 0 or lo >= length or hi < lo:
            return None
        return lo, min(hi, length - 1)

    async def _serve_span(self, writer, offset: int, length: int) -> None:
        """Stream [offset, offset+length) of the TORRENT byte space,
        waiting for pieces and walking the scheduler window along.

        Each connection holds its own window token, so a player's
        parallel head + tail connections each keep a stable read-ahead
        (the torrent unions them); re-points within the same piece are
        no-ops on the torrent side."""
        t = self.torrent
        plen = t.info.piece_length
        end = offset + length
        pos = offset
        token = object()
        try:
            while pos < end:
                n = min(CHUNK, end - pos)
                first, last = pos // plen, (pos + n - 1) // plen
                # the window must cover every piece this chunk will wait
                # on — small pieces or unaligned ranges can span more
                # pieces than the configured read-ahead, and waiting on
                # an unboosted piece would stall at background priority
                t.set_stream_window(
                    pos, max(self.window_pieces, last - first + 2), token=token
                )
                for piece in range(first, last + 1):
                    await t.wait_piece(piece)
                data = await asyncio.to_thread(t.storage.get, pos, n)
                writer.write(data)
                await writer.drain()
                pos += n
        finally:
            t.clear_stream_window(token)
