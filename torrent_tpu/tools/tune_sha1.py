"""On-device Pallas SHA1 knob sweep (TILE_SUB x UNROLL).

Ranks kernel tilings by sustained hash-plane throughput on the real
chip, with the measurement methodology this image requires (see
BASELINE.md "Measured environment characteristics"):

- **Data lives on device.** The input batch is generated with the TPU
  PRNG; only two rows ever cross the tunnel (for the hashlib golden
  check). A host-built batch would spend 30 s per config on a 35 MiB/s
  relay and measure the pipe, not the kernel.
- **Every timed dispatch is distinct.** The kernel input is
  ``rand ^ salt`` with a fresh salt per dispatch — identical repeated
  dispatches get deduplicated by the remote backend and time as
  impossibly fast.
- **Completion is forced by fetching an on-device reduction** of the
  final dispatch's digests (the device executes in-order, so the last
  result landing implies the whole queue ran; ``block_until_ready``
  alone returns early on this backend).

Each (tile_sub, unroll) point reloads ``ops.sha1_pallas`` so the
module-level tiling constants rebind; the digest of the salt=0 warmup
is checked bit-exact against hashlib before any timing is trusted.

Usage::

    python -m torrent_tpu.tools.tune_sha1 [--piece-kb 256] [--batch 4096]
        [--grid 8x16,8x32,16x16,16x32,32x8,32x16] [--iters 8]

Prints one ranked JSON line per config plus a ``best`` summary line.
"""

from __future__ import annotations

import argparse
import hashlib
import importlib
import json
import os
import sys
import time

import numpy as np


def _parse_grid(spec: str) -> list[tuple[int, int]]:
    out = []
    for part in spec.split(","):
        ts, un = part.lower().split("x")
        out.append((int(ts), int(un)))
    return out


def _pad_tail(plen: int) -> np.ndarray:
    """The 64-byte SHA1 padding block for a message of exactly ``plen``
    bytes (plen % 64 == 0, so the pad is a standalone final block)."""
    assert plen % 64 == 0
    tail = np.zeros(64, dtype=np.uint8)
    tail[0] = 0x80
    tail[-8:] = np.frombuffer((plen * 8).to_bytes(8, "big"), dtype=np.uint8)
    return tail


def run_sweep(
    piece_kb: int,
    batch: int,
    grid: list[tuple[int, int]],
    iters: int,
    interpret: bool = False,
):
    import jax

    if interpret:
        # smoke-test mode: stay off the real device (this image's
        # sitecustomize pins jax_platforms to the device plugin, so the
        # env var alone is not enough)
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    plen = piece_kb * 1024
    padded = plen + 64
    nblk = padded // 64
    tail = _pad_tail(plen)

    # One device-resident random payload, shared by every config. Golden
    # rows 0 and batch-1 come back over the tunnel exactly once. Bits are
    # generated as u32 inside one jit (u8 generation makes a 32-bit word
    # per element — 4x the HBM — and the jit frees the intermediates).
    key = jax.random.key(20260730)
    rand = jax.jit(
        lambda k: jax.lax.bitcast_convert_type(
            jax.random.bits(k, (batch, plen // 4), jnp.uint32), jnp.uint8
        ).reshape(batch, plen)
    )(key)
    rand_np_rows = {i: np.asarray(rand[i]) for i in (0, batch - 1)}
    golden = {i: hashlib.sha1(rand_np_rows[i].tobytes()).digest() for i in rand_np_rows}
    tail_dev = jax.device_put(tail)
    nblocks = jnp.full((batch,), nblk, dtype=jnp.int32)

    results = []
    for tile_sub, unroll in grid:
        os.environ["TORRENT_TPU_SHA1_TILE_SUB"] = str(tile_sub)
        os.environ["TORRENT_TPU_SHA1_UNROLL"] = str(unroll)
        import torrent_tpu.ops.sha1_pallas as sp

        sp = importlib.reload(sp)
        if batch % sp.TILE:
            print(
                f"# skip {tile_sub}x{unroll}: batch {batch} not a multiple of "
                f"TILE {sp.TILE}",
                file=sys.stderr,
            )
            continue

        # rand/tail/nblocks are explicit arguments: a closed-over device
        # array can get lowered as an embedded HLO constant (a 1 GiB
        # program that takes minutes to build and ship over the relay)
        @jax.jit
        def hash_salted(r, t, nb, salt, _sp=sp):
            data = jnp.concatenate([r ^ salt, jnp.broadcast_to(t, (batch, 64))], axis=1)
            return _sp.sha1_pieces_pallas(data, nb, interpret=interpret)

        reduce_sum = jax.jit(lambda s: jnp.sum(s, dtype=jnp.uint64))

        try:
            t0 = time.perf_counter()
            state0 = hash_salted(rand, tail_dev, nblocks, jnp.uint8(0))
            got = np.asarray(state0[np.array([0, batch - 1])])
            compile_s = time.perf_counter() - t0
        except Exception as e:  # Mosaic can reject a tiling outright
            print(
                json.dumps(
                    {"tile_sub": tile_sub, "unroll": unroll, "error": repr(e)[:200]}
                )
            )
            continue
        for row, idx in ((0, 0), (1, batch - 1)):
            want = np.frombuffer(golden[idx], dtype=">u4").astype(np.uint32)
            if not np.array_equal(got[row], want):
                raise SystemExit(
                    f"golden mismatch at {tile_sub}x{unroll} row {idx}: "
                    f"{got[row]} != {want}"
                )

        t0 = time.perf_counter()
        outs = [
            hash_salted(rand, tail_dev, nblocks, jnp.uint8(s))
            for s in range(1, iters + 1)
        ]
        _ = int(reduce_sum(outs[-1]))
        secs = time.perf_counter() - t0
        pps = iters * batch / secs
        line = {
            "tile_sub": tile_sub,
            "unroll": unroll,
            "pieces_per_sec": round(pps, 1),
            "gib_per_sec": round(pps * plen / 2**30, 2),
            "compile_s": round(compile_s, 1),
        }
        results.append(line)
        print(json.dumps(line), flush=True)

    if results:
        best = max(results, key=lambda r: r["pieces_per_sec"])
        print(json.dumps({"best": best, "piece_kb": piece_kb, "batch": batch}))
    return results


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--piece-kb", type=int, default=256)
    ap.add_argument("--batch", type=int, default=4096)
    ap.add_argument(
        "--grid", default="8x16,8x32,16x8,16x16,16x32,32x8,32x16,32x32"
    )
    ap.add_argument("--iters", type=int, default=8)
    ap.add_argument(
        "--interpret",
        action="store_true",
        help="interpret-mode kernel (CPU smoke test of the sweep itself)",
    )
    args = ap.parse_args()
    run_sweep(
        args.piece_kb, args.batch, _parse_grid(args.grid), args.iters, args.interpret
    )


if __name__ == "__main__":
    main()
