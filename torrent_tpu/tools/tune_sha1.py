"""On-device Pallas SHA1 knob sweep (tile_sub x unroll).

Ranks kernel tilings by sustained hash-plane throughput on the real
chip, with the measurement methodology this image requires (see
BASELINE.md "Measured environment characteristics"):

- **Data lives on device.** The input batch is generated with the TPU
  PRNG; only two rows ever cross the tunnel (for the hashlib golden
  check). A host-built batch would spend 30 s per config on a 35 MiB/s
  relay and measure the pipe, not the kernel.
- **Every timed dispatch is distinct.** The kernel input is
  ``rand ^ salt`` with a fresh salt per dispatch — identical repeated
  dispatches get deduplicated by the remote backend and time as
  impossibly fast.
- **Completion is forced by fetching an on-device reduction** of the
  final dispatch's digests (the device executes in-order, so the last
  result landing implies the whole queue ran; ``block_until_ready``
  alone returns early on this backend). The reduction executable is
  warmed before the timed loop.
- **The u32 fast path is what's measured** — host-order u32 input, the
  same form the verifier uploads (a u8 batch would add the 4x-widened
  bitcast fusion the production path exists to avoid).

Tilings are passed straight to ``sha1_pieces_pallas`` (they are call
parameters, not module state); the digest of the salt=0 warmup is
checked bit-exact against hashlib before any timing is trusted.

Usage::

    python -m torrent_tpu.tools.tune_sha1 [--piece-kb 256] [--batch 4096]
        [--grid 8x16,16x16,32x8,32x16] [--iters 8]

Prints one ranked JSON line per config plus a ``best`` summary line.
"""

from __future__ import annotations

import argparse
import functools
import hashlib
import json
import os
import sys
import time

import numpy as np


def _parse_grid(spec: str) -> list[tuple[int, int, bool]]:
    """``32x16`` → (32, 16, False); a trailing ``i`` (``32x16i``)
    selects the 2-way round-chain interleave variant (sha1_pallas
    ``interleave2`` — the BASELINE.md roofline knob, off by default in
    production until this sweep says it wins)."""
    out = []
    for part in spec.split(","):
        ts, un = part.lower().split("x")
        il2 = un.endswith("i")
        out.append((int(ts), int(un.rstrip("i")), il2))
    return out


def _pad_tail(plen: int) -> np.ndarray:
    """The 64-byte SHA1 padding block for a message of exactly ``plen``
    bytes (plen % 64 == 0, so the pad is a standalone final block)."""
    assert plen % 64 == 0
    tail = np.zeros(64, dtype=np.uint8)
    tail[0] = 0x80
    tail[-8:] = np.frombuffer((plen * 8).to_bytes(8, "big"), dtype=np.uint8)
    return tail


def run_sweep(
    piece_kb: int,
    batch: int,
    grid: list[tuple[int, int]],
    iters: int,
    interpret: bool = False,
):
    import jax

    if interpret:
        # smoke-test mode: stay off the real device (this image's
        # sitecustomize pins jax_platforms to the device plugin, so the
        # env var alone is not enough)
        jax.config.update("jax_platforms", "cpu")
    else:
        # a sweep compiles every grid config — persist the compiles so a
        # re-sweep (or the bench rung that follows with the winning
        # knobs) skips straight to execution inside a scarce window
        try:
            cache = os.path.join(
                os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
                ".bench",
                "xla_cache",
            )
            os.makedirs(cache, exist_ok=True)
            jax.config.update("jax_compilation_cache_dir", cache)
            jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
        except Exception:
            pass
    import jax.numpy as jnp

    from torrent_tpu.ops import sha1_pallas as sp
    from torrent_tpu.ops.padding import num_blocks_for, padded_len_for

    plen = piece_kb * 1024
    padded = padded_len_for(plen)
    nblk = int(num_blocks_for(plen))  # true chain length; ghost tail is masked
    tail = np.zeros(padded - plen, dtype=np.uint8)
    tail[: 64] = _pad_tail(plen)[: min(64, padded - plen)]

    # One device-resident random payload (host-order u32 — the verifier's
    # fast path), shared by every config. Golden rows 0 and batch-1 come
    # back over the tunnel exactly once. Generated in chunks: threefry's
    # temporaries are ~4x the output.
    key = jax.random.key(20260730)

    @functools.partial(jax.jit, static_argnames="rows")
    def _gen(k, rows):
        return jax.random.bits(k, (rows, plen // 4), jnp.uint32)

    rows_per = max(1, min(batch, (256 << 20) // plen))
    parts = []
    for i, start in enumerate(range(0, batch, rows_per)):
        parts.append(_gen(jax.random.fold_in(key, i), min(rows_per, batch - start)))
    rand = jnp.concatenate(parts, axis=0) if len(parts) > 1 else parts[0]
    del parts
    rand_rows = {
        i: np.asarray(rand[i]).view(np.uint8).tobytes() for i in (0, batch - 1)
    }
    golden = {i: hashlib.sha1(rand_rows[i]).digest() for i in rand_rows}
    tail_dev = jax.device_put(tail.view(np.uint32))
    nblocks = jnp.full((batch,), nblk, dtype=jnp.int32)

    results = []
    for tile_sub, unroll, il2 in grid:
        name = f"{tile_sub}x{unroll}{'i' if il2 else ''}"
        if batch % (tile_sub * 128):
            print(
                f"# skip {name}: batch {batch} not a multiple of "
                f"tile {tile_sub * 128}",
                file=sys.stderr,
            )
            continue

        @jax.jit
        def hash_salted(r, t, nb, salt, _ts=tile_sub, _un=unroll, _il2=il2):
            data = jnp.concatenate(
                [r ^ salt, jnp.broadcast_to(t, (batch, t.shape[0]))], axis=1
            )
            return sp.sha1_pieces_pallas(
                data,
                nb,
                interpret=interpret,
                tile_sub=_ts,
                unroll=_un,
                interleave2=_il2,
            )

        reduce_sum = jax.jit(lambda s: jnp.sum(s, dtype=jnp.uint32))

        try:
            t0 = time.perf_counter()
            state0 = hash_salted(rand, tail_dev, nblocks, jnp.uint32(0))
            got = np.asarray(state0[np.array([0, batch - 1])])
            compile_s = time.perf_counter() - t0
        except Exception as e:  # Mosaic can reject a tiling outright
            print(
                json.dumps(
                    {
                        "tile_sub": tile_sub,
                        "unroll": unroll,
                        "interleave2": il2,
                        "error": repr(e)[:200],
                    }
                )
            )
            continue
        for row, idx in ((0, 0), (1, batch - 1)):
            want = np.frombuffer(golden[idx], dtype=">u4").astype(np.uint32)
            if not np.array_equal(got[row], want):
                raise SystemExit(
                    f"golden mismatch at {name} row {idx}: "
                    f"{got[row]} != {want}"
                )
        _ = int(reduce_sum(state0))  # warm the completion-forcing reduction

        t0 = time.perf_counter()
        outs = [
            hash_salted(rand, tail_dev, nblocks, jnp.uint32(s))
            for s in range(1, iters + 1)
        ]
        _ = int(reduce_sum(outs[-1]))
        secs = time.perf_counter() - t0
        pps = iters * batch / secs
        line = {
            "tile_sub": tile_sub,
            "unroll": unroll,
            "interleave2": il2,
            "pieces_per_sec": round(pps, 1),
            "gib_per_sec": round(pps * plen / 2**30, 2),
            "compile_s": round(compile_s, 1),
        }
        results.append(line)
        print(json.dumps(line), flush=True)

    if results:
        best = max(results, key=lambda r: r["pieces_per_sec"])
        print(json.dumps({"best": best, "piece_kb": piece_kb, "batch": batch}))
    return results


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--piece-kb", type=int, default=256)
    ap.add_argument("--batch", type=int, default=4096)
    ap.add_argument("--grid", default="8x16,16x16,32x8,32x16,32x16i,16x16i")
    ap.add_argument("--iters", type=int, default=8)
    ap.add_argument(
        "--interpret",
        action="store_true",
        help="interpret-mode kernel (CPU smoke test of the sweep itself)",
    )
    args = ap.parse_args()
    run_sweep(
        args.piece_kb, args.batch, _parse_grid(args.grid), args.iters, args.interpret
    )


if __name__ == "__main__":
    main()
